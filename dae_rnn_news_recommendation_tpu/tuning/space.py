"""Per-kernel candidate spaces: legal tile grids, pruned before any compile.

One declaration per Pallas kernel — the axes the tuner may vary, the
alignment laws each axis must obey (the kernel wrappers raise on violations,
so an illegal candidate would waste a compile just to error), and a static
VMEM-footprint model that rejects tile combinations which cannot fit the
~16 MB per-core budget. Pruning is pure host arithmetic: no jax import, no
trace, no compile — the measured search loop (tuning/search.py) only ever
sees candidates that are worth a compile.

Shape-key conventions (the tuple `tuning.resolve(op, shape, dtype)` takes;
`profile_db.row_key` renders it "AxBxC"):

    topk_fused   (B, N, D, k)        dtype = corpus emb dtype
    ivf_topk     (B, C, cap, D, k, probes)   dtype = cell emb dtype
    batch_hard   (B, D)              dtype = encode dtype
    masking      (B, F)              dtype = x dtype
    wire_unpack  (B, words_per_row)  dtype = "int32" (packed words)

The grids are centered on the hand-picked defaults (ops/tile_defaults.py),
so the default is always one of the measured candidates and a tuned config
can never lose to it in the race that admits it.
"""

from ..ops import tile_defaults as td

# static VMEM budget the footprint model prunes against: ~16 MB per core
# minus headroom for Mosaic's own scratch, semaphores, and the compiler's
# double-buffering of streamed blocks (modeled explicitly below as x2 on
# grid-streamed operands)
VMEM_BUDGET_BYTES = 12 << 20

_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "int8": 1, "int32": 4,
                "float64": 8, "int16": 2, "uint16": 2}


def dtype_bytes(dtype):
    return _DTYPE_BYTES.get(str(dtype), 4)


def _lane_pad(n):
    return td.ceil_to(max(int(n), 1), 128)


# ----------------------------------------------------------- footprint model

def vmem_footprint(op, config, shape, dtype):
    """Estimated peak VMEM bytes for one grid step of `op` at `config`.

    Deliberately simple and conservative: streamed input blocks count twice
    (the pipeline double-buffers HBM->VMEM fetches), dequantized panels
    count at f32 on top of their raw bytes, and accumulator output blocks
    count once (they persist across the revisiting axis). The model only
    needs to be monotone and roughly right — it prunes the obviously
    impossible corner of the grid, and the measured race decides the rest.
    """
    item = dtype_bytes(dtype)
    if op == "topk_fused":
        b, n, d, k = shape
        block, bq = config["block"], config["bq"]
        dp = _lane_pad(d)
        panel = block * dp * item * 2        # raw streamed panel (x2 pipeline)
        panel += block * dp * 4              # dequantized f32 copy
        queries = bq * dp * 4 * 2
        scores = bq * block * 4              # [bq, block] panel scores
        acc = 2 * bq * 128 * 4               # score + index accumulators
        masks = 2 * block * 4 * 2            # valid + scales rows
        return panel + queries + scores + acc + masks
    if op == "ivf_topk":
        b, c, cap, d, k, probes = shape
        bq = config["bq"]
        cap = td.ceil_to(cap, config.get("cap_multiple", td.IVF_CAP_MULTIPLE))
        dp = _lane_pad(d)
        panel = cap * dp * item * 2 + cap * dp * 4
        queries = bq * dp * 4 * 2
        probe_lanes = _lane_pad(probes)
        member = bq * probe_lanes * 4 * 2
        scores = bq * cap * 4
        acc = 2 * bq * 128 * 4
        rows = 3 * cap * 4 * 2               # row_ids + valid + scales
        return panel + queries + member + scores + acc + rows
    if op == "batch_hard":
        b, d = shape
        block_rows = config["block_rows"]
        bp = td.ceil_to(b, 8)
        dots = block_rows * _lane_pad(bp) * 4 * 2   # [rows, B] slab of dp
        masks = 2 * block_rows * _lane_pad(bp) * 4
        enc = block_rows * _lane_pad(d) * item * 2
        return dots + masks + enc
    if op == "masking":
        b, f = shape
        block_rows = config["block_rows"]
        return block_rows * f * item * 3     # in + out + keep mask
    if op == "wire_unpack":
        b, w = shape
        block_rows = config["block_rows"]
        wp = _lane_pad(w)
        words = block_rows * wp * 4 * 2
        tri = wp * wp * 4                    # upper-triangular operand
        out = block_rows * wp * 4 * 4        # up to fpw planes of output
        return words + tri + out
    raise KeyError(f"no VMEM model for op {op!r}")


# ----------------------------------------------------------- candidate grids

# raw axis grids, before legality/footprint pruning; each includes its
# tile_defaults center
_TOPK_BLOCKS = (128, 256, 512, 1024, 2048)
_TOPK_BQS = (8, 16, 32, 64, 128, 256)
_IVF_BQS = (8, 16, 32)
_IVF_CAP_MULTIPLES = (32, 64, 128)
_BATCH_HARD_ROWS = (8, 16, 32, 64, 128)
_MASKING_ROWS = (64, 128, 256, 512, 1024)
_WIRE_ROWS = (8, 16, 32, 64)


def validate(op, config, shape, dtype=None):
    """Is `config` legal for `op` at `shape`? The same law the kernel
    wrappers enforce — used both to prune grids before compiling and to
    reject a stale/foreign tuned row at resolve() time (a DB captured
    against different constraints must degrade to the default, never
    crash the dispatch)."""
    try:
        if op == "topk_fused":
            b, n, d, k = shape
            block, bq = int(config["block"]), int(config["bq"])
            return (block % 128 == 0 and block >= 128 and k <= block
                    and bq % 8 == 0 and 8 <= bq <= max(td.ceil_to(b, 8), 8))
        if op == "ivf_topk":
            bq = int(config["bq"])
            mult = int(config.get("cap_multiple", td.IVF_CAP_MULTIPLE))
            return bq % 8 == 0 and bq >= 8 and mult % 32 == 0 and mult >= 32
        if op in ("batch_hard", "wire_unpack"):
            rows = int(config["block_rows"])
            return rows % 8 == 0 and rows >= 8
        if op == "masking":
            rows = int(config["block_rows"])
            return rows % 8 == 0 and rows >= 8
    except (KeyError, TypeError, ValueError):
        return False
    return False


def candidates(op, shape, dtype, stats=None):
    """The pruned candidate list for one (op, shape, dtype): every config is
    legal (validate), fits the VMEM model, and is de-duplicated after the
    shape-dependent clamps. The default config is always first.

    `stats`, when a dict, receives the pruning ledger:
    {"n_raw", "n_illegal", "n_vmem"} — what the static model rejected before
    any compile, provenance the tuner persists alongside the winner."""
    default = td.default_config(op, shape)
    grid = []
    if op == "topk_fused":
        b, n, d, k = shape
        n_pad_max = max(td.ceil_to(n, 128), 128)
        for block in _TOPK_BLOCKS:
            if block > n_pad_max * 2:
                continue     # panels past ~2x the padded corpus only add pad
            for bq in _TOPK_BQS:
                if bq > td.ceil_to(b, 8):
                    continue  # pure query padding
                grid.append({"block": block, "bq": bq})
    elif op == "ivf_topk":
        for bq in _IVF_BQS:
            for mult in _IVF_CAP_MULTIPLES:
                grid.append({"bq": bq, "cap_multiple": mult})
    elif op == "batch_hard":
        b, d = shape
        for rows in _BATCH_HARD_ROWS:
            if rows > td.ceil_to(b, 8):
                continue
            grid.append({"block_rows": rows})
    elif op == "masking":
        b, f = shape
        item = dtype_bytes(dtype)
        # the wrapper clamps to its ~2 MB VMEM row budget; candidates past
        # the clamp would all collapse onto it
        vmem_rows = max(8, (2 << 20) // (item * max(f, 1)) // 8 * 8)
        for rows in _MASKING_ROWS:
            rows = min(rows, vmem_rows, max(td.ceil_to(b, 8), 8))
            grid.append({"block_rows": rows})
    elif op == "wire_unpack":
        b, w = shape
        for rows in _WIRE_ROWS:
            if rows > td.ceil_to(b, 8):
                continue
            grid.append({"block_rows": rows})
    else:
        raise KeyError(f"no candidate space for op {op!r}")

    out, seen = [], set()
    n_illegal = n_vmem = 0
    for cfg in [default] + grid:
        key = tuple(sorted(cfg.items()))
        if key in seen:
            continue
        seen.add(key)
        if not validate(op, cfg, shape, dtype):
            n_illegal += 1
            continue
        if vmem_footprint(op, cfg, shape, dtype) > VMEM_BUDGET_BYTES:
            n_vmem += 1
            continue
        out.append(dict(cfg))
    if stats is not None:
        stats.update({"n_raw": len(seen), "n_illegal": n_illegal,
                      "n_vmem": n_vmem})
    return out


# per-op parity discipline the search loop enforces before admission:
#   "exact"      candidate output must be bitwise/tie-exact vs the oracle
#                AND vs the default config's output
#   "invariant"  the kernel's random stream is a function of the block grid
#                (masking mixes pl.program_id into its PRNG seed), so
#                cross-config outputs are legitimately different bits; the
#                search checks seeded determinism + structural invariants
#                instead, and only on real TPU hardware
PARITY = {"topk_fused": "exact", "ivf_topk": "exact", "batch_hard": "exact",
          "masking": "invariant", "wire_unpack": "exact"}


def default_shapes(op):
    """Representative (shape, dtype) tuning keys per op for the offline CLI
    — serving-record and mined-training shapes, small enough that a full
    sweep stays inside a modest --budget-s."""
    if op == "topk_fused":
        return [((8, 4096, 512, 10), "float32"),
                ((8, 4096, 512, 10), "int8"),
                ((64, 4096, 512, 10), "float32")]
    if op == "ivf_topk":
        return [((8, 64, 64, 512, 10, 8), "float32"),
                ((8, 64, 64, 512, 10, 8), "int8")]
    if op == "batch_hard":
        return [((2048, 500), "float32"), ((8192, 500), "bfloat16")]
    if op == "masking":
        return [((2048, 10000), "float32")]
    if op == "wire_unpack":
        return [((1024, 25), "int32")]
    raise KeyError(f"no default tuning shapes for op {op!r}")
