"""dae_rnn_news_recommendation_tpu — a TPU-native (JAX/XLA/pjit/Pallas) framework with the
capabilities of louislung/DAE_RNN_News_Recommendation.

Built from scratch, TPU-first: functional JAX core with on-device corruption and triplet
mining inside the jit-compiled train step, pjit/shard_map data parallelism over a device
mesh with psum gradient reduction, dense padded shards fed from host-side sparse
matrices, optax optimizers and orbax-style checkpointing, plus native C++ runtime
components (StarSpace-style baseline trainer, fast CSR batcher).

Reference capability map (see SURVEY.md):
  ops/       — corruption, reconstruction losses, triplet mining (triplet_loss_utils.py, utils.py twins)
  models/    — DAE core + sklearn-style estimators (autoencoder.py, autoencoder_triplet.py twins),
               stacked DAE pretrain, Switch-style mixture-of-denoisers, GRU
               user-state RNN (the paper's unimplemented half)
  train/     — jitted train-step factory, optax optimizer zoo, epoch driver
  parallel/  — mesh construction; dp/tp/sp/pp/ep sharding strategies; ring
               (ppermute) eval collectives; anchor-partitioned global mining;
               multi-host init + sharded feeds
  data/      — article pipeline, padded batcher, save/read IO (datasets/articles.py, helpers.py twins)
  eval/      — pairwise similarity, AUROC plots (helpers.py twin)
  utils/     — config/flags + .env override, provenance, metrics, checkpointing
  cli/       — main_autoencoder / main_autoencoder_triplet drivers
"""

__version__ = "0.2.0"  # keep in sync with pyproject.toml

from . import ops  # noqa: F401
