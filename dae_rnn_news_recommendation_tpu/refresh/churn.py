"""The churn supervisor: stream -> frozen-vocab vectorize -> micro-batch
encode -> drift gate -> incremental swap (or fine-tune-then-rebuild).

One `ingest()` call is one refresh cycle:

  1. `refresh.ingest` fires; raw texts are vectorized against the FROZEN
     vocabulary (data/incremental.IncrementalVectorizer — OOV terms hash into
     the existing feature space, never a refit). Pre-vectorized matrices pass
     through.
  2. `refresh.encode` fires per micro-batch; the batch is encoded through the
     same jitted scan graph the corpus build uses (serve/graph.
     make_corpus_encode_fn), at a FIXED micro-batch shape so the whole stream
     reuses one compile.
  3. The drift gate compares the fresh embeddings against the active corpus
     version's gate stats (telemetry/health.drift_health, in-graph): a
     centroid shift or collapse delta past the configured ceilings means the
     encoder is stale for this data — appending would serve drifted
     embeddings, so the swap is BLOCKED and the supervisor fine-tunes from
     checkpoint (`refresh.finetune`, models/estimator.finetune) and rebuilds
     the corpus with the fresh params instead.
  4. Otherwise `ServingCorpus.swap_incremental` appends the rows (age-based
     eviction, tail health gate, version-monotonic promote, rollback on any
     failure) — `refresh.swap` fires inside. On an IVF corpus the appended
     rows route to their nearest EXISTING cells (no re-clustering on the
     hot path); when the corpus's cell-imbalance staleness counter flips
     `reindex_due`, the supervisor immediately runs `corpus.reindex()` — a
     centroid refit over the resident rows riding the same health-gated
     promote — and reports the cycle as `incremental+reindex`.

Transient faults at ingest/encode are absorbed by a bounded RetryPolicy
(recorded, never silent); fatal/preempt faults propagate to the caller — the
chaos harness (reliability/chaos_churn.py) is the supervisor-of-supervisors
that restarts the interrupted cycle, exactly like the training soak restarts
a killed fit. The supervisor keeps a host-side mirror of the rows currently
resident (trimmed in lockstep with the corpus's evictions) so a
fine-tune-then-rebuild always has the full training set for the rows it is
about to re-encode.
"""

import dataclasses
import json
import os
import time

import numpy as np
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from ..reliability import faults as _faults
from ..reliability.retry import RetryPolicy
from ..serve.graph import block_indices, make_corpus_encode_fn
from ..telemetry.health import drift_health
from ..train.resident import build_resident


class DriftTripped(RuntimeError):
    """Embedding drift past the ceilings with no fine-tune path configured:
    the swap is blocked and the caller must decide (the configured-finetune
    path handles this automatically with fine-tune-then-rebuild)."""


@dataclasses.dataclass
class ChurnConfig:
    """Refresh-loop policy knobs.

    :param microbatch: encode micro-batch rows (one compiled shape).
    :param max_rows: corpus capacity; oldest-version rows evict beyond it.
    :param max_age_versions: rows older than this many corpus versions evict
        on the next incremental swap (news expiry). None = keep forever.
    :param drift_centroid_max: centroid cosine-shift ceiling for the gate.
    :param drift_collapse_max: |collapse delta| ceiling for the gate.
    :param finetune_every: fine-tune-then-rebuild every N successful cycles
        (0 = only on drift trips / explicit finetune() calls).
    """

    microbatch: int = 64
    max_rows: int = None
    max_age_versions: int = None
    drift_centroid_max: float = 0.25
    drift_collapse_max: float = 0.20
    finetune_every: int = 0


class ChurnSupervisor:
    """Drives continuous refresh of a ServingCorpus from an article stream.

    :param params: current encoder params (replaced after each fine-tune).
    :param config: the model's DAEConfig (the encode graph's shape source).
    :param corpus: a serve.corpus.ServingCorpus; bootstrap() seeds it.
    :param churn: a ChurnConfig (default: ChurnConfig()).
    :param vectorizer: data/incremental.IncrementalVectorizer for raw-text
        batches; pre-vectorized [n, F] batches need none.
    :param finetune_fn: `fn(train_rows) -> new_params` — typically a closure
        over models/estimator.finetune. Without one, a drift trip raises
        DriftTripped instead of fine-tuning.
    :param retry: RetryPolicy absorbing transient ingest/encode faults
        (default: 3 attempts, small jittered backoff).
    :param registry: optional telemetry.MetricsRegistry — the supervisor
        keeps corpus_version / corpus staleness gauges and cycle / drift /
        rollback counters current so the SLO monitor sees refresh health
        without reaching into the history list.
    """

    def __init__(self, params, config, corpus, *, churn=None, vectorizer=None,
                 finetune_fn=None, retry=None, registry=None):
        self.params = params
        self.config = config
        self.corpus = corpus
        self.churn = churn or ChurnConfig()
        self.vectorizer = vectorizer
        self.finetune_fn = finetune_fn
        self.metrics = registry
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, backoff_s=0.005, max_elapsed_s=0.5)
        self._encode_fn = make_corpus_encode_fn(config)
        self._drift_fn = jax.jit(drift_health)
        self._store = []      # host mirror of resident rows, age order
        self.n_cycles = 0
        self.history = []     # one report dict per ingest cycle
        self.drift_trips = []
        self.finetunes = []

    # ------------------------------------------------------------- lifecycle
    def bootstrap(self, articles, note="bootstrap"):
        """Seed the corpus with a full build + gate + promote, and start the
        host-side row mirror the fine-tune rebuilds train on."""
        slot = self.corpus.swap(self.params, articles, note=note)
        self._store = [articles]
        return slot

    # ----------------------------------------------------------- one cycle
    def ingest(self, batch, note=""):
        """One refresh cycle over `batch` (raw-text iterable when a
        vectorizer is configured, else a dense [n, F] / scipy CSR matrix).
        Returns the cycle report (also appended to `history`)."""
        self.n_cycles += 1
        cycle = self.n_cycles
        t0 = time.monotonic()
        self.retry.run(_faults.fire, "refresh.ingest", site="refresh.ingest",
                       cycle=cycle)
        X = self._vectorize(batch)
        t_enc = time.monotonic()
        emb = self._encode(X)
        encode_s = time.monotonic() - t_enc
        drift = self._drift(emb)
        report = {"cycle": cycle, "n_new": int(X.shape[0]), "drift": drift,
                  "note": note, "encode_s": round(encode_s, 4)}
        if self.vectorizer is not None:
            report["oov_fraction"] = round(self.vectorizer.oov_fraction, 6)
        if drift is not None and drift["tripped"]:
            self.drift_trips.append({"cycle": cycle, **drift})
            report.update(self._finetune_rebuild(
                X, reason=f"drift trip at cycle {cycle}"))
            report["action"] = "finetune_rebuild"
        else:
            report.update(self._append(X, emb, cycle))
        if (report["action"] == "incremental"
                and self.churn.finetune_every
                and cycle % self.churn.finetune_every == 0):
            report.update(self._finetune_rebuild(
                None, reason=f"periodic (every {self.churn.finetune_every})"))
            report["action"] = "incremental+finetune_rebuild"
        report["cycle_s"] = round(time.monotonic() - t0, 4)
        # honest reachable-row fraction after the cycle: 1.0 on a healthy
        # corpus, < 1.0 while quarantined shard losses mask rows/cells (r16:
        # on a sharded IVF corpus this is the index's cell-level coverage)
        report["coverage"] = float(getattr(self.corpus, "coverage", 1.0))
        self.history.append(report)
        m = self.metrics
        if m is not None:
            m.counter("churn_cycles").inc()
            if drift is not None and drift["tripped"]:
                m.counter("drift_trips").inc()
            if "rollback" in report["action"]:
                m.counter("corpus_rollbacks").inc()
            m.gauge("corpus_version").set(self.corpus.version)
            m.gauge("corpus_staleness").set(
                getattr(self.corpus, "ivf_stale_cycles", 0) or 0)
            m.gauge("corpus_coverage").set(report["coverage"])
        return report

    def finetune(self, reason="requested"):
        """Explicit fine-tune-then-rebuild over the resident rows."""
        out = self._finetune_rebuild(None, reason=reason)
        self.history.append({"cycle": self.n_cycles, "action": "finetune",
                             **out})
        return out

    # -------------------------------------------------------------- stages
    def _vectorize(self, batch):
        if hasattr(batch, "shape"):
            return batch
        assert self.vectorizer is not None, (
            "raw-text batches need an IncrementalVectorizer")
        return self.vectorizer.transform(batch)

    def _encode(self, X):
        """Fixed-shape micro-batch encode through the jitted scan graph; the
        rows come back unit-norm f32 on host, ready for the drift gate and
        the swap append."""
        mb = int(self.churn.microbatch)
        outs = []
        for start in range(0, int(X.shape[0]), mb):
            chunk = X[start:start + mb]
            self.retry.run(_faults.fire, "refresh.encode",
                           site="refresh.encode", rows=int(chunk.shape[0]))
            resident = build_resident(chunk)
            blocks = block_indices(int(chunk.shape[0]), mb)
            outs.append(np.asarray(jax.device_get(self._encode_fn(
                self.params, resident, blocks)))[: int(chunk.shape[0])])
        return np.concatenate(outs, axis=0)

    def _drift(self, emb):
        """Drift report of the fresh embeddings vs the active version's gate
        stats, or None before any reference exists. Padded to the micro-batch
        multiple so every cycle reuses one compiled drift graph."""
        slot = self.corpus.active
        ref = getattr(slot, "stats", None) or {}
        if "centroid" not in ref:
            return None
        mb = int(self.churn.microbatch)
        n = emb.shape[0]
        n_pad = int(np.ceil(n / mb)) * mb
        padded = np.zeros((n_pad, emb.shape[1]), np.float32)
        padded[:n] = emb
        valid = np.zeros(n_pad, np.float32)
        valid[:n] = 1.0
        rep = jax.device_get(self._drift_fn(
            jnp.asarray(padded), jnp.asarray(ref["centroid"], jnp.float32),
            jnp.float32(ref["collapse"]), row_valid=jnp.asarray(valid)))
        shift = float(rep["health/drift_centroid_shift"])
        delta = float(rep["health/drift_collapse_delta"])
        return {"centroid_shift": round(shift, 6),
                "collapse_delta": round(delta, 6),
                "ref_version": slot.version,
                "tripped": bool(shift > self.churn.drift_centroid_max
                                or delta > self.churn.drift_collapse_max)}

    def _append(self, X, emb, cycle):
        """Incremental swap + host-mirror bookkeeping. A rollback (injected
        refresh.swap fault, gate refusal) leaves both the corpus AND the
        mirror untouched — the caller sees action='rollback' and owns the
        retry, so a replayed cycle reconverges to the fault-free state.

        A shard-degraded corpus (lost device shard quarantined, serving
        partial coverage) blocks every swap until healed, so the supervisor
        recovers FIRST — re-materializing the lost shard from the host
        mirror — then appends; the returned action carries a 'recover+'
        prefix so the soak can see the heal happened on this cycle."""
        recovered = False
        if getattr(self.corpus, "degraded_shards", ()):
            self.corpus.recover_shards(note=f"churn-{cycle}-shard-recover")
            recovered = True
        before = self.corpus.version
        self.corpus.swap_incremental(
            self.params, X, emb=emb, max_rows=self.churn.max_rows,
            max_age_versions=self.churn.max_age_versions,
            note=f"churn-{cycle}")
        led = self.corpus.ledger[-1]
        prefix = "recover+" if recovered else ""
        if not led["ok"] or self.corpus.version == before:
            return {"action": prefix + "rollback",
                    "version": self.corpus.version,
                    "error": led.get("error", "")}
        self._store.append(X)
        self._trim_store(led["n_evicted"])
        out = {"action": prefix + "incremental", "version": led["version"],
               "n_added": led["n_added"], "n_evicted": led["n_evicted"],
               "gate": led["gate"], "swap_s": led["duration_s"]}
        if getattr(self.corpus, "reindex_due", False):
            # append-routing has skewed the cells past the imbalance ceiling
            # for reindex_after consecutive swaps: refit the centroids now,
            # through the same gate -> promote -> ledger path as any swap
            self.corpus.reindex(note=f"churn-{cycle}-reindex")
            led = self.corpus.ledger[-1]
            out["action"] = prefix + ("incremental+reindex" if led["ok"]
                                      else "incremental+reindex_rollback")
            out["reindex"] = {"ok": led["ok"], "version": led["version"]}
        return out

    def _finetune_rebuild(self, X_new, reason):
        """The drift response: fine-tune the encoder from its newest
        checkpoint over everything resident (plus the triggering batch), then
        FULL-rebuild the corpus with the fresh params — never an incremental
        append of embeddings the gate just called stale."""
        self.retry.run(_faults.fire, "refresh.finetune",
                       site="refresh.finetune", reason=reason)
        if self.finetune_fn is None:
            raise DriftTripped(
                f"{reason}: drift past ceilings and no finetune_fn "
                "configured — refusing to swap stale embeddings")
        rows = self._store + ([X_new] if X_new is not None else [])
        train = _stack(rows)
        t0 = time.monotonic()
        self.params = self.finetune_fn(train)
        finetune_s = round(time.monotonic() - t0, 4)
        slot = self.corpus.swap(self.params, train,
                                note=f"finetune-rebuild: {reason}")
        self._store = [train]
        out = {"reason": reason, "finetune_s": finetune_s,
               "version": slot.version, "n_rows": int(train.shape[0])}
        self.finetunes.append(out)
        return out

    def _trim_store(self, n_evicted):
        """Mirror the corpus's oldest-first eviction: drop `n_evicted` rows
        off the front of the host store (splitting a block if needed)."""
        n = int(n_evicted)
        while n > 0 and self._store:
            head = self._store[0]
            rows = int(head.shape[0])
            if rows <= n:
                self._store.pop(0)
                n -= rows
            else:
                self._store[0] = head[n:]
                n = 0

    # ------------------------------------------------------------ reporting
    def resident_rows(self):
        return sum(int(b.shape[0]) for b in self._store)

    def summary(self):
        return {"n_cycles": self.n_cycles,
                "resident_rows": self.resident_rows(),
                "corpus_version": self.corpus.version,
                "corpus_coverage": float(getattr(self.corpus, "coverage",
                                                 1.0)),
                "drift_trips": list(self.drift_trips),
                "finetunes": list(self.finetunes),
                "retries": list(self.retry.events),
                "ledger": list(self.corpus.ledger)}

    def dump_history(self, path):
        """Write the cycle history + summary as JSON for `telemetry report
        --churn` (dropped as churn_history.json next to a trace, the report
        auto-detects it like the health bundle). Atomic tmp+rename so a
        crash mid-dump never leaves a torn file for the report to choke on."""
        payload = {"history": self.history, "summary": {
            k: v for k, v in self.summary().items() if k != "ledger"}}
        payload["summary"]["finetunes"] = len(self.finetunes)
        payload["summary"]["retries"] = len(self.retry.events)
        tmp = f"{path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, default=str)
        os.replace(tmp, path)
        return path


def _stack(blocks):
    if any(sp.issparse(b) for b in blocks):
        return sp.vstack([sp.csr_matrix(b) for b in blocks], format="csr")
    return np.concatenate([np.asarray(b) for b in blocks], axis=0)
