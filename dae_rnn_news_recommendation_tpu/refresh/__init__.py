"""Continuous corpus churn: crash-safe incremental refresh of the serving
corpus, with drift-gated promotion.

News articles live for hours, not epochs — the pipeline (vectorize -> DAE
encode -> resident corpus) is only production-real if new articles stream in,
get encoded, and start serving without a full refit and without ever serving
a corrupt or drifted corpus. This package is the seam between the crash-exact
training side (reliability/) and the health-gated serving side (serve/):

    vec = IncrementalVectorizer.from_fitted(count_vectorizer)   # frozen vocab
    sup = ChurnSupervisor(params, config, corpus,
                          churn=ChurnConfig(max_rows=10_000,
                                            max_age_versions=48),
                          vectorizer=vec, finetune_fn=my_finetune)
    sup.bootstrap(initial_articles)       # full build + gate + promote
    for batch in article_stream:
        report = sup.ingest(batch)        # vectorize -> encode -> drift gate
                                          # -> incremental swap (or
                                          # fine-tune-then-rebuild on a trip)

Every refresh step has a fault site (`refresh.ingest` / `refresh.encode` /
`refresh.swap` / `refresh.finetune`) and the chaos_churn soak
(reliability/chaos_churn.py) replays seeded fault plans through the whole
loop, asserting the served corpus is always a health-gated, version-monotonic
state and that a crashed fine-tune resumes bitwise-exact. Full story in
docs/reliability.md ("Corpus churn & refresh") and docs/serving.md.
"""

from .churn import ChurnConfig, ChurnSupervisor, DriftTripped

__all__ = ["ChurnConfig", "ChurnSupervisor", "DriftTripped"]
