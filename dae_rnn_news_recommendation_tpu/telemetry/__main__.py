"""CLI: `python -m dae_rnn_news_recommendation_tpu.telemetry report ...`

    report <trace.json> [--metrics PATH] [--bench PATH] [--health PATH]
                        [--churn PATH] [--fleet [PATH]] [--profile [PATH]]
                        [--quality [PATH]] [--tuning [PATH]] [--json]

Prints the per-span p50/p95/total table (with feed-stall and compile-count
columns) from a trace exported by a traced fit; optionally joins metrics.jsonl
scalars, reconciles a bench record's H2D probes against measured transfer
counters, and renders a flight-recorder health bundle (auto-detected next to
the trace when --health is omitted). Unreadable OPTIONAL inputs degrade to
warning notes. Exit codes: 0 report rendered, 1 trace had no span events and
nothing else loaded, 2 usage / unreadable trace.
"""

import argparse
import sys

from .report import report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m dae_rnn_news_recommendation_tpu.telemetry",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = parser.add_subparsers(dest="command", required=True)
    rep = sub.add_parser("report", help="render a per-span table from a "
                                        "Chrome trace exported by a fit")
    rep.add_argument("trace", help="trace.json exported by a traced fit")
    rep.add_argument("--metrics", default=None,
                     help="metrics.jsonl (or its directory) from the same "
                          "run, for the FeedStats cross-check")
    rep.add_argument("--bench", default=None,
                     help="bench stdout JSON line or evidence sidecar, for "
                          "the h2d probe-vs-measured reconciliation")
    rep.add_argument("--health", default=None,
                     help="flight-recorder health_bundle.json (default: "
                          "auto-detect next to the trace)")
    rep.add_argument("--churn", default=None,
                     help="churn_history.json dumped by a ChurnSupervisor "
                          "(default: auto-detect next to the trace)")
    rep.add_argument("--fleet", nargs="?", const="auto", default=None,
                     help="fleet_observability.json dumped by "
                          "dump_fleet_observability; bare --fleet (or no "
                          "flag) auto-detects next to the trace")
    rep.add_argument("--profile", nargs="?", const="auto", default=None,
                     help="profile_db.json written by devprof/ProfileDB; "
                          "bare --profile (or no flag) auto-detects next "
                          "to the trace")
    rep.add_argument("--quality", nargs="?", const="auto", default=None,
                     help="quality_observability.json dumped by "
                          "dump_quality_observability; bare --quality (or "
                          "no flag) auto-detects next to the trace")
    rep.add_argument("--tuning", nargs="?", const="auto", default=None,
                     help="a ProfileDB with autotuner rows (tuning/search); "
                          "renders tuned-vs-default configs; bare --tuning "
                          "(or no flag) auto-detects profile_db.json next "
                          "to the trace")
    rep.add_argument("--json", action="store_true",
                     help="emit the report as JSON instead of a table")
    args = parser.parse_args(argv)

    try:
        text, code = report(args.trace, metrics_path=args.metrics,
                            bench_path=args.bench, health_path=args.health,
                            churn_path=args.churn, fleet_path=args.fleet,
                            profile_path=args.profile,
                            quality_path=args.quality,
                            tuning_path=args.tuning, as_json=args.json)
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    print(text)
    return code


if __name__ == "__main__":
    sys.exit(main())
