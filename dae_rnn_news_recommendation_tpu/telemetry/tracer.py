"""Fenced span tracer: nestable, thread-aware timed regions with Chrome-trace
export.

Why fencing is the core design point: under async dispatch a naive
`perf_counter()` pair around device work measures *enqueue*, not compute —
and on this repo's axon TPU tunnel even `block_until_ready` has been observed
returning before the work finished (bench.py `_hard_sync`, measured
2026-08-02). So every span here ends, by default, with a real host round trip
(`jax.device_get` of a tiny slice): either of a value the span body nominated
via `sp.fence_on(out)`, or of a one-element jitted token op enqueued at span
exit (single-device executions complete in dispatch order, so fetching the
token fences everything dispatched before it). That makes spans
jaxcheck-R2-clean by construction, and jaxcheck recognizes `telemetry.span`
as a fence (analysis/rules.py). `fence=False` opts a span out — for host-only
regions (padding, queue waits); rule R6 flags `fence=False` spans that wrap
device work without their own fence.

Overhead when disabled: `span()` returns a shared null object and decorated
functions take one extra `if` per call — no clock reads, no fence, no
allocation. Tracing is a diagnosis mode: when enabled, fenced spans serialize
with the device (that is what makes the numbers honest), so enable it to ask
"where did the time go", not while benchmarking peak throughput.

Thread-awareness: each span records the thread it ran on (`tid`), and thread
names (e.g. the pipelined feed's "pipelined-feed" worker vs the consumer
"MainThread") become Chrome-trace thread_name metadata — producer and
consumer land on separate tracks in Perfetto.
"""

import functools
import json
import os
import threading
import time

# virtual track for events that are not tied to a Python thread (XLA compile
# durations reported by jax.monitoring); real thread idents are pointer-sized
# so a tiny constant can never collide
XLA_TRACK_TID = 1


class Tracer:
    """Collects Chrome-trace "X" (complete) events; thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._origin = time.perf_counter()
        self._events = []
        self._thread_names = {XLA_TRACK_TID: "xla-events"}
        self.pid = os.getpid()
        # filled by telemetry.disable() from the active XlaEventListener so an
        # exported trace carries its counters; {} until then
        self.counters = {}

    def now_us(self):
        return (time.perf_counter() - self._origin) * 1e6

    def note_thread(self, tid, name):
        if tid not in self._thread_names:
            with self._lock:
                self._thread_names.setdefault(tid, name)

    def record_span(self, name, ts_us, dur_us, tid, cat="span", args=None):
        if tid not in self._thread_names and tid == threading.get_ident():
            # threads born AFTER tracing started (the fleet's hedger, a
            # rollout worker) reach here without ever passing through
            # _Span.__enter__'s note_thread — name their track from the
            # live thread object so Chrome-trace export never shows an
            # anonymous tid. Only the CALLING thread is nameable this way:
            # events recorded on behalf of another tid (the XLA track) keep
            # whatever name was noted for them.
            self.note_thread(tid, threading.current_thread().name)
        event = {"name": name, "cat": cat, "ph": "X",
                 "ts": round(ts_us, 3), "dur": round(dur_us, 3),
                 "pid": self.pid, "tid": tid}
        if args:
            event["args"] = args
        with self._lock:
            self._events.append(event)

    def record_xla_event(self, name, duration_s, args=None):
        """A duration reported after the fact (jax.monitoring fires when the
        event *ends*): place it at [now - duration, now] on the XLA track."""
        dur_us = duration_s * 1e6
        self.record_span(name, self.now_us() - dur_us, dur_us,
                         XLA_TRACK_TID, cat="xla", args=args)

    def events(self):
        with self._lock:
            return list(self._events)

    def chrome_trace(self, metadata=None):
        """The trace as a Chrome-trace-event JSON object (Perfetto-loadable):
        thread_name/process_name "M" metadata first, then the "X" events
        sorted by ts."""
        with self._lock:
            events = sorted(self._events,
                            key=lambda e: (e["ts"], -e["dur"]))
            names = dict(self._thread_names)
        meta = [{"ph": "M", "pid": self.pid, "tid": 0,
                 "name": "process_name", "args": {"name": "dae-telemetry"}}]
        for tid, name in sorted(names.items()):
            meta.append({"ph": "M", "pid": self.pid, "tid": tid,
                         "name": "thread_name", "args": {"name": name}})
        out = {"traceEvents": meta + events, "displayTimeUnit": "ms",
               "metadata": {"counters": self.counters}}
        if metadata:
            out["metadata"].update(metadata)
        return out

    def export(self, path, metadata=None):
        """Write the Chrome trace JSON (atomic replace) and return `path`."""
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(metadata), f)
            f.write("\n")
        os.replace(tmp, path)
        return path


# ------------------------------------------------------------- module state

_state_lock = threading.Lock()
_enabled = False   # read on every span()/instrument() call: keep it a plain bool
_tracer = None
_listener = None
_fence_fn = None


def enabled():
    return _enabled


def current_tracer():
    """The active Tracer, or None when tracing is disabled."""
    return _tracer if _enabled else None


def enable(tracer=None, xla_events=True):
    """Turn tracing on. Returns the active Tracer (a fresh one unless given).
    Idempotent: enabling while enabled returns the current tracer untouched.
    `xla_events=True` also registers a `jax.monitoring` listener so XLA
    compile durations land in the trace and in named counters."""
    global _enabled, _tracer, _listener
    with _state_lock:
        if _enabled:
            return _tracer
        _tracer = tracer or Tracer()
        if xla_events:
            from .xla_events import XlaEventListener

            _listener = XlaEventListener(tracer=_tracer).start()
        _enabled = True
        return _tracer


def disable():
    """Turn tracing off and return the Tracer (with `.counters` filled from
    the XLA listener). No-op returning None when already disabled."""
    global _enabled, _tracer, _listener
    with _state_lock:
        if not _enabled:
            return None
        _enabled = False
        tracer, _tracer = _tracer, None
        listener, _listener = _listener, None
    if listener is not None:
        listener.stop()
        tracer.counters = listener.summary()
    return tracer


def counters():
    """The active listener's counter dict ({} when tracing is off)."""
    listener = _listener
    return listener.summary() if (_enabled and listener) else {}


def record_transfer(direction, duration_s, nbytes):
    """Account a fence-measured host<->device transfer ('h2d'/'d2h') into the
    active listener's counters. This jax version emits no transfer events via
    jax.monitoring, so the pipelined feed's fenced H2D spans call this with
    their measured durations instead (train/pipeline.py). No-op when tracing
    is off or the span was unfenced (duration_s None)."""
    listener = _listener
    if listener is not None and duration_s is not None:
        listener.record_transfer(direction, duration_s, nbytes)


# ------------------------------------------------------------------ fencing

def _fence_token():
    """A tiny jitted op on the default device; fetching its output fences all
    work dispatched to that device before it (single-device executions
    complete in dispatch order — the bench.py `_hard_sync` lesson)."""
    global _fence_fn
    import jax
    import jax.numpy as jnp

    if _fence_fn is None:
        _fence_fn = jax.jit(lambda: jnp.zeros((), jnp.int32) + 1)
    return _fence_fn()


def device_fence(x=None):
    """Force device completion with a real host round trip.

    With `x`: fetch a one-element slice of its last array leaf (the whole
    executable that produced it completes atomically, so one element fences
    the lot). Without: enqueue and fetch the token op. Never raises — a
    telemetry fence must not be able to kill training."""
    try:
        import jax

        if x is not None:
            leaves = [leaf for leaf in jax.tree_util.tree_leaves(x)
                      if hasattr(leaf, "dtype")]
            if leaves:
                leaf = leaves[-1]
                jax.device_get(leaf.ravel()[:1] if getattr(leaf, "ndim", 0)
                               else leaf)
                return
        jax.device_get(_fence_token())
    except Exception:
        pass


# -------------------------------------------------------------------- spans

class _NullSpan:
    """What span() hands out while tracing is disabled: every operation is a
    no-op, `fence_on` passes its value through, and decorating with it yields
    a wrapper that re-checks enablement at call time (so decoration at import
    time doesn't bake the disabled state in — the wrapper keeps the span's
    name and fence mode for when tracing turns on). One instance per
    (name, fence) pair, cached forever: span names are a static vocabulary,
    so the disabled hot path is a dict hit, not an allocation."""

    __slots__ = ("name", "fence")
    duration_s = None

    def __init__(self, name=None, fence=True):
        self.name = name
        self.fence = fence

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence_on(self, x):
        return x

    def set_args(self, **kw):
        return self

    def __call__(self, fn):
        return _wrap(fn, self.name, self.fence)


_null_spans = {}


class _Span:
    """One timed region: context manager and decorator.

    `fence=True` (default): exit runs `device_fence` — on the value nominated
    via `fence_on(x)` if any, else the token op. `fence=False`: host-only
    region, no fence, and jaxcheck R6 will flag device work inside it.
    `duration_s` holds the fenced duration after exit."""

    __slots__ = ("name", "fence", "args", "_tracer", "_tid", "_ts_us", "_t0",
                 "_fence_target", "duration_s")

    def __init__(self, tracer, name, fence=True, args=None):
        self.name = name
        self.fence = fence
        self.args = dict(args) if args else None
        self._tracer = tracer
        self._fence_target = None
        self.duration_s = None

    def __enter__(self):
        self._tid = threading.get_ident()
        self._tracer.note_thread(self._tid, threading.current_thread().name)
        self._ts_us = self._tracer.now_us()
        self._t0 = time.perf_counter()
        return self

    def fence_on(self, x):
        """Nominate the device value whose completion defines this span's end
        (e.g. the step's metrics, the staged batch). Returns `x`."""
        self._fence_target = x
        return x

    def set_args(self, **kw):
        self.args = {**(self.args or {}), **kw}
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.fence:
            device_fence(self._fence_target)
        self._fence_target = None  # never outlive the span (donation safety)
        self.duration_s = time.perf_counter() - self._t0
        args = self.args
        if exc_type is not None:
            args = {**(args or {}), "error": exc_type.__name__}
        self._tracer.record_span(self.name, self._ts_us,
                                 self.duration_s * 1e6, self._tid, args=args)
        return False  # exceptions propagate; the span still recorded

    def __call__(self, fn):
        return _wrap(fn, self.name, self.fence)


def span(name, fence=True, args=None):
    """`with telemetry.span("fit/epoch") as sp:` — or `@telemetry.span(...)`.

    Near-zero cost while tracing is disabled (returns a cached null object).
    When enabled, the region ends with a device fence unless `fence=False`;
    call `sp.fence_on(out)` inside the body to fence on a specific value."""
    if not _enabled:
        try:
            return _null_spans[name, fence]
        except KeyError:
            return _null_spans.setdefault((name, fence),
                                          _NullSpan(name, fence))
    return _Span(_tracer, name, fence=fence, args=args)


def _wrap(fn, name, fence):
    span_name = name or getattr(fn, "__qualname__", repr(fn))

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        if not _enabled:
            return fn(*a, **kw)
        with _Span(_tracer, span_name, fence=fence):
            return fn(*a, **kw)
    return wrapper


def instrument(fn, name, fence_result=True):
    """Wrap a callable (typically a jitted step) so each call becomes a span
    fenced on its *result* — the span measures compute, not dispatch. The
    wrapper holds no reference to the call's arguments after it returns, so
    donated inputs stay donation-safe. One extra `if` per call when tracing
    is off."""

    @functools.wraps(fn)
    def wrapper(*a, **kw):
        if not _enabled:
            return fn(*a, **kw)
        with _Span(_tracer, name, fence=fence_result) as sp:
            out = fn(*a, **kw)
            if fence_result:
                sp.fence_on(out)
            return out
    return wrapper
