"""Runtime telemetry: fenced span tracing, XLA event capture, run manifests.

Quick tour (full story in docs/observability.md):

    from dae_rnn_news_recommendation_tpu import telemetry

    telemetry.enable()                      # start tracing + XLA listener
    with telemetry.span("fit/epoch") as sp: # fenced timed region
        out = step(params, opt, batch)
        sp.fence_on(out)                    # span ends when `out` is real
    tracer = telemetry.disable()
    tracer.export("trace.json")             # Chrome trace; open in Perfetto

    python -m dae_rnn_news_recommendation_tpu.telemetry report trace.json

Spans default to ending with a device fence (a real host round trip), so a
span's duration is compute time, not dispatch time — the jaxcheck R2
invariant, built in. `telemetry.span(..., fence=False)` marks host-only
regions; jaxcheck R6 flags device work inside them.
"""

from . import devprof
from .health import (drift_health, embedding_health, mining_health,
                     sentinel_metrics)
from .manifest import build_manifest, read_manifest, write_manifest
from .metrics_registry import (DEFAULT_LATENCY_BOUNDS_MS, Counter, Gauge,
                               Histogram, MetricsRegistry, aggregate,
                               histogram_percentile)
from .profile_db import ProfileDB, row_key
from .recorder import FlightRecorder, summarize_batch
from .slo import SLOMonitor, SLOSpec, quality_slo_specs, serving_slo_specs
from .tracer import (Tracer, counters, current_tracer, device_fence, disable,
                     enable, enabled, instrument, record_transfer, span)
from .xla_events import XlaEventListener

__all__ = [
    "Counter",
    "DEFAULT_LATENCY_BOUNDS_MS",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ProfileDB",
    "SLOMonitor",
    "SLOSpec",
    "Tracer",
    "XlaEventListener",
    "aggregate",
    "build_manifest",
    "counters",
    "current_tracer",
    "device_fence",
    "devprof",
    "disable",
    "drift_health",
    "embedding_health",
    "enable",
    "enabled",
    "histogram_percentile",
    "instrument",
    "mining_health",
    "quality_slo_specs",
    "read_manifest",
    "record_transfer",
    "row_key",
    "sentinel_metrics",
    "serving_slo_specs",
    "span",
    "summarize_batch",
    "write_manifest",
]
