"""ProfileDB: the persisted device-time measurement cache.

One JSON file, rows keyed by ``(op, shape, dtype, device_kind)`` — the four
coordinates that determine a kernel's device time. Every row carries the
measurement's provenance (best/median over n timed iterations, warmup count,
compiles observed during warmup vs timed) next to the static cost-analysis
join (FLOPs, bytes accessed, roofline fraction), so a reader can tell a
trustworthy number from a polluted one without re-running anything.

This is the cache the ROADMAP item-4 kernel autotuner will read: an autotuner
sweep is just ``measure()`` over a tile grid with each result ``record()``-ed
here, and the serving/default tile choice becomes "best row for this key".

Durability contract (same as every artifact dump in this repo): writes go
through a tmp file + ``os.replace``, so a concurrent reader always parses a
complete JSON document — either the previous generation or the new one, never
a torn write. The reader side tolerates a missing file (empty DB) but not a
malformed one (that is a corrupted artifact worth failing loudly on).
"""

import json
import os

_SCHEMA_VERSION = 1

# fields that make up the row key, in key-string order
KEY_FIELDS = ("op", "shape", "dtype", "device_kind")


def row_key(op, shape, dtype, device_kind):
    """The canonical string key for one measurement row. ``shape`` is any
    iterable of ints (or a pre-rendered "AxBxC" string); dtype is the jnp
    dtype name. Keys must be stable across processes — they are dict keys in
    the JSON file — so everything is stringified one way."""
    if not isinstance(shape, str):
        shape = "x".join(str(int(d)) for d in shape)
    return "|".join((str(op), shape, str(dtype), str(device_kind)))


class ProfileDB:
    """Load-mutate-save store for measurement rows.

    The in-memory form is ``{key_string: row_dict}`` where each row also
    carries its key fields inline (op/shape/dtype/device_kind) so ``rows()``
    consumers never have to parse key strings."""

    def __init__(self, path):
        self.path = path
        self._rows = {}
        self.load()

    # ------------------------------------------------------------------ I/O
    def load(self):
        """(Re)read the file. Missing file -> empty DB; malformed JSON or a
        wrong top-level shape raises ValueError (a corrupt cache must not be
        silently treated as empty and then clobbered)."""
        self._rows = {}
        if not os.path.exists(self.path):
            return self
        with open(self.path, encoding="utf-8") as f:
            obj = json.load(f)
        if not isinstance(obj, dict) or not isinstance(obj.get("rows"), dict):
            raise ValueError(f"{self.path}: not a profile DB")
        self._rows = dict(obj["rows"])
        return self

    def save(self):
        """Atomic rewrite: tmp + os.replace, so a reader mid-rewrite sees a
        complete old or complete new document."""
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": _SCHEMA_VERSION,
                       "rows": self._rows}, f, indent=1, sort_keys=True,
                      default=str)
            f.write("\n")
        os.replace(tmp, self.path)
        return self.path

    # ---------------------------------------------------------------- store
    def record(self, result_or_row, **extra):
        """Upsert one row. Accepts a devprof ``MeasureResult`` (anything with
        ``as_row()``) or a plain dict carrying at least the KEY_FIELDS.
        Returns the stored row dict."""
        row = (result_or_row.as_row()
               if hasattr(result_or_row, "as_row") else dict(result_or_row))
        row.update(extra)
        missing = [k for k in KEY_FIELDS if row.get(k) is None]
        if missing:
            raise ValueError(f"profile row missing key fields: {missing}")
        key = row_key(row["op"], row["shape"], row["dtype"],
                      row["device_kind"])
        self._rows[key] = row
        return row

    def get(self, op, shape, dtype, device_kind):
        return self._rows.get(row_key(op, shape, dtype, device_kind))

    def rows(self):
        """All rows, stably ordered by key."""
        return [self._rows[k] for k in sorted(self._rows)]

    def top(self, n=10, by="best_ms"):
        """The n most expensive rows by a timing field (for the report's
        top-N table). Rows without the field sort last."""
        def cost(row):
            v = row.get(by)
            return -float(v) if isinstance(v, (int, float)) else 0.0

        return sorted(self._rows.values(), key=cost)[:n]

    def __len__(self):
        return len(self._rows)

    def __contains__(self, key):
        return key in self._rows
