"""Flight recorder: a bounded ring of recent step metrics + crash bundles.

Host-side twin of telemetry/health.py's in-graph sentinel: the estimator
feeds every step's (already fetched) metric row into `record()`, which keeps
the last `capacity` rows in a deque and watches for three anomaly classes:

  * nonfinite  — any NaN/Inf metric value, or the sentinel's
                 `health/nonfinite` flag tripping;
  * divergence — cost exceeding `divergence_factor` x its own EMA (after a
                 short warmup so the first noisy steps don't trip it);
  * exception  — an uncaught exception in fit (the estimator calls `dump`
                 from its handler and re-raises).

On the first anomaly the estimator dumps a diagnostics bundle
(`health_bundle.json` in the run dir): the ring contents, the trace tail
(when tracing is on), the run manifest, a batch signature, the first bad and
last good step ids. Further dumps in the same run (a later divergence, an
exception after a degrade) get `health_bundle_<n>.json` suffixes instead of
clobbering the first bundle — the FIRST anomaly is usually the root cause.
`python -m ...telemetry report --health` renders them.

Detection granularity follows the metric fetch: all three feed paths fetch
step metrics once per epoch (the async-dispatch design), so anomalies are
noticed at the epoch boundary — but the ring pins the exact step, because
every step's row is recorded with its global step id. `health_abort=True`
(opt-in, estimator ctor) stops fit at that boundary; the default records and
keeps going, matching prior behavior exactly.
"""

import collections
import json
import math
import os

import numpy as np


def _jsonable(v):
    """Best-effort scalar conversion; non-numeric values pass through repr."""
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


def summarize_batch(batch):
    """Host-side summary of a feed batch: shape/dtype per key, plus value
    stats (min/max/mean, nonfinite count) for host numpy arrays. Device or
    donated buffers stay shape-only — a diagnostics path must never force a
    transfer or touch freed memory."""
    if not isinstance(batch, dict):
        return {"type": type(batch).__name__}
    out = {}
    for k, v in batch.items():
        entry = {"shape": list(getattr(v, "shape", ())),
                 "dtype": str(getattr(v, "dtype", type(v).__name__))}
        if isinstance(v, np.ndarray) and v.size and \
                np.issubdtype(v.dtype, np.floating):
            finite = np.isfinite(v)
            entry["n_nonfinite"] = int(v.size - finite.sum())
            if finite.any():
                fv = v[finite]
                entry.update(min=float(fv.min()), max=float(fv.max()),
                             mean=float(fv.mean()))
        out[k] = entry
    return out


class FlightRecorder:
    """Ring buffer of step metrics with anomaly detection.

    :param capacity: steps of history the bundle carries
    :param divergence_factor: cost > factor * EMA(cost) flags divergence
    :param ema_alpha: EMA smoothing for the divergence baseline
    :param warmup_steps: steps before divergence can trip (the EMA needs a
        baseline; nonfinite detection is active from step one)
    """

    BUNDLE_SCHEMA = 1

    def __init__(self, capacity=256, divergence_factor=10.0, ema_alpha=0.05,
                 warmup_steps=10):
        self.capacity = int(capacity)
        self.divergence_factor = float(divergence_factor)
        self.ema_alpha = float(ema_alpha)
        self.warmup_steps = int(warmup_steps)
        self.ring = collections.deque(maxlen=self.capacity)
        self.ema = None
        self.n_recorded = 0
        self.status = "ok"
        self.first_bad_step = None
        self.first_bad_reason = None
        self.last_good_step = None
        self.batch_signature = None
        self.bundle_path = None
        self.faults = []

    # ------------------------------------------------------------ recording

    def record(self, step, metrics):
        """Feed one step's host metrics. Returns the anomaly reason string
        the first time this step looks bad, else None. Later anomalies only
        update the ring (the bundle names the FIRST bad step)."""
        row = {"step": int(step)}
        nonfinite_keys = []
        for k, v in metrics.items():
            fv = _jsonable(v)
            row[k] = fv
            if isinstance(fv, float) and not math.isfinite(fv):
                nonfinite_keys.append(k)
        self.ring.append(row)
        self.n_recorded += 1

        reason = None
        cost = row.get("cost")
        if nonfinite_keys:
            reason = f"nonfinite metrics at step {step}: " \
                     f"{sorted(nonfinite_keys)[:4]}"
        elif row.get("health/nonfinite", 0.0) > 0.0:
            reason = (f"sentinel nonfinite flag at step {step} "
                      "(grads/updates contain NaN or Inf)")
        elif (isinstance(cost, float) and self.ema is not None
                and self.n_recorded > self.warmup_steps
                and cost > self.divergence_factor * self.ema):
            reason = (f"divergence at step {step}: cost {cost:.6g} > "
                      f"{self.divergence_factor:g} x EMA {self.ema:.6g}")

        if isinstance(cost, float) and math.isfinite(cost):
            self.ema = (cost if self.ema is None else
                        self.ema + self.ema_alpha * (cost - self.ema))
        if reason is None:
            if self.status == "ok":
                self.last_good_step = int(step)
            return None
        if self.first_bad_step is None:
            self.first_bad_step = int(step)
            self.first_bad_reason = reason
            self.status = "degraded"
            return reason
        return None

    def note_batch_signature(self, batch):
        """Record the feed's batch signature once (shape/dtype per key, value
        stats when the arrays are host numpy). Called at most once per epoch
        by the estimator — cheap, and enough to tie a bundle to its feed."""
        try:
            self.batch_signature = summarize_batch(batch)
        except Exception:
            self.batch_signature = None  # diagnostics must never kill a fit

    def note_exception(self, exc):
        """Mark the run failed by an uncaught exception (dump() records it)."""
        self.status = "failed"
        if self.first_bad_reason is None:
            self.first_bad_reason = f"exception: {type(exc).__name__}: {exc}"

    def note_fault(self, event):
        """Record one recovered fault (an I/O retry, an injected transient) —
        recoveries must never be silent, so they ride the diagnostics bundle
        alongside the step ring. `event` is a small JSON-able dict
        (reliability/retry.py shapes it)."""
        try:
            self.faults.append(dict(event))
        except Exception:
            pass  # diagnostics must never kill a fit

    # ------------------------------------------------------------ snapshots

    def snapshot(self):
        """Small health summary for checkpoint metadata
        (utils/checkpoint.py): enough for restore to warn when the run that
        wrote the checkpoint was already degraded."""
        last = self.ring[-1] if self.ring else {}
        return {
            "status": self.status,
            "step": last.get("step"),
            "loss_ema": self.ema,
            "grad_norm": last.get("health/grad_norm"),
            "first_bad_step": self.first_bad_step,
            "reason": self.first_bad_reason,
        }

    def _next_path(self, path):
        """First dump of this recorder takes `path` verbatim (a fresh run may
        legitimately overwrite a stale bundle from a previous run); later
        dumps — repeated anomalies in ONE run — must not clobber the first
        bundle, so they take the next free `<stem>_<n><ext>` suffix."""
        if self.bundle_path is None:
            return path
        stem, ext = os.path.splitext(path)
        n = 2
        while os.path.exists(f"{stem}_{n}{ext}"):
            n += 1
        return f"{stem}_{n}{ext}"

    def dump(self, path, reason=None, manifest_path=None, trace_tail=None,
             extra=None):
        """Write the diagnostics bundle (atomic replace); returns the path
        written (suffixed `_<n>` after the first dump — see `_next_path`), or
        None when writing failed — the recorder must never take down the fit
        it is documenting."""
        path = self._next_path(path)
        bundle = {
            "schema": self.BUNDLE_SCHEMA,
            "reason": reason or self.first_bad_reason or "manual dump",
            "status": self.status,
            "first_bad_step": self.first_bad_step,
            "last_good_step": self.last_good_step,
            "loss_ema": self.ema,
            "n_steps_recorded": self.n_recorded,
            "ring": list(self.ring),
            "batch_signature": self.batch_signature,
            "faults": list(self.faults),
        }
        if manifest_path and os.path.exists(manifest_path):
            try:
                with open(manifest_path, encoding="utf-8") as f:
                    bundle["manifest"] = json.load(f)
            except (OSError, ValueError):
                bundle["manifest"] = None
        if trace_tail:
            bundle["trace_tail"] = trace_tail
        if extra:
            bundle.update(extra)
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(bundle, f, indent=1, default=str)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            return None
        self.bundle_path = path
        return path
