"""jax.monitoring listener: XLA compile (and transfer) accounting.

Generalizes `analysis/runtime.CompileWatcher` (which counts exactly one event
kind for budget assertions) into named counters over *every* duration event
jax reports, optionally mirrored into a Tracer as Chrome-trace events on a
dedicated "xla-events" track.

What this jax version (0.4.37) actually emits as duration events: the
compile-path trio — jaxpr tracing, MLIR lowering, XLA backend compile
(jax/_src/dispatch.py: /jax/core/compile/*_duration) — plus compilation-cache
timings. It emits NO H2D/D2H transfer duration events; transfer accounting
therefore comes from the pipelined feed's *fenced* `feed/h2d` spans
(train/pipeline.py), which report their measured durations and byte counts
here via `record_transfer`, landing in the same counter namespace
(`transfer/h2d`). If a future jax adds transfer monitoring events, the
catch-all listener picks them up with no code change.

Counter shape: {name: {"count": n, "total_s": secs[, "bytes": n]}}. The
compile trio also keeps short names (xla/backend_compile, xla/jaxpr_trace,
xla/lower_to_mlir) for trace events and the report CLI's compile column.
"""

import threading

from ..analysis.runtime import BACKEND_COMPILE_EVENT

# jax/_src/dispatch.py event names -> short trace/report names
EVENT_SHORT_NAMES = {
    BACKEND_COMPILE_EVENT: "xla/backend_compile",
    "/jax/core/compile/jaxpr_trace_duration": "xla/jaxpr_trace",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "xla/lower_to_mlir",
}


class XlaEventListener:
    """Accumulate every jax.monitoring duration event into named counters.

    Same registration mechanics as CompileWatcher: registration is
    append-only in older jax, so the callback no-ops once stopped and the
    private unregister hook is used where it exists. Listeners fire inside
    jax's dispatch path — the callback must never raise."""

    def __init__(self, tracer=None):
        self._lock = threading.Lock()
        self._active = False
        self._registered = False
        self._tracer = tracer
        self._counters = {}

    # -- accounting

    def _note(self, name, duration_s, nbytes=None):
        with self._lock:
            c = self._counters.setdefault(name, {"count": 0, "total_s": 0.0})
            c["count"] += 1
            c["total_s"] += float(duration_s)
            if nbytes is not None:
                c["bytes"] = c.get("bytes", 0) + int(nbytes)

    def _listener(self, event, duration_secs, **kwargs):
        if not self._active:
            return
        try:
            short = EVENT_SHORT_NAMES.get(event)
            self._note(short or event, duration_secs)
            if self._tracer is not None and short is not None:
                self._tracer.record_xla_event(short, duration_secs)
        except Exception:
            pass  # never propagate into jax's dispatch path

    def record_transfer(self, direction, duration_s, nbytes):
        """Fence-measured transfer accounting (see module docstring):
        `direction` is 'h2d' or 'd2h'; lands under counter transfer/<dir>."""
        self._note(f"transfer/{direction}", duration_s, nbytes=nbytes)

    # -- introspection

    @property
    def compile_count(self):
        with self._lock:
            return self._counters.get("xla/backend_compile",
                                      {}).get("count", 0)

    def summary(self):
        """Counters as plain data, total_s rounded for JSON artifacts."""
        with self._lock:
            return {name: {**c, "total_s": round(c["total_s"], 6)}
                    for name, c in sorted(self._counters.items())}

    # -- lifecycle

    def start(self):
        import jax.monitoring

        with self._lock:
            self._counters = {}
            self._active = True
        if not self._registered:
            jax.monitoring.register_event_duration_secs_listener(
                self._listener)
            self._registered = True
        return self

    def stop(self):
        with self._lock:
            self._active = False
        if self._registered:
            try:
                from jax._src import monitoring as _m

                _m._unregister_event_duration_listener_by_callback(
                    self._listener)
                self._registered = False
            except Exception:
                pass  # stays registered but inactive; harmless
        return self
