"""In-graph model-health metrics: the numeric sentinel.

Everything here runs INSIDE the jitted step — pure jnp, no host sync, no
Python branching on traced values (jaxcheck R1-clean by construction). The
step factories (train/step.py, train/resident.py, parallel/dp.py,
parallel/ep.py) merge `sentinel_metrics` into the metrics dict they already
return, so the health flags ride the existing once-per-epoch metric fetch:
zero extra device round trips per step (tests/test_health.py asserts the
fetch count and the single compile).

Three layers, merged into the same metrics namespace:

  * `sentinel_metrics`  — step-level: isfinite over loss/grads/updates,
    global grad/param norms, update-to-param ratio. Catches NaN/Inf the step
    it happens and exploding updates before they NaN.
  * `embedding_health`  — batch-embedding stats: hidden norm mean/max and a
    collapse score (mean pairwise cosine of the batch's unit embeddings).
    A collapsed encoder (every article mapping to the same direction) keeps
    a healthy-looking loss while AUROC dies; the collapse score goes to 1.
  * `mining_health`     — the paper's `data_weight` distribution
    (mean/max/fraction-zero) and the margin-violation rate. `data_weight`
    re-weighting of the reconstruction loss is the paper's core novelty
    (reference triplet_loss_utils.py:129, :251-277) and `data_weight -> 0`
    means mining has gone dead: the model trains a plain autoencoder.

The collapse score uses the closed form for masked mean pairwise cosine:
with unit rows u_i (n valid rows), sum_{i!=j} cos(i,j) = ||sum u||^2 - n,
so the mean is (||s||^2 - n) / (n(n-1)) — O(B*D), no B^2 matrix.
"""

import jax
import jax.numpy as jnp

_EPS = 1e-12


def _nonfinite_count(tree):
    """Number of NaN/Inf scalars across the floating leaves of `tree`, as an
    int32 (0 = all finite — an exact integer comparison; a float fraction
    would be off by an XLA reciprocal-ulp under jit and misfire). Integer
    leaves (optax counts, labels) are skipped — they cannot be non-finite and
    isfinite is undefined for them."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    if not leaves:
        return jnp.int32(0)
    return sum(jnp.sum((~jnp.isfinite(l)).astype(jnp.int32)) for l in leaves)


def _global_norm(tree):
    """sqrt(sum of squared floating leaves) — optax.global_norm without the
    dependency surface."""
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)]
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                        for l in leaves))


def sentinel_metrics(cost, grads, updates, params):
    """Step-level health flags, computed in-graph after the optimizer update.

    `params` must be the PRE-update params so `health/update_ratio` is the
    classic ||update|| / ||param|| step-size diagnostic (≈ learning-rate ×
    relative gradient scale; a sudden jump precedes divergence).

    `health/nonfinite` is 1.0 when ANY of cost / grads / updates contains a
    NaN or Inf — one flag the flight recorder can trip on without scanning
    every metric."""
    grad_norm = _global_norm(grads)
    param_norm = _global_norm(params)
    update_norm = _global_norm(updates)
    all_finite = (jnp.isfinite(cost)
                  & (_nonfinite_count(grads) == 0)
                  & (_nonfinite_count(updates) == 0))
    return {
        "health/grad_norm": grad_norm,
        "health/param_norm": param_norm,
        "health/update_ratio": update_norm / jnp.maximum(param_norm, _EPS),
        "health/nonfinite": 1.0 - all_finite.astype(jnp.float32),
    }


def embedding_health(h, row_valid=None, prefix="health/embedding"):
    """Norm stats + collapse score for a batch of embeddings `h` [B, D].

    collapse = masked mean pairwise cosine over the valid rows: 0 for a
    well-spread isotropic batch, -> 1.0 when every row points the same way
    (the dead-encoder failure the serving-scale system in PAPERS.md monitors
    continuously). Closed form (||sum u||^2 - n) / (n(n-1)), O(B*D)."""
    dtype = jnp.float32
    v = (jnp.ones(h.shape[0], dtype) if row_valid is None
         else row_valid.astype(dtype))
    n = jnp.maximum(jnp.sum(v), 1.0)
    norms = jnp.sqrt(jnp.sum(jnp.square(h.astype(dtype)), axis=1))
    norm_mean = jnp.sum(norms * v) / n
    norm_max = jnp.max(norms * v)
    u = h.astype(dtype) / jnp.maximum(norms, _EPS)[:, None] * v[:, None]
    s = jnp.sum(u, axis=0)
    pair_sum = jnp.sum(jnp.square(s)) - n  # sum_{i!=j} cos(u_i, u_j)
    collapse = pair_sum / jnp.maximum(n * (n - 1.0), 1.0)
    return {
        f"{prefix}_norm_mean": norm_mean,
        f"{prefix}_norm_max": norm_max,
        f"{prefix}_collapse": collapse,
    }


def drift_health(h, ref_centroid, ref_collapse, row_valid=None,
                 prefix="health/drift"):
    """Embedding drift of a batch `h` [B, D] against a reference corpus
    version, in-graph (pure jnp — the churn supervisor jits this alongside
    the encode so a refresh cycle pays zero extra host syncs until the one
    swap-time fetch).

    Two signals, matching the two ways a refresh can go stale:

      * `centroid_shift` — cosine distance (1 - cos) between the batch's mean
        unit embedding and the reference version's centroid. Catches topic
        drift / distribution shift: new articles living in a different part
        of the embedding space than what the encoder was fine-tuned on.
      * `collapse_delta` — |collapse(batch) - collapse(reference)|, reusing
        `embedding_health`'s closed-form pairwise-cosine score. Catches the
        encoder degrading ON the new data (collapsing or dispersing) even
        when the centroid barely moves.

    `ref_centroid` is the (possibly unnormalized) mean unit-embedding vector
    recorded when the reference version passed its health gate;
    `ref_collapse` the collapse score from the same gate sample."""
    dtype = jnp.float32
    v = (jnp.ones(h.shape[0], dtype) if row_valid is None
         else row_valid.astype(dtype))
    n = jnp.maximum(jnp.sum(v), 1.0)
    norms = jnp.sqrt(jnp.sum(jnp.square(h.astype(dtype)), axis=1))
    u = h.astype(dtype) / jnp.maximum(norms, _EPS)[:, None] * v[:, None]
    c = jnp.sum(u, axis=0) / n
    ref = jnp.asarray(ref_centroid, dtype)
    cos = jnp.sum(c * ref) / jnp.maximum(
        jnp.linalg.norm(c) * jnp.linalg.norm(ref), _EPS)
    pair_sum = jnp.sum(jnp.square(jnp.sum(u, axis=0))) - n
    collapse = pair_sum / jnp.maximum(n * (n - 1.0), 1.0)
    return {
        f"{prefix}_centroid_shift": 1.0 - cos,
        f"{prefix}_collapse_delta":
            jnp.abs(collapse - jnp.asarray(ref_collapse, dtype)),
        f"{prefix}_collapse": collapse,
        f"{prefix}_centroid": c,
    }


def mining_health(data_weight, fraction, row_valid=None):
    """Distribution stats of the paper's triplet-participation `data_weight`
    [B] plus the margin-violation rate.

    `fraction` is the mining fn's fraction-of-violating-triplets (batch_all)
    or fraction-of-violating-anchors (batch_hard) — recorded under one name
    so dashboards don't fork per strategy. `data_weight_zero_fraction -> 1`
    is the dead-mining signal: every row's reconstruction loss gets weight 0
    and the triplet term stops shaping the embedding space."""
    dtype = jnp.float32
    w = data_weight.astype(dtype)
    v = (jnp.ones(w.shape[0], dtype) if row_valid is None
         else row_valid.astype(dtype))
    n = jnp.maximum(jnp.sum(v), 1.0)
    return {
        "health/data_weight_mean": jnp.sum(w * v) / n,
        "health/data_weight_max": jnp.max(w * v),
        "health/data_weight_zero_fraction":
            jnp.sum((w <= 0.0).astype(dtype) * v) / n,
        "health/margin_violation_rate": jnp.asarray(fraction, dtype),
    }
