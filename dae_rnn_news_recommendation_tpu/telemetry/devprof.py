"""Device-time profiling: fenced best-of-N timing, cost/roofline join, HBM.

Where device time goes, measured honestly. Three instruments, all built on
the repo's existing fencing and compile-accounting discipline:

  * ``measure(fn, args, n=, warmup=)`` — the fenced best-of-N device timer.
    Every timed iteration ends with a real host round trip
    (``telemetry.device_fence``: the bench.py ``_hard_sync`` lesson — under
    async dispatch even ``block_until_ready`` has been observed lying on the
    tunneled TPU, so only a fetch fences), and every iteration runs under its
    own ``CompileWatcher`` so an XLA compile inside a timed iteration marks
    that sample as polluted and excludes it from best/median. Warmup absorbs
    the expected compiles; the counts travel with the result as provenance.

  * ``cost_analysis(fn, args)`` + ``roofline(...)`` — the static FLOPs /
    bytes-accessed numbers XLA already knows
    (``fn.lower(...).compile().cost_analysis()``), joined against the
    per-``device_kind`` peak table into MFU and roofline fractions. The peak
    table lives HERE (bench.py delegates) so the two can never disagree.
    CPU caveat: there is no peak entry for host CPUs, so roofline fields are
    None off-TPU — the ms/FLOPs/bytes columns still record.

  * ``sample_memory(registry)`` / ``phase(name, registry)`` — per-device HBM
    gauges (``device.memory_stats()``) into the existing metrics registry,
    plus a per-phase high-water mark sampled at phase exit. Degrades to a
    no-op where the backend exposes no memory stats (CPU).

Results persist to a ``ProfileDB`` (profile_db.py): atomic JSON keyed by
``(op, shape, dtype, device_kind)`` — the cache the ROADMAP item-4 kernel
autotuner reads, and what ``telemetry report --profile`` renders.

Overhead contract: nothing here touches a hot path unless explicitly called.
``instrument(fn, op)`` exists for always-on wiring and costs one ``if`` per
call while profiling is disabled — no clock reads, no fences, no host syncs,
no extra compiles (the wrapper is transparent to jit caching). The
``profile_overhead_lt_1pct`` evidence gate and the fetch-count regression
test pin that contract.
"""

import contextlib
import dataclasses
import statistics
import threading
import time

from ..analysis.runtime import CompileWatcher
from .profile_db import ProfileDB  # noqa: F401  (re-exported convenience)
from .tracer import device_fence

# per-chip peak (bf16 TFLOP/s, HBM GB/s) by device_kind substring, most
# specific first (public spec-sheet numbers; device_kind strings look like
# "TPU v5 lite"). Single source of truth — bench.py delegates here.
PEAK = (
    ("v5p", (459.0, 2765.0)),
    ("v5 lite", (197.0, 819.0)),
    ("v5e", (197.0, 819.0)),
    ("v6", (918.0, 1640.0)),
    ("v4", (275.0, 1228.0)),
    ("v3", (123.0, 900.0)),
    ("v2", (45.0, 700.0)),
)


def peak_for(device_kind):
    """(peak bf16 TFLOP/s, peak HBM GB/s) for a device_kind string, or None
    when the kind is unknown (host CPUs: no roofline denominator exists)."""
    dk = (device_kind or "").lower()
    for sub, spec in PEAK:
        if sub in dk:
            return spec
    return None


# ------------------------------------------------------------------ results

@dataclasses.dataclass
class MeasureResult:
    """One fenced measurement with its provenance and cost join."""

    op: str
    shape: str
    dtype: str
    device_kind: str
    best_ms: float
    median_ms: float
    n: int                    # timed iterations requested
    n_clean: int              # iterations that saw zero compiles (the stats)
    warmup: int
    compiles_warmup: int
    compiles_timed: int
    times_ms: tuple = ()
    flops: float = None
    bytes_accessed: float = None
    mfu: float = None         # achieved / peak compute (None off-TPU)
    bw_fraction: float = None  # achieved / peak HBM bandwidth
    roofline_fraction: float = None  # fraction of the BINDING roof
    bound: str = None         # "compute" | "memory" | None

    def as_row(self):
        """The ProfileDB row form: key fields inline + rounded figures."""
        row = dataclasses.asdict(self)
        row["times_ms"] = [round(t, 6) for t in self.times_ms]
        for k in ("best_ms", "median_ms"):
            row[k] = round(row[k], 6)
        for k in ("mfu", "bw_fraction", "roofline_fraction"):
            if row[k] is not None:
                row[k] = round(row[k], 6)
        return row


# ------------------------------------------------------------- cost account

def cost_analysis(fn, args=()):
    """XLA's static cost model for one jitted call: {"flops", "bytes_accessed"}
    (whichever keys the backend reports; {} when unavailable). Works on
    jax.jit-wrapped callables; a bare callable is jitted for the analysis
    (the analysis compile is NOT the caller's executable — run this outside
    timed regions). Never raises: cost accounting is advisory."""
    try:
        import jax

        lowerable = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = lowerable.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):  # some jax versions: one per device
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return {}
        out = {}
        if isinstance(ca.get("flops"), (int, float)):
            out["flops"] = float(ca["flops"])
        ba = ca.get("bytes accessed", ca.get("bytes_accessed"))
        if isinstance(ba, (int, float)):
            out["bytes_accessed"] = float(ba)
        return out
    except Exception:
        return {}


def roofline(flops, bytes_accessed, seconds, device_kind):
    """Join a measured time against the peak table: MFU, bandwidth fraction,
    and the fraction of the BINDING roof (max of the two — how close the
    kernel runs to the resource that limits it). All None when the
    device_kind has no peak entry (the CPU caveat) or the time is unusable."""
    spec = peak_for(device_kind)
    if spec is None or not seconds or seconds <= 0:
        return {}
    peak_tflops, peak_gbs = spec
    out = {}
    fracs = []
    if isinstance(flops, (int, float)) and flops > 0:
        out["mfu"] = (flops / seconds) / (peak_tflops * 1e12)
        fracs.append(("compute", out["mfu"]))
    if isinstance(bytes_accessed, (int, float)) and bytes_accessed > 0:
        out["bw_fraction"] = (bytes_accessed / seconds) / (peak_gbs * 1e9)
        fracs.append(("memory", out["bw_fraction"]))
    if fracs:
        bound, frac = max(fracs, key=lambda bf: bf[1])
        out["roofline_fraction"] = frac
        out["bound"] = bound
    return out


def _args_signature(args):
    """(shape, dtype) of the largest array leaf in args — the honest default
    key coordinates when the caller doesn't name them explicitly."""
    try:
        import jax

        leaves = [leaf for leaf in jax.tree_util.tree_leaves(args)
                  if hasattr(leaf, "shape") and hasattr(leaf, "dtype")]
        if not leaves:
            return "scalar", "none"
        big = max(leaves, key=lambda a: int(getattr(a, "size", 0) or 0))
        shape = "x".join(str(int(d)) for d in big.shape) or "0d"
        return shape, str(big.dtype)
    except Exception:
        return "unknown", "unknown"


def _device_kind():
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


# ------------------------------------------------------------------ measure

def measure(fn, args=(), *, n=5, warmup=1, op=None, shape=None, dtype=None,
            device_kind=None, db=None, cost=True):
    """Fenced best-of-N device timing of ``fn(*args)``.

    Each warmup call and each timed iteration ends with a real host fetch
    (``device_fence`` on the call's result), and each runs under its own
    ``CompileWatcher``: warmup absorbs the expected XLA compiles, and any
    compile landing inside a timed iteration excludes that sample from the
    best/median statistics (the counts stay in the result as provenance —
    ``n_clean`` says how many samples the stats actually rest on). When every
    timed iteration compiled, the stats fall back to all samples rather than
    returning nothing: a caller measuring an uncacheable path still gets a
    number, flagged by ``n_clean == 0``.

    ``db`` (a ProfileDB) records-and-saves the result. ``cost=True`` joins
    XLA's static FLOPs/bytes and the peak-table roofline fractions (None off
    TPU — the CPU caveat)."""
    assert n >= 1, "measure() needs at least one timed iteration"
    op = op or getattr(fn, "__name__", "fn")
    sig_shape, sig_dtype = _args_signature(args)
    shape = shape if shape is not None else sig_shape
    dtype = dtype if dtype is not None else sig_dtype
    device_kind = device_kind or _device_kind()

    wwatch = CompileWatcher().start()
    try:
        for _ in range(warmup):
            device_fence(fn(*args))
    finally:
        compiles_warmup = wwatch.stop()

    times, dirty = [], 0
    for _ in range(n):
        iwatch = CompileWatcher().start()
        t0 = time.perf_counter()
        out = fn(*args)
        device_fence(out)
        dt_ms = (time.perf_counter() - t0) * 1e3
        compiled = iwatch.stop() > 0
        times.append((dt_ms, compiled))
        dirty += int(compiled)

    clean = [t for t, compiled in times if not compiled]
    stats_over = clean or [t for t, _ in times]
    best_ms = min(stats_over)
    median_ms = float(statistics.median(stats_over))

    result = MeasureResult(
        op=op, shape=shape, dtype=dtype, device_kind=device_kind,
        best_ms=best_ms, median_ms=median_ms, n=n, n_clean=len(clean),
        warmup=warmup, compiles_warmup=compiles_warmup, compiles_timed=dirty,
        times_ms=tuple(t for t, _ in times))
    if cost:
        ca = cost_analysis(fn, args)
        result.flops = ca.get("flops")
        result.bytes_accessed = ca.get("bytes_accessed")
        roof = roofline(result.flops, result.bytes_accessed,
                        best_ms / 1e3, device_kind)
        result.mfu = roof.get("mfu")
        result.bw_fraction = roof.get("bw_fraction")
        result.roofline_fraction = roof.get("roofline_fraction")
        result.bound = roof.get("bound")
    if db is not None:
        db.record(result)
        db.save()
    return result


# -------------------------------------------------------------- HBM gauges

# memory_stats keys worth exporting, canonical name -> gauge suffix
_MEMORY_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def memory_snapshot(devices=None):
    """Per-device ``memory_stats()`` as {label: {key: bytes}}. Empty where
    the backend exposes nothing (CPU) — callers degrade by absence."""
    out = {}
    try:
        import jax

        devices = jax.local_devices() if devices is None else devices
    except Exception:
        return out
    for d in devices:
        try:
            ms = d.memory_stats()
        except (RuntimeError, NotImplementedError, AttributeError):
            ms = None  # backend exposes no allocator stats
        if not ms:
            continue
        stats = {k: int(ms[k]) for k in _MEMORY_KEYS
                 if isinstance(ms.get(k), (int, float))}
        if stats:
            out[f"{d.platform}:{d.id}"] = stats
    return out


def sample_memory(registry=None, devices=None):
    """Sample HBM gauges into a MetricsRegistry (per-device plus the
    fleet-aggregatable worst-device rollups ``hbm_bytes_in_use`` /
    ``hbm_peak_bytes_in_use``). Returns the raw snapshot; {} on CPU (no
    gauges are created, so the memory-growth SLO stays silent by absence)."""
    snap = memory_snapshot(devices)
    if registry is not None and snap:
        for label, stats in snap.items():
            for key, val in stats.items():
                registry.gauge(f"hbm_{key}/{label}").set(float(val))
        registry.gauge("hbm_bytes_in_use").set(float(
            max(s.get("bytes_in_use", 0) for s in snap.values())))
        registry.gauge("hbm_peak_bytes_in_use").set(float(
            max(s.get("peak_bytes_in_use", 0) for s in snap.values())))
    return snap


@contextlib.contextmanager
def phase(name, registry=None):
    """Per-phase HBM high-water mark: on exit, the max ``peak_bytes_in_use``
    across devices lands in gauge ``hbm_phase_peak_bytes/<name>`` (plus a
    fresh ``sample_memory`` rollup). A no-op where memory_stats is absent."""
    try:
        yield
    finally:
        snap = sample_memory(registry)
        if registry is not None and snap:
            registry.gauge(f"hbm_phase_peak_bytes/{name}").set(float(
                max(s.get("peak_bytes_in_use", 0) for s in snap.values())))


# ----------------------------------------------- always-on instrumentation

_enabled = False  # read on every instrumented call: keep it a plain bool
_lock = threading.Lock()
_accum = {}       # op -> {"count", "times_ms" (bounded ring)}
_RING = 64


def enabled():
    return _enabled


def enable():
    """Arm the instrumented-call accumulator. Profiling is a diagnosis mode:
    enabled calls fence (that is what makes the numbers honest), so enable it
    to ask where device time goes, not while benchmarking peak throughput."""
    global _enabled
    with _lock:
        _accum.clear()
        _enabled = True


def disable():
    """Disarm and return {op: MeasureResult-shaped row} for everything the
    instrumented calls accumulated while enabled."""
    global _enabled
    with _lock:
        _enabled = False
        rows = {op: dict(rec) for op, rec in _accum.items()}
        _accum.clear()
    return rows


def collect(device_kind=None, db=None):
    """The accumulator as ProfileDB-recordable rows (without disarming).
    ``db`` records-and-saves them."""
    device_kind = device_kind or _device_kind()
    with _lock:
        items = [(op, dict(rec)) for op, rec in _accum.items()]
    rows = []
    for op, rec in items:
        times = rec["times_ms"]
        rows.append({
            "op": op, "shape": rec["shape"], "dtype": rec["dtype"],
            "device_kind": device_kind, "n": rec["count"],
            "n_clean": len(times), "warmup": 0,
            "compiles_warmup": 0, "compiles_timed": 0,
            "best_ms": round(min(times), 6),
            "median_ms": round(float(statistics.median(times)), 6),
            "times_ms": [round(t, 6) for t in times],
        })
    if db is not None:
        for row in rows:
            db.record(row)
        if rows:
            db.save()
    return rows


def instrument(fn, op):
    """Wrap ``fn`` so each call is fenced-and-timed into the accumulator
    while profiling is enabled. Disabled cost: ONE ``if`` per call — no clock
    reads, no fences, no host syncs, and the wrapper adds no jit signatures
    (the fetch-count + compile_guard regression test pins this)."""

    def wrapper(*args, **kwargs):
        if not _enabled:
            return fn(*args, **kwargs)
        shape, dtype = _args_signature(args)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        device_fence(out)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with _lock:
            rec = _accum.setdefault(
                op, {"count": 0, "times_ms": [], "shape": shape,
                     "dtype": dtype})
            rec["count"] += 1
            rec["times_ms"].append(dt_ms)
            del rec["times_ms"][:-_RING]
        return out

    wrapper.__name__ = getattr(fn, "__name__", "instrumented")
    wrapper.__wrapped__ = fn
    return wrapper
