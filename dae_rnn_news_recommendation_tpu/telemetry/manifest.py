"""Run manifests: make every metrics.jsonl / trace / bench artifact
self-describing.

A manifest is one JSON object written at fit start (models/estimator.py) and
embedded in bench records (bench.py): enough provenance — config, device
topology, library versions, git sha, feed mode, bucket set — that a number
found in an artifact six months later can be tied to the code and hardware
that produced it. Schema (versioned via the "schema" key; see
docs/observability.md):

    schema            int, currently 1
    created_utc       ISO-8601 UTC timestamp
    git_rev           HEAD sha of the repo checkout (or "unknown")
    jax_version / numpy_version / python_version
    backend           jax.default_backend() ("cpu" | "tpu" | ...)
    process_index / process_count
    devices           [{id, platform, kind}] for jax.devices()
    feed_mode         "stream" | "pipelined" | "resident" | None
    buckets           shape-bucket tuple the pipelined feed pads to, or None
    config            the DAEConfig as a dict, or None
    ...               anything passed via extra= (model class, batch size...)
"""

import dataclasses
import json
import os
import platform as _platform
import subprocess


def _git_rev():
    """HEAD sha of the checkout containing this package (same recipe as
    bench.py's sidecar provenance); 'unknown' outside a git checkout."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        out = subprocess.run(["git", "-C", here, "rev-parse", "HEAD"],
                             capture_output=True, text=True, timeout=15)
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def build_manifest(config=None, feed_mode=None, buckets=None, extra=None):
    """Assemble the manifest dict. Device/topology fields degrade to None
    rather than raising if the backend is unreachable — a manifest must never
    be the thing that kills a run."""
    import jax
    import numpy as np

    manifest = {
        "schema": 1,
        "git_rev": _git_rev(),
        "jax_version": jax.__version__,
        "numpy_version": np.__version__,
        "python_version": _platform.python_version(),
        "feed_mode": feed_mode,
        "buckets": list(buckets) if buckets else None,
    }
    import datetime

    manifest["created_utc"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")
    try:
        manifest["backend"] = jax.default_backend()
        manifest["process_index"] = jax.process_index()
        manifest["process_count"] = jax.process_count()
        manifest["devices"] = [
            {"id": d.id, "platform": d.platform, "kind": d.device_kind}
            for d in jax.devices()]
    except Exception:
        manifest.setdefault("backend", None)
        manifest.setdefault("devices", None)
    if config is not None:
        manifest["config"] = (dataclasses.asdict(config)
                              if dataclasses.is_dataclass(config)
                              else dict(config))
    try:
        # which tile config every kernel dispatched with this process and
        # where it came from (tuned capture vs hand-picked default) —
        # degrade-never-raise like the device fields above
        from .. import tuning

        manifest["tuning"] = tuning.resolution_manifest()
    except Exception:
        manifest.setdefault("tuning", None)
    if extra:
        manifest.update(extra)
    return manifest


def write_manifest(path, manifest):
    """Write `manifest` as JSON (atomic replace). Returns `path`."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, default=str)
        f.write("\n")
    os.replace(tmp, path)
    return path


def read_manifest(path):
    with open(path, encoding="utf-8") as f:
        return json.load(f)
