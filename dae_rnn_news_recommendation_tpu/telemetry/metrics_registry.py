"""Fleet metrics registry: cheap thread-safe counters, gauges, and
fixed-bucket latency histograms.

The tracer (telemetry/tracer.py) answers "where did the time go" for one
diagnosed run; this module answers "what is the fleet doing right now" for
every run. The design constraints are the serving hot path's, not a metrics
product's:

  * NO PER-OBSERVATION ALLOCATION. A histogram is a fixed list of bucket
    counts chosen at creation (`bisect` into a precomputed bound tuple);
    `Counter.inc` / `Gauge.set` touch one int/float under a lock. Nothing
    appends, nothing resizes, nothing formats — a registry attached to the
    microbatcher costs nanoseconds per request, so it stays on in
    production, unlike tracing (a diagnosis mode).
  * THREAD-SAFE BY LOCK, NOT BY HOPE. `x += 1` on a Python attribute is a
    read-modify-write — two batcher threads CAN lose increments. Every
    metric carries its own small lock; `snapshot()` takes each once, so a
    snapshot is per-metric consistent (counters never tear) without a
    global stop-the-world.
  * PER-REPLICA REGISTRIES + ONE FLEET AGGREGATE. Each replica/router owns
    a named `MetricsRegistry`; `aggregate()` folds their snapshots into the
    fleet view (counters sum, gauges keep min/max/mean, histogram buckets
    add) — the shape `telemetry report --fleet` renders and the SLO monitor
    (telemetry/slo.py) evaluates.

Metric mutation belongs on the HOST side of the serving stack — admission,
callbacks, the batcher loop. Inside a jitted function an `.inc()` runs once
at trace time and never again (or re-runs spuriously on retrace); jaxcheck
R14 flags metric mutation reachable from traced code.
"""

import threading
from bisect import bisect_right

# default latency bucket upper bounds, in milliseconds: sub-ms serving
# replies up through the multi-second straggler tail. The last bucket is
# open-ended (+inf) by construction.
DEFAULT_LATENCY_BOUNDS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0)


class Counter:
    """Monotonic event count. `inc(n)` only — a counter never goes down
    (rates are computed from deltas by the SLO monitor's windows)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, corpus version,
    coverage). `None` until first set — a snapshot distinguishes "never
    observed" from 0."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = None

    def set(self, value):
        with self._lock:
            self._value = float(value)

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket distribution. Buckets are chosen ONCE at creation
    (upper bounds, ascending; a final +inf bucket is implicit), so
    `observe()` is a bisect + one increment — no allocation, no resize.
    Tracks count/sum/min/max exactly; percentiles are bucket estimates
    (linear interpolation within the landing bucket)."""

    __slots__ = ("name", "bounds", "_lock", "_counts", "_count", "_sum",
                 "_min", "_max")

    def __init__(self, name, bounds=DEFAULT_LATENCY_BOUNDS_MS):
        bounds = tuple(float(b) for b in bounds)
        assert bounds == tuple(sorted(bounds)) and bounds, (
            f"histogram bounds must be ascending and non-empty: {bounds}")
        self.name = name
        self.bounds = bounds
        self._lock = threading.Lock()
        self._counts = [0] * (len(bounds) + 1)  # last = overflow (+inf)
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    def observe(self, value):
        value = float(value)
        idx = bisect_right(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value

    @property
    def count(self):
        with self._lock:
            return self._count

    def state(self):
        """One consistent read of the whole distribution."""
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self._counts), "count": self._count,
                    "sum": round(self._sum, 6), "min": self._min,
                    "max": self._max}

    def percentile(self, q):
        """Bucket-estimated q-th percentile (None when empty)."""
        return histogram_percentile(self.state(), q)


def histogram_percentile(state, q):
    """q-th percentile estimate from a histogram snapshot/state dict:
    nearest-rank into the cumulative bucket counts, linearly interpolated
    within the landing bucket. The overflow bucket reports the observed max
    (the honest answer for an open-ended bucket). None when empty."""
    counts = state.get("counts") or []
    total = state.get("count") or 0
    if not total:
        return None
    bounds = state["bounds"]
    rank = max(1, int(round(q / 100.0 * total)))
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if cum + c >= rank:
            if i >= len(bounds):       # overflow bucket: no upper bound
                return state["max"]
            lo = bounds[i - 1] if i > 0 else min(
                state["min"] if state["min"] is not None else 0.0, bounds[i])
            frac = (rank - cum) / c
            return round(lo + (bounds[i] - lo) * frac, 6)
        cum += c
    return state["max"]


class MetricsRegistry:
    """One component's named metrics (a replica, the router, the fleet
    supervisor). `counter/gauge/histogram` are create-or-get, so call sites
    never coordinate registration; `snapshot()` is the serializable view
    every consumer (SLO monitor, report --fleet, chaos audits) reads."""

    def __init__(self, name="default"):
        self.name = str(name)
        self._lock = threading.Lock()   # metric-map mutations only
        self._counters = {}
        self._gauges = {}
        self._histograms = {}

    def _get(self, table, name, factory):
        m = table.get(name)             # lock-free fast path (dict reads
        if m is not None:               # are atomic under the GIL)
            return m
        with self._lock:
            return table.setdefault(name, factory())

    def counter(self, name):
        return self._get(self._counters, name, lambda: Counter(name))

    def gauge(self, name):
        return self._get(self._gauges, name, lambda: Gauge(name))

    def histogram(self, name, bounds=DEFAULT_LATENCY_BOUNDS_MS):
        return self._get(self._histograms, name,
                         lambda: Histogram(name, bounds=bounds))

    def snapshot(self):
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {"registry": self.name,
                "counters": {n: c.value for n, c in sorted(counters.items())},
                "gauges": {n: g.value for n, g in sorted(gauges.items())},
                "histograms": {n: h.state()
                               for n, h in sorted(histograms.items())}}


def aggregate(snapshots, name="fleet"):
    """Fold per-component snapshots into one fleet-level snapshot: counters
    sum, gauges keep {min, max, mean} across components that observed them,
    histograms with IDENTICAL bounds merge bucket-wise (mismatched bounds
    keep the first and note the skip — never a crash mid-report)."""
    counters, gauge_vals, hists, notes = {}, {}, {}, []
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        for n, v in (snap.get("counters") or {}).items():
            counters[n] = counters.get(n, 0) + int(v)
        for n, v in (snap.get("gauges") or {}).items():
            if v is not None:
                gauge_vals.setdefault(n, []).append(float(v))
        for n, st in (snap.get("histograms") or {}).items():
            if n not in hists:
                hists[n] = {"bounds": list(st["bounds"]),
                            "counts": list(st["counts"]),
                            "count": st["count"], "sum": st["sum"],
                            "min": st["min"], "max": st["max"]}
                continue
            agg = hists[n]
            if agg["bounds"] != list(st["bounds"]):
                notes.append(f"histogram {n}: mismatched bounds across "
                             "registries — kept the first, skipped "
                             f"{snap.get('registry')}")
                continue
            agg["counts"] = [a + b for a, b in zip(agg["counts"],
                                                   st["counts"])]
            agg["count"] += st["count"]
            agg["sum"] = round(agg["sum"] + st["sum"], 6)
            for key, pick in (("min", min), ("max", max)):
                vals = [v for v in (agg[key], st[key]) if v is not None]
                agg[key] = pick(vals) if vals else None
    gauges = {n: {"min": min(vs), "max": max(vs),
                  "mean": round(sum(vs) / len(vs), 6)}
              for n, vs in gauge_vals.items()}
    out = {"registry": name, "n_sources": len(snapshots),
           "counters": dict(sorted(counters.items())),
           "gauges": dict(sorted(gauges.items())),
           "histograms": dict(sorted(hists.items()))}
    if notes:
        out["notes"] = notes
    return out
