"""Declarative SLOs with multi-window burn-rate alerting.

An SLO here is a statement about the REGISTRY (telemetry/metrics_registry),
not about one request: "deadline-miss rate <= 2%", "coverage >= 0.99",
"p95 <= 250 ms". The monitor samples registry snapshots over time and
evaluates each spec over TWO rolling windows — the Google-SRE multi-window
burn-rate discipline:

  * the LONG window proves the burn is sustained (one slow request cannot
    page anyone);
  * the SHORT window proves it is STILL happening (an alert stops firing
    soon after the bleeding stops, instead of dragging the long window's
    memory around).

An alert fires only when BOTH windows burn past their thresholds
(`fast_burn` for short, `slow_burn` for long), where burn = observed error
rate / objective. Zero-objective specs ("this event class must never
happen": an injected hedge fault, an unplanned replica kill) treat ANY
occurrence in the window as an infinite burn — the chaos soaks use these
to pin one alert per injected fault family, and their fault-free reference
replays to prove the monitor stays silent when nothing is wrong.

Rates are computed from COUNTER DELTAS between snapshots (counters are
monotonic), never from raw totals — so a long-running fleet's ancient
errors cannot hold an alert open. Gauges (coverage) and histogram
percentiles (latency) are evaluated on the freshest snapshot inside each
window. Alerts are recorded once per breach episode (firing -> resolved ->
firing again records twice), with the burn numbers that justified them —
they land in the chaos ledger/manifest, not a pager.
"""

import dataclasses
import threading
import time

from .metrics_registry import histogram_percentile

_RING_MAX = 4096   # bounded observation history, like every other buffer


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective.

    :param name: stable alert id ("deadline-miss-rate", "hedge-faults").
    :param kind: "rate_max" (numerator/denominator counters, objective is
        the max acceptable ratio; objective 0.0 = the event must never
        happen), "gauge_min" (gauge must stay >= objective), "gauge_max"
        (gauge must stay <= objective — a quality CEILING such as the
        swap-time quantization score error; evaluated on the aggregate's
        worst/`max` value, and an absent gauge never breaches),
        "latency_max" (histogram percentile must stay <= objective, in the
        histogram's own unit), or "gauge_growth_max" (the gauge's
        long-window GROWTH — latest minus window baseline — must stay <=
        objective while the short window is still climbing; an absent gauge
        never breaches, so backends without the underlying stat stay
        silent by construction).
    :param objective: the target (ratio / floor / ceiling by kind).
    :param numerator / denominator: counter names for "rate_max"
        (denominator "" with objective 0.0 = pure event count).
    :param gauge: gauge name for "gauge_min".
    :param histogram: histogram name for "latency_max".
    :param percentile: which percentile "latency_max" checks.
    :param short_window_s / long_window_s: the two rolling windows.
    :param fast_burn / slow_burn: burn-rate thresholds (short AND long must
        both breach for the alert to fire).
    """

    name: str
    kind: str
    objective: float
    numerator: str = ""
    denominator: str = ""
    gauge: str = ""
    histogram: str = ""
    percentile: float = 95.0
    short_window_s: float = 60.0
    long_window_s: float = 300.0
    fast_burn: float = 2.0
    slow_burn: float = 1.0

    def __post_init__(self):
        assert self.kind in ("rate_max", "gauge_min", "gauge_max",
                             "latency_max", "gauge_growth_max"), (
            f"unknown SLO kind {self.kind!r}")
        assert self.short_window_s <= self.long_window_s


class SLOMonitor:
    """Evaluates SLOSpecs over a ring of timestamped registry snapshots.

    Feed it with `observe(snapshot)` (typically the fleet aggregate) at
    whatever cadence the harness likes, then `evaluate()` — every call
    re-derives each spec's state and records an alert on the inactive ->
    firing edge. Thread-safe; `alerts` / `summary()` are the outputs the
    chaos audits and `report --fleet` consume."""

    def __init__(self, specs, clock=time.monotonic):
        self.specs = tuple(specs)
        names = [s.name for s in self.specs]
        assert len(set(names)) == len(names), f"duplicate SLO names: {names}"
        self._clock = clock
        self._lock = threading.Lock()
        self._ring = []        # (t, snapshot), append order == time order
        self._active = set()   # spec names currently firing
        self.alerts = []       # append-only firing records

    # ---------------------------------------------------------- observation
    def observe(self, snapshot, t=None):
        t = self._clock() if t is None else float(t)
        with self._lock:
            self._ring.append((t, snapshot))
            del self._ring[:-_RING_MAX]
        return t

    # ----------------------------------------------------------- evaluation
    def evaluate(self, now=None):
        """Evaluate every spec; returns the list of alerts NEWLY fired by
        this call (all alerts accumulate on `self.alerts`)."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            ring = list(self._ring)
        if not ring:
            return []
        fired = []
        for spec in self.specs:
            state = self._evaluate_spec(spec, ring, now)
            with self._lock:
                if state["breached"] and spec.name not in self._active:
                    self._active.add(spec.name)
                    alert = {"slo": spec.name, "kind": spec.kind,
                             "objective": spec.objective, "t": round(now, 6),
                             **state["evidence"]}
                    self.alerts.append(alert)
                    fired.append(alert)
                elif not state["breached"]:
                    self._active.discard(spec.name)
        return fired

    def _evaluate_spec(self, spec, ring, now):
        if spec.kind == "rate_max":
            return self._eval_rate(spec, ring, now)
        if spec.kind == "gauge_min":
            return self._eval_gauge(spec, ring, now)
        if spec.kind == "gauge_max":
            return self._eval_gauge_max(spec, ring, now)
        if spec.kind == "gauge_growth_max":
            return self._eval_gauge_growth(spec, ring, now)
        return self._eval_latency(spec, ring, now)

    # one window's (baseline, latest) snapshot pair: the baseline is the
    # newest sample AT OR BEFORE the window start (so a delta spans the
    # whole window), falling back to the oldest sample when the monitor is
    # younger than the window
    @staticmethod
    def _window(ring, now, window_s):
        start = now - window_s
        baseline = ring[0]
        for t, snap in ring:
            if t <= start:
                baseline = (t, snap)
            else:
                break
        return baseline, ring[-1]

    @staticmethod
    def _counter(snapshot, name):
        return int((snapshot.get("counters") or {}).get(name, 0) or 0)

    def _eval_rate(self, spec, ring, now):
        burns, evidence = [], {}
        for label, window_s, threshold in (
                ("short", spec.short_window_s, spec.fast_burn),
                ("long", spec.long_window_s, spec.slow_burn)):
            (t0, base), (t1, last) = self._window(ring, now, window_s)
            num = self._counter(last, spec.numerator) - self._counter(
                base, spec.numerator)
            if spec.denominator:
                den = self._counter(last, spec.denominator) - self._counter(
                    base, spec.denominator)
            else:
                den = None
            if spec.objective <= 0.0:
                # zero-tolerance: any occurrence is an infinite burn
                burn = float("inf") if num > 0 else 0.0
                rate = num
            else:
                rate = (num / den) if den else 0.0
                burn = rate / spec.objective
            evidence[f"{label}_burn"] = (round(burn, 4)
                                         if burn != float("inf") else "inf")
            evidence[f"{label}_value"] = round(rate, 6) if den else num
            burns.append(burn >= threshold and (num > 0 or burn > 0))
        return {"breached": all(burns), "evidence": evidence}

    def _gauge_in(self, snapshot, name):
        g = (snapshot.get("gauges") or {}).get(name)
        if isinstance(g, dict):      # fleet aggregate form: {min,max,mean}
            return g.get("min")
        return g

    def _eval_gauge(self, spec, ring, now):
        _, (t1, last) = self._window(ring, now, spec.long_window_s)
        val = self._gauge_in(last, spec.gauge)
        breached = val is not None and float(val) < spec.objective
        return {"breached": breached,
                "evidence": {"gauge": spec.gauge,
                             "value": None if val is None else round(
                                 float(val), 6)}}

    def _eval_gauge_max(self, spec, ring, now):
        """The quality-ceiling mirror of gauge_min: breach when the gauge
        RISES past the objective, judged on the aggregate's worst (`max`)
        component. An absent gauge never breaches — a float32 corpus
        publishes no quantization error, so the ceiling stays silent by
        absence."""
        _, (t1, last) = self._window(ring, now, spec.long_window_s)
        val = self._gauge_peak(last, spec.gauge)
        breached = val is not None and float(val) > spec.objective
        return {"breached": breached,
                "evidence": {"gauge": spec.gauge,
                             "value": None if val is None else round(
                                 float(val), 6)}}

    def _gauge_peak(self, snapshot, name):
        g = (snapshot.get("gauges") or {}).get(name)
        if isinstance(g, dict):      # fleet aggregate form: {min,max,mean}
            return g.get("max")
        return g

    def _eval_gauge_growth(self, spec, ring, now):
        """Sustained-growth detector (the memory-leak shape): breach when
        the LONG window's growth (latest - baseline, worst device via the
        aggregate max) exceeds the objective AND the SHORT window is still
        climbing — a one-off allocation spike that then plateaus resolves
        as soon as the short window flattens. A gauge absent from either
        snapshot (CPU backends export no memory stats) never breaches."""
        evidence = {"gauge": spec.gauge}
        growths = []
        for label, window_s in (("short", spec.short_window_s),
                                ("long", spec.long_window_s)):
            (t0, base), (t1, last) = self._window(ring, now, window_s)
            v0 = self._gauge_peak(base, spec.gauge)
            v1 = self._gauge_peak(last, spec.gauge)
            if v0 is None or v1 is None:
                evidence[f"{label}_growth"] = None
                growths.append(None)
                continue
            growth = float(v1) - float(v0)
            evidence[f"{label}_growth"] = round(growth, 6)
            growths.append(growth)
        short_g, long_g = growths
        breached = (long_g is not None and long_g > spec.objective
                    and short_g is not None and short_g > 0.0)
        return {"breached": breached, "evidence": evidence}

    def _eval_latency(self, spec, ring, now):
        burns, evidence = [], {}
        for label, window_s, threshold in (
                ("short", spec.short_window_s, spec.fast_burn),
                ("long", spec.long_window_s, spec.slow_burn)):
            (t0, base), (t1, last) = self._window(ring, now, window_s)
            delta = _histogram_delta(
                (last.get("histograms") or {}).get(spec.histogram),
                (base.get("histograms") or {}).get(spec.histogram))
            p = (histogram_percentile(delta, spec.percentile)
                 if delta else None)
            burn = 0.0 if p is None or spec.objective <= 0 else (
                p / spec.objective)
            evidence[f"{label}_p{spec.percentile:g}"] = p
            evidence[f"{label}_burn"] = round(burn, 4)
            burns.append(burn >= threshold)
        return {"breached": all(burns), "evidence": evidence}

    # ------------------------------------------------------------ reporting
    def summary(self):
        """Manifest/report fragment: the declared specs and every alert."""
        with self._lock:
            return {"specs": [dataclasses.asdict(s) for s in self.specs],
                    "alerts": list(self.alerts),
                    "active": sorted(self._active),
                    "n_observations": len(self._ring)}


def _histogram_delta(last, base):
    """Window delta of two histogram states (bucket-wise subtraction).
    min/max come from the latest state — approximate for the window, exact
    for the run, and monotonic counts guarantee non-negative buckets."""
    if not last:
        return None
    if not base or base.get("bounds") != last.get("bounds"):
        return last
    counts = [a - b for a, b in zip(last["counts"], base["counts"])]
    return {"bounds": last["bounds"], "counts": counts,
            "count": last["count"] - base["count"],
            "sum": last["sum"] - base["sum"],
            "min": last["min"], "max": last["max"]}


def serving_slo_specs(*, deadline_miss_max=0.05, shed_max=0.05,
                      coverage_floor=0.99, p95_ms_max=2500.0,
                      memory_growth_bytes_max=256e6,
                      short_window_s=60.0, long_window_s=300.0):
    """The default serving SLO set: the generic health objectives every
    fleet run carries (fault-family zero-tolerance specs ride alongside —
    see fleet/chaos_fleet.fleet_fault_slo_specs).

    `memory_growth_bytes_max` bounds sustained per-device HBM growth over
    the long window (the leak detector over devprof.sample_memory's
    `hbm_bytes_in_use` gauge). Where the backend exports no memory stats
    (CPU tier-1, chaos reference replays) the gauge is never set and the
    spec stays silent by absence."""
    w = {"short_window_s": short_window_s, "long_window_s": long_window_s}
    return (
        SLOSpec("deadline-miss-rate", "rate_max", deadline_miss_max,
                numerator="deadline_missed", denominator="replied",
                fast_burn=1.0, slow_burn=1.0, **w),
        SLOSpec("shed-rate", "rate_max", shed_max,
                numerator="shed", denominator="submitted",
                fast_burn=1.0, slow_burn=1.0, **w),
        SLOSpec("corpus-coverage", "gauge_min", coverage_floor,
                gauge="corpus_coverage", **w),
        SLOSpec("reply-p95", "latency_max", p95_ms_max,
                histogram="request_latency_ms", percentile=95.0,
                fast_burn=1.0, slow_burn=1.0, **w),
        SLOSpec("device-memory-growth", "gauge_growth_max",
                float(memory_growth_bytes_max), gauge="hbm_bytes_in_use",
                **w),
    )


def quality_slo_specs(*, recall_miss_max=0.05, coverage_floor=0.99,
                      quant_error_max=0.05,
                      short_window_s=60.0, long_window_s=300.0):
    """The retrieval-quality SLO set fed by the shadow scorer and the
    corpus quality gauges (serve/shadow.py, ServingCorpus):

    - ``quality-recall``: windowed recall burn-rate. The shadow scorer
      counts every exact-top-k row it expected (`shadow_expected`) and
      every one the served shortlist missed (`shadow_misses`); the miss
      RATIO must stay under `recall_miss_max` in both windows. With no
      shadow samples in the window the denominator is zero and the spec
      stays silent — quality alerting is pass-by-absence like every
      other optional signal.
    - ``quality-coverage``: live row coverage floor over the
      `corpus_coverage` gauge the corpus publishes at promote /
      quarantine / recover time. Named distinctly from the serving
      "corpus-coverage" spec so a fleet run can carry both sets without
      colliding in alert history.
    - ``quality-quant-error``: ceiling on the swap-time int8 score error
      (`int8_score_error` gauge, measured against the fp32 reference
      Gram matrix at build time). float32 corpora never publish the
      gauge, so the ceiling is silent by absence.
    """
    w = {"short_window_s": short_window_s, "long_window_s": long_window_s}
    return (
        SLOSpec("quality-recall", "rate_max", float(recall_miss_max),
                numerator="shadow_misses", denominator="shadow_expected",
                fast_burn=1.0, slow_burn=1.0, **w),
        SLOSpec("quality-coverage", "gauge_min", float(coverage_floor),
                gauge="corpus_coverage", **w),
        SLOSpec("quality-quant-error", "gauge_max", float(quant_error_max),
                gauge="int8_score_error", **w),
    )
