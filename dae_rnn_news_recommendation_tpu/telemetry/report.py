"""`telemetry report` — join a Chrome trace with metrics.jsonl and bench JSON.

Reads the trace exported by a traced fit (models/estimator.py `trace=True` ->
<tf_summary_dir>/trace.json) and prints a per-span table:

    span        count  total s  p50 ms  p95 ms  stall%  compiles

* stall% — fraction of the span's wall time the consumer spent blocked on the
  feed queue: the overlap of `feed/wait` spans with this span's intervals
  (the trace-side view of FeedStats.feed_stall_fraction).
* compiles — XLA backend compiles whose event midpoint falls inside the span
  (the captured jax.monitoring events; see xla_events.py).

`--metrics` joins the per-epoch `feed/*` scalars from metrics.jsonl so the
trace-derived stall can be cross-checked against the FeedStats numbers logged
by the same run. `--bench` reconciles a bench record's
`h2d_bandwidth_mbytes_per_sec` probes against the fence-measured transfer
counters captured during that run (`extra.transfer_events`) — the measured
answer to the README Performance stream-vs-probe discrepancy. `--health`
renders a flight-recorder bundle (telemetry/recorder.py) — status, first bad
step, the anomaly reason, and the last recorded ring rows; when the flag is
omitted a `health_bundle.json` sitting next to the trace is picked up
automatically. `--churn` renders a refresh-loop history (refresh/churn.py
`ChurnSupervisor.dump_history`) — per-action cycle counts, drift extremes vs
trips, promoted-version span, and the swap/encode latency rollup — with the
same next-to-the-trace auto-detection (`churn_history.json`). `--fleet`
renders a serving-fleet observability bundle (fleet/observability.py
`dump_fleet_observability`) — the per-request join table (request id, status,
replica, latency and its timing decomposition), the fleet-aggregate
counter/gauge rollup, SLO alerts, rollout stages, and the outcome-ledger
cross-check — auto-detecting `fleet_observability.json` next to the trace.
`--quality` renders a retrieval-quality bundle (fleet/observability.py
`dump_quality_observability`) — the shadow scorer's sampled recall /
rank-displacement / score-delta story, the corpus & index quality gauges
(live coverage, swap-time quantization error, cell imbalance, staleness),
and the quality SLO alert history — auto-detecting
`quality_observability.json` next to the trace.

Optional sections degrade gracefully: an unreadable metrics/bench/health
input becomes a warning note in the report instead of an error, and a trace
with no span events still renders whatever optional sections loaded (only a
trace that is empty AND alone exits 1).
"""

import json
import os


# ------------------------------------------------------------------ loading

def load_trace(path):
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    if isinstance(trace, list):  # bare-array Chrome trace flavor
        trace = {"traceEvents": trace, "metadata": {}}
    return trace


def load_metrics(path):
    """Records from metrics.jsonl. `path` may be the file itself or a
    directory (looks for metrics.jsonl, then train/metrics.jsonl)."""
    if os.path.isdir(path):
        for sub in ("metrics.jsonl", os.path.join("train", "metrics.jsonl")):
            cand = os.path.join(path, sub)
            if os.path.exists(cand):
                path = cand
                break
    records = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # a torn tail line must not kill the report
    return records


def _canonicalize_bench_keys(extra):
    """Accept pre-r06 bench records in place: `h2d_bandwidth_mbps` was the
    canonical key's earlier name (the value was always MBytes/s — the "mbps"
    was a misnomer, see VERDICT r5 item 3). New records emit only
    `h2d_bandwidth_mbytes_per_sec`; old history (BENCH_r05.json) is read
    through this alias so reconciliation never goes blind on a legacy file.
    The applied alias is recorded in the extra so the report says which
    spelling the record actually carried."""
    legacy, canonical = "h2d_bandwidth_mbps", "h2d_bandwidth_mbytes_per_sec"
    if isinstance(extra, dict) and legacy in extra and canonical not in extra:
        extra[canonical] = extra[legacy]
        extra["h2d_bandwidth_key_alias"] = f"{legacy} (legacy, pre-r06)"
    return extra


def load_bench(path):
    """The `extra` dict of a bench record: accepts the bench stdout JSON line
    (a {"metric", ..., "extra"} object), the evidence sidecar ({"record":
    ...}), or a file of JSON lines containing either. Legacy bench-history
    key spellings are normalized via `_canonicalize_bench_keys`."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    candidates = []
    try:
        candidates.append(json.loads(text))
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    candidates.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    for obj in candidates:
        if "record" in obj and isinstance(obj["record"], dict):
            obj = obj["record"]
        if "extra" in obj:
            return _canonicalize_bench_keys(obj["extra"])
    return None


def load_health(path):
    """A flight-recorder bundle (telemetry/recorder.py dump())."""
    with open(path, encoding="utf-8") as f:
        bundle = json.load(f)
    if not isinstance(bundle, dict):
        raise ValueError(f"{path}: not a health bundle object")
    return bundle


def load_churn(path):
    """A churn history dump (refresh/churn.py ChurnSupervisor.dump_history):
    either the {"history": [...], "summary": {...}} object or a bare list of
    cycle reports."""
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if isinstance(obj, list):
        obj = {"history": obj}
    if not isinstance(obj, dict) or not isinstance(obj.get("history"), list):
        raise ValueError(f"{path}: not a churn history dump")
    return obj


def load_fleet(path):
    """A fleet observability bundle (fleet/observability.py
    dump_fleet_observability): per-request router records, registry
    snapshots + aggregate, SLO summary, rollout history, ledger counts."""
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or not any(
            k in obj for k in ("requests", "registries", "aggregate")):
        raise ValueError(f"{path}: not a fleet observability bundle")
    return obj


def load_quality(path):
    """A retrieval-quality observability bundle (fleet/observability.py
    dump_quality_observability): shadow-scorer summary, corpus
    coverage/ledger tail, registry snapshots + aggregate, quality SLO
    summary."""
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or not any(
            k in obj for k in ("shadow", "registries", "aggregate", "slo")):
        raise ValueError(f"{path}: not a quality observability bundle")
    return obj


def load_profile(path):
    """A ProfileDB file (telemetry/profile_db.py): {"version", "rows":
    {key: row}} with rows keyed by (op, shape, dtype, device_kind)."""
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict) or not isinstance(obj.get("rows"), dict):
        raise ValueError(f"{path}: not a profile DB")
    return obj


# -------------------------------------------------------------- aggregation

def _percentile(sorted_vals, q):
    """Nearest-rank percentile over a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, round(q / 100.0 * (len(sorted_vals) - 1))))
    return sorted_vals[idx]

def _overlap_s(intervals, others):
    """Total seconds of `others` intervals overlapping `intervals` (both in
    µs)."""
    total = 0.0
    for a0, a1 in intervals:
        for b0, b1 in others:
            lo, hi = max(a0, b0), min(a1, b1)
            if hi > lo:
                total += hi - lo
    return total / 1e6


def span_table(trace):
    """Aggregate the trace's X events into per-span rows (sorted by total
    time, descending)."""
    spans = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
    by_name = {}
    for e in spans:
        by_name.setdefault(e["name"], []).append(e)
    wait_iv = [(e["ts"], e["ts"] + e["dur"])
               for e in by_name.get("feed/wait", [])]
    compile_mid = [e["ts"] + e["dur"] / 2.0
                   for e in by_name.get("xla/backend_compile", [])]
    rows = []
    for name, events in by_name.items():
        durs_ms = sorted(e["dur"] / 1e3 for e in events)
        iv = [(e["ts"], e["ts"] + e["dur"]) for e in events]
        total_s = sum(durs_ms) / 1e3
        stall = (_overlap_s(iv, wait_iv) / total_s) if (
            wait_iv and total_s > 0 and name != "feed/wait") else None
        compiles = sum(1 for m in compile_mid
                       if any(a0 <= m <= a1 for a0, a1 in iv))
        rows.append({
            "span": name, "count": len(events),
            "total_s": round(total_s, 4),
            "p50_ms": round(_percentile(durs_ms, 50), 3),
            "p95_ms": round(_percentile(durs_ms, 95), 3),
            "stall_fraction": (round(stall, 4)
                               if stall is not None else None),
            "compiles": compiles,
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def metrics_summary(records):
    """Per-epoch feed scalars + cost trajectory out of metrics.jsonl."""
    feed_stall = [(r["step"], r["value"]) for r in records
                  if r.get("tag") == "feed/feed_stall_fraction"]
    costs = [(r["step"], r["value"]) for r in records
             if r.get("tag") == "cost"]
    out = {"n_records": len(records)}
    if feed_stall:
        vals = [v for _, v in feed_stall]
        out["feed_stall_fraction_mean"] = round(sum(vals) / len(vals), 4)
        out["feed_stall_epochs"] = len(vals)
    if costs:
        out["cost_first"] = round(costs[0][1], 6)
        out["cost_last"] = round(costs[-1][1], 6)
    return out


def bench_reconciliation(extra):
    """The h2d story of one bench record, probes vs fence-measured feed.

    `h2d_bandwidth_mbytes_per_sec` / `h2d_feed_bandwidth_mbytes_per_sec` are
    synthetic device_put probes (bench._measure_h2d_bandwidth);
    `encode_stream_implied_mbytes_per_sec` is what the encode stream's
    throughput implies it moved; `transfer_events` is what the instrumented
    pipelined feed *measured* moving its real batches (fenced spans,
    bench._measure_feed_transfers)."""
    if not extra:
        return None
    out = {}
    _canonicalize_bench_keys(extra)  # a caller may pass a raw legacy dict
    for key in ("h2d_bandwidth_mbytes_per_sec",
                "h2d_feed_bandwidth_mbytes_per_sec",
                "encode_stream_implied_mbytes_per_sec",
                "h2d_bandwidth_key_alias",
                "feed_wire_bytes_per_article",
                "feed_padded_csr_bytes_per_article"):
        if key in extra:
            out[key] = extra[key]
    transfers = extra.get("transfer_events")
    if transfers:
        out["transfer_events"] = transfers
        measured = transfers.get("h2d_feed_measured_mbytes_per_sec")
        probe = extra.get("h2d_feed_bandwidth_mbytes_per_sec")
        if measured and probe:
            out["measured_vs_feed_probe"] = round(measured / probe, 3)
    if "xla_events" in extra:
        compiles = extra["xla_events"].get("xla/backend_compile", {})
        out["xla_backend_compiles"] = compiles.get("count", 0)
    if "manifest" in extra:
        m = extra["manifest"]
        out["provenance"] = {k: m.get(k) for k in
                             ("git_rev", "backend", "created_utc")}
    return out or None


def health_summary(bundle):
    """The load-bearing fields of a flight-recorder bundle, plus the tail of
    the metrics ring (the steps leading into the anomaly)."""
    if not bundle:
        return None
    out = {k: bundle.get(k) for k in
           ("status", "reason", "first_bad_step", "last_good_step",
            "loss_ema", "n_steps_recorded")}
    ring = bundle.get("ring") or []
    out["ring_steps"] = len(ring)
    tail = []
    for row in ring[-5:]:
        entry = {"step": row.get("step")}
        for k in ("cost", "health/grad_norm", "health/update_ratio",
                  "health/nonfinite"):
            if k in row:
                entry[k] = row[k]
        tail.append(entry)
    out["ring_tail"] = tail
    return out


def churn_summary(dump):
    """Aggregate a churn history (refresh/churn.py cycle reports) into the
    drift/refresh story: per-action counts, promoted-version span, drift
    extremes vs the trip count, and the encode/swap latency rollup the bench
    records as `churn_encode_articles_per_sec` / `refresh_swap_p95_ms`."""
    history = (dump or {}).get("history") or []
    if not history:
        return None
    actions = {}
    for rep in history:
        a = rep.get("action", "?")
        actions[a] = actions.get(a, 0) + 1
    versions = [rep["version"] for rep in history if "version" in rep]
    shifts = [rep["drift"]["centroid_shift"] for rep in history
              if isinstance(rep.get("drift"), dict)]
    deltas = [rep["drift"]["collapse_delta"] for rep in history
              if isinstance(rep.get("drift"), dict)]
    trips = sum(1 for rep in history
                if isinstance(rep.get("drift"), dict)
                and rep["drift"].get("tripped"))
    swaps_ms = sorted(rep["swap_s"] * 1e3 for rep in history
                      if "swap_s" in rep)
    encode_s = sum(rep.get("encode_s", 0.0) for rep in history)
    n_new = sum(rep.get("n_new", 0) for rep in history)
    out = {"n_cycles": len(history), "actions": actions,
           "drift_trips": trips}
    if versions:
        out["version_span"] = [min(versions), max(versions)]
    if shifts:
        out["drift_centroid_shift_max"] = round(max(shifts), 6)
        out["drift_collapse_delta_max"] = round(max(deltas), 6)
    if swaps_ms:
        out["swap_p50_ms"] = round(_percentile(swaps_ms, 50), 2)
        out["swap_p95_ms"] = round(_percentile(swaps_ms, 95), 2)
    if encode_s > 0 and n_new:
        out["encode_articles_per_sec"] = round(n_new / encode_s, 1)
    oov = [rep["oov_fraction"] for rep in history if "oov_fraction" in rep]
    if oov:
        out["oov_fraction_last"] = oov[-1]
    if isinstance((dump or {}).get("summary"), dict):
        s = dump["summary"]
        for k in ("resident_rows", "corpus_version", "finetunes", "retries"):
            if k in s:
                out[k] = s[k]
    return out


# per-request timing components, in hop order (serve/service.py _timings +
# the router's remainder) — the decomposition that sums to latency_s
_TIMING_KEYS = ("admit_s", "queue_s", "batch_form_s", "compute_s",
                "resolve_s", "router_s")


def fleet_summary(bundle, max_rows=12):
    """Join a fleet observability bundle into the serving story: per-request
    rows keyed by request id (status, replica, hop counts, latency and its
    timing decomposition), the fleet-aggregate counter/gauge rollup, SLO
    alerts, rollout stages, and the outcome-ledger cross-check (table rows
    vs ledger submissions — the exactly-one-outcome contract, joined)."""
    if not bundle:
        return None
    reqs = bundle.get("requests") or []
    rows, statuses = [], {}
    comp_tot = {k: 0.0 for k in _TIMING_KEYS}
    comp_n = 0
    for rec in reqs:
        t = rec.get("timings") or {}
        status = rec.get("status", "?")
        statuses[status] = statuses.get(status, 0) + 1
        if status == "ok" and t:
            comp_n += 1
            for k in _TIMING_KEYS:
                comp_tot[k] += t.get(k, 0.0)
        rows.append({
            "request_id": rec.get("request_id") or str(rec.get("id", "?")),
            "status": status,
            "replica": rec.get("replica"),
            "hedged": bool(rec.get("hedged")),
            "retries": rec.get("retries", 0),
            "latency_ms": round(1e3 * (rec.get("latency_s") or 0.0), 2),
            "timings_ms": {k: round(1e3 * t[k], 2)
                           for k in _TIMING_KEYS if k in t},
        })
    out = {"n_requests": len(rows), "statuses": statuses,
           "requests": rows[:max_rows],
           "n_rows_omitted": max(0, len(rows) - max_rows)}
    if comp_n:
        out["timing_means_ms"] = {
            k: round(1e3 * comp_tot[k] / comp_n, 3) for k in _TIMING_KEYS}
        out["timing_n_replied"] = comp_n
    agg = bundle.get("aggregate")
    if isinstance(agg, dict):
        out["registries"] = [s.get("registry", "?")
                             for s in bundle.get("registries") or []]
        out["counters"] = agg.get("counters") or {}
        gauges = {}
        for name, g in (agg.get("gauges") or {}).items():
            gauges[name] = (round(g["mean"], 4)
                            if isinstance(g, dict) and "mean" in g else g)
        out["gauges"] = gauges
    slo = bundle.get("slo")
    if isinstance(slo, dict):
        out["slo_alerts"] = [
            {"slo": a.get("slo"), "short_burn": a.get("short_burn"),
             "long_burn": a.get("long_burn")}
            for a in slo.get("alerts") or []]
        out["slo_n_specs"] = len(slo.get("specs") or [])
    rollout = bundle.get("rollout") or []
    stages = []
    for rep in rollout:
        stage = {"action": rep.get("action", "?")}
        for k in ("ok", "stage", "note"):
            if k in rep:
                stage[k] = rep[k]
        if rep.get("reverted"):
            stage["reverted"] = rep["reverted"]
        stages.append(stage)
    if stages:
        out["rollout"] = stages
    ledger = bundle.get("ledger")
    if isinstance(ledger, dict):
        out["ledger"] = {"n_submitted": ledger.get("n_submitted"),
                         "counts": ledger.get("counts") or {},
                         "n_problems": len(ledger.get("problems") or [])}
        # the join check: every router record must be a ledger submission
        if isinstance(ledger.get("n_submitted"), int):
            out["ledger"]["join_ok"] = (ledger["n_submitted"] == len(rows))
    # aggregate() records keep-first decisions (mismatched histogram bounds
    # across registries) in "notes" — surface them instead of silently
    # winning: a skewed fleet histogram merge must be visible in the report
    if isinstance(agg, dict) and agg.get("notes"):
        out["aggregate_notes"] = list(agg["notes"])
    return out


_QUALITY_GAUGES = ("shadow_recall", "shadow_recall_mean", "corpus_coverage",
                   "int8_score_error", "ivf_imbalance", "ivf_frac_empty",
                   "ivf_n_cells", "ivf_stale_cycles", "corpus_staleness")


def quality_summary(bundle):
    """Join a quality observability bundle into the retrieval-quality
    story: the shadow scorer's sample counts and recall window, the quality
    gauges (live coverage, quantization error, index shape/staleness), the
    shadow counters the recall SLO burns on, and the quality alert
    history."""
    if not bundle:
        return None
    out = {}
    shadow = bundle.get("shadow")
    if isinstance(shadow, dict):
        counts = shadow.get("counts") or {}
        out["shadow"] = {
            "rate": shadow.get("rate"),
            "counts": counts,
            "recall_mean": shadow.get("recall_mean"),
            "recall_min": shadow.get("recall_min"),
            "n_samples": shadow.get("n_samples"),
        }
        worst = sorted((s for s in shadow.get("samples") or []
                        if isinstance(s.get("recall"), (int, float))),
                       key=lambda s: s["recall"])[:5]
        if worst:
            out["shadow"]["worst_samples"] = [
                {"rid": s.get("rid"), "recall": s.get("recall"),
                 "rank_displacement": s.get("rank_displacement"),
                 "score_delta": s.get("score_delta"),
                 "corpus_version": s.get("corpus_version")}
                for s in worst]
    corpus = bundle.get("corpus")
    if isinstance(corpus, dict):
        out["coverage"] = corpus.get("coverage")
        ledger = corpus.get("ledger") or []
        out["corpus_versions"] = len(ledger)
    agg = bundle.get("aggregate")
    if isinstance(agg, dict):
        gauges = {}
        for name in _QUALITY_GAUGES:
            g = (agg.get("gauges") or {}).get(name)
            if g is None:
                continue
            gauges[name] = (round(g["mean"], 4)
                            if isinstance(g, dict) and "mean" in g else g)
        if gauges:
            out["gauges"] = gauges
        counters = {k: v for k, v in (agg.get("counters") or {}).items()
                    if k.startswith("shadow_") or k.startswith("shard_")}
        if counters:
            out["counters"] = counters
        if agg.get("notes"):
            out["aggregate_notes"] = list(agg["notes"])
    slo = bundle.get("slo")
    if isinstance(slo, dict):
        out["alerts"] = [
            {"slo": a.get("slo"), "kind": a.get("kind"), "t": a.get("t"),
             "value": a.get("value"),
             "short_burn": a.get("short_burn"),
             "long_burn": a.get("long_burn")}
            for a in slo.get("alerts") or []]
        out["n_specs"] = len(slo.get("specs") or [])
        out["active_alerts"] = slo.get("active") or []
    return out or None


def profile_summary(dump, top=10):
    """The ProfileDB's device-time story: the top-N most expensive rows by
    best_ms (device ms / FLOPs / bytes / roofline fraction), the device kinds
    measured, and how many rows carry polluted samples (a timed iteration
    that saw an XLA compile — provenance the autotuner reads before trusting
    a number)."""
    rows = list(((dump or {}).get("rows") or {}).values())
    if not rows:
        return None

    def cost(row):
        v = row.get("best_ms")
        return -float(v) if isinstance(v, (int, float)) else 0.0

    rows.sort(key=cost)
    kinds = sorted({str(r.get("device_kind")) for r in rows
                    if r.get("device_kind") is not None})
    polluted = sum(1 for r in rows
                   if isinstance(r.get("compiles_timed"), int)
                   and r["compiles_timed"] > 0)
    table = []
    for r in rows[:top]:
        table.append({
            "op": str(r.get("op", "?")),
            "shape": str(r.get("shape", "?")),
            "dtype": str(r.get("dtype", "?")),
            "device_kind": str(r.get("device_kind", "?")),
            "best_ms": r.get("best_ms"),
            "median_ms": r.get("median_ms"),
            "n": r.get("n"),
            "flops": r.get("flops"),
            "bytes_accessed": r.get("bytes_accessed"),
            "roofline_fraction": r.get("roofline_fraction"),
            "bound": r.get("bound"),
        })
    return {"n_rows": len(rows), "n_polluted": polluted,
            "device_kinds": kinds, "top": table,
            "n_rows_omitted": max(0, len(rows) - top)}


def tuning_summary(dump):
    """The ProfileDB's autotuner story: every row the measured search
    recorded (tuning/search.py — rows carrying `config` + `tuner`
    provenance), tuned-vs-default timing side by side, parity discipline,
    and how much of each candidate grid the static pruner rejected before
    any compile. Plain measurement rows (r18 devprof captures) are not
    tuning rows and are skipped."""
    rows = [r for r in ((dump or {}).get("rows") or {}).values()
            if isinstance(r.get("config"), dict)
            and isinstance(r.get("tuner"), dict)]
    if not rows:
        return None
    rows.sort(key=lambda r: (str(r.get("op")), str(r.get("shape")),
                             str(r.get("dtype"))))
    table = []
    n_interpret = 0
    for r in rows:
        t = r["tuner"]
        if t.get("interpret"):
            n_interpret += 1
        table.append({
            "op": str(r.get("op", "?")),
            "shape": str(r.get("shape", "?")),
            "dtype": str(r.get("dtype", "?")),
            "device_kind": str(r.get("device_kind", "?")),
            "config": dict(r["config"]),
            "best_ms": r.get("best_ms"),
            "default_config": t.get("default_config"),
            "default_best_ms": t.get("default_best_ms"),
            "speedup": t.get("speedup_vs_default"),
            "parity": t.get("parity"),
            "n_candidates": t.get("n_candidates"),
            "n_rejected": t.get("n_rejected"),
            "n_pruned": (t.get("n_pruned_illegal") or 0)
            + (t.get("n_pruned_vmem") or 0),
            "interpret": bool(t.get("interpret")),
            "alias_of": t.get("alias_of"),
        })
    kinds = sorted({r["device_kind"] for r in table})
    return {"n_rows": len(table), "device_kinds": kinds,
            "n_interpret": n_interpret, "rows": table}


def faults_summary(manifest):
    """The manifest's `faults` section (models/estimator.py
    `_write_fault_manifest`): injected chaos faults, recorded I/O retries,
    and any checkpoint-cadence fallback — the zero-silent-recoveries ledger
    of the run."""
    section = (manifest or {}).get("faults")
    if not isinstance(section, dict):
        return None
    out = {"n_retries": len(section.get("retries") or []),
           "n_injected": len(section.get("injected") or []),
           "retries": section.get("retries") or [],
           "injected": section.get("injected") or []}
    if "plan_seed" in section:
        out["plan_seed"] = section["plan_seed"]
    if section.get("cadence_fallback"):
        out["cadence_fallback"] = section["cadence_fallback"]
    if not (out["n_retries"] or out["n_injected"]
            or out.get("cadence_fallback")):
        return None  # an empty ledger renders nothing
    return out


# ---------------------------------------------------------------- rendering

_COLS = ("span", "count", "total_s", "p50_ms", "p95_ms",
         "stall_fraction", "compiles")
_HEADS = ("span", "count", "total s", "p50 ms", "p95 ms", "stall", "compiles")


def _fmt_row(values, widths):
    cells = []
    for i, v in enumerate(values):
        text = "-" if v is None else (f"{v:.3f}" if isinstance(v, float)
                                      else str(v))
        cells.append(text.ljust(widths[i]) if i == 0 else text.rjust(widths[i]))
    return "  ".join(cells).rstrip()


def _render_fleet(fleet, lines):
    head = f"serving fleet: {fleet['n_requests']} requests"
    if fleet.get("statuses"):
        head += " (" + ", ".join(f"{k} x{v}" for k, v in
                                 sorted(fleet["statuses"].items())) + ")"
    lines.append(head)
    if fleet.get("registries"):
        lines.append("  registries: " + ", ".join(fleet["registries"]))
    means = fleet.get("timing_means_ms")
    if means:
        parts = [f"{k[:-2]} {means[k]:.3f}" for k in _TIMING_KEYS
                 if k in means]
        lines.append(f"  timing means over {fleet['timing_n_replied']} "
                     "replied (ms): " + "  ".join(parts))
    reqs = fleet.get("requests") or []
    if reqs:
        lines.append("  request join (id / status / replica / lat ms / "
                     "compute ms / retries / hedged):")
        for r in reqs:
            t = r.get("timings_ms") or {}
            lines.append(
                f"    {r['request_id']:<12} {r['status']:<8} "
                f"{str(r.get('replica') or '-'):<6} "
                f"{r['latency_ms']:>8.2f} "
                f"{t.get('compute_s', 0.0):>8.2f} "
                f"{r.get('retries', 0):>3} "
                f"{'h' if r.get('hedged') else '-'}")
        if fleet.get("n_rows_omitted"):
            lines.append(f"    ... {fleet['n_rows_omitted']} more")
    if fleet.get("counters"):
        items = ", ".join(f"{k}={v}" for k, v in
                          sorted(fleet["counters"].items()))
        lines.append(f"  counters: {items}")
    if fleet.get("gauges"):
        items = ", ".join(f"{k}={v}" for k, v in
                          sorted(fleet["gauges"].items()))
        lines.append(f"  gauges (fleet mean): {items}")
    if "slo_alerts" in fleet:
        alerts = fleet["slo_alerts"]
        if alerts:
            names = ", ".join(
                f"{a['slo']} (burn {a.get('short_burn')})" for a in alerts)
            lines.append(f"  SLO alerts ({fleet.get('slo_n_specs', '?')} "
                         f"specs): {names}")
        else:
            lines.append(f"  SLO alerts: none "
                         f"({fleet.get('slo_n_specs', '?')} specs quiet)")
    for stage in fleet.get("rollout") or ():
        bits = [stage["action"]]
        if "note" in stage:
            bits.append(stage["note"])
        if "stage" in stage:
            bits.append(f"stage={stage['stage']}")
        if "ok" in stage:
            bits.append(f"ok={stage['ok']}")
        if "reverted" in stage:
            bits.append(f"reverted={','.join(stage['reverted'])}")
        lines.append("  rollout: " + "  ".join(bits))
    ledger = fleet.get("ledger")
    if ledger:
        line = (f"  ledger: {ledger['n_submitted']} submitted, counts "
                + ", ".join(f"{k} x{v}" for k, v in
                            sorted(ledger["counts"].items()))
                + f", problems {ledger['n_problems']}")
        if "join_ok" in ledger:
            line += ("  [join ok]" if ledger["join_ok"]
                     else "  [JOIN MISMATCH vs request table]")
        lines.append(line)
    for note in fleet.get("aggregate_notes") or ():
        lines.append(f"  aggregate note: {note}")


def _render_quality(quality, lines):
    shadow = quality.get("shadow")
    if shadow:
        counts = shadow.get("counts") or {}
        lines.append(
            "retrieval quality: shadow rate "
            f"{shadow.get('rate')}, {counts.get('scored', 0)} scored / "
            f"{counts.get('sampled', 0)} sampled / "
            f"{counts.get('seen', 0)} seen "
            f"(dropped {counts.get('dropped', 0)}, "
            f"errors {counts.get('errors', 0)})")
        lines.append(f"  shadow recall: mean {shadow.get('recall_mean')}  "
                     f"min {shadow.get('recall_min')}  over "
                     f"{shadow.get('n_samples')} samples")
        worst = shadow.get("worst_samples") or []
        if worst:
            lines.append("  worst samples (rid / recall / rank disp / "
                         "score delta / corpus v):")
            for s in worst:
                lines.append(
                    f"    {str(s.get('rid')):<14} {s.get('recall'):>7} "
                    f"{s.get('rank_displacement'):>9} "
                    f"{s.get('score_delta'):>11} "
                    f"v{s.get('corpus_version')}")
    else:
        lines.append("retrieval quality:")
    if quality.get("coverage") is not None:
        line = f"  live coverage: {quality['coverage']}"
        if quality.get("corpus_versions"):
            line += f"  (ledger: {quality['corpus_versions']} records)"
        lines.append(line)
    if quality.get("gauges"):
        items = ", ".join(f"{k}={v}" for k, v in
                          sorted(quality["gauges"].items()))
        lines.append(f"  quality gauges: {items}")
    if quality.get("counters"):
        items = ", ".join(f"{k}={v}" for k, v in
                          sorted(quality["counters"].items()))
        lines.append(f"  shadow counters: {items}")
    if "alerts" in quality:
        alerts = quality["alerts"]
        if alerts:
            names = ", ".join(
                f"{a['slo']}"
                + (f" (burn {a['short_burn']})"
                   if a.get("short_burn") is not None
                   else (f" (value {a['value']})"
                         if a.get("value") is not None else ""))
                for a in alerts)
            lines.append(f"  quality alerts ({quality.get('n_specs', '?')} "
                         f"specs): {names}")
        else:
            lines.append(f"  quality alerts: none "
                         f"({quality.get('n_specs', '?')} specs quiet)")
    for note in quality.get("aggregate_notes") or ():
        lines.append(f"  aggregate note: {note}")


def _fmt_quantity(v):
    """Human-scaled FLOPs/bytes: 1.23e9 -> '1.2G'."""
    if not isinstance(v, (int, float)):
        return "-"
    for thresh, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(v) >= thresh:
            return f"{v / thresh:.1f}{suffix}"
    return f"{v:.0f}"


def _render_profile(profile, lines):
    head = (f"device-time profile: {profile['n_rows']} rows, device kinds "
            + (", ".join(profile["device_kinds"]) or "?"))
    if profile.get("n_polluted"):
        head += f"  ({profile['n_polluted']} with compile-polluted samples)"
    lines.append(head)
    lines.append("  op / shape / dtype / best ms / median ms / flops / "
                 "bytes / roofline")
    for r in profile.get("top") or ():
        roof = r.get("roofline_fraction")
        roof_txt = (f"{roof:.3f} ({r.get('bound') or '?'})"
                    if isinstance(roof, (int, float)) else "-")
        best = r.get("best_ms")
        med = r.get("median_ms")
        best_txt = f"{best:.3f}" if isinstance(best, (int, float)) else "-"
        med_txt = f"{med:.3f}" if isinstance(med, (int, float)) else "-"
        lines.append(
            f"    {r['op']:<28} {r['shape']:>14} {r['dtype']:>9} "
            f"{best_txt:>10} {med_txt:>10} "
            f"{_fmt_quantity(r.get('flops')):>8} "
            f"{_fmt_quantity(r.get('bytes_accessed')):>8} "
            f" {roof_txt}")
    if profile.get("n_rows_omitted"):
        lines.append(f"    ... {profile['n_rows_omitted']} more")


def _fmt_config(cfg):
    if not isinstance(cfg, dict):
        return "-"
    return ",".join(f"{k}={v}" for k, v in sorted(cfg.items()))


def _render_tuning(tuning, lines):
    head = (f"kernel autotuner: {tuning['n_rows']} tuned rows, device kinds "
            + (", ".join(tuning["device_kinds"]) or "?"))
    if tuning.get("n_interpret"):
        head += (f"  ({tuning['n_interpret']} interpreter captures — "
                 "parity only, not hardware timings)")
    lines.append(head)
    lines.append("  op / shape / dtype / tuned config / best ms / "
                 "default ms / speedup / parity")
    for r in tuning.get("rows") or ():
        spd = r.get("speedup")
        spd_txt = f"x{spd:.3f}" if isinstance(spd, (int, float)) else "-"
        best = r.get("best_ms")
        dflt = r.get("default_best_ms")
        best_txt = f"{best:.3f}" if isinstance(best, (int, float)) else "-"
        dflt_txt = f"{dflt:.3f}" if isinstance(dflt, (int, float)) else "-"
        parity = r.get("parity") or "?"
        extras = []
        if r.get("alias_of"):
            extras.append(f"alias of {r['alias_of']}")
        if r.get("interpret"):
            extras.append("interpret")
        tail = f"  [{'; '.join(extras)}]" if extras else ""
        lines.append(
            f"    {r['op']:<14} {r['shape']:>16} {r['dtype']:>9} "
            f"{_fmt_config(r.get('config')):>24} {best_txt:>9} "
            f"{dflt_txt:>10} {spd_txt:>8}  {parity}{tail}")


def render_text(rows, counters=None, manifest=None, metrics=None, bench=None,
                health=None, faults=None, churn=None, fleet=None,
                profile=None, quality=None, tuning=None, notes=None):
    lines = []
    if manifest:
        lines.append("run: git %s  backend=%s  feed=%s  created %s" % (
            str(manifest.get("git_rev", "unknown"))[:12],
            manifest.get("backend"), manifest.get("feed_mode"),
            manifest.get("created_utc")))
    for note in notes or ():
        lines.append(f"note: {note}")
    if rows:
        table = [tuple(r[c] for c in _COLS) for r in rows]
        widths = [max([len(_HEADS[i])] +
                      [len("-" if v is None else
                           (f"{v:.3f}" if isinstance(v, float) else str(v)))
                       for v in (row[i] for row in table)])
                  for i in range(len(_COLS))]
        lines.append(_fmt_row(_HEADS, widths))
        for row in table:
            lines.append(_fmt_row(row, widths))
    else:
        lines.append("no span events in trace")
    if counters:
        lines.append("")
        lines.append("counters:")
        for name, c in counters.items():
            extra_bytes = (f"  {c['bytes'] / 1e6:.2f} MB"
                           if "bytes" in c else "")
            lines.append(f"  {name}: count={c['count']} "
                         f"total={c['total_s']:.4f}s{extra_bytes}")
    if metrics:
        lines.append("")
        lines.append("metrics.jsonl join:")
        for k, v in metrics.items():
            lines.append(f"  {k}: {v}")
        stall_m = metrics.get("feed_stall_fraction_mean")
        trace_stall = next((r["total_s"] for r in rows
                            if r["span"] == "feed/wait"), None)
        fit_total = next((r["total_s"] for r in rows
                          if r["span"] == "fit/epoch"), None)
        if stall_m is not None and trace_stall is not None and fit_total:
            lines.append(
                f"  trace-derived stall (feed/wait / fit/epoch): "
                f"{trace_stall / fit_total:.4f} vs FeedStats {stall_m:.4f}")
    if bench:
        lines.append("")
        lines.append("bench h2d reconciliation:")
        for k, v in bench.items():
            lines.append(f"  {k}: {v}")
    if health:
        lines.append("")
        status = health.get("status") or "unknown"
        lines.append(f"model health: {status}")
        if health.get("reason"):
            lines.append(f"  reason: {health['reason']}")
        if health.get("first_bad_step") is not None:
            lines.append(f"  first bad step: {health['first_bad_step']}  "
                         f"(last good: {health.get('last_good_step')})")
        lines.append(f"  loss EMA: {health.get('loss_ema')}  "
                     f"steps recorded: {health.get('n_steps_recorded')}")
        tail = health.get("ring_tail") or []
        if tail:
            lines.append("  ring tail (last recorded steps):")
            for row in tail:
                parts = [f"step={row.get('step')}"]
                parts += [f"{k.split('/')[-1]}={row[k]:.6g}"
                          for k in ("cost", "health/grad_norm",
                                    "health/update_ratio",
                                    "health/nonfinite")
                          if isinstance(row.get(k), float)]
                lines.append("    " + "  ".join(parts))
    if faults:
        lines.append("")
        head = (f"faults/retries: {faults['n_injected']} injected, "
                f"{faults['n_retries']} retried")
        if "plan_seed" in faults:
            head += f"  (chaos plan seed {faults['plan_seed']})"
        lines.append(head)
        for ev in faults["injected"]:
            where = ev.get("site", "?")
            call = ev.get("call")
            loc = f"{where} call {call}" if call else where
            lines.append(f"  injected: {ev.get('kind', '?')} at {loc}"
                         + (f" — {ev['note']}" if ev.get("note") else ""))
        for ev in faults["retries"]:
            lines.append(f"  retry: {ev.get('site', '?')} attempt "
                         f"{ev.get('attempt')}/{ev.get('max_attempts')} "
                         f"after {ev.get('error')}")
        if faults.get("cadence_fallback"):
            lines.append(f"  cadence fallback: {faults['cadence_fallback']}")
    if churn:
        lines.append("")
        head = (f"corpus churn: {churn['n_cycles']} cycles, "
                f"{churn['drift_trips']} drift trips")
        if "version_span" in churn:
            lo, hi = churn["version_span"]
            head += f", versions v{lo}..v{hi}"
        lines.append(head)
        acts = ", ".join(f"{k} x{v}"
                         for k, v in sorted(churn["actions"].items()))
        lines.append(f"  actions: {acts}")
        if "drift_centroid_shift_max" in churn:
            lines.append(
                f"  drift max: centroid shift "
                f"{churn['drift_centroid_shift_max']}  collapse delta "
                f"{churn['drift_collapse_delta_max']}")
        if "swap_p95_ms" in churn:
            lines.append(f"  swap latency: p50 {churn['swap_p50_ms']} ms  "
                         f"p95 {churn['swap_p95_ms']} ms")
        if "encode_articles_per_sec" in churn:
            lines.append("  encode throughput: "
                         f"{churn['encode_articles_per_sec']} articles/s")
        if "oov_fraction_last" in churn:
            lines.append("  vectorizer OOV fraction: "
                         f"{churn['oov_fraction_last']}")
        tail = [f"{k}={churn[k]}" for k in
                ("resident_rows", "corpus_version", "finetunes", "retries")
                if k in churn]
        if tail:
            lines.append("  supervisor: " + "  ".join(tail))
    if fleet:
        lines.append("")
        _render_fleet(fleet, lines)
    if quality:
        lines.append("")
        _render_quality(quality, lines)
    if profile:
        lines.append("")
        _render_profile(profile, lines)
    if tuning:
        lines.append("")
        _render_tuning(tuning, lines)
    return "\n".join(lines)


def report(trace_path, metrics_path=None, bench_path=None, health_path=None,
           churn_path=None, fleet_path=None, profile_path=None,
           quality_path=None, tuning_path=None, as_json=False):
    """Build the report. Returns (text, exit_code).

    The trace is the report's backbone — an unreadable trace still raises
    (the CLI maps it to exit 2). Every OTHER input is optional and degrades
    gracefully: a missing/garbled metrics, bench, or health file becomes a
    `note:` line and its section is skipped, and a trace with zero span
    events renders a partial report as long as some other section loaded
    (empty AND alone stays exit 1).

    `fleet_path` follows the health/churn contract with one refinement:
    None auto-detects `fleet_observability.json` next to the trace and stays
    SILENT when it isn't there (an r12-era run directory renders exactly as
    before); the sentinel "auto" (the CLI's bare `--fleet`) also auto-detects
    but notes the absence, since the section was explicitly asked for.
    `profile_path` (a ProfileDB file, default name `profile_db.json`),
    `quality_path` (a retrieval-quality bundle, default name
    `quality_observability.json`) and `tuning_path` (also a ProfileDB —
    the autotuner's rows render as tuned-vs-default) follow the same
    sentinel contract."""
    trace = load_trace(trace_path)
    rows = span_table(trace)
    meta = trace.get("metadata", {}) or {}
    counters = meta.get("counters") or None
    manifest = meta.get("manifest") if isinstance(meta.get("manifest"), dict) \
        else None
    if manifest is None and isinstance(meta.get("manifest_path"), str):
        try:
            from .manifest import read_manifest

            manifest = read_manifest(meta["manifest_path"])
        except Exception:
            manifest = None

    notes = []

    def optional(path, loader, label):
        if not path:
            return None
        try:
            return loader(path)
        except (OSError, ValueError) as exc:
            notes.append(f"{label} unavailable, section skipped ({exc})")
            return None

    records = optional(metrics_path, load_metrics, "metrics")
    metrics = metrics_summary(records) if records is not None else None
    bench = bench_reconciliation(optional(bench_path, load_bench, "bench"))
    if health_path is None:
        # a traced fit drops health_bundle.json next to trace.json — pick it
        # up without a flag
        cand = os.path.join(os.path.dirname(os.path.abspath(trace_path)),
                            "health_bundle.json")
        health_path = cand if os.path.exists(cand) else None
    health = health_summary(optional(health_path, load_health,
                                     "health bundle"))
    if churn_path is None:
        # a churn supervisor drops churn_history.json next to the trace —
        # same auto-detection contract as the health bundle
        cand = os.path.join(os.path.dirname(os.path.abspath(trace_path)),
                            "churn_history.json")
        churn_path = cand if os.path.exists(cand) else None
    churn = churn_summary(optional(churn_path, load_churn, "churn history"))
    if fleet_path in (None, "auto"):
        cand = os.path.join(os.path.dirname(os.path.abspath(trace_path)),
                            "fleet_observability.json")
        if os.path.exists(cand):
            fleet_path = cand
        elif fleet_path == "auto":
            notes.append("fleet bundle unavailable, section skipped "
                         "(no fleet_observability.json next to trace)")
            fleet_path = None
        else:
            fleet_path = None
    fleet = fleet_summary(optional(fleet_path, load_fleet, "fleet bundle"))
    if profile_path in (None, "auto"):
        cand = os.path.join(os.path.dirname(os.path.abspath(trace_path)),
                            "profile_db.json")
        if os.path.exists(cand):
            profile_path = cand
        elif profile_path == "auto":
            notes.append("profile DB unavailable, section skipped "
                         "(no profile_db.json next to trace)")
            profile_path = None
        else:
            profile_path = None
    profile = profile_summary(optional(profile_path, load_profile,
                                       "profile DB"))
    if quality_path in (None, "auto"):
        cand = os.path.join(os.path.dirname(os.path.abspath(trace_path)),
                            "quality_observability.json")
        if os.path.exists(cand):
            quality_path = cand
        elif quality_path == "auto":
            notes.append("quality bundle unavailable, section skipped "
                         "(no quality_observability.json next to trace)")
            quality_path = None
        else:
            quality_path = None
    quality = quality_summary(optional(quality_path, load_quality,
                                       "quality bundle"))
    if tuning_path in (None, "auto"):
        cand = os.path.join(os.path.dirname(os.path.abspath(trace_path)),
                            "profile_db.json")
        if os.path.exists(cand):
            tuning_path = cand
        elif tuning_path == "auto":
            notes.append("tuning DB unavailable, section skipped "
                         "(no profile_db.json next to trace)")
            tuning_path = None
        else:
            tuning_path = None
    tuning = tuning_summary(optional(tuning_path, load_profile,
                                     "tuning DB"))
    faults = faults_summary(manifest)
    if as_json:
        return json.dumps({"spans": rows, "counters": counters,
                           "manifest": manifest, "metrics": metrics,
                           "bench": bench, "health": health,
                           "faults": faults, "churn": churn,
                           "fleet": fleet, "profile": profile,
                           "quality": quality, "tuning": tuning,
                           "notes": notes or None},
                          indent=2, default=str), 0
    if not rows and not (metrics or bench or health or churn or fleet
                         or profile or quality or tuning):
        return "no span events in trace", 1
    return render_text(rows, counters=counters, manifest=manifest,
                       metrics=metrics, bench=bench, health=health,
                       faults=faults, churn=churn, fleet=fleet,
                       profile=profile, quality=quality, tuning=tuning,
                       notes=notes), 0
