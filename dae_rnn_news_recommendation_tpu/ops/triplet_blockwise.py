"""Blockwise (anchor-tiled) online triplet mining in O(B^2) memory, any backend.

The dense reference (ops/triplet.py) materializes the full [B, B, B] triplet
cube — masks, distances, softplus — which caps the mined batch size long before
the chip runs out of FLOPs (B=8192 would need a 2 TiB f32 cube). These twins
compute the exact same reductions as a `lax.scan` over tiles of the ANCHOR
axis: the working set per step is one [T, B, B] slab of the cube (T = tile of
anchors, default 8), everything carried across steps is O(B). Any backend runs
them — including the CPU tier-1 suite, where they parity-test against the
dense oracle — and at large B they double as the correctness oracle for the
Pallas kernels (ops/pallas_kernels.py), whose VMEM tiling is hardware-only.

Padding strategy: only the ANCHOR axis is padded (to a multiple of the tile),
with padded anchors carrying all-zero masks so they mine nothing. The
positive/negative axes keep their true length B, which sidesteps every
padded-column quirk of the batch_hard reference math (zero-valued invalid
negatives, float-equality tie counting) — those only bite when fake columns
exist, as they do in the Pallas kernels.

Gradients:
  * batch_all carries a custom VJP. Plain autodiff through the scan would
    stack per-step residuals — the [T, B, B] softplus/mask slabs — recreating
    the O(B^3) footprint the scan exists to avoid. The VJP rescans instead:
    only `loss` has a nonzero true gradient (data_weight/fraction/num are
    indicator counts, gradient exactly zero under XLA autodiff of the dense
    oracle), and dL/d(dp) accumulates tile by tile, then dE = (G + G^T) E.
  * batch_hard uses plain autodiff: its per-step compute is min/max/where
    over [T, B] tiles, so the scan's stacked residuals are O(B^2) already,
    and reusing XLA's own min/max subgradients reproduces the dense path's
    tie-breaking exactly.

Return tuples, epsilons, dtypes, and quirks match ops/triplet.py to float
roundoff (tile-order summation differs); tests/test_mining_dispatch.py holds
the parity contract.
"""

import functools

import jax
import jax.numpy as jnp

_EPS = 1e-16

# anchors per scan step: the transient slab is [TILE, B, B] — at B=8192 and
# TILE=8 that is 2 GiB of cube *compute* per step but only O(B^2) live memory
_ANCHOR_TILE = 8


def _pad_rows(x, bp):
    """Pad axis 0 of `x` up to `bp` rows with zeros."""
    b = x.shape[0]
    if bp == b:
        return x
    return jnp.pad(x, ((0, bp - b),) + ((0, 0),) * (x.ndim - 1))


def _prep_batch_all(labels, encode, row_valid, tile):
    """dp + pair masks, anchor axis padded to the tile multiple and reshaped
    to [S, T, B] scan inputs. Mask semantics match triplet_mask exactly:
    a[i,j] = labels eq & i!=j & both valid; bm[i,k] = labels neq & both valid
    (i!=k is implied); the j!=k term is applied per-tile."""
    b = labels.shape[0]
    dtype = encode.dtype
    valid = (jnp.ones(b, bool) if row_valid is None
             else row_valid.astype(bool))
    dp = jnp.matmul(encode, encode.T, precision=jax.lax.Precision.HIGHEST)
    eq = labels[:, None] == labels[None, :]
    vv = valid[:, None] & valid[None, :]
    eye = jnp.eye(b, dtype=bool)
    a = (eq & ~eye & vv).astype(dtype)
    bm = (~eq & vv).astype(dtype)
    neq_jk = (~eye).astype(dtype)

    s = -(-b // tile)
    bp = s * tile
    dp_t = _pad_rows(dp.astype(dtype), bp).reshape(s, tile, b)
    a_t = _pad_rows(a, bp).reshape(s, tile, b)
    bm_t = _pad_rows(bm, bp).reshape(s, tile, b)
    return dp_t, a_t, bm_t, neq_jk, bp


def _tile_mask_dist(dp_t, a_t, bm_t, neq_jk, pos_only):
    """One anchor tile's slab of the cube quantities (the only rank-3 values
    anywhere in this module — [T, B, B], freed every scan step)."""
    # jaxcheck: disable=R8 (anchor-tile slab [T,B,B], T static — this IS the O(B^2) fix; the full cube never exists)
    dist = dp_t[:, None, :] - dp_t[:, :, None]   # d[i,j,k] = dp[i,k]-dp[i,j]
    # jaxcheck: disable=R8 (anchor-tile slab [T,B,B], T static — this IS the O(B^2) fix; the full cube never exists)
    valid3 = a_t[:, :, None] * bm_t[:, None, :] * neq_jk[None, :, :]
    pos3 = (valid3 * dist > _EPS).astype(dp_t.dtype)     # reference :114
    mask = pos3 if pos_only else valid3
    return dist, valid3, pos3, mask


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 4))
def _batch_all_vjp(labels, encode, pos_triplets_only, row_valid, tile):
    out, _ = _batch_all_fwd(labels, encode, pos_triplets_only, row_valid, tile)
    return out


def _batch_all_fwd(labels, encode, pos_triplets_only, row_valid, tile):
    b = labels.shape[0]
    dtype = encode.dtype
    dp_t, a_t, bm_t, neq_jk, _bp = _prep_batch_all(labels, encode, row_valid,
                                                   tile)

    def body(carry, sl):
        s_loss, n_pos, n_valid, as_pos, as_neg = carry
        dp_i, a_i, bm_i = sl
        dist, valid3, pos3, mask = _tile_mask_dist(dp_i, a_i, bm_i, neq_jk,
                                                   pos_triplets_only)
        s_loss = s_loss + jnp.sum(jax.nn.softplus(dist) * mask)
        n_pos = n_pos + jnp.sum(pos3)
        n_valid = n_valid + jnp.sum(valid3)
        as_pos = as_pos + jnp.sum(mask, axis=(0, 2))   # row as positive (j)
        as_neg = as_neg + jnp.sum(mask, axis=(0, 1))   # row as negative (k)
        as_anchor = jnp.sum(mask, axis=(1, 2))         # [T], this tile's rows
        return (s_loss, n_pos, n_valid, as_pos, as_neg), as_anchor

    zero = jnp.zeros((), dtype)
    zeros_b = jnp.zeros((b,), dtype)
    (s_loss, n_pos, n_valid, as_pos, as_neg), aw = jax.lax.scan(
        body, (zero, zero, zero, zeros_b, zeros_b), (dp_t, a_t, bm_t))

    num_sel = n_pos if pos_triplets_only else n_valid
    loss = s_loss / jnp.maximum(num_sel, _EPS)
    data_weight = aw.reshape(-1)[:b] + as_pos + as_neg
    fraction = n_pos / jnp.maximum(n_valid, _EPS)
    out = (loss, data_weight, fraction, n_pos, {})
    residuals = (dp_t, a_t, bm_t, neq_jk, num_sel, encode)
    return out, residuals


def _batch_all_bwd(pos_triplets_only, tile, residuals, cotangents):
    """Rescan for G = dL/d(dp) * num_sel, tile by tile, then the MXU-sized
    dE = (G + G^T) E. Only cotangents[0] (loss) feeds back — every other
    output is a count with true gradient zero (see module docstring)."""
    dp_t, a_t, bm_t, neq_jk, num_sel, encode = residuals
    loss_bar = cotangents[0]
    b = encode.shape[0]

    def body(_, sl):
        dp_i, a_i, bm_i = sl
        dist, _, _, mask = _tile_mask_dist(dp_i, a_i, bm_i, neq_jk,
                                           pos_triplets_only)
        s = jax.nn.sigmoid(dist) * mask                    # [T, B, B]
        # dN/d dp[i,c]: +sum over j where c is the negative, -sum over k
        # where c is the positive (d[i,j,k] = dp[i,k] - dp[i,j])
        g_i = jnp.sum(s, axis=1) - jnp.sum(s, axis=2)      # [T, B]
        return None, g_i

    _, g = jax.lax.scan(body, None, (dp_t, a_t, bm_t))
    g = g.reshape(-1, b)[:b].astype(jnp.float32)
    g = g * (loss_bar / jnp.maximum(num_sel, _EPS)).astype(jnp.float32)
    de = jnp.matmul(g + g.T, encode.astype(jnp.float32),
                    precision=jax.lax.Precision.HIGHEST)
    return None, de.astype(encode.dtype), None


_batch_all_vjp.defvjp(_batch_all_fwd, _batch_all_bwd)


def batch_all_triplet_loss_blockwise(labels, encode, pos_triplets_only=False,
                                     row_valid=None, anchor_tile=_ANCHOR_TILE):
    """Drop-in for ops.triplet.batch_all_triplet_loss in O(B^2) memory.

    Same return tuple: (loss, data_weight[B], fraction_positive, num_positive,
    {}). `anchor_tile` anchors per scan step trade compile-time unrolled slab
    size against scan length; any positive int works (the anchor axis pads up).
    """
    return _batch_all_vjp(labels, encode, bool(pos_triplets_only), row_valid,
                          int(anchor_tile))


def batch_hard_triplet_loss_blockwise(labels, encode, row_valid=None,
                                      anchor_tile=_ANCHOR_TILE):
    """Drop-in for ops.triplet.batch_hard_triplet_loss in O(B^2) memory.

    Scans anchor tiles of the [B, B] dot-product matrix; per-tile math is the
    dense reference verbatim (valid-column row max with its isfinite guard,
    zero-valued invalid negatives in the hardest-negative max, float-equality
    tie counting in data_weight), so plain autodiff through the scan yields
    the dense path's gradients — ties included — with O(B^2) residuals.
    """
    b = labels.shape[0]
    dtype = encode.dtype
    tile = int(anchor_tile)
    valid = (jnp.ones(b, bool) if row_valid is None
             else row_valid.astype(bool))
    validf = valid.astype(dtype)
    dp = jnp.matmul(encode, encode.T, precision=jax.lax.Precision.HIGHEST)

    eq = labels[:, None] == labels[None, :]
    vv = valid[:, None] & valid[None, :]
    eye = jnp.eye(b, dtype=bool)
    mask_ap = (eq & ~eye & vv).astype(dtype)
    mask_an = (~eq & vv).astype(dtype)

    s = -(-b // tile)
    bp = s * tile
    dp_t = _pad_rows(dp.astype(dtype), bp).reshape(s, tile, b)
    ap_t = _pad_rows(mask_ap, bp).reshape(s, tile, b)
    an_t = _pad_rows(mask_an, bp).reshape(s, tile, b)
    va_t = _pad_rows(validf, bp).reshape(s, tile)

    neg_inf = jnp.asarray(-jnp.inf, dtype)

    def body(carry, sl):
        total, s_loss, hit_pos, hit_neg, sum_hp, sum_hn = carry
        dp_i, ap_i, an_i, va_i = sl                        # [T, B] / [T]

        # hardest positive (reference :227-231): shift invalid entries up by
        # the valid-column row max, guarded like the dense path
        max_row = jnp.max(jnp.where(valid[None, :], dp_i, neg_inf),
                          axis=1, keepdims=True)
        max_row = jnp.where(jnp.isfinite(max_row), max_row,
                            jnp.zeros_like(max_row))
        ap_dp = dp_i + max_row * (1.0 - ap_i)
        hardest_pos = jnp.min(ap_dp, axis=1, keepdims=True)   # [T, 1]

        # hardest negative: invalid entries are literal zeros (reference :240)
        hardest_neg = jnp.max(an_i * dp_i, axis=1, keepdims=True)

        dist = jnp.maximum(hardest_neg - hardest_pos, 0.0)
        count = (dist > 0.0).astype(dtype) * va_i[:, None]    # [T, 1]

        # tie-counting participation by exact float equality (reference :251)
        eq_pos = (dp_i == hardest_pos).astype(dtype) * validf[None, :]
        eq_neg = (dp_i == hardest_neg).astype(dtype) * validf[None, :]
        hit_pos = hit_pos + jnp.sum(count * eq_pos, axis=0)   # [B]
        hit_neg = hit_neg + jnp.sum(count * eq_neg, axis=0)

        total = total + jnp.sum(count)
        s_loss = s_loss + jnp.sum(jax.nn.softplus(dist) * count)
        sum_hp = sum_hp + jnp.sum(hardest_pos[:, 0] * va_i)
        sum_hn = sum_hn + jnp.sum(hardest_neg[:, 0] * va_i)
        return (total, s_loss, hit_pos, hit_neg, sum_hp, sum_hn), count[:, 0]

    zero = jnp.zeros((), dtype)
    zeros_b = jnp.zeros((b,), dtype)
    (total, s_loss, hit_pos, hit_neg, sum_hp, sum_hn), counts = jax.lax.scan(
        body, (zero, zero, zeros_b, zeros_b, zero, zero),
        (dp_t, ap_t, an_t, va_t))

    data_weight = counts.reshape(-1)[:b] + hit_pos + hit_neg
    loss = s_loss / jnp.maximum(total, _EPS)
    n_rows = jnp.sum(validf)
    fraction = total / jnp.maximum(n_rows, 1.0)
    extras = {
        "hardest_positive_dotproduct": sum_hp / jnp.maximum(n_rows, 1.0),
        "hardest_negative_dotproduct": sum_hn / jnp.maximum(n_rows, 1.0),
    }
    return loss, data_weight, fraction, total, extras
