"""Compressed CSR wire format: delta + bit-packed indices, quantized values.

The padded-CSR feed ships `(uint16/uint32 indices, float32 values)` pairs —
`K * 6` bytes per article at the default dtypes — across a host→device link
that bench.py measures in the tens of MB/s. This module shrinks the wire:

* **indices** — per row, sorted column indices are delta-encoded (first index
  shipped whole, then gaps) and the gaps bit-packed into int32 words at a
  corpus-static field width `bits ∈ {4, 8, 16, 32}` (a divisor of 32, so a
  word always holds exactly `32 // bits` fields and unpack is pure
  shift/mask — no cross-word fields, the same code path on host numpy, XLA,
  and Mosaic);
* **values** — shipped as `f32` (lossless), `f16`, `i8` (per-row absmax
  linear quantization), or elided entirely in `binary` mode (0/1 corpora,
  the padded-CSR binary convention: `pad_index = n_features`, values None).

The packed layout is *planar*: the `K-1` gap fields are laid out as
`32 // bits` planes of `W = ceil((K-1) / (32 // bits))` fields each, with
plane `l` occupying bit range `[l*bits, (l+1)*bits)` of every word. Unpack
extracts each plane with one logical shift + mask and concatenates planes
along the slot axis — a layout chosen so the Pallas kernel never needs a
gather or an interleaving reshape.

Round-trip contract (tests/test_wire.py): `unpack_wire_host(pack_csr_wire(m))`
is **bitwise identical** to `pad_csr_batch(m)` for `f32` and `binary` modes
(and for `f16` when every value is exactly representable, e.g. 0/1 data).
The jnp unpack matches the host unpack bitwise on CPU, which is what makes a
packed-wire fit reproduce the plain pipelined fit digest-for-digest.

The `WireSpec` carried alongside a packed batch is registered as an empty
pytree node whose *aux data* is the spec itself — it rides inside jitted
batch dicts as a static (hashable) part of the treedef, so one spec means
one compiled program no matter how many batches flow through.
"""

import dataclasses
import functools

import numpy as np

import jax
import jax.numpy as jnp

VALUE_MODES = ("f32", "f16", "i8", "binary")
_WIRE_BITS = (4, 8, 16, 32)


@dataclasses.dataclass(frozen=True)
class WireSpec:
    """Static shape/format metadata for one packed corpus.

    One spec per fit: every batch packed under it unpacks with the same
    compiled program (the spec is jit-static via its pytree registration).
    """

    k: int            # padded slots per row (multiple of the packer's 64)
    bits: int         # gap field width: 4 | 8 | 16 | 32
    mode: str         # "f32" | "f16" | "i8" | "binary"
    n_features: int   # column space (pad_index == n_features in binary mode)
    index_dtype: str  # "uint16" | "uint32" — the unpacked indices dtype

    @property
    def pad_index(self):
        return self.n_features if self.mode == "binary" else 0

    @property
    def fields_per_word(self):
        return 32 // self.bits

    @property
    def words_per_row(self):
        # K-1 gap fields, planar: ceil over fields-per-word
        return -(-(self.k - 1) // self.fields_per_word)

    @property
    def np_index_dtype(self):
        return np.uint16 if self.index_dtype == "uint16" else np.uint32

    def wire_bytes_per_row(self):
        """Bytes one packed row occupies on the wire (indices side: words +
        first + nnz; values side per mode)."""
        n = self.words_per_row * 4 + 4 + 4
        if self.mode == "f32":
            n += self.k * 4
        elif self.mode == "f16":
            n += self.k * 2
        elif self.mode == "i8":
            n += self.k + 4  # int8 codes + per-row f32 scale
        return n


# Empty-children pytree whose aux data IS the spec: jit treats it as part of
# the treedef (static + hashable), so it can ride inside traced batch dicts.
jax.tree_util.register_pytree_node(
    WireSpec, lambda s: ((), s), lambda aux, _: aux)


def _bits_for(max_gap):
    """Smallest divisor-of-32 field width covering `max_gap`."""
    for bits in _WIRE_BITS:
        if max_gap < (1 << bits):
            return bits
    raise ValueError(f"gap {max_gap} does not fit 32 bits")


def _padded_k(k, k_multiple=64):
    return int(max(k_multiple, -(-int(k) // k_multiple) * k_multiple))


def _ensure_sorted_f32(m):
    import scipy.sparse as sp

    m = sp.csr_matrix(m)
    if m.dtype != np.float32:
        m = m.astype(np.float32)
    if not m.has_sorted_indices:
        m = m.copy()
        m.sort_indices()
    return m


def _padded_cols(m, k):
    """[B, k] int64 column matrix + int32 nnz (clipped to k, mirroring the
    packer's truncation) from a sorted CSR."""
    b = m.shape[0]
    nnz = np.minimum(np.diff(m.indptr), k).astype(np.int32)
    pos = np.arange(k)[None, :]
    valid = pos < nnz[:, None]
    idx = np.zeros((b, k), np.int64)
    flat = m.indptr[:-1, None] + pos
    idx[valid] = m.indices[flat[valid]]
    return idx, nnz, valid


def plan_wire(m, k=None, k_multiple=64, mode="f32", index_dtype=np.uint16):
    """Scan a corpus once and fix the static wire format for the whole fit.

    `bits` covers the largest per-row gap anywhere in the corpus, so every
    batch packed under the returned spec is exact. Mirrors pad_csr_batch's
    k rounding and uint16→uint32 promotion rule so the unpacked layout is
    the one the rest of the feed already speaks.
    """
    assert mode in VALUE_MODES, mode
    m = _ensure_sorted_f32(m)
    f = m.shape[1]
    if k is None:
        k = int(np.diff(m.indptr).max(initial=1))
    kk = _padded_k(k, k_multiple)
    binary = mode == "binary"
    if f + (1 if binary else 0) > np.iinfo(index_dtype).max + 1:
        index_dtype = np.uint32
    # largest gap between consecutive in-row columns (row boundaries masked)
    max_gap = 0
    if m.indices.size:
        gaps = np.diff(m.indices.astype(np.int64))
        boundary = np.zeros(gaps.shape[0], bool)
        starts = m.indptr[1:-1]  # position of each row's first element
        boundary[starts[(starts > 0) & (starts <= gaps.shape[0])] - 1] = True
        in_row = gaps[~boundary]
        if in_row.size:
            max_gap = int(in_row.max())
    return WireSpec(k=kk, bits=_bits_for(max_gap), mode=mode,
                    n_features=int(f),
                    index_dtype=np.dtype(index_dtype).name)


def pack_csr_wire(m, spec=None, k=None, k_multiple=64, mode="f32",
                  index_dtype=np.uint16):
    """Pack a CSR block into the wire format.

    Returns `{"words", "first", "nnz", "values"?, "scale"?, "spec"}` — every
    array leading-dim B so bucket padding and device placement treat a packed
    batch like any other. Pass `spec` (from plan_wire) when packing batches
    of a larger corpus; otherwise a per-call spec is derived.
    """
    m = _ensure_sorted_f32(m)
    if spec is None:
        spec = plan_wire(m, k=k, k_multiple=k_multiple, mode=mode,
                         index_dtype=index_dtype)
    b = m.shape[0]
    kk = spec.k
    idx, nnz, valid = _padded_cols(m, kk)

    gaps = np.diff(idx, axis=1)
    gaps[~valid[:, 1:]] = 0
    if gaps.size and (gaps.min() < 0 or gaps.max() >= (1 << spec.bits)):
        raise ValueError(
            f"row gaps outside the spec's {spec.bits}-bit field "
            f"(min {gaps.min()}, max {gaps.max()}): corpus does not match "
            "the plan_wire spec (unsorted rows or a different corpus?)")

    fpw = spec.fields_per_word
    w = spec.words_per_row
    planes = np.zeros((b, fpw, w), np.uint32)
    flat = planes.reshape(b, fpw * w)
    flat[:, : kk - 1] = gaps.astype(np.uint32)
    words = np.zeros((b, w), np.uint32)
    for l in range(fpw):
        words |= planes[:, l, :] << np.uint32(l * spec.bits)

    first = np.where(nnz > 0, idx[:, 0], 0).astype(np.int32)
    out = {"words": words.view(np.int32), "first": first, "nnz": nnz,
           "spec": spec}
    if spec.mode != "binary":
        vals = np.zeros((b, kk), np.float32)
        pos = np.arange(kk)[None, :]
        flatv = m.indptr[:-1, None] + pos
        vals[valid] = m.data[flatv[valid]]
        if spec.mode == "f32":
            out["values"] = vals
        elif spec.mode == "f16":
            out["values"] = vals.astype(np.float16)
        else:  # i8: per-row absmax linear quantization
            absmax = np.abs(vals).max(axis=1)
            scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
            out["values"] = np.rint(vals / scale[:, None]).astype(np.int8)
            out["scale"] = scale
    return out


def wire_nbytes(wire):
    """Total wire bytes of one packed batch (arrays only, spec excluded)."""
    return int(sum(v.nbytes for k, v in wire.items()
                   if k != "spec" and hasattr(v, "nbytes")))


def wire_bytes_per_article(wire):
    b = wire["nnz"].shape[0]
    return wire_nbytes(wire) / max(1, b)


# ----------------------------------------------------------------- unpack


def _dequantize_jnp(spec, values, scale):
    if spec.mode == "binary":
        return None
    if spec.mode == "f32":
        return values
    if spec.mode == "f16":
        return values.astype(jnp.float32)
    return values.astype(jnp.float32) * scale[:, None]


def unpack_wire_jnp(words, first, nnz, spec, values=None, scale=None):
    """Pure-jnp unpack: packed words → padded `(indices, values)`.

    Trace-compatible (spec is static), bitwise-identical to
    `unpack_wire_host` — the reference the Pallas kernel is tested against.
    """
    bits = spec.bits
    if bits == 32:
        planes = [words]
    else:
        mask = jnp.int32((1 << bits) - 1)
        planes = [jax.lax.shift_right_logical(words, jnp.int32(l * bits)) & mask
                  for l in range(spec.fields_per_word)]
    gaps = jnp.concatenate(planes, axis=1)[:, : spec.k - 1]
    base = first[:, None].astype(jnp.int32)
    idx = jnp.concatenate(
        [base, base + jnp.cumsum(gaps, axis=1, dtype=jnp.int32)], axis=1)
    slot = jnp.arange(spec.k, dtype=jnp.int32)[None, :]
    valid = slot < nnz[:, None]
    indices = jnp.where(valid, idx, jnp.int32(spec.pad_index))
    indices = indices.astype(spec.np_index_dtype)
    return indices, _dequantize_jnp(spec, values, scale)


def unpack_wire_host(wire):
    """Host (numpy) unpack of a packed batch: returns the exact
    `{"indices", "values", "k"}` dict pad_csr_batch would have produced."""
    spec = wire["spec"]
    words = wire["words"].view(np.uint32)
    bits = spec.bits
    if bits == 32:
        planes = [words]
    else:
        mask = np.uint32((1 << bits) - 1)
        planes = [(words >> np.uint32(l * bits)) & mask
                  for l in range(spec.fields_per_word)]
    gaps = np.concatenate(planes, axis=1)[:, : spec.k - 1].astype(np.int32)
    base = wire["first"][:, None].astype(np.int32)
    idx = np.concatenate(
        [base, base + np.cumsum(gaps, axis=1, dtype=np.int32)], axis=1)
    slot = np.arange(spec.k, dtype=np.int32)[None, :]
    valid = slot < wire["nnz"][:, None]
    indices = np.where(valid, idx, spec.pad_index).astype(spec.np_index_dtype)
    if spec.mode == "binary":
        values = None
    elif spec.mode == "f32":
        values = wire["values"]
    elif spec.mode == "f16":
        values = wire["values"].astype(np.float32)
    else:
        values = (wire["values"].astype(np.float32)
                  * wire["scale"][:, None]).astype(np.float32)
    return {"indices": indices, "values": values, "k": spec.k}


# ----------------------------------------------------- Pallas unpack kernel


def _on_tpu():
    return jax.default_backend() == "tpu"


def _lane_pad(n):
    return int(-(-n // 128) * 128)


@functools.partial(jax.jit,
                   static_argnames=("spec", "block_rows", "interpret"))
def _unpack_pallas_call(words, first, nnz, spec, block_rows, interpret):
    # import-light at module level (mirrors ops/__init__'s lazy pallas
    # policy): the experimental API loads only when the kernel path runs
    from jax.experimental import pallas as pl

    b = words.shape[0]
    w_real = spec.words_per_row
    w_pad = _lane_pad(w_real)
    fpw = spec.fields_per_word
    bits = spec.bits
    pad_index = spec.pad_index
    rows = block_rows
    bp = int(-(-b // rows) * rows)
    if bp != b or w_pad != w_real:
        words = jnp.pad(words, ((0, bp - b), (0, w_pad - w_real)))
    first2 = jnp.pad(first.reshape(-1, 1), ((0, bp - b), (0, 0)))
    nnz2 = jnp.pad(nnz.reshape(-1, 1), ((0, bp - b), (0, 0)))
    tri = jnp.triu(jnp.ones((w_pad, w_pad), jnp.float32))

    def kernel(words_ref, first_ref, nnz_ref, tri_ref, idx_ref):
        """One row-block of the unpack: extract each bit plane with a
        logical shift + mask, turn it into in-plane prefix sums on the MXU
        (gap counts are small ints — exact in f32 well past any uint16
        column space), carry plane totals forward, and write the
        padded/masked indices for slots 1..K-1. Slot 0 (the whole `first`
        index) is prepended by the wrapper — keeping every lane write here
        at a plane-aligned static offset."""
        wds = words_ref[:]                       # [R, Wp] int32 packed words
        fst = first_ref[:].astype(jnp.float32)   # [R, 1]
        nz = nnz_ref[:]                          # [R, 1] int32
        tr = tri_ref[:]                          # [Wp, Wp] upper-tri (incl diag)
        mask = jnp.int32((1 << bits) - 1) if bits < 32 else None
        carry = jnp.zeros_like(fst)              # sum of earlier planes
        for l in range(fpw):
            plane = (jax.lax.shift_right_logical(wds, jnp.int32(l * bits))
                     & mask if mask is not None else wds)
            planef = plane.astype(jnp.float32)   # [R, Wp]; zero in pad lanes
            prefix = jnp.dot(planef, tr, preferred_element_type=jnp.float32)
            idx = fst + carry + prefix           # slot l*w_real + lane + 1
            carry = carry + jnp.sum(planef, axis=1, keepdims=True)
            # slot per lane (lanes >= w_real are padding the wrapper drops)
            lane = jax.lax.broadcasted_iota(jnp.int32, planef.shape, 1)
            slot = lane + jnp.int32(l * w_real + 1)
            out = jnp.where(slot < nz, idx,
                            jnp.float32(pad_index)).astype(jnp.int32)
            idx_ref[:, pl.ds(l * w_pad, w_pad)] = out

    cols = pl.pallas_call(
        kernel,
        grid=(bp // rows,),
        in_specs=[
            pl.BlockSpec((rows, w_pad), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((w_pad, w_pad), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows, fpw * w_pad), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, fpw * w_pad), jnp.int32),
        interpret=interpret,
    )(words, first2, nnz2, tri)
    # drop row padding + per-plane lane padding, keep the first K-1 slots
    planes = [cols[:b, l * w_pad: l * w_pad + w_real] for l in range(fpw)]
    tail = jnp.concatenate(planes, axis=1)[:, : spec.k - 1]
    col0 = jnp.where(nnz.reshape(-1, 1) > 0, first.reshape(-1, 1).astype(jnp.int32),
                     jnp.int32(spec.pad_index))
    return jnp.concatenate([col0, tail], axis=1)


def unpack_wire_pallas(words, first, nnz, spec, values=None, scale=None,
                       block_rows=None, interpret=None):
    """Pallas-kernel unpack (interpret mode off-TPU). Exactness bound: the
    in-kernel prefix sums run on the MXU in f32, exact while every column
    index < 2**24 — `unpack_wire` auto-routes wider corpora to the jnp path.

    :param block_rows: rows per grid step (%8); None resolves through the
        autotuner cache (tuned row for this batch/width/device if one
        exists, tile_defaults.WIRE_UNPACK_BLOCK_ROWS otherwise)
    """
    if interpret is None:
        interpret = not _on_tpu()
    assert spec.n_features < (1 << 24), (
        "Pallas unpack is exact only for n_features < 2**24; use the jnp path")
    if block_rows is None:
        from .. import tuning  # lazy: ops must import without the cache

        cfg, _ = tuning.resolve(
            "wire_unpack", (words.shape[0], spec.words_per_row), "int32")
        block_rows = cfg["block_rows"]
    if block_rows % 8 != 0 or block_rows < 8:
        raise ValueError(f"block_rows must be a positive multiple of 8, "
                         f"got {block_rows}")
    indices = _unpack_pallas_call(words, first, nnz, spec, int(block_rows),
                                  bool(interpret))
    return (indices.astype(spec.np_index_dtype),
            _dequantize_jnp(spec, values, scale))


def unpack_wire(words, first, nnz, spec, values=None, scale=None, impl="auto"):
    """Device-side unpack dispatch, callable inside a jitted step.

    impl="auto" takes the Pallas kernel on TPU (where the feed's decode
    belongs on-chip next to the consumer) and the jnp path elsewhere —
    including any corpus too wide for the kernel's f32-exactness bound.
    """
    if impl == "auto":
        impl = ("pallas" if _on_tpu() and spec.n_features < (1 << 24)
                else "jnp")
    if impl == "pallas":
        return unpack_wire_pallas(words, first, nnz, spec, values=values,
                                  scale=scale)
    return unpack_wire_jnp(words, first, nnz, spec, values=values, scale=scale)
