"""Online triplet mining over dot-product similarity, plus the precomputed-triplet loss.

Division guards use maximum(x, eps) rather than the reference's x + eps: identical
values in float32 (counts are integers or 0), but immune to XLA reassociating the
guard away inside fusions (see ops/losses.py).

Twin of reference autoencoder/triplet_loss_utils.py — same semantics, rebuilt for XLA:

  - mining runs on the *encoded* batch [B, D] (D = n_components, small), so the B^2
    dot-product matrix and the batch_all B^3 mask tensor live comfortably in HBM for
    typical B; for data-parallel global mining the [B, D] embeddings are all_gathered
    over the mesh (cheap on ICI) before mining — see parallel/.
  - similarity is the raw dot product (NOT euclidean distance): a triplet's "distance"
    is  d(a,p,n) = -dot(a,p) + dot(a,n)  and the loss is softplus(d) = -log_sigmoid(-d)
    (reference :106, :126, :256).
  - all epsilons (1e-16) and normalizations match the reference exactly so the NumPy
    oracle tests (tests/test_triplet.py, modeled on the reference's
    autoencoder/tests/test_triplet_loss_utils.py) agree to float tolerance.
  - every function takes an optional `row_valid` mask so padded batches (XLA static
    shapes) mine zero triplets from padding without changing the unpadded math. This is
    net-new vs the reference, which fed ragged final batches.

Returns follow the reference tuple: (loss, data_weight, fraction_positive, num_triplets)
(batch_all :131, batch_hard :259). `data_weight` counts each row's participation as
anchor + positive + negative and re-weights the reconstruction loss (the repo's main
novelty, SURVEY.md capability 5). `extras` carries the hardest pos/neg dot products the
reference exports as TF summaries (:232, :244).
"""

import jax
import jax.numpy as jnp

_EPS = 1e-16


def _as_valid(labels, row_valid):
    if row_valid is None:
        return jnp.ones(labels.shape[0], dtype=bool)
    return row_valid.astype(bool)


def anchor_positive_mask(labels, row_valid=None):
    """mask[a,p] True iff a != p, labels equal, both rows valid (reference :6-26)."""
    valid = _as_valid(labels, row_valid)
    b = labels.shape[0]
    not_eye = ~jnp.eye(b, dtype=bool)
    label_eq = labels[None, :] == labels[:, None]
    return not_eye & label_eq & valid[:, None] & valid[None, :]


def anchor_negative_mask(labels, row_valid=None):
    """mask[a,n] True iff labels differ, both rows valid (reference :29-44)."""
    valid = _as_valid(labels, row_valid)
    label_eq = labels[None, :] == labels[:, None]
    return (~label_eq) & valid[:, None] & valid[None, :]


def triplet_mask(labels, row_valid=None):
    """mask[a,p,n] True iff a,p,n distinct, label[a]==label[p]!=label[n], all valid
    (reference :47-76)."""
    valid = _as_valid(labels, row_valid)
    b = labels.shape[0]
    not_eye = ~jnp.eye(b, dtype=bool)
    i_ne_j = not_eye[:, :, None]
    i_ne_k = not_eye[:, None, :]
    j_ne_k = not_eye[None, :, :]
    # jaxcheck: disable=R8 (dense reference oracle — O(B^3) by design; auto-dispatch routes B>1024 to blockwise/pallas)
    distinct = i_ne_j & i_ne_k & j_ne_k

    label_eq = labels[None, :] == labels[:, None]
    i_eq_j = label_eq[:, :, None]
    i_eq_k = label_eq[:, None, :]
    # jaxcheck: disable=R8 (dense reference oracle — O(B^3) by design; auto-dispatch routes B>1024 to blockwise/pallas)
    valid_labels = i_eq_j & (~i_eq_k)

    # jaxcheck: disable=R8 (dense reference oracle — O(B^3) by design; auto-dispatch routes B>1024 to blockwise/pallas)
    all_valid = valid[:, None, None] & valid[None, :, None] & valid[None, None, :]
    return distinct & valid_labels & all_valid


def batch_all_triplet_loss(labels, encode, pos_triplets_only=False, row_valid=None):
    """Mine ALL valid triplets in the batch; average softplus loss over them.

    Twin of reference triplet_loss_utils.py:79-131.

    :param labels: [B] int labels
    :param encode: [B, D] embeddings
    :param pos_triplets_only: average over positive-loss triplets only (reference :118)
    :return: (loss, data_weight[B], fraction_positive, num_positive, extras_dict)
    """
    dtype = encode.dtype
    # dot-product similarity; keep full precision — mining decisions and the 1e-4
    # loss-parity target are sensitive to bf16 rounding on TPU.
    dp = jnp.matmul(encode, encode.T, precision=jax.lax.Precision.HIGHEST)

    # d[i,j,k] = -dp(anchor=i, pos=j) + dp(anchor=i, neg=k)   (reference :96-106)
    # jaxcheck: disable=R8 (dense reference oracle — O(B^3) by design; auto-dispatch routes B>1024 to blockwise/pallas)
    dist = -dp[:, :, None] + dp[:, None, :]

    valid_mask = triplet_mask(labels, row_valid).astype(dtype)
    num_valid = jnp.sum(valid_mask)

    pos_mask = (valid_mask * dist > _EPS).astype(dtype)  # reference :114
    num_pos = jnp.sum(pos_mask)

    if pos_triplets_only:
        mask, num = pos_mask, num_pos
    else:
        mask, num = valid_mask, num_valid

    # -log_sigmoid(-d) == softplus(d)  (reference :126)
    loss = jnp.sum(jax.nn.softplus(dist) * mask) / jnp.maximum(num, _EPS)

    # participation count: as anchor + as negative + as positive  (reference :129)
    data_weight = (
        jnp.sum(mask, axis=(1, 2)) + jnp.sum(mask, axis=(0, 1)) + jnp.sum(mask, axis=(0, 2))
    )

    fraction = num_pos / jnp.maximum(num_valid, _EPS)
    return loss, data_weight, fraction, num_pos, {}


def batch_hard_triplet_loss(labels, encode, row_valid=None):
    """For each anchor mine the hardest positive (smallest dot) and hardest negative
    (largest dot); softplus loss over anchors with a violating hard triplet.

    Twin of reference triplet_loss_utils.py:202-259, including its quirks:
      - invalid negatives enter the hardest-negative max as literal zeros
        (mask * dp, reference :240) rather than -inf;
      - data_weight finds the hardest pos/neg columns by exact float equality
        (reference :251-253), double-counting ties.

    :return: (loss, data_weight[B], fraction, num_triplets, extras_dict) where extras
        has 'hardest_positive_dotproduct'/'hardest_negative_dotproduct' means
        (the reference's TF summaries, :232, :244).
    """
    dtype = encode.dtype
    valid = _as_valid(labels, row_valid)
    validf = valid.astype(dtype)
    dp = jnp.matmul(encode, encode.T, precision=jax.lax.Precision.HIGHEST)

    # hardest positive: min over valid positives, after shifting invalid entries up by
    # the row max (reference :227-231). Row max over valid columns only, so padding
    # can't perturb the shift (equals the reference's full-row max when unpadded).
    mask_ap = anchor_positive_mask(labels, row_valid).astype(dtype)
    neg_inf = jnp.asarray(-jnp.inf, dtype)
    max_row = jnp.max(jnp.where(valid[None, :], dp, neg_inf), axis=1, keepdims=True)
    max_row = jnp.where(jnp.isfinite(max_row), max_row, jnp.zeros_like(max_row))
    ap_dp = dp + max_row * (1.0 - mask_ap)
    hardest_pos = jnp.min(ap_dp, axis=1, keepdims=True)

    # hardest negative: max over mask*dp — invalid entries are zeros, as in reference :240
    mask_an = anchor_negative_mask(labels, row_valid).astype(dtype)
    an_dp = mask_an * dp
    hardest_neg = jnp.max(an_dp, axis=1, keepdims=True)

    dist = jnp.maximum(hardest_neg - hardest_pos, 0.0)  # [B,1]
    count = (dist > 0.0).astype(dtype) * validf[:, None]  # [B,1]

    # participation: anchor + hardest-pos hits + hardest-neg hits (reference :251-253);
    # padded columns gated so dp==0 can't spuriously match.
    eq_pos = (dp == hardest_pos).astype(dtype) * validf[None, :]
    eq_neg = (dp == hardest_neg).astype(dtype) * validf[None, :]
    data_weight = (
        jnp.squeeze(count, axis=1)
        + jnp.sum(count * eq_pos, axis=0)
        + jnp.sum(count * eq_neg, axis=0)
    )

    total = jnp.sum(count)
    loss = jnp.sum(jax.nn.softplus(dist) * count) / jnp.maximum(total, _EPS)
    n_rows = jnp.sum(validf)
    fraction = total / jnp.maximum(n_rows, 1.0)

    extras = {
        "hardest_positive_dotproduct": jnp.sum(hardest_pos[:, 0] * validf) / jnp.maximum(n_rows, 1.0),
        "hardest_negative_dotproduct": jnp.sum(hardest_neg[:, 0] * validf) / jnp.maximum(n_rows, 1.0),
    }
    return loss, data_weight, fraction, total, extras


def precomputed_triplet_loss(encode, encode_pos, encode_neg, row_valid=None):
    """Triplet loss over precomputed anchor/pos/neg encodings.

    Twin of reference autoencoder_triplet.py:308-311:
        mean(-log_sigmoid(sum(enc*enc_pos - enc*enc_neg, axis=1)))
    = mean(softplus(-(dot(a,p) - dot(a,n)))).
    """
    margin = jnp.sum(encode * encode_pos - encode * encode_neg, axis=1)
    per_row = jax.nn.softplus(-margin)
    if row_valid is None:
        return jnp.mean(per_row)
    v = row_valid.astype(per_row.dtype)
    return jnp.sum(per_row * v) / jnp.maximum(jnp.sum(v), _EPS)
