"""Fused cosine->top-k: score a corpus panel-by-panel in VMEM, never HBM.

The r07 serving graph answered a microbatch with `h @ emb.T` followed by
`lax.top_k` — which materializes the full [B, N] score matrix in HBM (N is
the corpus; at paper scale that intermediate dwarfs the embeddings it was
computed from, the exact assumed-dense tensor PAPERS.md's Sparton/densifying
papers warn about). This kernel streams the [N_pad, D] corpus through VMEM in
[block, D] panels and carries a per-query top-k accumulator across panels:

  grid (B_pad/bq, N_pad/block), panel axis INNERMOST — compiled Pallas TPU
  only guarantees an output block survives across CONSECUTIVE same-index grid
  steps (see ops/pallas_kernels.py's bwd kernels for the probed rule), and the
  accumulator is exactly such an output block, revisited once per panel.

Per step: one [bq, D] x [D, block] MXU dot (f32 accumulation forced via
`preferred_element_type` whatever the corpus dtype — bf16 and int8 panels are
dequantized in VMEM, int8 by a per-row scale vector), invalid rows masked to
-inf, then k unrolled selection steps merge the panel into the accumulator.
Each selection extracts the (max score, lowest index achieving it) pair from
the union of accumulator and panel and retires it — reproducing
`lax.top_k`'s exact ordering contract (descending value, ties broken by
ascending index), which the parity tests pin score-bitwise and index-exact.
No sort, no concat: just max/min lane reductions and lane-iota selects, the
shapes Mosaic is known to lower (everything >=2D, reductions keepdims).

Only the accumulator [B_pad, 128] x2 ever returns to HBM: bytes moved per
query drop from `N*D*itemsize + 2*N*4` (score matrix out + back through
top_k) to `N*D*itemsize / B` amortized panel traffic (bench.py records the
roofline under `serve_roofline`).

Off-TPU `topk_fused` routes to a jnp fallback that IS
`lax.top_k(masked scores)` — bitwise the oracle by construction — while
`impl="pallas"` + interpret mode exercises the kernel's own selection logic
on CPU (tests/test_topk_fused.py).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import _shard_map
from .tile_defaults import TOPK_FUSED_PANEL as DEFAULT_PANEL
from .tile_defaults import topk_fused_default_bq

# accumulator lane width: one lane tile; k must fit in it (serving k is ~5-10)
_ACC_LANES = 128

# "no entry here": larger than any real corpus index, so consumed/empty slots
# lose every min-index tie-break
_IDX_SENTINEL = np.iinfo(np.int32).max


def _on_tpu():
    return jax.default_backend() == "tpu"


def _topk_kernel(q_ref, e_ref, v_ref, s_ref, os_ref, oi_ref, *, k, bq, block):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        os_ref[:] = jnp.full((bq, _ACC_LANES), -jnp.inf, jnp.float32)
        oi_ref[:] = jnp.full((bq, _ACC_LANES), _IDX_SENTINEL, jnp.int32)

    q = q_ref[:]                                    # [bq, D] f32 queries
    panel = e_ref[:].astype(jnp.float32)            # [block, D] dequant to f32
    ps = jax.lax.dot_general(q, panel, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ps = ps * s_ref[:]                              # per-row int8 scale (ones
    ps = jnp.where(v_ref[:] > 0, ps, -jnp.inf)      # otherwise: bitwise no-op)
    # invalid rows keep their REAL index: lax.top_k breaks -inf ties by
    # ascending index over the whole masked row, and so must we
    pidx = jax.lax.broadcasted_iota(jnp.int32, (bq, block), 1) + j * block

    acc_s, acc_i = os_ref[:], oi_ref[:]
    lane = jax.lax.broadcasted_iota(jnp.int32, (bq, _ACC_LANES), 1)
    new_s = jnp.full((bq, _ACC_LANES), -jnp.inf, jnp.float32)
    new_i = jnp.full((bq, _ACC_LANES), _IDX_SENTINEL, jnp.int32)
    for t in range(k):  # k static selection steps, unrolled
        m = jnp.maximum(jnp.max(acc_s, axis=1, keepdims=True),
                        jnp.max(ps, axis=1, keepdims=True))        # [bq, 1]
        sel = jnp.minimum(                          # lowest index achieving m
            jnp.min(jnp.where(acc_s == m, acc_i, _IDX_SENTINEL),
                    axis=1, keepdims=True),
            jnp.min(jnp.where(ps == m, pidx, _IDX_SENTINEL),
                    axis=1, keepdims=True))                        # [bq, 1]
        new_s = jnp.where(lane == t, m, new_s)
        new_i = jnp.where(lane == t, sel, new_i)
        # retire the selected entry from whichever side held it (indices are
        # globally unique, so exactly one slot matches)
        acc_s = jnp.where(acc_i == sel, -jnp.inf, acc_s)
        acc_i = jnp.where(acc_i == sel, _IDX_SENTINEL, acc_i)
        ps = jnp.where(pidx == sel, -jnp.inf, ps)
        pidx = jnp.where(pidx == sel, _IDX_SENTINEL, pidx)
    os_ref[:] = new_s
    oi_ref[:] = new_i


@functools.partial(jax.jit, static_argnames=("k", "block", "bq", "interpret"))
def _topk_pallas(queries, emb, valid, scales, k, block, bq, interpret):
    b, d = queries.shape
    n = emb.shape[0]
    bp = -(-b // bq) * bq
    dp = -(-d // 128) * 128
    n_pad = -(-n // block) * block
    # zero-padding is inert: pad lanes contribute 0 to every dot, pad corpus
    # rows are valid=0 (-inf, and their indices exceed every real row's, so
    # they lose all -inf ties to real rows — parity holds on the caller's N)
    q = jnp.pad(queries.astype(jnp.float32), ((0, bp - b), (0, dp - d)))
    e = jnp.pad(emb, ((0, n_pad - n), (0, dp - d)))
    v = jnp.pad(valid.astype(jnp.float32), (0, n_pad - n)).reshape(1, n_pad)
    s = jnp.pad(scales.astype(jnp.float32), (0, n_pad - n),
                constant_values=1.0).reshape(1, n_pad)
    kernel = functools.partial(_topk_kernel, k=k, bq=bq, block=block)
    out_s, out_i = pl.pallas_call(
        kernel,
        grid=(bp // bq, n_pad // block),   # panel axis innermost: consecutive
        in_specs=[                         # revisits of the accumulator block
            pl.BlockSpec((bq, dp), lambda i, j: (i, 0)),
            pl.BlockSpec((block, dp), lambda i, j: (j, 0)),
            pl.BlockSpec((1, block), lambda i, j: (0, j)),
            pl.BlockSpec((1, block), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((bq, _ACC_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bq, _ACC_LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, _ACC_LANES), jnp.float32),
            jax.ShapeDtypeStruct((bp, _ACC_LANES), jnp.int32),
        ],
        interpret=interpret,
    )(q, e, v, s)
    return out_s[:b, :k], out_i[:b, :k]


def _topk_reference(queries, emb, valid, k, scales=None):
    """The oracle the kernel must match: masked scores -> `lax.top_k`.

    Also the off-TPU serving path. f32 accumulation is forced the same way
    the kernel forces it (dequantize, then `preferred_element_type`), and the
    int8 scale multiplies the SCORES (post-dot), bitwise-matching the kernel's
    `(q . row_int8) * scale` order.
    """
    embf = emb.astype(jnp.float32)
    scores = jax.lax.dot_general(queries.astype(jnp.float32), embf,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    if scales is not None:
        scores = scores * scales[None, :].astype(jnp.float32)
    scores = jnp.where(valid[None, :] > 0, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def topk_fused(queries, emb, valid, k, *, scales=None, block=None,
               bq=None, impl=None, interpret=None):
    """Top-k cosine matches of each query against a resident corpus.

    :param queries: [B, D] float32, unit-normalized upstream
    :param emb: [N, D] corpus embeddings — float32, bfloat16 or int8
    :param valid: [N] mask; rows with valid <= 0 score -inf (but keep their
        index for `lax.top_k`-exact -inf tie ordering)
    :param k: static; output is ([B, k] f32 scores, [B, k] int32 indices),
        descending score, ties broken by ascending index — `lax.top_k`'s
        contract exactly
    :param scales: [N] f32 per-row dequant scales (int8 corpus), else None
    :param block: corpus rows per VMEM panel (multiple of 128); None
        resolves through the autotuner cache (tuned row for this
        shape/dtype/device if one exists, tile_defaults otherwise)
    :param impl: "pallas" | "jnp" | None (None: pallas on TPU, jnp elsewhere)
    :param interpret: Pallas interpreter mode; None = not on TPU
    """
    k = int(k)
    n = emb.shape[0]
    if not 1 <= k <= n:
        raise ValueError(f"k={k} outside [1, N={n}]")
    if impl is None:
        impl = "pallas" if _on_tpu() else "jnp"
    if impl == "pallas" and k > _ACC_LANES:
        impl = "jnp"   # the accumulator holds k lanes; huge k is top_k's game
    if impl == "pallas" and (block is None or bq is None):
        from .. import tuning  # lazy: ops must import without the cache

        cfg, _ = tuning.resolve(
            "topk_fused", (queries.shape[0], n, emb.shape[1], k), emb.dtype)
        block = cfg["block"] if block is None else block
        bq = cfg["bq"] if bq is None else bq
    if impl == "pallas" and k > block:
        impl = "jnp"   # a panel must hold k candidate rows
    if impl == "jnp":
        with jax.named_scope(f"ops/topk_fused_jnp_k{k}"):
            return _topk_reference(queries, emb, valid, k, scales)
    if block % 128 != 0:
        raise ValueError(f"block={block} must be a multiple of 128")
    if interpret is None:
        interpret = not _on_tpu()
    if bq is None:
        bq = topk_fused_default_bq(queries.shape[0])
    if scales is None:
        scales = jnp.ones((n,), jnp.float32)
    # trace-time label only (host-side wrapper — never inside the kernel
    # body): trace spans attribute the pallas_call to this op by name
    with jax.named_scope(f"ops/topk_fused_k{k}"):
        return _topk_pallas(queries, emb, valid, scales, k=k, block=block,
                            bq=bq, interpret=interpret)


def topk_sharded(queries, emb, valid, k, *, mesh, axis_name="data",
                 scales=None, impl=None, interpret=None):
    """`topk_fused` over a ROW-SHARDED corpus: shard-local fused top-k, then
    one axis-offset k-way merge.

    Each device runs the fused kernel over its local rows, local indices are
    offset by `axis_index * shard_rows` to global, and the gathered
    [B, n_dev*k] candidates collapse through one final `lax.top_k` whose
    positional tie-break — device-major, slot-minor — IS ascending global
    index order (shard i holds the contiguous row span [i*rows, (i+1)*rows)),
    so scores and indices match the single-device call (scores to fp32 merge
    roundoff, indices exactly).

    :param emb/valid/scales: placed with `parallel.mesh.shard_rows` (N_pad
        divisible by the mesh size; shard rows must stay >= k)
    """
    k = int(k)
    n_pad = emb.shape[0]
    n_dev = int(mesh.shape[axis_name])
    assert n_pad % n_dev == 0, f"N_pad={n_pad} not divisible by {n_dev}"
    assert n_pad // n_dev >= k, f"shard rows {n_pad // n_dev} < k={k}"
    if scales is None:
        scales = jnp.ones((n_pad,), jnp.float32)

    def local(emb_l, valid_l, scales_l, h_l):
        s, i = topk_fused(h_l, emb_l, valid_l, k, scales=scales_l, impl=impl,
                          interpret=interpret)
        return s, i + jax.lax.axis_index(axis_name) * emb_l.shape[0]

    s_cat, i_cat = _shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name), P(axis_name),
                  P(None, None)),
        out_specs=(P(None, axis_name), P(None, axis_name)),
        check_rep=False)(  # pallas_call has no replication rule
            emb, valid, scales, queries)
    with jax.named_scope(f"ops/topk_sharded_merge_k{k}"):
        s_top, pos = jax.lax.top_k(s_cat, k)     # [B, n_dev*k] -> [B, k]
        return s_top, jnp.take_along_axis(i_cat, pos, axis=1)
