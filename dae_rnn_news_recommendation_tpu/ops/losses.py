"""Reconstruction losses and triplet-participation re-weighting.

Twin of reference autoencoder/triplet_loss_utils.py:262-277 (weighted_loss): per-row
reconstruction loss (cross-entropy / mean-squared / cosine-proximity) re-weighted by a
per-row weight (triplet participation count under online mining; ones otherwise), with
the reference's exact epsilons and normalization:

    loss = sum_r(per_row_loss[r] * w[r]) / (sum_r w[r] + 1e-16)

TPU notes: rows are dense [B, F] tiles (sparse inputs are densified into padded shards
on host — TPUs want dense MXU tiles, not scatter/gather); a `row_valid` mask makes
padded rows contribute exactly zero to both numerator and denominator, so padded batches
keep XLA shapes static without changing the math.
"""

import jax.numpy as jnp

from .normalize import l2_normalize as _l2_normalize

LOSS_FUNCS = ("cross_entropy", "mean_squared", "cosine_proximity")

_EPS = 1e-16


def reconstruction_loss_per_row(x, decode, loss_func="cross_entropy"):
    """Per-row reconstruction loss [B] (reference triplet_loss_utils.py:268-273).

    The reference guards the logs with `+ 1e-16`; under XLA fusion that guard can be
    reassociated away ((1 - d) + eps -> (1 + eps) - d == 0 when d == 1), yielding
    0 * log(0) = NaN — so we clip instead, which is reassociation-proof and
    numerically identical in float32 (adding 1e-16 to any normal float32 is already
    a no-op)."""
    if loss_func == "cross_entropy":
        return -jnp.sum(
            x * jnp.log(jnp.clip(decode, _EPS, None))
            + (1.0 - x) * jnp.log(jnp.clip(1.0 - decode, _EPS, None)),
            axis=1,
        )
    if loss_func == "mean_squared":
        return jnp.sum(jnp.square(x - decode), axis=1)
    if loss_func == "cosine_proximity":
        return -jnp.sum(_l2_normalize(x, 1) * _l2_normalize(decode, 1), axis=1)
    raise ValueError(f"unknown loss_func: {loss_func!r}")


def weighted_loss(x, decode, loss_func="cross_entropy", weight=None, row_valid=None):
    """Weighted mean reconstruction loss (reference triplet_loss_utils.py:262-277).

    :param x: clean input [B, F]
    :param decode: reconstruction [B, F]
    :param weight: per-row weight [B]; defaults to ones (reference :266)
    :param row_valid: optional [B] float/bool mask; padded rows are excluded from both
        numerator and denominator (net-new — the reference has no padding).
    """
    per_row = reconstruction_loss_per_row(x, decode, loss_func)
    if weight is None:
        weight = jnp.ones(x.shape[0], dtype=per_row.dtype)
    if row_valid is not None:
        weight = weight * row_valid.astype(per_row.dtype)
    # maximum() not (+ eps): see reconstruction_loss_per_row's reassociation note
    return jnp.sum(per_row * weight) / jnp.maximum(jnp.sum(weight), _EPS)
