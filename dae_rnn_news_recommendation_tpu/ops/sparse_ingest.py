"""Sparse device ingestion: feed (indices, values) instead of dense rows.

The reference feeds scipy csr through tf.sparse placeholders (utils.py:162-180,
autoencoder.py:228-230). TPUs have no sparse matmul — but the feed itself is the
bottleneck when rows are ~2% dense: a 10k-feature article is 40KB dense f32 vs ~400B
as uint16 indices (~100x less host->device traffic, which dominates off-chip feeds).

Two consumption strategies, both fully on device:

  - `sparse_encode_matmul`: computes x @ W directly as a weighted gather-accumulate
    over W's rows (x @ W == sum_j vals_j * W[idx_j]) — also ~50x fewer FLOPs than the
    dense matmul at 2% density. Batch is processed in chunks via lax.map so the
    gathered [chunk, K, D] tile stays small in HBM.
  - `densify_on_device`: scatter-add into a dense [B, F] tile for paths that need the
    dense row anyway (reconstruction targets, corruption).

Rows are padded to K nonzeros (multiple of `k_multiple` for stable XLA shapes);
padding entries point at index 0 with value 0, so they contribute nothing.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from .. import native


def pad_csr_batch(rows, k=None, k_multiple=64, index_dtype=np.uint16, binary=False):
    """csr matrix -> padded {indices [B,K], values [B,K] or None, k}.

    :param rows: scipy.sparse matrix (any format; converted to csr)
    :param k: pad width; default = max row nnz rounded up to k_multiple
    :param index_dtype: uint16 when n_features < 65535 (half the feed bytes)
    :param binary: don't ship values (implicit 1.0 — only valid when all stored
        values are 1); padding slots point at the out-of-vocab index F, so the
        consumer must use a W extended with a zero row at index F
        (see `extend_w_for_binary`). Cuts feed bytes by another ~2/3.
    :return: dict with 'indices' (index_dtype), 'values' (float32 or None), 'k'
    """
    rows = rows.tocsr()
    b, f = rows.shape
    pad_index = f if binary else 0
    if f + (1 if binary else 0) > np.iinfo(index_dtype).max + 1:
        index_dtype = np.uint32
    nnz = np.diff(rows.indptr)
    kk = int(nnz.max(initial=1)) if k is None else int(k)
    kk = max(k_multiple, int(np.ceil(kk / k_multiple) * k_multiple))
    indices = np.empty((b, kk), index_dtype)
    values = None if binary else np.empty((b, kk), np.float32)

    lib = native.load()
    if lib is not None and index_dtype in (np.uint16, np.uint32):
        import ctypes

        indptr = np.ascontiguousarray(rows.indptr, np.int64)
        cols = np.ascontiguousarray(rows.indices, np.int32)
        # binary mode never reads values: skip the data conversion entirely
        data = None if binary else np.ascontiguousarray(rows.data, np.float32)
        ctype = ctypes.c_uint16 if index_dtype == np.uint16 else ctypes.c_uint32
        pack = lib.pack_csr_u16 if index_dtype == np.uint16 else lib.pack_csr_u32
        pack(native.as_ptr(indptr, ctypes.c_int64),
             native.as_ptr(cols, ctypes.c_int32),
             None if binary else native.as_ptr(data, ctypes.c_float),
             b, kk, pad_index,
             native.as_ptr(indices, ctype),
             None if binary else native.as_ptr(values, ctypes.c_float),
             min(8, max(1, b // 8192)))
        return {"indices": indices, "values": values, "k": kk}

    indices.fill(pad_index)
    if values is not None:
        values.fill(0.0)
    for i in range(b):
        lo, hi = rows.indptr[i], rows.indptr[i + 1]
        n = min(hi - lo, kk)
        indices[i, :n] = rows.indices[lo : lo + n].astype(index_dtype)
        if not binary:
            values[i, :n] = rows.data[lo : lo + n]
    return {"indices": indices, "values": values, "k": kk}


def pad_csr_rows(csr, row_ids, k, k_multiple=64, index_dtype=np.uint16,
                 binary=False):
    """Gather rows `row_ids` of a csr matrix and pack them padded — one native
    pass, no intermediate csr slice (the scipy fancy-index `csr[row_ids]` costs
    more than the pack itself at feed rates). Layout contract matches
    pad_csr_batch exactly; rows longer than the padded K are truncated to their
    first K entries, so pass a K >= the matrix's max row nnz (the feed computes
    it once per epoch). Falls back to pad_csr_batch(csr[row_ids]) when the
    native library is unavailable.
    """
    csr = csr.tocsr()
    b = len(row_ids)
    f = csr.shape[1]
    pad_index = f if binary else 0
    if f + (1 if binary else 0) > np.iinfo(index_dtype).max + 1:
        index_dtype = np.uint32
    kk = max(k_multiple, int(np.ceil(int(k) / k_multiple) * k_multiple))

    lib = native.load()
    if lib is None or index_dtype not in (np.uint16, np.uint32):
        return pad_csr_batch(csr[row_ids], k=kk, k_multiple=k_multiple,
                             index_dtype=index_dtype, binary=binary)
    import ctypes

    indices = np.empty((b, kk), index_dtype)
    values = None if binary else np.empty((b, kk), np.float32)
    indptr = np.ascontiguousarray(csr.indptr, np.int64)
    cols = np.ascontiguousarray(csr.indices, np.int32)
    data = None if binary else np.ascontiguousarray(csr.data, np.float32)
    rows64 = np.ascontiguousarray(row_ids, np.int64)
    ctype = ctypes.c_uint16 if index_dtype == np.uint16 else ctypes.c_uint32
    pack = (lib.pack_csr_gather_u16 if index_dtype == np.uint16
            else lib.pack_csr_gather_u32)
    pack(native.as_ptr(indptr, ctypes.c_int64),
         native.as_ptr(cols, ctypes.c_int32),
         None if binary else native.as_ptr(data, ctypes.c_float),
         native.as_ptr(rows64, ctypes.c_int64),
         b, kk, pad_index,
         native.as_ptr(indices, ctype),
         None if binary else native.as_ptr(values, ctypes.c_float),
         min(8, max(1, b // 8192)))
    return {"indices": indices, "values": values, "k": kk}


def extend_w_for_binary(w):
    """Append a zero row at index F so binary-mode padding (index F) is a no-op."""
    return jnp.concatenate([w, jnp.zeros((1, w.shape[1]), w.dtype)], axis=0)


def sparse_encode_matmul(w, indices, values=None, chunk=256,
                         precision=jax.lax.Precision.DEFAULT):
    """x @ W as chunked weighted gather-accumulate: [B, K] idx/vals -> [B, D].

    Equivalent to densify(indices, values) @ w; padding (idx 0, val 0) is a no-op.

    `values=None` is binary mode (implicit 1.0, no values shipped): indices must come
    from `pad_csr_batch(..., binary=True)` (padding points at out-of-vocab index F)
    and `w` must be extended with a zero row at F via `extend_w_for_binary`.
    """
    b = indices.shape[0]
    d = w.shape[1]
    idx = indices.astype(jnp.int32)
    vals = None if values is None else values.astype(w.dtype)
    chunk = min(chunk, b)

    def contract(c_idx, c_vals):
        g = jnp.take(w, c_idx, axis=0)  # [c, K, D]
        if c_vals is None:
            return jnp.sum(g, axis=1)
        return jnp.einsum("ckd,ck->cd", g, c_vals, precision=precision)

    if b % chunk != 0:
        # ragged tail (chunk was clamped to min(chunk, b), so here b > chunk):
        # adapt to the largest divisor of b that still fits the requested
        # working set — the memory bound survives without caller padding
        div = next(c for c in range(chunk, 0, -1) if b % c == 0)
        if div >= max(32, chunk // 8):
            chunk = div
        else:
            # no usable divisor (e.g. prime b): one unchunked pass, loud at
            # trace time — the full [B, K, D] gather loses the chunked
            # [chunk, K, D] memory bound and a frequently-ragged B must not
            # silently regress memory
            warnings.warn(
                f"sparse_encode_matmul: batch {b} has no usable divisor <= "
                f"chunk {chunk}; running unchunked (peak gather memory ~"
                f"{b / chunk:.1f}x the chunked bound). Pad B or pick a "
                "divisor chunk.", stacklevel=2)
            return contract(idx, vals)

    idx_c = idx.reshape(b // chunk, chunk, -1)
    if vals is None:
        out = jax.lax.map(lambda a: contract(a, None), idx_c)
    else:
        vals_c = vals.reshape(b // chunk, chunk, -1)
        out = jax.lax.map(lambda a: contract(a[0], a[1]), (idx_c, vals_c))
    return out.reshape(b, d)


def sparse_encode_scan(params, indices, values, config, chunk=256,
                       via_dense=False):
    """Encode M packed batches in ONE dispatch: lax.scan of `sparse_encode`
    over stacked [M, B, K] indices (and values, or None for binary mode),
    returning [M, B, D].

    Why: each jitted call pays a dispatch round trip; over a high-latency link
    (tunneled TPU: ~23-70 ms measured) per-batch dispatch leaves the chip
    idle. Scanning amortizes one dispatch over M batches while the per-batch
    [B, K] working-set bound of `sparse_encode` is unchanged.
    """
    def body(carry, sl):
        idx, vals = sl if values is not None else (sl, None)
        return carry, sparse_encode(params, idx, vals, config, chunk=chunk,
                                    via_dense=via_dense)

    xs = indices if values is None else (indices, values)
    _, out = jax.lax.scan(body, None, xs)
    return out


def densify_on_device(indices, values, n_features, dtype=jnp.float32):
    """Scatter-add (indices, values) into a dense [B, F] tile on device.

    Duplicate indices accumulate (count-vector semantics); the padding (0, 0.0)
    entries add zero.
    """
    b, k = indices.shape
    idx = indices.astype(jnp.int32)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, k))
    out = jnp.zeros((b, n_features), dtype)
    return out.at[rows, idx].add(values.astype(dtype))


def sparse_encode(params, indices, values, config, chunk=256, via_dense=False):
    """The DAE encode pass (models/dae_core.py) fed by (indices, values):
    H = act(x@W + bh) - act(bh). `values=None` = binary mode.

    Two equivalent device strategies for x@W (identical results, tested):
      via_dense=False — chunked weighted gather-accumulate over W's rows
        (VPU/bandwidth bound; never materializes [B, F]);
      via_dense=True — scatter into a dense [B, F] HBM tile, then one MXU
        matmul (burns 2x[B,F] HBM traffic to buy systolic-array throughput).
    Which wins depends on density and chip generation — measure on hardware
    before switching a production default."""
    from ..models.dae_core import resolve_activation, _precision

    act = resolve_activation(config.enc_act_func)
    dt = jnp.dtype(config.compute_dtype)
    w = params["W"].astype(dt)
    if via_dense:
        f = params["W"].shape[0]
        if values is None:
            # binary-mode padding points at out-of-vocab index F: scatter into
            # an F+1-wide tile so padding lands in a throwaway column
            x = densify_on_device(indices, jnp.ones(indices.shape, dt), f + 1,
                                  dtype=dt)[:, :f]
        else:
            x = densify_on_device(indices, values, f, dtype=dt)
        # jaxcheck: disable=R12 (via_dense is the parity oracle for the sparse kernel: it must accumulate exactly like dae_core.encode's compute_dtype matmul, narrow rounding included)
        pre = jnp.matmul(x, w, precision=_precision(config))
    else:
        if values is None:
            w = extend_w_for_binary(w)
        pre = sparse_encode_matmul(
            w, indices, values, chunk=chunk,
            precision=_precision(config) or jax.lax.Precision.DEFAULT)
    h = pre.astype(jnp.float32) + params["bh"]
    return act(h) - act(params["bh"])
