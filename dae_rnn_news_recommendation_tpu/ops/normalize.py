"""The one L2-normalize used by every cosine surface in the repo.

Before r09 three copies had drifted: serve/graph divided by `norm + 1e-9`,
while ops/losses (cosine-proximity loss) and parallel/ring (ring similarity)
both used the tf.nn.l2_normalize form with eps 1e-12. Cosine scores compared
across those paths (serving top-k vs mining similarity vs eval) were computed
under two different epsilons — invisible at fp32 for healthy embeddings, but a
real divergence for near-zero rows. One helper, one epsilon, pinned by test.

tf.nn.l2_normalize form on purpose: `x * rsqrt(max(sum(x^2), eps))` maps an
exactly-zero row to exactly zero (0 * rsqrt(eps)), whereas the `x / (norm+eps)`
form does so only approximately and changes every healthy row by O(eps/norm).
"""

import jax.numpy as jnp

# the reference epsilon (tf.nn.l2_normalize default), shared by serving,
# mining, eval and the ring similarity — pinned by tests/test_ops.py
NORMALIZE_EPS = 1e-12


def l2_normalize(x, axis=-1, eps=NORMALIZE_EPS):
    """tf.nn.l2_normalize: x * rsqrt(max(sum(x^2, axis), eps)).

    Zero rows map to zero rows (not NaN); everything else to unit L2 norm.
    """
    sq = jnp.sum(jnp.square(x), axis=axis, keepdims=True)
    return x * jnp.reciprocal(jnp.sqrt(jnp.maximum(sq, eps)))
