"""Named tile-size defaults for every Pallas kernel — one table, two readers.

Before r20 these numbers were magic literals scattered through the kernel
wrappers (`topk_fused.py` fixed the VMEM panel at 512 and derived `bq` from
an inline `min(256, ...)`, `pallas_kernels.py` hardcoded 8/256 row blocks,
`wire.py` buried `rows = 8` inside its pallas_call builder). They now live
here, named and documented, because two subsystems must agree on them:

  * the kernel dispatch fallback — `tuning.resolve()` returns exactly these
    when the ProfileDB has no tuned row for an (op, shape, dtype, device)
    key, so an untuned run behaves bit-for-bit like every run before r20;
  * the autotuner's candidate grids (`tuning/space.py`) — each grid is
    centered on its default, so the hand-picked choice is always a measured
    candidate and "tuned" can never mean "worse than before".

The alignment rationale for each number is the kernel's own: panels stream
through VMEM in sublane-tile multiples (8 f32 / 16 bf16 / 32 int8, lane
width 128), and the grid axes that revisit an accumulator block must keep
that block identical across steps (ops/topk_fused.py module docstring).
"""

# ---------------------------------------------------------------- topk_fused
# corpus rows per VMEM panel: 512 x 128 lanes of f32 panel + [bq, block]
# scores stay ~1 MB per step, far under the ~16 MB VMEM budget, and 512 is a
# multiple of every dtype's min sublane tile (8 f32 / 16 bf16 / 32 int8)
TOPK_FUSED_PANEL = 512
# queries per grid row-block, capped: past ~256 queries the [bq, block] score
# slab starts crowding the panel out of VMEM with no MXU utilization gain
TOPK_FUSED_BQ_CAP = 256

# ------------------------------------------------------------------ ivf_topk
# queries per block: the f32 min sublane tile. Shortlists are per-block
# unions, so a bigger bq widens every query's scanned set — keep it minimal.
IVF_BQ = 8
# uniform cell capacity rounds up to the int8 sublane tile (32), the
# strictest of the f32/bf16/int8 minimums, so one layout serves every dtype.
# Larger multiples trade padding waste for fewer, longer panel DMAs.
IVF_CAP_MULTIPLE = 32

# ---------------------------------------------------------------- batch_hard
# anchor rows per grid step of the O(B^2) mining scan; compiled requires %8
BATCH_HARD_BLOCK_ROWS = 8

# ------------------------------------------------------------------- masking
# rows per PRNG block of the corruption kernel (clamped so the block stays
# ~2 MB whatever the feature width — see masking_noise_pallas)
MASKING_BLOCK_ROWS = 256

# --------------------------------------------------------------- wire unpack
# rows per grid step of the bit-plane unpack; the prefix-sum matmul is
# [rows, Wp] x [Wp, Wp], so small row blocks keep the triangular operand hot
WIRE_UNPACK_BLOCK_ROWS = 8


def ceil_to(n, multiple):
    """Smallest multiple of `multiple` >= n (n >= 1)."""
    return int(-(-int(n) // int(multiple)) * int(multiple))


def topk_fused_default_bq(batch_rows):
    """The pre-r20 inline heuristic, named: queries round up to the f32
    sublane tile and cap at TOPK_FUSED_BQ_CAP."""
    return int(min(TOPK_FUSED_BQ_CAP, ceil_to(batch_rows, 8)))


def default_config(op, shape=None):
    """The hand-picked fallback config for one op, as the dict
    `tuning.resolve()` returns on a cache miss.

    `shape` is the op's tuning-key shape tuple (see tuning/space.py for the
    per-op conventions); only topk_fused consumes it (its default bq depends
    on the batch)."""
    if op == "topk_fused":
        bq = (topk_fused_default_bq(shape[0]) if shape
              else TOPK_FUSED_BQ_CAP)
        return {"block": TOPK_FUSED_PANEL, "bq": bq}
    if op == "ivf_topk":
        return {"bq": IVF_BQ, "cap_multiple": IVF_CAP_MULTIPLE}
    if op == "batch_hard":
        return {"block_rows": BATCH_HARD_BLOCK_ROWS}
    if op == "masking":
        return {"block_rows": MASKING_BLOCK_ROWS}
    if op == "wire_unpack":
        return {"block_rows": WIRE_UNPACK_BLOCK_ROWS}
    raise KeyError(f"no tile defaults for op {op!r}")


# every op the table (and the tuner) knows, in stable order
TUNED_OPS = ("topk_fused", "ivf_topk", "batch_hard", "masking",
             "wire_unpack")
