"""Numerical ops: initializers, corruption, reconstruction losses, triplet mining.

These are the JAX twins of the reference's L2 layer (autoencoder/utils.py and
autoencoder/triplet_loss_utils.py) — pure functions designed to live *inside* a
jit-compiled train step (explicit PRNG keys, static shapes, padding-mask aware).
"""

from .initializers import xavier_init  # noqa: F401
from .corruption import (  # noqa: F401
    masking_noise,
    salt_and_pepper_noise,
    decay_noise,
    corrupt,
    masking_noise_sparse_host,
)
from .losses import reconstruction_loss_per_row, weighted_loss, LOSS_FUNCS  # noqa: F401
from .normalize import l2_normalize, NORMALIZE_EPS  # noqa: F401
from .sparse_ingest import (  # noqa: F401
    pad_csr_batch,
    sparse_encode_matmul,
    densify_on_device,
    sparse_encode,
)
from .triplet import (  # noqa: F401
    anchor_positive_mask,
    anchor_negative_mask,
    triplet_mask,
    batch_all_triplet_loss,
    batch_hard_triplet_loss,
    precomputed_triplet_loss,
)
_PALLAS_EXPORTS = ("batch_all_triplet_loss_pallas", "masking_noise_pallas")

# topk_fused lives in its own module but is lazy for the same reason: its
# import pulls jax.experimental.pallas
_TOPK_EXPORTS = ("topk_fused",)

# clustered (IVF) two-stage retrieval; lazy for the same pallas reason
_IVF_EXPORTS = ("ivf_topk",)

# __all__ lists only the eager names: a star-import must not trigger __getattr__,
# which would eagerly pull in jax.experimental.pallas. __dir__ still advertises
# the Pallas names for completion.
__all__ = [
    "xavier_init", "masking_noise", "salt_and_pepper_noise", "decay_noise",
    "corrupt", "masking_noise_sparse_host", "reconstruction_loss_per_row",
    "weighted_loss", "LOSS_FUNCS", "l2_normalize", "NORMALIZE_EPS",
    "pad_csr_batch", "sparse_encode_matmul",
    "densify_on_device", "sparse_encode", "anchor_positive_mask",
    "anchor_negative_mask", "triplet_mask", "batch_all_triplet_loss",
    "batch_hard_triplet_loss", "precomputed_triplet_loss",
]


def __getattr__(name):
    """Lazy: jax.experimental.pallas (experimental API) loads only when the Pallas
    kernels are actually used, keeping the production XLA paths decoupled."""
    if name in _PALLAS_EXPORTS:
        from . import pallas_kernels

        return getattr(pallas_kernels, name)
    if name in _TOPK_EXPORTS:
        from . import topk_fused

        return getattr(topk_fused, name)
    if name in _IVF_EXPORTS:
        from . import ivf_topk

        return getattr(ivf_topk, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_PALLAS_EXPORTS) | set(_TOPK_EXPORTS)
                  | set(_IVF_EXPORTS))
