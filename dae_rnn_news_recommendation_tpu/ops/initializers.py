"""Weight initializers.

Twin of reference autoencoder/utils.py:16-26 (xavier_init): uniform on
[-c*sqrt(6/(fan_in+fan_out)), +c*sqrt(6/(fan_in+fan_out))] — but as a pure JAX
function taking an explicit PRNG key instead of mutating global RNG state.
"""

import jax
import jax.numpy as jnp


def xavier_init(key, fan_in, fan_out, const=1.0, dtype=jnp.float32):
    """Xavier-uniform weight init.

    :param key: jax PRNG key
    :param fan_in: input feature count (n_features)
    :param fan_out: output feature count (n_components)
    :param const: multiplicative constant on the bound
    """
    bound = const * jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(
        key, (fan_in, fan_out), minval=-bound, maxval=bound, dtype=dtype
    )
