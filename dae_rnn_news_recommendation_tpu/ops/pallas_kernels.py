"""Pallas TPU kernels for the hot ops, with honest measurements.

1. `batch_all_triplet_loss_pallas` — blockwise online batch_all mining (twin of
   ops/triplet.py:78 / reference triplet_loss_utils.py:79-131). Every [B, B, B]
   quantity (distance cube, masks, softplus) is derived tile-by-tile in VMEM with an
   explicit (B/ti, B/tj, B/tk) grid; the cube never exists in HBM, and the three
   axis-reductions composing `data_weight` accumulate across grid steps. Trainable:
   a custom VJP (a second kernel over the same grid accumulating dL/d(dp) tiles,
   then dE = (G+G^T)E on the MXU) matches XLA autodiff of the oracle to float
   roundoff — the cube stays out of HBM in the backward pass too.

2. `masking_noise_pallas` — fused masking corruption from the TPU's hardware PRNG
   (pltpu.prng_seed / prng_random_bits): one read-mask-write pass with on-chip
   randomness instead of counter-based threefry bit generation.

3. `topk_fused` (lives in ops/topk_fused.py, registered here) — the serving
   scorer: cosine scores + running top-k in one kernel. The [N_pad, D] corpus
   streams through VMEM in panels along the innermost grid axis while a
   [bq, 128] score/index accumulator pair rides the output revisit guarantee
   (consecutive same-index steps), so the [B, N] score matrix never exists in
   HBM — the unfused serve graph materializes it at 4·B·N bytes per batch and
   reads it back through lax.top_k. int8/bf16 corpora dot in fp32 via
   `preferred_element_type` with per-row scales applied post-dot. Parity
   contract (tests/test_topk_fused.py): bitwise scores and tie-exact indices
   vs masked-matmul + `lax.top_k`, including all-rows-invalid and k>n_valid.
   Off-TPU it lowers to exactly that reference graph (serve keeps one code
   path; see docs/serving.md).

4. `ivf_topk` (lives in ops/ivf_topk.py, registered here) — clustered
   two-stage retrieval over the cell-major IVF layout (index/layout.py):
   stage 1 reuses `topk_fused` with the k-means centroid table as its
   "corpus" (the [B, n_cells] centroid scores never exist in HBM), stage 2
   is a `PrefetchScalarGridSpec` kernel whose cell-panel BlockSpec index_map
   reads the block's deduplicated probe list from a scalar-prefetch operand
   — the gather IS the pipelined HBM->VMEM panel fetch, so neither a
   [B, shortlist] score matrix nor a [B, shortlist, D] gather buffer ever
   materializes. A per-query membership mask keeps candidate sets exact
   despite the block-union scan; panel indices come from the layout's
   row_ids, so results are directly comparable with the exact scorer.
   Parity contract (tests/test_ivf.py): at probes = n_cells, bitwise scores
   and tie-exact indices vs the exact scorer; k beyond the shortlist
   degrades honestly to `topk_fused`. Off-TPU it lowers to the masked-matmul
   fallback (non-probed cells scored -inf).

STATUS: DISPATCHED AT LARGE BATCH / ON-TPU MASKING (promoted round 6 for the
regimes the dense path cannot reach; small-batch mining stays on XLA). The
round-3/5 measurements stand: on a real v5e-1 XLA wins dense-representable
batch_all — its fusion also never materializes the cube (runs B=4096 where
the cube would be 256 GiB). Round-5 numbers (2026-08-02, hard host-fetch sync
per bench.py:_hard_sync — the earlier block_until_ready timings were
optimistic for BOTH sides, ratio unchanged): grad-step XLA vs Pallas
8.6 vs 30.2 ms at B=800/D=500; 129 vs 288 ms at B=2048; 950 vs 2308 ms at
B=4096, tiles (8,128,128). Masking is sub-millisecond in both forms at
[8192, 10000] — below reliable timing resolution over the axon tunnel. A round-2
re-tune (tile sweep + fused-mask variant) was abandoned as unmeasurable: the
tunnel memoizes (executable, inputs) dispatches, so microbenchmarks neither scale
with volume nor reproduce (any future attempt must feed DISTINCT inputs per
dispatch, bench.py-style). Dispatch policy today (train/step.py
resolve_mining_impl + ops/corruption.py): mining batches <= 1024 rows keep the
measured-fastest dense XLA path byte-stable; past that the cube's footprint —
not FLOPs — is binding, and "auto" routes to these kernels on TPU (the
anchor-tiled XLA scan in ops/triplet_blockwise.py elsewhere, which is also
the large-B parity oracle for them); TPU masking corruption routes here
unconditionally (fused pass, hardware PRNG). bench.py's train_mined_big
corner is the evidence harness for the large-batch claim.

Mosaic layout rules discovered on hardware (encoded in the kernels/asserts below):
3D reductions need keepdims (or drop axis 0 only); [n,1,1]->(n,1) reshape lowers but
singleton-squeeze doesn't; dynamic-slice offsets need 8-alignment on the sublane
axis and 128-alignment on the lane axis; uint32->f32 casts don't lower (use logical
shifts on int32); rank-1 intermediates don't lower (keep everything >=2D).

Off-TPU the wrappers default to interpreter mode (`interpret=None` -> "not on
TPU"); note the interpreter stubs prng_random_bits to zeros, so masking statistics
are only testable on hardware (tests/test_pallas_kernels.py gates those).

Not a kernel on purpose: the sparse gather-accumulate encode (ops/sparse_ingest.py).
XLA's native dynamic-gather lowering on TPU already pipelines HBM row fetches well,
and a Pallas version would need per-(row, nnz) DMAs that are latency-bound at ~2 KB
each — the measured-first rule says leave it to XLA.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the TPU-flavored interpreter (emulates the TPU PRNG primitives with
# zero-stubbed bits) only exists on jax >= 0.5; on 0.4.x this is None and the
# masking path falls back to its exact v == 0 identity short-circuit
_INTERPRET_PARAMS = getattr(pltpu, "InterpretParams", None)

_EPS = 1e-16


def _on_tpu():
    return jax.default_backend() == "tpu"


# --------------------------------------------------------------------- batch_all

def _tile_terms(dp_ij, dp_ik, a, b, j, k, tj, tk, pos_only):
    """One VMEM tile of the [B, B, B] quantities, shared by the forward and
    BOTH backward kernels so the loss definition lives in exactly one place:
    returns (valid3, dist, pos3, mask) for logical block coords (j, k)."""
    # j != k is the only distinctness not implied by the label masks
    jj = jax.lax.broadcasted_iota(jnp.int32, (tj, tk), 0) + j * tj
    kk = jax.lax.broadcasted_iota(jnp.int32, (tj, tk), 1) + k * tk
    neq_jk = (jj != kk).astype(jnp.float32)

    # jaxcheck: disable=R8 (a [ti,tj,tk] VMEM tile, not the HBM cube — the cube exists only blockwise)
    valid3 = a[:, :, None] * b[:, None, :] * neq_jk[None, :, :]
    # jaxcheck: disable=R8 (a [ti,tj,tk] VMEM tile, not the HBM cube — the cube exists only blockwise)
    dist = dp_ik[:, None, :] - dp_ij[:, :, None]   # reference :96-106
    pos3 = (valid3 * dist > _EPS).astype(jnp.float32)  # reference :114
    mask = pos3 if pos_only else valid3
    return valid3, dist, pos3, mask


def _batch_all_kernel(dp_ij_ref, dp_ik_ref, a_ref, b_ref,
                      stats_ref, aw_ref, pw_ref, nw_ref,
                      *, ti, tj, tk, pos_only):
    i = pl.program_id(0)
    j = pl.program_id(1)
    k = pl.program_id(2)

    @pl.when((i == 0) & (j == 0) & (k == 0))
    def _():
        stats_ref[:] = jnp.zeros_like(stats_ref)
        aw_ref[:] = jnp.zeros_like(aw_ref)
        pw_ref[:] = jnp.zeros_like(pw_ref)
        nw_ref[:] = jnp.zeros_like(nw_ref)

    dp_ij = dp_ij_ref[:]          # [ti, tj] dot(anchor, positive)
    dp_ik = dp_ik_ref[:]          # [ti, tk] dot(anchor, negative)
    a = a_ref[:]                  # [ti, tj] anchor/positive validity (labels eq, i!=j, rows valid)
    b = b_ref[:]                  # [ti, tk] anchor/negative validity (labels neq => i!=k free)

    valid3, dist, pos3, mask = _tile_terms(dp_ij, dp_ik, a, b, j, k, tj, tk,
                                           pos_only)

    sp = jax.nn.softplus(dist)                      # reference :126
    s_loss = jnp.sum(sp * mask)
    n_pos = jnp.sum(pos3)
    n_valid = jnp.sum(valid3)

    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    contrib = jnp.where(lane == 0, s_loss,
                        jnp.where(lane == 1, n_pos,
                                  jnp.where(lane == 2, n_valid, 0.0)))
    stats_ref[:] += contrib

    # participation counts (reference :129): row as anchor / positive / negative.
    # Mosaic layout rules (probed on v5e): 3D reductions must keep dims (or drop
    # axis 0), and [n,1,1]->(n,1) reshape lowers while singleton-squeeze doesn't.
    # Anchor/positive counts land on the sublane axis (column accumulators,
    # offsets need 8-alignment), negative counts on the lane axis (row
    # accumulator, offsets need 128-alignment) — hence the wrapper's tile asserts.
    m_jk = jnp.sum(mask, axis=0)                                  # [tj, tk]
    aw_col = jnp.sum(jnp.sum(mask, axis=2, keepdims=True),
                     axis=1, keepdims=True).reshape(ti, 1)        # [ti, 1]
    aw_ref[pl.ds(pl.multiple_of(i * ti, 8), ti), :] += aw_col
    pw_ref[pl.ds(pl.multiple_of(j * tj, 8), tj), :] += (
        jnp.sum(m_jk, axis=1, keepdims=True))                     # [tj, 1]
    nw_ref[:, pl.ds(pl.multiple_of(k * tk, 128), tk)] += (
        jnp.sum(m_jk, axis=0, keepdims=True))                     # [1, tk]


@functools.partial(jax.jit, static_argnames=("pos_triplets_only", "tiles", "interpret"))
def _batch_all_pallas(dp, a, b, pos_triplets_only, tiles, interpret):
    bp = dp.shape[0]
    ti, tj, tk = tiles
    grid = (bp // ti, bp // tj, bp // tk)
    kernel = functools.partial(_batch_all_kernel, ti=ti, tj=tj, tk=tk,
                               pos_only=pos_triplets_only)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((ti, tj), lambda i, j, k: (i, j)),   # dp[anchor, positive]
            pl.BlockSpec((ti, tk), lambda i, j, k: (i, k)),   # dp[anchor, negative]
            pl.BlockSpec((ti, tj), lambda i, j, k: (i, j)),   # A mask
            pl.BlockSpec((ti, tk), lambda i, j, k: (i, k)),   # B mask
        ],
        out_specs=[
            pl.BlockSpec((1, 128), lambda i, j, k: (0, 0)),
            pl.BlockSpec((bp, 1), lambda i, j, k: (0, 0)),
            pl.BlockSpec((bp, 1), lambda i, j, k: (0, 0)),
            pl.BlockSpec((1, bp), lambda i, j, k: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 128), jnp.float32),
            jax.ShapeDtypeStruct((bp, 1), jnp.float32),
            jax.ShapeDtypeStruct((bp, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, bp), jnp.float32),
        ],
        interpret=interpret,
    )(dp, dp, a, b)


def _batch_all_bwd_gij_kernel(dp_ij_ref, dp_ik_ref, a_ref, b_ref, gij_ref,
                              *, ti, tj, tk, pos_only):
    """-dL/d(dp[i,j]) * num_sel: grid (I, J, K) — the k-reduction is the
    INNERMOST grid axis, so the gij[i,j] output block is revisited on
    consecutive steps only. Compiled Pallas TPU preserves an output buffer
    across consecutive same-index steps and does not re-read flushed blocks;
    a middle-axis reduction would silently drop partial sums on hardware
    (interpret mode can't catch that — hence one kernel per reduction)."""
    j = pl.program_id(1)
    k = pl.program_id(2)
    _, dist, _, mask = _tile_terms(dp_ij_ref[:], dp_ik_ref[:], a_ref[:],
                                   b_ref[:], j, k, tj, tk, pos_only)
    s = jax.nn.sigmoid(dist) * mask                       # [ti, tj, tk]

    @pl.when(k == 0)
    def _():
        gij_ref[:] = jnp.zeros_like(gij_ref)

    gij_ref[:] += -jnp.sum(s, axis=2)                     # [ti, tj]


def _batch_all_bwd_gik_kernel(dp_ij_ref, dp_ik_ref, a_ref, b_ref, gik_ref,
                              *, ti, tj, tk, pos_only):
    """dL/d(dp[i,k]) * num_sel: grid (I, K, J) — program_id(1) is the k-block
    and program_id(2) the j-block, putting the j-reduction innermost so the
    gik[i,k] output block sees only consecutive revisits (see gij twin)."""
    k = pl.program_id(1)
    j = pl.program_id(2)
    _, dist, _, mask = _tile_terms(dp_ij_ref[:], dp_ik_ref[:], a_ref[:],
                                   b_ref[:], j, k, tj, tk, pos_only)
    s = jax.nn.sigmoid(dist) * mask                       # [ti, tj, tk]

    @pl.when(j == 0)
    def _():
        gik_ref[:] = jnp.zeros_like(gik_ref)

    gik_ref[:] += jnp.sum(s, axis=1)                      # [ti, tk]


@functools.partial(jax.jit, static_argnames=("pos_triplets_only", "tiles",
                                             "interpret"))
def _batch_all_pallas_bwd(dp, a, b, pos_triplets_only, tiles, interpret):
    """Two passes over the cube, one per reduction axis — each pallas_call
    keeps its accumulated output block on the innermost grid axis (the only
    revisit pattern compiled Mosaic guarantees to accumulate correctly)."""
    bp = dp.shape[0]
    ti, tj, tk = tiles
    gij = pl.pallas_call(
        functools.partial(_batch_all_bwd_gij_kernel, ti=ti, tj=tj, tk=tk,
                          pos_only=pos_triplets_only),
        grid=(bp // ti, bp // tj, bp // tk),
        in_specs=[
            pl.BlockSpec((ti, tj), lambda i, j, k: (i, j)),
            pl.BlockSpec((ti, tk), lambda i, j, k: (i, k)),
            pl.BlockSpec((ti, tj), lambda i, j, k: (i, j)),
            pl.BlockSpec((ti, tk), lambda i, j, k: (i, k)),
        ],
        out_specs=pl.BlockSpec((ti, tj), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((bp, bp), jnp.float32),
        interpret=interpret,
    )(dp, dp, a, b)
    gik = pl.pallas_call(
        functools.partial(_batch_all_bwd_gik_kernel, ti=ti, tj=tj, tk=tk,
                          pos_only=pos_triplets_only),
        grid=(bp // ti, bp // tk, bp // tj),   # (I, K, J): j innermost
        in_specs=[
            pl.BlockSpec((ti, tj), lambda i, k, j: (i, j)),
            pl.BlockSpec((ti, tk), lambda i, k, j: (i, k)),
            pl.BlockSpec((ti, tj), lambda i, k, j: (i, j)),
            pl.BlockSpec((ti, tk), lambda i, k, j: (i, k)),
        ],
        out_specs=pl.BlockSpec((ti, tk), lambda i, k, j: (i, k)),
        out_shape=jax.ShapeDtypeStruct((bp, bp), jnp.float32),
        interpret=interpret,
    )(dp, dp, a, b)
    return gij + gik


def _prep_masks(labels, encode, row_valid, tiles, interpret):
    """Shared forward/backward prep: dp + validity masks, padded to the tile
    step (padded rows mine nothing by construction)."""
    b = labels.shape[0]
    valid = (jnp.ones(b, bool) if row_valid is None
             else row_valid.astype(bool))
    dp = jnp.matmul(encode, encode.T, precision=jax.lax.Precision.HIGHEST)
    dp = dp.astype(jnp.float32)
    eq = labels[:, None] == labels[None, :]
    vv = valid[:, None] & valid[None, :]
    eye = jnp.eye(b, dtype=bool)
    a = (eq & ~eye & vv).astype(jnp.float32)   # anchor/positive validity
    bm = (~eq & vv).astype(jnp.float32)        # anchor/negative (i!=k implied)

    ti, tj, tk = tiles
    # one padded size must be divisible by every tile or the bp//tile grid
    # dims would truncate and silently drop the trailing blocks — the lcm is
    # the smallest such size (== max for the usual power-of-two tiles)
    step = math.lcm(ti, tj, tk)
    if not interpret:
        # compiled Mosaic alignment: sublane slices 8-aligned, lane slices 128-aligned
        assert ti % 8 == 0 and tj % 8 == 0 and tk % 128 == 0, (
            f"compiled tiles need ti%8==0, tj%8==0, tk%128==0; got {tiles}")
    bp = int(-(-b // step) * step)
    if bp != b:
        pad = ((0, bp - b), (0, bp - b))
        dp = jnp.pad(dp, pad)
        a = jnp.pad(a, pad)
        bm = jnp.pad(bm, pad)
    return dp, a, bm


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 4, 5))
def _batch_all_loss_vjp(labels, encode, pos_triplets_only, row_valid, tiles,
                        interpret):
    """Differentiable core: returns the full tuple; only `loss` carries a
    gradient (data_weight/fraction/num are indicator counts whose true
    gradient is zero, matching XLA autodiff of the oracle)."""
    out, _ = _batch_all_fwd(labels, encode, pos_triplets_only, row_valid,
                            tiles, interpret)
    return out


def _batch_all_fwd(labels, encode, pos_triplets_only, row_valid, tiles,
                   interpret):
    b = labels.shape[0]
    dp, a, bm = _prep_masks(labels, encode, row_valid, tiles, interpret)
    stats, aw, pw, nw = _batch_all_pallas(dp, a, bm, bool(pos_triplets_only),
                                          tuple(tiles), bool(interpret))
    sum_loss, num_pos, num_valid = stats[0, 0], stats[0, 1], stats[0, 2]
    num_sel = num_pos if pos_triplets_only else num_valid
    loss = sum_loss / jnp.maximum(num_sel, _EPS)
    data_weight = (aw[:, 0] + pw[:, 0] + nw[0])[:b]
    fraction = num_pos / jnp.maximum(num_valid, _EPS)
    out = (loss, data_weight, fraction, num_pos, {})
    residuals = (dp, a, bm, num_sel, encode)
    return out, residuals


def _batch_all_bwd(pos_triplets_only, tiles, interpret, residuals, cotangents):
    dp, a, bm, num_sel, encode = residuals
    loss_bar = cotangents[0]
    b = encode.shape[0]
    # G[bp, bp] = dL/d(dp) * num_sel; the cube never exists in HBM here either
    g = _batch_all_pallas_bwd(dp, a, bm, bool(pos_triplets_only),
                              tuple(tiles), bool(interpret))
    g = (g[:b, :b] * (loss_bar / jnp.maximum(num_sel, _EPS)))
    # dp = E E^T  =>  dL/dE = (G + G^T) E
    de = jnp.matmul(g + g.T, encode.astype(jnp.float32),
                    precision=jax.lax.Precision.HIGHEST)
    return None, de.astype(encode.dtype), None


_batch_all_loss_vjp.defvjp(_batch_all_fwd, _batch_all_bwd)


def batch_all_triplet_loss_pallas(labels, encode, pos_triplets_only=False,
                                  row_valid=None, tiles=(8, 128, 128),
                                  interpret=None):
    """Drop-in for ops.triplet.batch_all_triplet_loss with O(tile^3) working set.

    Validated infrastructure, NOT a production path (see module docstring):
    measured slower than XLA's fusion at every tested shape — training and
    eval use ops/triplet.py. Trainable nonetheless: a custom VJP (a second
    Pallas kernel over the same grid) gives d(loss)/d(encode) with the same
    never-materialize-the-cube bound, gradient-parity-tested against XLA
    autodiff of the oracle.

    Same return tuple: (loss, data_weight[B], fraction_positive, num_positive, {}).
    The dot-product matrix is computed by XLA (MXU); the kernel owns everything cubic.

    :param tiles: (ti, tj, tk) VMEM tile sizes; B is padded to their lcm with
        invalid rows, which mine nothing by construction.
    :param interpret: force interpreter mode (defaults to True off-TPU).
    """
    if interpret is None:
        interpret = not _on_tpu()
    # trace-time label only (host-side wrapper — never inside the kernel)
    with jax.named_scope("ops/batch_all_pallas"):
        return _batch_all_loss_vjp(labels, encode, bool(pos_triplets_only),
                                   row_valid, tuple(tiles), bool(interpret))


# --------------------------------------------------------------------- batch_hard

def _batch_hard_kernel(dp_ref, a_ref, b_ref, rv_ref, cr_ref, va_ref,
                       stats_ref, aw_ref, hp_hits_ref, hn_hits_ref, *, ti):
    """Full-row batch_hard mining: one grid axis over anchor row-blocks, each
    step sees [ti, Bp] rows of dp + masks. Single grid axis == innermost axis,
    so the stats/hits output blocks are revisited on consecutive steps only —
    the one accumulation pattern compiled Mosaic guarantees (see the batch_all
    backward kernels).

    Padded-COLUMN handling (the blockwise XLA twin avoids fake columns by
    padding anchors only; here both axes pad to the tile step):
      * hardest positive: dense min ranges over dp + max_row*(1-mask) of REAL
        columns — real-but-invalid columns contribute their shifted dp. Fake
        columns must contribute +inf: an anchor with no valid positive takes
        its min over shifted real dp, and a fake column's dp=0 + max_row
        could win it.
      * hardest negative: dense max ranges over mask*dp of real columns, so
        invalid REAL columns are literal zeros (reference :240) — but that
        max can be negative (all columns valid negatives, all dp < 0), so
        fake zero columns must be -inf, not 0."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        stats_ref[:] = jnp.zeros_like(stats_ref)
        hp_hits_ref[:] = jnp.zeros_like(hp_hits_ref)
        hn_hits_ref[:] = jnp.zeros_like(hn_hits_ref)

    dp = dp_ref[:]                # [ti, Bp] dot products, this block of anchors
    a = a_ref[:]                  # [ti, Bp] anchor/positive validity
    bm = b_ref[:]                 # [ti, Bp] anchor/negative validity
    rv = rv_ref[:]                # [1, Bp]  row_valid over columns (pad -> 0)
    cr = cr_ref[:]                # [1, Bp]  1.0 iff the column is a real row
    va = va_ref[:]                # [ti, 1]  row_valid for this block's anchors

    neg_inf = jnp.float32(-jnp.inf)
    # valid-column row max with the dense guard — no isfinite in Mosaic, so
    # gate on the valid-column count instead (equivalent: the max is -inf
    # exactly when no column is valid)
    n_valid = jnp.sum(rv, axis=1, keepdims=True)                    # [1, 1]
    max_row = jnp.max(jnp.where(rv > 0.0, dp, neg_inf), axis=1,
                      keepdims=True)                                # [ti, 1]
    max_row = jnp.where(n_valid > 0.0, max_row, 0.0)

    ap_dp = jnp.where(cr > 0.0, dp + max_row * (1.0 - a),
                      jnp.float32(jnp.inf))
    hardest_pos = jnp.min(ap_dp, axis=1, keepdims=True)             # [ti, 1]
    an_dp = jnp.where(cr > 0.0, bm * dp, neg_inf)
    hardest_neg = jnp.max(an_dp, axis=1, keepdims=True)             # [ti, 1]

    dist = jnp.maximum(hardest_neg - hardest_pos, 0.0)
    count = (dist > 0.0).astype(jnp.float32) * va                   # [ti, 1]

    aw_ref[pl.ds(pl.multiple_of(i * ti, 8), ti), :] = count
    # float-equality tie hits (reference :251-253), padded columns gated by rv
    hp_hits_ref[:] += jnp.sum(count * (dp == hardest_pos).astype(jnp.float32)
                              * rv, axis=0, keepdims=True)          # [1, Bp]
    hn_hits_ref[:] += jnp.sum(count * (dp == hardest_neg).astype(jnp.float32)
                              * rv, axis=0, keepdims=True)

    s_loss = jnp.sum(jax.nn.softplus(dist) * count)
    total = jnp.sum(count)
    sum_hp = jnp.sum(hardest_pos * va)
    sum_hn = jnp.sum(hardest_neg * va)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    contrib = jnp.where(lane == 0, s_loss,
                        jnp.where(lane == 1, total,
                                  jnp.where(lane == 2, sum_hp,
                                            jnp.where(lane == 3, sum_hn,
                                                      0.0))))
    stats_ref[:] += contrib


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _batch_hard_pallas(dp, a, bm, rv, cr, va, block_rows, interpret):
    bp = dp.shape[0]
    ti = block_rows
    row_spec = pl.BlockSpec((ti, bp), lambda i: (i, 0))
    full_row = pl.BlockSpec((1, bp), lambda i: (0, 0))
    return pl.pallas_call(
        functools.partial(_batch_hard_kernel, ti=ti),
        grid=(bp // ti,),
        in_specs=[
            row_spec,                                   # dp rows
            row_spec,                                   # anchor/positive mask
            row_spec,                                   # anchor/negative mask
            full_row,                                   # row_valid columns
            full_row,                                   # real-column mask
            pl.BlockSpec((ti, 1), lambda i: (i, 0)),    # anchor validity
        ],
        out_specs=[
            pl.BlockSpec((1, 128), lambda i: (0, 0)),   # stats lanes
            pl.BlockSpec((bp, 1), lambda i: (0, 0)),    # per-anchor count
            full_row,                                   # hardest-pos tie hits
            full_row,                                   # hardest-neg tie hits
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 128), jnp.float32),
            jax.ShapeDtypeStruct((bp, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, bp), jnp.float32),
            jax.ShapeDtypeStruct((1, bp), jnp.float32),
        ],
        interpret=interpret,
    )(dp, a, bm, rv, cr, va)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _batch_hard_loss_vjp(labels, encode, row_valid, block_rows, interpret):
    """Differentiable core: only `loss` carries gradient (count/tie outputs
    are comparison-derived, true gradient zero — same argument as batch_all)."""
    out, _ = _batch_hard_fwd(labels, encode, row_valid, block_rows, interpret)
    return out


def _batch_hard_fwd(labels, encode, row_valid, block_rows, interpret):
    b = labels.shape[0]
    dtype = encode.dtype
    validf = ((jnp.ones(b) if row_valid is None else row_valid)
              .astype(jnp.float32))
    # reuse the batch_all prep: same dp / pair masks, padded to the tile step
    dp, a, bm = _prep_masks(labels, encode, row_valid, (block_rows, 8, 128),
                            interpret)
    bp = dp.shape[0]
    rv = jnp.pad(validf, (0, bp - b)).reshape(1, bp)
    cr = (jnp.arange(bp) < b).astype(jnp.float32).reshape(1, bp)
    va = rv.reshape(bp, 1)
    stats, aw, hph, hnh = _batch_hard_pallas(dp, a, bm, rv, cr, va,
                                             int(block_rows), bool(interpret))
    s_loss, total, sum_hp, sum_hn = (stats[0, 0], stats[0, 1], stats[0, 2],
                                     stats[0, 3])
    data_weight = (aw[:, 0] + hph[0] + hnh[0])[:b].astype(dtype)
    loss = (s_loss / jnp.maximum(total, _EPS)).astype(dtype)
    n_rows = jnp.sum(validf)
    fraction = (total / jnp.maximum(n_rows, 1.0)).astype(dtype)
    extras = {
        "hardest_positive_dotproduct":
            (sum_hp / jnp.maximum(n_rows, 1.0)).astype(dtype),
        "hardest_negative_dotproduct":
            (sum_hn / jnp.maximum(n_rows, 1.0)).astype(dtype),
    }
    out = (loss, data_weight, fraction, total.astype(dtype), extras)
    residuals = (labels, encode, row_valid)
    return out, residuals


def _batch_hard_bwd(block_rows, interpret, residuals, cotangents):
    """Recompute-backward through the O(B^2) blockwise twin: batch_hard's
    gradient is min/max routing over the [B, B] dot matrix (no cube), so XLA
    autodiff of the anchor-tiled scan — tie subgradients identical to the
    dense path — is already memory-optimal; a hand-written transpose kernel
    would buy nothing."""
    labels, encode, row_valid = residuals
    from .triplet_blockwise import batch_hard_triplet_loss_blockwise

    loss_bar = cotangents[0]
    de = jax.grad(
        lambda e: batch_hard_triplet_loss_blockwise(
            labels, e, row_valid=row_valid)[0])(encode)
    return None, de * loss_bar.astype(encode.dtype), None


_batch_hard_loss_vjp.defvjp(_batch_hard_fwd, _batch_hard_bwd)


def batch_hard_triplet_loss_pallas(labels, encode, row_valid=None,
                                   block_rows=None, interpret=None):
    """Drop-in for ops.triplet.batch_hard_triplet_loss, tiled over anchor
    row-blocks so only [block_rows, B] slabs of the dot matrix live in VMEM.

    Keeps the dense reference's quirks bit-for-bit where they are observable
    (zero-valued invalid negatives, float-equality tie counting in
    data_weight) — see _batch_hard_kernel's padded-column notes for why the
    pad columns need ±inf sentinels rather than zeros. Trainable via a
    custom VJP that recomputes through the blockwise XLA twin
    (ops/triplet_blockwise.py), which is O(B^2) by construction.

    Same return tuple: (loss, data_weight[B], fraction, num_triplets, extras).

    :param block_rows: anchor rows per grid step; compiled requires %8==0.
        None resolves through the autotuner cache (tuned row for this
        shape/dtype/device if one exists, tile_defaults otherwise).
    :param interpret: force interpreter mode (defaults to True off-TPU).
    """
    if interpret is None:
        interpret = not _on_tpu()
    if block_rows is None:
        from .. import tuning  # lazy: ops must import without the cache

        cfg, _ = tuning.resolve("batch_hard", encode.shape, encode.dtype)
        block_rows = cfg["block_rows"]
    # trace-time label only (host-side wrapper — never inside the kernel)
    with jax.named_scope("ops/batch_hard_pallas"):
        return _batch_hard_loss_vjp(labels, encode, row_valid,
                                    int(block_rows), bool(interpret))


# ------------------------------------------------------------------ masking noise

def _masking_kernel(seed_ref, x_ref, out_ref, *, v):
    # decorrelate blocks AND seeds: mix with odd-constant multiplies + XOR.
    # Within one call blocks stay distinct (odd multiply is a bijection mod 2^32);
    # across seeds collisions become unstructured ~2^-32 events rather than the
    # systematic block-shifted-mask aliasing of seed+program_id, or the int32
    # wraparound of seed*num_programs+program_id for large seeds/row counts.
    pltpu.prng_seed(seed_ref[0] * 668265295 ^ pl.program_id(0) * 374761393)
    # logical (not arithmetic) shift: raw bits come back signed and Mosaic can't
    # cast uint32->f32, so keep int32 and shift the sign bit out of the way.
    # top 24 bits -> uniform [0, 1): exact float32 arithmetic
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.int32)
    u = jax.lax.shift_right_logical(bits, 8).astype(jnp.float32) * (1.0 / (1 << 24))
    keep = (u >= v).astype(x_ref.dtype)
    out_ref[:] = x_ref[:] * keep


@functools.partial(jax.jit, static_argnames=("v", "block_rows", "interpret"))
def _masking_pallas(seed, x, v, block_rows, interpret):
    bp, f = x.shape
    grid = (bp // block_rows,)
    return pl.pallas_call(
        functools.partial(_masking_kernel, v=v),
        grid=grid,
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((block_rows, f), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_rows, f), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, f), x.dtype),
        # the generic interpreter has no rule for the TPU PRNG primitives — the
        # TPU-flavored interpreter emulates them (bits stubbed to zeros)
        interpret=_INTERPRET_PARAMS() if interpret else False,
    )(seed, x)


def masking_noise_pallas(seed, x, v, block_rows=None, interpret=None):
    """Masking corruption (reference utils.py:94-115 semantics: each element zeroed
    independently with prob v) fused into one pass with on-chip hardware randomness.

    Distributionally equivalent to ops.corruption.masking_noise but a different
    stream — per-seed deterministic, not bit-identical to threefry.

    :param seed: int (or int32 scalar) seed; same seed -> same mask.
    :param v: static python float corruption fraction in [0, 1].
    """
    if not 0.0 <= float(v) <= 1.0:
        raise ValueError(f"corruption fraction must be in [0, 1], got {v}")
    if interpret is None:
        interpret = not _on_tpu()
    if interpret and float(v) > 0.0:
        # the TPU interpreter stubs prng_random_bits to zeros: every element would
        # be dropped (u=0 < v), silently returning an all-zero "corruption"
        raise NotImplementedError(
            "masking_noise_pallas with v > 0 needs real TPU hardware (the "
            "interpreter's PRNG is stubbed to zeros); use "
            "ops.corruption.masking_noise off-TPU")
    if interpret and _INTERPRET_PARAMS is None:
        # jax 0.4.x has no TPU-flavored interpreter at all, and the generic one
        # lacks rules for prng_seed/prng_random_bits. v == 0 here (the v > 0
        # case raised above), and at v == 0 the kernel is the identity
        # (u >= 0 holds for every draw), so skip the pallas_call outright
        return x
    b, f = x.shape
    if block_rows is None:
        from .. import tuning  # lazy: ops must import without the cache

        cfg, _ = tuning.resolve("masking", (b, f), x.dtype)
        block_rows = cfg["block_rows"]
    # keep the (rows, F) block near 2 MB so in+out+temps stay inside ~16 MB VMEM
    vmem_rows = max(8, (2 << 20) // (x.dtype.itemsize * f) // 8 * 8)
    block_rows = min(block_rows, vmem_rows, b)
    bp = int(-(-b // block_rows) * block_rows)
    xp = jnp.pad(x, ((0, bp - b), (0, 0))) if bp != b else x
    seed = jnp.asarray(seed, jnp.int32).reshape(1)
    # trace-time label only (host-side wrapper — never inside the kernel)
    with jax.named_scope("ops/masking_noise_pallas"):
        out = _masking_pallas(seed, xp, float(v), int(block_rows),
                              bool(interpret))
    return out[:b]
