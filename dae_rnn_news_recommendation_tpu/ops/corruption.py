"""Input-corruption transforms for denoising training.

Twins of reference autoencoder/utils.py:94-159 (masking_noise, salt_and_pepper_noise,
decay_noise) — redesigned TPU-first: pure `f(key, x, ...)` functions with static shapes
so they run *inside* the jit-compiled train step on device (the reference corrupts the
whole train set per epoch on host NumPy, autoencoder/autoencoder.py:218).

Distributional semantics are preserved:
  - masking: each element independently zeroed with prob v (reference draws a 0/1 mask
    with p=[v, 1-v], utils.py:108).
  - salt_and_pepper: per row, `n_corrupt` feature indices drawn uniformly *with
    replacement* (reference `np.random.randint(0, n_features, v)`, utils.py:135) are set
    to the data min or max by a fair coin flip. `n_corrupt` is the reference's
    `corruption_ratio = round(corr_frac * n_features)` (autoencoder.py:187). The
    reference's O(rows*v) lil_matrix Python loop (SURVEY §2.3.9) becomes one vectorized
    scatter.
  - decay: multiply by (1 - v) — deterministic, no key needed.

A host-side sparse masking variant is kept for scipy.sparse inputs that never reach the
device (reference utils.py:111-114 nnz-drop semantics).
"""

import jax
import jax.numpy as jnp
import numpy as np


def masking_noise(key, x, v):
    """Zero a fraction v of the elements of x, each chosen independently.

    :param key: jax PRNG key
    :param x: [B, F] array
    :param v: corruption fraction in [0, 1] (python float or scalar)
    """
    if not 0.0 <= float(v) <= 1.0:
        raise ValueError(f"corruption fraction must be in [0, 1], got {v}")
    keep = jax.random.bernoulli(key, p=1.0 - v, shape=x.shape)
    return jnp.where(keep, x, jnp.zeros_like(x))


def salt_and_pepper_noise(key, x, n_corrupt, mn=None, mx=None):
    """Set `n_corrupt` random positions per row to the min or max value (fair coin).

    :param key: jax PRNG key
    :param x: [B, F] array
    :param n_corrupt: static int — number of (with-replacement) positions per row
    :param mn, mx: corruption extremes. Default: min/max of this batch. Pass the global
        train-set min/max to reproduce the reference's whole-matrix semantics
        (utils.py:131-132).
    """
    if n_corrupt <= 0:
        return x
    if mn is None:
        mn = jnp.min(x)
    if mx is None:
        mx = jnp.max(x)
    b, f = x.shape
    k_idx, k_coin = jax.random.split(key)
    cols = jax.random.randint(k_idx, (b, n_corrupt), 0, f)
    coin = jax.random.bernoulli(k_coin, p=0.5, shape=(b, n_corrupt))
    vals = jnp.where(coin, mx, mn).astype(x.dtype)
    rows = jnp.broadcast_to(jnp.arange(b)[:, None], (b, n_corrupt))
    return x.at[rows, cols].set(vals)


def decay_noise(x, v):
    """Decay all elements by fraction v (reference utils.py:147-159)."""
    return x * (1.0 - v)


def corrupt(key, x, corr_type, corr_frac, n_features=None, mn=None, mx=None):
    """Dispatch on corruption type (reference autoencoder.py:248-270 _corrupt_input).

    `corr_type` must be a static python string (selects the traced graph) and
    `corr_frac` a static python float in [0, 1] (reference main_autoencoder.py:100).
    """
    if corr_type != "none" and not 0.0 <= float(corr_frac) <= 1.0:
        raise ValueError(f"corr_frac must be in [0, 1], got {corr_frac}")
    if corr_type == "masking":
        if jax.default_backend() == "tpu" and float(corr_frac) > 0.0:
            # fused hardware-PRNG kernel (same auto-dispatch pattern as the
            # mining paths, train/step.py resolve_mining_impl): one
            # read-mask-write pass with on-chip randomness instead of
            # threefry bit generation + separate where. Distributionally
            # identical, different stream — the kernel is seeded from the
            # step key so runs remain reproducible by key. Trace-time
            # static branch: every other backend (and corr_frac == 0)
            # keeps the threefry path byte-stable.
            from .pallas_kernels import masking_noise_pallas

            seed = jax.random.randint(key, (), 0, jnp.iinfo(jnp.int32).max,
                                      dtype=jnp.int32)
            return masking_noise_pallas(seed, x, float(corr_frac))
        return masking_noise(key, x, corr_frac)
    if corr_type == "salt_and_pepper":
        f = n_features if n_features is not None else x.shape[1]
        n_corrupt = int(np.round(corr_frac * f))
        return salt_and_pepper_noise(key, x, n_corrupt, mn=mn, mx=mx)
    if corr_type == "decay":
        return decay_noise(x, corr_frac)
    if corr_type == "none":
        return x
    raise ValueError(f"unknown corr_type: {corr_type!r}")


def masking_noise_sparse_host(rng, x_sparse, v):
    """Host-side masking for scipy sparse matrices: drop each stored nnz with prob v.

    Reference semantics utils.py:111-114 (an approximation of element-wise masking:
    zeros never flip, only stored entries are dropped).

    :param rng: numpy Generator or RandomState
    :param x_sparse: scipy.sparse matrix
    :param v: drop fraction
    """
    coo = x_sparse.tocoo(copy=True)
    keep = rng.random(coo.nnz) >= v
    coo.row, coo.col, coo.data = coo.row[keep], coo.col[keep], coo.data[keep]
    return coo.tocsr()
