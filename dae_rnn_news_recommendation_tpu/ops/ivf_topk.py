"""Fused IVF retrieval: centroid-scan -> probed-cell gather -> exact rescore.

Two-stage clustered top-k over a cell-major corpus (`index/layout.py`),
making per-query cost sub-linear in corpus size — the exact scorer
(`ops/topk_fused.py`) touches all N rows per query; this path touches
`n_cells` centroids plus `probes` cells' rows:

  stage 1  `topk_fused(h, centroids, ...)` — the existing VMEM-panel
           accumulator kernel reused verbatim with the centroid table as
           its "corpus", so the [B, n_cells] centroid score matrix never
           materializes in HBM; output is just [B, probes] cell ids.
  stage 2  one Pallas kernel per query block: a `PrefetchScalarGridSpec`
           carries the block's deduplicated probe-cell list as a scalar-
           prefetch operand, and the cell-panel BlockSpec's index_map reads
           it — `lambda i, s, cells: (cells[i, s], 0)` — so the gather IS
           the pipelined HBM->VMEM panel fetch; no [B, shortlist] score or
           [B, shortlist, D] gather buffer ever exists in HBM. Inside, the
           [bq, 128] top-k accumulator from `_topk_kernel` is reused
           unchanged except that panel indices come from the layout's
           `row_ids` (original slot row numbers), so results are directly
           comparable with the exact scorer.

Queries in a block share the scanned cell list (the union of their probe
sets, duplicates pointed at the all-padding dummy cell), but a per-query
membership mask keeps the CANDIDATE set per query exactly its own probed
cells — so the kernel and the jnp fallback agree wherever scores are
finite, and at `probes = n_cells` both reproduce the exact scorer bitwise
(scores and indices, -inf ties included; tests/test_ivf.py pins this).
Entries past a query's last finite candidate score -inf; the kernel
reports the INT32_MAX sentinel index there, while the jnp fallback (which
scores all N rows with non-probed rows masked) reports `lax.top_k`'s
real-index tail — callers must treat the -inf tail's indices as
unspecified unless `probes = n_cells`.

Degrades honestly rather than truncating: if `k` exceeds the shortlist
(`probes * cell_cap`) or the accumulator lanes, the call routes to the
exact `topk_fused` over the flat slot arrays the caller already holds.

Off-TPU the default is the jnp fallback; `impl="pallas"` + interpret mode
exercises the kernel's gather/masking/selection logic on CPU.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from .topk_fused import (_ACC_LANES, _IDX_SENTINEL, _on_tpu, topk_fused,
                         topk_sharded)

from ..parallel.mesh import _shard_map
from .tile_defaults import IVF_BQ as DEFAULT_BQ


def _resolve_bq(bq, queries, cells, emb_dtype, k, probes):
    """The rescore kernel's query block: explicit caller choice wins, else
    the autotuner cache (tuned row for this shape/dtype/device if one
    exists), else the hand-picked tile_defaults.IVF_BQ."""
    if bq is not None:
        return bq
    from .. import tuning  # lazy: ops must import without the cache

    cfg, _ = tuning.resolve(
        "ivf_topk",
        (queries.shape[0], cells.n_cells, cells.cell_cap,
         queries.shape[1], k, probes), emb_dtype)
    return cfg["bq"]


def _ivf_kernel(cells_ref, q_ref, p_ref, e_ref, r_ref, v_ref, s_ref,
                os_ref, oi_ref, *, k, bq, cap):
    i, s = pl.program_id(0), pl.program_id(1)

    @pl.when(s == 0)
    def _():
        os_ref[:] = jnp.full((bq, _ACC_LANES), -jnp.inf, jnp.float32)
        oi_ref[:] = jnp.full((bq, _ACC_LANES), _IDX_SENTINEL, jnp.int32)

    cell_id = cells_ref[i, s]                       # which cell this step is
    q = q_ref[:]                                    # [bq, D] f32 queries
    panel = e_ref[:].astype(jnp.float32)            # [cap, D] dequant to f32
    ps = jax.lax.dot_general(q, panel, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ps = ps * s_ref[:]                              # per-row int8 scale
    # candidate set per QUERY is its own probe list, even though the block
    # scans the union: non-members see the whole panel as -inf
    member = jnp.any(p_ref[:] == cell_id, axis=1, keepdims=True)  # [bq, 1]
    ps = jnp.where(member & (v_ref[:] > 0), ps, -jnp.inf)
    # original slot row ids from the layout; padding slots carry the
    # sentinel and lose every -inf tie to real rows
    pidx = jnp.broadcast_to(r_ref[:], (bq, cap))

    acc_s, acc_i = os_ref[:], oi_ref[:]
    lane = jax.lax.broadcasted_iota(jnp.int32, (bq, _ACC_LANES), 1)
    new_s = jnp.full((bq, _ACC_LANES), -jnp.inf, jnp.float32)
    new_i = jnp.full((bq, _ACC_LANES), _IDX_SENTINEL, jnp.int32)
    for t in range(k):  # k static selection steps, unrolled
        m = jnp.maximum(jnp.max(acc_s, axis=1, keepdims=True),
                        jnp.max(ps, axis=1, keepdims=True))
        sel = jnp.minimum(
            jnp.min(jnp.where(acc_s == m, acc_i, _IDX_SENTINEL),
                    axis=1, keepdims=True),
            jnp.min(jnp.where(ps == m, pidx, _IDX_SENTINEL),
                    axis=1, keepdims=True))
        new_s = jnp.where(lane == t, m, new_s)
        new_i = jnp.where(lane == t, sel, new_i)
        # real row ids are unique across the deduped cell list; only the
        # sentinel repeats, and retiring it is a no-op (-inf already)
        acc_s = jnp.where(acc_i == sel, -jnp.inf, acc_s)
        acc_i = jnp.where(acc_i == sel, _IDX_SENTINEL, acc_i)
        ps = jnp.where(pidx == sel, -jnp.inf, ps)
        pidx = jnp.where(pidx == sel, _IDX_SENTINEL, pidx)
    os_ref[:] = new_s
    oi_ref[:] = new_i


@functools.partial(jax.jit, static_argnames=("k", "cap", "bq", "interpret"))
def _ivf_pallas(queries, cell_ids, cell_emb, cell_valid, cell_scales,
                row_ids, k, cap, bq, interpret):
    b, d = queries.shape
    probes = cell_ids.shape[1]
    total = row_ids.shape[0]
    c = total // cap - 1                             # real cells; dummy = c
    dp = -(-d // 128) * 128
    bp = -(-b // bq) * bq
    nb = bp // bq

    q = jnp.pad(queries.astype(jnp.float32), ((0, bp - b), (0, dp - d)))
    e = jnp.pad(cell_emb, ((0, 0), (0, dp - d)))
    v = cell_valid.astype(jnp.float32).reshape(1, total)
    sc = cell_scales.astype(jnp.float32).reshape(1, total)
    r = row_ids.reshape(1, total)

    # pad queries probe the dummy cell only; then dedup each block's union
    # (sorted, repeats -> dummy) so no real row id is scanned twice
    ids = jnp.pad(cell_ids.astype(jnp.int32), ((0, bp - b), (0, 0)),
                  constant_values=c)
    s_list = jnp.sort(ids.reshape(nb, bq * probes), axis=1)
    dup = jnp.concatenate(
        [jnp.zeros((nb, 1), bool), s_list[:, 1:] == s_list[:, :-1]], axis=1)
    block_cells = jnp.where(dup, c, s_list).astype(jnp.int32)

    # per-query membership lists, lane-padded with the dummy cell id
    p_lanes = -(-probes // 128) * 128
    probed = jnp.pad(ids, ((0, 0), (0, p_lanes - probes)), constant_values=c)

    kernel = functools.partial(_ivf_kernel, k=k, bq=bq, cap=cap)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, bq * probes),            # cell-list axis innermost: the
        in_specs=[                         # accumulator block is revisited
            pl.BlockSpec((bq, dp), lambda i, s, cells: (i, 0)),
            pl.BlockSpec((bq, p_lanes), lambda i, s, cells: (i, 0)),
            # the gather: the probed cell's slab IS this step's input block
            pl.BlockSpec((cap, dp), lambda i, s, cells: (cells[i, s], 0)),
            pl.BlockSpec((1, cap), lambda i, s, cells: (0, cells[i, s])),
            pl.BlockSpec((1, cap), lambda i, s, cells: (0, cells[i, s])),
            pl.BlockSpec((1, cap), lambda i, s, cells: (0, cells[i, s])),
        ],
        out_specs=[
            pl.BlockSpec((bq, _ACC_LANES), lambda i, s, cells: (i, 0)),
            pl.BlockSpec((bq, _ACC_LANES), lambda i, s, cells: (i, 0)),
        ],
    )
    out_s, out_i = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bp, _ACC_LANES), jnp.float32),
            jax.ShapeDtypeStruct((bp, _ACC_LANES), jnp.int32),
        ],
        interpret=interpret,
    )(block_cells, q, probed, e, r, v, sc)
    return out_s[:b, :k], out_i[:b, :k]


def _ivf_reference(queries, emb, valid, scales, assign, cell_ids, k,
                   n_cells):
    """jnp fallback: the exact scorer with non-probed cells masked out.

    At `probes = n_cells` the mask is all-True and this IS
    `_topk_reference` — bitwise the oracle by construction.
    """
    b, n = queries.shape[0], emb.shape[0]
    probed = jnp.zeros((b, n_cells + 1), bool)
    probed = probed.at[jnp.arange(b)[:, None], cell_ids].set(True)
    row_probed = jnp.take_along_axis(
        probed, jnp.broadcast_to(assign[None, :].astype(jnp.int32), (b, n)),
        axis=1)
    embf = emb.astype(jnp.float32)
    scores = jax.lax.dot_general(queries.astype(jnp.float32), embf,
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    if scales is not None:
        scores = scores * scales[None, :].astype(jnp.float32)
    scores = jnp.where((valid[None, :] > 0) & row_probed, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def ivf_topk(queries, emb, valid, k, *, cells, probes, scales=None,
             impl=None, interpret=None, bq=None):
    """Clustered top-k: probe `probes` cells per query, rescore exactly.

    :param queries: [B, D] float32, unit-normalized upstream
    :param emb: [N, D] flat slot corpus (fallback + degrade paths)
    :param valid: [N] flat mask
    :param k: static; output is ([B, k] f32 scores, [B, k] int32 ORIGINAL
        slot row ids), descending score, finite entries tie-broken by
        ascending index exactly like `lax.top_k`
    :param cells: IVFCells layout built over the SAME slot arrays
    :param probes: cells scanned per query; `probes = n_cells` is exact
    :param scales: [N] f32 per-row dequant scales (int8 corpus), else None
    :param impl: "pallas" | "jnp" | None (None: pallas on TPU, jnp elsewhere)
    :param interpret: Pallas interpreter mode; None = not on TPU
    :param bq: queries per kernel block (min f32 sublane tile by default)
    """
    k = int(k)
    n = emb.shape[0]
    if not 1 <= k <= n:
        raise ValueError(f"k={k} outside [1, N={n}]")
    n_cells, cap = cells.n_cells, cells.cell_cap
    probes = int(min(max(int(probes), 1), n_cells))
    if impl is None:
        impl = "pallas" if _on_tpu() else "jnp"
    if k > min(probes * cap, _ACC_LANES):
        # the shortlist (or accumulator) cannot hold k candidates: degrade
        # honestly to the exact scorer instead of returning a truncated list
        return topk_fused(queries, emb, valid, k, scales=scales, impl=impl,
                          interpret=interpret)
    h = queries.astype(jnp.float32)
    cent_valid = jnp.ones((n_cells,), jnp.float32)
    with jax.named_scope(f"ops/ivf_centroid_scan_p{probes}"):
        _, cell_ids = topk_fused(h, cells.centroids, cent_valid, probes,
                                 impl=impl, interpret=interpret)
    if impl == "jnp":
        with jax.named_scope(f"ops/ivf_rescore_jnp_k{k}"):
            return _ivf_reference(h, emb, valid, scales, cells.assign,
                                  cell_ids, k, n_cells)
    if interpret is None:
        interpret = not _on_tpu()
    bq = _resolve_bq(bq, queries, cells, emb.dtype, k, probes)
    cell_scales = (cells.cell_scales if scales is not None else
                   jnp.ones((cells.row_ids.shape[0],), jnp.float32))
    # trace-time label only (host-side wrapper — never inside the kernel)
    with jax.named_scope(f"ops/ivf_rescore_k{k}"):
        return _ivf_pallas(h, cell_ids, cells.cell_emb, cells.cell_valid,
                           cell_scales, cells.row_ids, k=k, cap=cap, bq=bq,
                           interpret=interpret)


def _ivf_local_reference(queries, cell_emb, cell_valid, cell_scales,
                         row_ids, local_ids, k, cap):
    """Shard-local jnp fallback over one shard's slab arrays.

    Rows are sorted ascending by GLOBAL slot row id before `lax.top_k`, so
    finite ties break exactly like the unsharded fallback (and the kernel's
    min-global-id selection); sentinel padding rows sort last and score
    -inf. Scores are the same bytes as the unsharded scorer's — each row's
    dot reduces the same D values in the same order, and the ×1.0 scale on
    fp32 corpora is an IEEE identity.
    """
    b = queries.shape[0]
    total = row_ids.shape[0]
    probed = jnp.zeros((b, total // cap), bool)
    probed = probed.at[jnp.arange(b)[:, None], local_ids].set(True)
    row_probed = probed[:, jnp.arange(total, dtype=jnp.int32) // cap]
    scores = jax.lax.dot_general(queries.astype(jnp.float32),
                                 cell_emb.astype(jnp.float32),
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    scores = scores * cell_scales[None, :].astype(jnp.float32)
    scores = jnp.where((cell_valid[None, :] > 0) & row_probed, scores,
                       -jnp.inf)
    order = jnp.argsort(row_ids)
    s_top, pos = jax.lax.top_k(scores[:, order], k)
    return s_top, row_ids[order][pos]


def sharded_ivf_topk(queries, emb, valid, k, *, cells, probes, mesh,
                     axis_name="data", scales=None, impl=None,
                     interpret=None, bq=None):
    """`ivf_topk` over a mesh-sharded cell layout (`ShardedIVFCells`).

    Stage 1 (centroid scan) runs replicated — centroids are [C, D] on every
    device and only [B, probes] cell ids come out. Stage 2 runs under
    `shard_map`: each shard maps the probed GLOBAL cell ids to local slots
    (non-owned probes point at its local all-padding dummy), then runs the
    same scalar-prefetch gather kernel / jnp fallback as the unsharded path
    over ONLY its own (cps+1) slabs. Because the layout's `row_ids` carry
    global slot rows, the per-shard [B, k] results merge with the same
    axis-offset index-exact k-way merge the sharded exact scorer uses:
    concatenate along the shard axis, sort candidates ascending by global
    id, and let `lax.top_k`'s positional tie-break reproduce the unsharded
    (score desc, id asc) order bitwise for all finite entries. The -inf
    tail's indices remain unspecified unless `probes = n_cells`.

    Degrades like `ivf_topk`, but to the sharded exact scorer
    (`topk_sharded`) over the flat slot arrays.

    :param emb: [N_pad, D] row-sharded flat slots (degrade path only)
    :param valid: [N_pad] row-sharded flat mask (degrade path only)
    :param cells: ShardedIVFCells with `n_shards == mesh.shape[axis_name]`
    :param mesh: the mesh the corpus (and `cells`) are sharded over
    """
    k = int(k)
    n = emb.shape[0]
    if not 1 <= k <= n:
        raise ValueError(f"k={k} outside [1, N={n}]")
    n_dev = int(mesh.shape[axis_name])
    if n_dev != cells.n_shards:
        raise ValueError(
            f"index built for {cells.n_shards} shards, mesh has {n_dev}")
    n_cells, cap = cells.n_cells, cells.cell_cap
    cps = int(cells.cells_per_shard)
    probes = int(min(max(int(probes), 1), n_cells))
    if impl is None:
        impl = "pallas" if _on_tpu() else "jnp"
    if k > min(probes * cap, _ACC_LANES):
        return topk_sharded(queries, emb, valid, k, mesh=mesh,
                            axis_name=axis_name, scales=scales, impl=impl,
                            interpret=interpret)
    h = queries.astype(jnp.float32)
    cent_valid = jnp.ones((n_cells,), jnp.float32)
    with jax.named_scope(f"ops/ivf_centroid_scan_p{probes}"):
        _, cell_ids = topk_fused(h, cells.centroids, cent_valid, probes,
                                 impl=impl, interpret=interpret)
    if interpret is None:
        interpret = not _on_tpu()
    bq = _resolve_bq(bq, queries, cells, emb.dtype, k, probes)
    cell_scales = (cells.cell_scales if scales is not None else
                   jnp.ones(cells.row_ids.shape, jnp.float32))

    def local(e_l, v_l, sc_l, r_l, h_l, ids_l):
        s = jax.lax.axis_index(axis_name)
        gid = ids_l.astype(jnp.int32)
        owned = (gid >= s * cps) & (gid < s * cps + cps)
        local_ids = jnp.where(owned, gid - s * cps, cps).astype(jnp.int32)
        if impl == "jnp":
            return _ivf_local_reference(h_l, e_l, v_l, sc_l, r_l, local_ids,
                                        k, cap)
        return _ivf_pallas(h_l, local_ids, e_l, v_l, sc_l, r_l, k=k, cap=cap,
                           bq=bq, interpret=interpret)

    s_cat, i_cat = _shard_map(
        local, mesh=mesh,
        in_specs=(P(axis_name, None), P(axis_name), P(axis_name),
                  P(axis_name), P(None, None), P(None, None)),
        out_specs=(P(None, axis_name), P(None, axis_name)),
        check_rep=False)(  # pallas_call has no replication rule
            cells.cell_emb, cells.cell_valid, cell_scales, cells.row_ids,
            h, cell_ids)
    with jax.named_scope(f"ops/ivf_sharded_merge_k{k}"):
        order = jnp.argsort(i_cat, axis=1)      # ascending global id
        s_srt = jnp.take_along_axis(s_cat, order, axis=1)
        i_srt = jnp.take_along_axis(i_cat, order, axis=1)
        s_top, pos = jax.lax.top_k(s_srt, k)
        return s_top, jnp.take_along_axis(i_srt, pos, axis=1)
