"""Legacy image-dataset driver: DAE on MNIST / CIFAR-10, unsupervised.

Twin of the reference's autoencoder/run_autoencoder.py (flags :9-46, main :48-90),
which is BROKEN upstream — it passes n_components=/dataset= kwargs the current ctor
does not accept and imports an empty package (SURVEY §2.3.7). This version actually
runs: the estimator grew an explicit `n_components` override, and the dataset flag
only selects the loader.

Run: python -m dae_rnn_news_recommendation_tpu.cli.run_autoencoder \
        --dataset mnist --n_components 64 --num_epochs 5 --verbose
"""

import argparse

from ..data.image_datasets import MNIST_SHAPE, load_cifar10_dataset, load_mnist_dataset
from ..models import DenoisingAutoencoder


def build_parser():
    p = argparse.ArgumentParser(description="DAE on legacy image datasets (MNIST/CIFAR-10)")
    # global configuration (reference run_autoencoder.py:13-21)
    p.add_argument("--model_name", default="dae")
    p.add_argument("--dataset", default="mnist", choices=["mnist", "cifar10"])
    p.add_argument("--cifar_dir", default="")
    p.add_argument("--mnist_dir", default="MNIST_data/")
    p.add_argument("--seed", type=int, default=-1)
    p.add_argument("--restore_previous_model", action="store_true", default=False)
    p.add_argument("--encode_train", action="store_true", default=False)
    p.add_argument("--encode_valid", action="store_true", default=False)
    p.add_argument("--encode_test", action="store_true", default=False)
    # model parameters (reference :24-40)
    p.add_argument("--n_components", type=int, default=256)
    p.add_argument("--corr_type", default="none",
                   choices=["none", "masking", "salt_and_pepper", "decay"])
    p.add_argument("--corr_frac", type=float, default=0.0)
    p.add_argument("--xavier_init", type=int, default=1)
    p.add_argument("--enc_act_func", default="tanh", choices=["sigmoid", "tanh"])
    p.add_argument("--dec_act_func", default="none", choices=["sigmoid", "tanh", "none"])
    p.add_argument("--main_dir", default="legacy")
    p.add_argument("--loss_func", default="mean_squared",
                   choices=["cross_entropy", "mean_squared"])
    p.add_argument("--verbose", type=int, default=0)
    p.add_argument("--weight_images", type=int, default=0)
    p.add_argument("--opt", default="gradient_descent",
                   choices=["gradient_descent", "ada_grad", "momentum", "adam"])
    p.add_argument("--learning_rate", type=float, default=0.01)
    p.add_argument("--momentum", type=float, default=0.5)
    p.add_argument("--num_epochs", type=int, default=10)
    p.add_argument("--batch_size", type=int, default=10)
    return p


def main(argv=None):
    FLAGS = build_parser().parse_args(argv)
    assert 0.0 <= FLAGS.corr_frac <= 1.0

    if FLAGS.dataset == "mnist":
        trX, vlX, teX = load_mnist_dataset(mode="unsupervised", data_dir=FLAGS.mnist_dir)
        width, height = MNIST_SHAPE
    else:
        trX, teX = load_cifar10_dataset(FLAGS.cifar_dir, mode="unsupervised")
        vlX = teX[: max(1, len(teX) // 2)]  # reference: first half of test (:66)
        width = height = 32

    dae = DenoisingAutoencoder(
        seed=FLAGS.seed, model_name=FLAGS.model_name,
        n_components=FLAGS.n_components, enc_act_func=FLAGS.enc_act_func,
        dec_act_func=FLAGS.dec_act_func, xavier_init=FLAGS.xavier_init,
        corr_type=FLAGS.corr_type, corr_frac=FLAGS.corr_frac,
        loss_func=FLAGS.loss_func, main_dir=FLAGS.main_dir, opt=FLAGS.opt,
        learning_rate=FLAGS.learning_rate, momentum=FLAGS.momentum,
        verbose=FLAGS.verbose, num_epochs=FLAGS.num_epochs,
        batch_size=FLAGS.batch_size, triplet_strategy="none")

    # unsupervised: validation is the test set, like the reference (:85)
    dae.fit(trX, teX, restore_previous_model=FLAGS.restore_previous_model)

    if FLAGS.encode_train:
        dae.transform(trX, name="train", save=True)
    if FLAGS.encode_valid:
        dae.transform(vlX, name="validation", save=True)
    if FLAGS.encode_test:
        dae.transform(teX, name="test", save=True)

    if FLAGS.weight_images > 0:
        dae.get_weights_as_images(width, height, max_images=FLAGS.weight_images)
    return dae


if __name__ == "__main__":
    main()
