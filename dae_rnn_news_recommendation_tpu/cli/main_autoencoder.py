"""End-to-end experiment driver: data prep -> DAE fit (online triplet mining) ->
encode -> AUROC plots -> nearest-neighbor printout.

Twin of reference main_autoencoder.py (flags :23-111, data prep :161-263, fit :277,
eval tail :303-360), with its known defects fixed rather than replicated:
  - restore path actually appends the validation rows (SURVEY §2.3.3)
  - validation labels come from the validation split, not the train split (§2.3.2)
  - corr_type/corr_frac env keys are wired correctly (§2.3.1, in utils/config.py)

Run: python -m dae_rnn_news_recommendation_tpu.cli.main_autoencoder \
        --model_name uci --verbose --synthetic --num_epochs 5
"""

import os

import joblib
import numpy as np
import pandas as pd

from ..data import articles, io as hio
from ..models import DenoisingAutoencoder
from ..ops.corruption import decay_noise
from ..utils.config import parse_flags


def prepare_or_restore_data(model, FLAGS):
    """Reference main_autoencoder.py:161-263."""
    train_row, validate_row = FLAGS.train_row, FLAGS.validate_row

    if FLAGS.restore_previous_data:
        d = model.data_dir
        article_contents = pd.concat([
            hio.read_file(d + "article.snappy.parquet"),
            hio.read_file(d + "article_validate.snappy.parquet"),
        ])
        X = hio.read_file(d + "article_binary_count_vectorized.npz")
        X_validate = hio.read_file(d + "article_binary_count_vectorized_validate.npz")
        labels = {
            ("category_publish_name", "train"): hio.read_file(
                d + "article_label_category_publish_name.pkl", data_type="pandas_series"),
            ("category_publish_name", "validate"): hio.read_file(
                d + "article_label_category_publish_name_validate.pkl", data_type="pandas_series"),
            ("story", "train"): hio.read_file(
                d + "article_label_story.pkl", data_type="pandas_series"),
            ("story", "validate"): hio.read_file(
                d + "article_label_story_validate.pkl", data_type="pandas_series"),
        }
        X_tfidf = hio.read_file(d + "article_tfidf_vectorized.npz")
        X_tfidf_validate = hio.read_file(d + "article_tfidf_vectorized_validate.npz")
        return article_contents, X, X_validate, X_tfidf, X_tfidf_validate, labels

    if FLAGS.synthetic:
        n = int((train_row + validate_row)
                * max(getattr(FLAGS, "synthetic_oversample", 1.0), 1.0))
        article_contents = articles.synthetic_articles(
            n_articles=max(n, 100), vocab_size=FLAGS.synthetic_vocab,
            seed=max(FLAGS.seed, 0))
    else:
        article_contents = articles.read_articles(path=FLAGS.data_path)
    article_contents = article_contents.sort_index(ascending=False)

    # label engineering (reference :180-198)
    story_counts = article_contents.story.value_counts()
    story_idx = article_contents.story.isin(story_counts[story_counts > 0].index)
    article_contents["label_story_valid"] = 0
    article_contents.loc[story_idx, "label_story_valid"] = 1
    article_contents["label_story"] = pd.factorize(article_contents.story)[0]

    cate = article_contents.category_publish_name.map(lambda s: s.lstrip("即時"))
    cate_counts = article_contents.category_publish_name.value_counts()
    cate_idx = article_contents.category_publish_name.isin(
        cate_counts[cate_counts > 0].index)
    article_contents["label_category_publish_name_valid"] = 0
    article_contents.loc[cate_idx, "label_category_publish_name_valid"] = 1
    article_contents["label_category_publish_name"] = pd.factorize(cate)[0]

    if FLAGS.triplet_strategy != "none":
        article_contents = article_contents.loc[
            article_contents["label_" + FLAGS.label + "_valid"] == 1, ]

    article_contents = (article_contents.iloc[: train_row + validate_row]
                        .sample(frac=1, random_state=max(FLAGS.seed, 0)))
    article_contents = article_contents.sort_values("article_id")
    if FLAGS.validation and len(article_contents) <= train_row:
        raise ValueError(
            f"only {len(article_contents)} rows remain after filtering to "
            f"label_{FLAGS.label}_valid rows but --train_row {train_row} "
            "+ --validation needs more; lower the split sizes or raise "
            "--synthetic_oversample (the story label keeps ~35% of "
            "synthetic rows)")
    train_row = min(train_row, len(article_contents))

    count_vectorizer, X, _, _ = articles.count_vectorize(
        article_contents.main_content[:train_row],
        tokenizer=None, stop_words="english",
        min_df=FLAGS.min_df, max_df=FLAGS.max_df,
        max_features=FLAGS.max_features, binary=False)
    X_validate = count_vectorizer.transform(
        article_contents.main_content[train_row : train_row + validate_row])
    tfidf_transformer, X_tfidf = articles.tfidf_transform(X)
    X_tfidf_validate = tfidf_transformer.transform(X_validate)

    labels = {}
    for lab in ("category_publish_name", "story"):
        labels[(lab, "train")] = article_contents["label_" + lab][:train_row]
        labels[(lab, "validate")] = article_contents["label_" + lab][
            train_row : train_row + validate_row]

    # save artifacts (reference :227-244)
    d = model.data_dir
    hio.save_file(article_contents.iloc[:train_row], d + "article.snappy.parquet")
    hio.save_file(article_contents.iloc[train_row : train_row + validate_row],
                  d + "article_validate.snappy.parquet")
    hio.save_file(labels[("category_publish_name", "train")],
                  d + "article_label_category_publish_name.pkl")
    hio.save_file(labels[("category_publish_name", "validate")],
                  d + "article_label_category_publish_name_validate.pkl")
    hio.save_file(labels[("story", "train")], d + "article_label_story.pkl")
    hio.save_file(labels[("story", "validate")], d + "article_label_story_validate.pkl")
    hio.save_file(X, d + "article_count_vectorized.npz")
    hio.save_file(X_validate, d + "article_count_vectorized_validate.npz")
    X = X.copy(); X.data = np.ones_like(X.data)
    X_validate = X_validate.copy(); X_validate.data = np.ones_like(X_validate.data)
    hio.save_file(X, d + "article_binary_count_vectorized.npz")
    hio.save_file(X_validate, d + "article_binary_count_vectorized_validate.npz")
    hio.save_file(X_tfidf, d + "article_tfidf_vectorized.npz")
    hio.save_file(X_tfidf_validate, d + "article_tfidf_vectorized_validate.npz")
    joblib.dump(count_vectorizer, d + "count_vectorizer.joblib")
    joblib.dump(tfidf_transformer, d + "tfidf_transformer.joblib")

    return article_contents, X, X_validate, X_tfidf, X_tfidf_validate, labels


def main(argv=None):
    FLAGS = parse_flags(argv)
    print(__file__ + ": Start")

    mesh = None
    if FLAGS.model_parallel > 1:
        from ..parallel import get_mesh_2d
        assert FLAGS.n_devices % FLAGS.model_parallel == 0, (
            f"--model_parallel {FLAGS.model_parallel} must divide "
            f"--n_devices {FLAGS.n_devices}")
        mesh = get_mesh_2d(FLAGS.n_devices // FLAGS.model_parallel,
                           FLAGS.model_parallel)

    model_cls, extra_kwargs = DenoisingAutoencoder, {}
    if FLAGS.n_experts > 1:
        from ..models import MoEDenoisingAutoencoder

        model_cls = MoEDenoisingAutoencoder
        extra_kwargs = {"n_experts": FLAGS.n_experts}

    model = model_cls(
        **extra_kwargs,
        mesh=mesh, seed=FLAGS.seed, model_name=FLAGS.model_name,
        compress_factor=FLAGS.compress_factor, enc_act_func=FLAGS.enc_act_func,
        dec_act_func=FLAGS.dec_act_func, xavier_init=FLAGS.xavier_init,
        corr_type=FLAGS.corr_type, corr_frac=FLAGS.corr_frac,
        loss_func=FLAGS.loss_func, main_dir=FLAGS.main_dir, opt=FLAGS.opt,
        learning_rate=FLAGS.learning_rate, momentum=FLAGS.momentum,
        verbose=FLAGS.verbose, verbose_step=FLAGS.verbose_step,
        num_epochs=FLAGS.num_epochs, batch_size=FLAGS.batch_size,
        alpha=FLAGS.alpha, triplet_strategy=FLAGS.triplet_strategy,
        label2_alpha=(FLAGS.label2_alpha if FLAGS.label2 != "none" else 0.0),
        n_devices=FLAGS.n_devices, mining_scope=FLAGS.mining_scope,
        compute_dtype=FLAGS.compute_dtype, checkpoint_every=FLAGS.checkpoint_every,
        profile=FLAGS.profile, sparse_feed=bool(FLAGS.sparse_feed),
        weight_update_sharding=FLAGS.weight_update_sharding,
        resident_feed={"auto": "auto", "on": True, "off": False}[
            FLAGS.resident_feed])

    (article_contents, X, X_validate, X_tfidf, X_tfidf_validate,
     labels) = prepare_or_restore_data(model, FLAGS)

    data_dict = {
        "binary": {"train": X, "validate": X_validate},
        "tfidf": {"train": X_tfidf, "validate": X_tfidf_validate},
        "label_category_publish_name": {
            "train": labels[("category_publish_name", "train")],
            "validate": labels[("category_publish_name", "validate")]},
        "label_story": {"train": labels[("story", "train")],
                        "validate": labels[("story", "validate")]},
    }

    trX = data_dict[FLAGS.input_format]["train"]
    trX_label = data_dict["label_" + FLAGS.label]["train"]
    trX_label2 = vlX_label2 = None
    if FLAGS.label2 != "none":
        trX_label2 = data_dict["label_" + FLAGS.label2]["train"]
    vlX = vlX_label = None
    if FLAGS.validation:
        vlX = data_dict[FLAGS.input_format]["validate"]
        # fixed: the reference fed TRAIN labels here (SURVEY §2.3.2)
        vlX_label = data_dict["label_" + FLAGS.label]["validate"]
        if FLAGS.label2 != "none":
            vlX_label2 = data_dict["label_" + FLAGS.label2]["validate"]

    print("fit")
    model.fit(train_set=trX, validation_set=vlX, train_set_label=trX_label,
              validation_set_label=vlX_label,
              restore_previous_model=FLAGS.restore_previous_model,
              train_set_label2=trX_label2, validation_set_label2=vlX_label2)
    with open(model.parameter_file, "a+") as f:
        for k in ("train_row", "validate_row", "input_format", "label",
                  "label2", "restore_previous_data", "restore_previous_model"):
            print(f"{k}={getattr(FLAGS, k)}", file=f)
    print("fit done")

    # encode with expected-value scaling of the masking corruption (reference
    # :289-290). The sparse matrix goes to transform() as-is: it densifies per
    # batch internally, so the full [N, F] array never materializes on host.
    X_encoded = model.transform(
        decay_noise(data_dict[FLAGS.input_format]["train"], FLAGS.corr_frac),
        name="article_encoded", save=FLAGS.encode_full)
    X_encoded_validate = model.transform(
        decay_noise(data_dict[FLAGS.input_format]["validate"], FLAGS.corr_frac),
        name="article_encoded_validate", save=FLAGS.encode_full)

    if FLAGS.save_tsv:
        hio.save_file(X_tfidf, model.tsv_dir + "article_tfidf_vectorized.tsv")
        hio.save_file(X_tfidf_validate, model.tsv_dir + "article_tfidf_vectorized_validate.tsv")
        hio.save_file(X, model.tsv_dir + "article_binary_count_vectorized.tsv")
        hio.save_file(X_validate, model.tsv_dir + "article_binary_count_vectorized_validate.tsv")
        cols = ["label_story", "label_category_publish_name", "title", "story",
                "category_publish_name"]
        n_train = len(labels[("category_publish_name", "train")])
        hio.save_file(article_contents.iloc[:n_train][cols],
                      model.tsv_dir + "article_label.tsv")
        hio.save_file(article_contents.iloc[n_train:][cols],
                      model.tsv_dir + "article_label_validate.tsv")
        hio.save_file(X_encoded, model.tsv_dir + "article_encoded.tsv")
        hio.save_file(X_encoded_validate, model.tsv_dir + "article_encoded_validate.tsv")

    # the default eval tail holds six full [N, N] float32 matrices on host; above
    # the threshold that's the memory wall, so the streaming path takes over
    # (tfidf rows are l2-normalized, so cosine == the reference's linear kernel)
    n_eval_max = max(X.shape[0], X_validate.shape[0])
    streaming = FLAGS.streaming_eval or n_eval_max > FLAGS.streaming_eval_threshold
    if streaming and not FLAGS.streaming_eval:
        print(f"eval: {n_eval_max} rows > streaming_eval_threshold="
              f"{FLAGS.streaming_eval_threshold}, using streaming path")

    from .eval_tail import nn_printout, similarity_eval

    wanted = [r.strip() for r in FLAGS.eval_reps.split(",") if r.strip()]
    reps = {"tfidf": (X_tfidf, X_tfidf_validate),
            "binary_count": (X, X_validate),
            "encoded": (X_encoded, X_encoded_validate)}
    reps = {k: v for k, v in reps.items() if k in wanted}
    label_dict = {
        "label_category_publish_name": {
            "train": labels[("category_publish_name", "train")],
            "validate": labels[("category_publish_name", "validate")]},
        "label_story": {"train": labels[("story", "train")],
                        "validate": labels[("story", "validate")]},
    }
    sim_cache = {}
    aurocs = similarity_eval(reps, label_dict, model.plot_dir, streaming,
                             sim_cache=sim_cache)
    for k, v in sorted(aurocs.items()):
        print(f"AUROC {k}: {v:.4f}")

    n_train = len(labels[("category_publish_name", "train")])
    nn_printout(article_contents.iloc[:n_train], X_encoded, X, streaming,
                sim_cache=sim_cache)

    print(__file__ + ": End")
    return model, aurocs


if __name__ == "__main__":
    main()
