"""StarSpace baseline driver: the CLI equivalent of the reference's
starspace/prepare_starspace_formatted_data.ipynb — export fastText-format
files, train the native StarSpace-style embedding trainer, embed train and
validation docs, and compare AUROC against tf-idf similarity.

Reference flow (notebook cells): 3 inverse-transform token lists, 4-5 write
"w1 w2 ... __label__cat" files, 6 `starspace train -dim 50 -epoch 50
-thread 20`, 7 `embed_doc`, 9-13 AUROC comparison. The external binary is
replaced by the in-repo native trainer (native/src/starspace.cc).

Run: python -m dae_rnn_news_recommendation_tpu.cli.main_starspace \
        --model_name uci_starspace --synthetic --train_row 500 --validate_row 200
"""

import argparse
import os

import numpy as np
import pandas as pd

from ..baselines import (StarSpaceConfig, embed_docs, export_fasttext_format,
                         train_starspace)
from ..baselines.starspace import tokens_from_csr
from ..data import articles, io as hio
from ..eval import pairwise_similarity, visualize_pairwise_similarity


def parse_flags(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model_name", default="uci_starspace")
    p.add_argument("--main_dir", default="")
    p.add_argument("--data_path", default="datasets/uci_news.snappy.parquet")
    p.add_argument("--synthetic", action="store_true",
                   help="generate a synthetic UCI-news-shaped corpus")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--train_row", type=int, default=5000)   # train.log:26
    p.add_argument("--validate_row", type=int, default=5348)
    p.add_argument("--max_features", type=int, default=10000)
    p.add_argument("--dim", type=int, default=50)           # train.log:4
    p.add_argument("--lr", type=float, default=0.01)        # train.log:2
    p.add_argument("--margin", type=float, default=0.05)    # train.log:9
    p.add_argument("--epochs", type=int, default=50)
    p.add_argument("--neg", type=int, default=10)           # train.log:11
    p.add_argument("--threads", type=int, default=20)       # train.log:13
    p.add_argument("--patience", type=int, default=10)      # train.log:21
    p.add_argument("--from_artifacts", default="",
                   help="data dir of a main_autoencoder run: train on the "
                        "EXACT article split it saved (article.snappy.parquet"
                        " / article_validate.snappy.parquet), the way the "
                        "reference notebook exports the DAE run's own split "
                        "(prepare_starspace_formatted_data.ipynb cells 3-5) — "
                        "makes three-way DAE/tfidf/StarSpace AUROCs "
                        "same-corpus by construction")
    return p.parse_args(argv)


def main(argv=None):
    FLAGS = parse_flags(argv)
    print(__file__ + ": Start")
    out_dir = os.path.join("results", "starspace",
                           FLAGS.main_dir or FLAGS.model_name) + os.sep
    os.makedirs(out_dir, exist_ok=True)

    if FLAGS.from_artifacts:
        # the reference notebook doesn't build its own corpus — it exports the
        # DAE run's saved split and trains StarSpace on that, so the AUROC
        # comparison is one corpus by construction; mirror that here
        d = FLAGS.from_artifacts.rstrip(os.sep) + os.sep
        tr = hio.read_file(d + "article.snappy.parquet", data_type="pandas_df")
        vl = hio.read_file(d + "article_validate.snappy.parquet",
                           data_type="pandas_df")
        contents = pd.concat([tr, vl])
        contents = contents[contents.category_publish_name.notna()].copy()
        # one factorization over both splits keeps label ids consistent
        contents["label_category"] = pd.factorize(
            contents.category_publish_name)[0]
        n_tr = len(tr[tr.category_publish_name.notna()])
        tr = contents.iloc[:n_tr]
        vl = contents.iloc[n_tr:]
        print(f"from_artifacts: {len(tr)} train / {len(vl)} validate rows "
              f"from {d}")
    else:
        n = FLAGS.train_row + FLAGS.validate_row
        if FLAGS.synthetic:
            contents = articles.synthetic_articles(n_articles=max(n, 100),
                                                   seed=FLAGS.seed)
        else:
            contents = articles.read_articles(path=FLAGS.data_path)
        # factorize gives -1 for missing categories, which the trainer rejects
        contents = contents[contents.category_publish_name.notna()].iloc[:n]
        contents = contents.copy()
        contents["label_category"] = pd.factorize(
            contents.category_publish_name)[0]
        tr = contents.iloc[: FLAGS.train_row]
        vl = contents.iloc[FLAGS.train_row : n]

    vec, X, _, _ = articles.count_vectorize(
        tr.main_content, tokenizer=None, stop_words="english",
        max_features=FLAGS.max_features, binary=True)
    X_vl = vec.transform(vl.main_content)
    vocab = {v: k for k, v in vec.vocabulary_.items()}

    # fastText-format artifacts, interchangeable with the real binary's input
    export_fasttext_format(tokens_from_csr(X, vocab),
                           tr.category_publish_name,
                           out_dir + "uci_train_starspace.txt")
    export_fasttext_format(tokens_from_csr(X_vl, vocab),
                           vl.category_publish_name,
                           out_dir + "uci_validate_starspace.txt")

    config = StarSpaceConfig(dim=FLAGS.dim, lr=FLAGS.lr, margin=FLAGS.margin,
                             epochs=FLAGS.epochs, neg=FLAGS.neg,
                             threads=FLAGS.threads, patience=FLAGS.patience,
                             seed=FLAGS.seed)
    result = train_starspace(
        X, tr.label_category.to_numpy(),
        X_vl, vl.label_category.to_numpy(), config=config)
    print(f"early stopping loss is {result['best_val_error']:.6f}")
    for e, err in enumerate(result["epoch_errors"]):
        print(f"epoch {e} validation error {err:.6f}")

    emb_tr = embed_docs(X, result["word_emb"])
    emb_vl = embed_docs(X_vl, result["word_emb"])
    # embedding dumps in the reference's uci_*_embed.txt shape (rows x dim tsv)
    np.savetxt(out_dir + "uci_train_starspace_embed.txt", emb_tr, fmt="%.6f",
               delimiter="\t")
    np.savetxt(out_dir + "uci_validate_starspace_embed.txt", emb_vl,
               fmt="%.6f", delimiter="\t")

    # AUROC comparison vs tf-idf (notebook cells 9-13)
    tfidf_tf, X_tfidf = articles.tfidf_transform(X)
    X_tfidf_vl = tfidf_tf.transform(X_vl)
    aurocs = {}
    for name, sim, labels in (
        ("starspace_train", pairwise_similarity(emb_tr, metric="cosine"),
         tr.label_category),
        ("starspace_validate", pairwise_similarity(emb_vl, metric="cosine"),
         vl.label_category),
        ("tfidf_train", pairwise_similarity(X_tfidf, metric="linear kernel"),
         tr.label_category),
        ("tfidf_validate",
         pairwise_similarity(X_tfidf_vl, metric="linear kernel"),
         vl.label_category),
    ):
        aurocs[name] = visualize_pairwise_similarity(
            labels.to_numpy(), sim, plot="boxplot",
            title=f"Cosine Similarity ({name})",
            save_path=out_dir + f"similarity_{name}.png")
    for k, v in sorted(aurocs.items()):
        print(f"AUROC {k}: {v:.4f}")
    print(__file__ + ": End")
    return result, aurocs


if __name__ == "__main__":
    main()
