"""Shared similarity-eval tail for the experiment drivers: representations x
splits x label kinds -> AUROCs + boxplot PNGs + nearest-neighbor printout.

One implementation of the reference's duplicated eval blocks
(main_autoencoder.py:303-360 and main_autoencoder_triplet.py:249-321 repeat the
same pairwise-similarity/plot/NN code driver by driver), with the memory-safe
streaming variant selected by the caller: above the full-matrix threshold the
[N, N] similarity matrices never materialize (eval/streaming_auroc.py)."""

import numpy as np

LABEL_KINDS = (("label_category_publish_name", "(Category)"),
               ("label_story", "(Story)"))
REP_TITLES = {"tfidf": "TFIDF Vectorized",
              "binary_count": "Binary Count Vectorized",
              "encoded": "Encoded"}


def similarity_eval(reps, labels, plot_dir, streaming, sim_cache=None):
    """AUROCs for every representation x split x label kind.

    reps:   {kind: (train_matrix, validate_matrix_or_None)}
    labels: {label_kind: {"train": 1-D labels, "validate": labels_or_None}}
            with label kinds named as in LABEL_KINDS
    Returns {key: auroc} under the reference's artifact naming
    (`similarity_boxplot_{kind}[_validate]{suffix}`); degenerate label/split
    combinations yield nan and skip their plot.

    `sim_cache` (non-streaming only): a dict the TRAIN-split [N, N] similarity
    matrices are stashed into by kind, so nn_printout can reuse instead of
    recompute them — they are the eval tail's memory high-water mark.
    """
    aurocs = {}
    if streaming:
        from ..eval import streaming_auroc, visualize_similarity_from_histograms

        for kind, (tr_rep, vl_rep) in reps.items():
            for split, rep in (("train", tr_rep), ("validate", vl_rep)):
                if rep is None:
                    continue
                # both label kinds share one pair sweep (similarity blocks
                # are label-independent)
                kinds_here = [(lab, sfx) for lab, sfx in LABEL_KINDS
                              if labels.get(lab, {}).get(split) is not None]
                if not kinds_here:
                    continue
                lab_mat = np.stack([np.asarray(labels[lab][split])
                                    for lab, _ in kinds_here])
                _, h_rel, h_unrel, edges = streaming_auroc(
                    rep, lab_mat, return_histograms=True)
                for l, (lab, suffix) in enumerate(kinds_here):
                    key = (f"similarity_boxplot_{kind}"
                           f"{'_validate' if split == 'validate' else ''}"
                           f"{suffix}")
                    aurocs[key] = visualize_similarity_from_histograms(
                        h_rel[l], h_unrel[l], edges,
                        title=(f"Cosine Similarity ({REP_TITLES[kind]}) "
                               f"({split.title()} Data){suffix}"),
                        save_path=plot_dir + key + ".png")
        return aurocs

    from ..eval import pairwise_similarity, visualize_pairwise_similarity

    for kind, (tr_rep, vl_rep) in reps.items():
        metric = "linear kernel" if kind == "tfidf" else "cosine"
        for split, rep in (("train", tr_rep), ("validate", vl_rep)):
            if rep is None:
                continue
            sim = pairwise_similarity(rep, metric=metric)
            if (split == "train" and sim_cache is not None
                    and kind in ("encoded", "binary_count")):
                sim_cache[kind] = sim
            for lab, suffix in LABEL_KINDS:
                lab_vals = labels.get(lab, {}).get(split)
                if lab_vals is None:
                    continue
                key = (f"similarity_boxplot_{kind}"
                       f"{'_validate' if split == 'validate' else ''}{suffix}")
                aurocs[key] = visualize_pairwise_similarity(
                    np.asarray(lab_vals), sim, plot="boxplot",
                    title=(f"Cosine Similarity ({REP_TITLES[kind]}) "
                           f"({split.title()} Data){suffix}"),
                    save_path=plot_dir + key + ".png")
    return aurocs


def nn_printout(article_rows, enc_rep, count_rep, streaming, sim_cache=None):
    """Print the reference's 5-article nearest-neighbor comparison (encoded vs
    count representation); article_rows must align with the matrices' rows.
    `sim_cache` reuses train-split similarity matrices a preceding
    similarity_eval already built (missing kinds are computed here)."""
    if streaming:
        from ..eval import nearest_neighbor_report_from_top1, streaming_top1

        rows = nearest_neighbor_report_from_top1(
            article_rows,
            streaming_top1(enc_rep, metric="cosine"),
            streaming_top1(count_rep, metric="cosine"))
    else:
        from ..eval import nearest_neighbor_report, pairwise_similarity

        cache = sim_cache or {}
        enc_sim = cache.get("encoded")
        if enc_sim is None:
            enc_sim = pairwise_similarity(enc_rep, metric="cosine")
        count_sim = cache.get("binary_count")
        if count_sim is None:
            count_sim = pairwise_similarity(count_rep, metric="cosine")
        rows = nearest_neighbor_report(article_rows, enc_sim, count_sim)
    for row in rows:
        print(row["article"])
        print("most similar article using count vectorizer")
        print(row["most_similar_by_count"])
        print("most similar article using DAE")
        print(row["most_similar_by_embedding"])
        print(f"score: {row['score']}")
        print()
