"""End-to-end user-embedding pipeline: DAE article embeddings -> per-user browse
sequences -> GRU user states -> pairwise-ranked recommendation eval.

This is the second half of the Yahoo! paper ("Embedding-based News Recommendation
for Millions of Users" §4-5) that the reference repo never implemented (its
README.md:5 defers it; SURVEY §1 "nothing RNN-related exists") — completed here
TPU-native: article embeddings from the jitted DAE, the user GRU trained with the
paper's pairwise softplus rank loss (models/gru_user.py), optional sequence-parallel
inference over a time-sharded mesh (parallel/seq.py).

Stages:
  1. corpus: synthetic UCI-news-shaped articles (or a parquet via --data_path)
     -> binary count vectors (data/articles.py)
  2. articles: DAE fit + encode -> [N, D] embeddings (models/estimator.py)
  3. sessions: simulated browse histories — each user has an interest category,
     browses mostly inside it; the clicked "next article" is the positive, a
     random other-category article the negative
  4. user model: GRUUserModel fit on (seq, pos, neg) embedding triples
  5. eval: held-out users — per-step ranking accuracy (s_pos > s_neg) and top-1
     interest-category accuracy over one candidate article per category

Run: python -m dae_rnn_news_recommendation_tpu.cli.main_user_model \
        --model_name demo --n_users 200 --seq_len 12 --verbose
"""

import argparse
import json
import os

import numpy as np

from ..data import articles
from ..models import DenoisingAutoencoder
from ..models.gru_user import GRUUserModel
from ..utils.dirs import create_run_directories


def build_parser():
    p = argparse.ArgumentParser(description="DAE->GRU user-embedding pipeline")
    p.add_argument("--model_name", default="user")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--verbose", action="store_true", default=False)
    # corpus / article embeddings
    p.add_argument("--n_articles", type=int, default=2000)
    p.add_argument("--max_features", type=int, default=2000)
    p.add_argument("--n_components", type=int, default=64)
    p.add_argument("--dae_epochs", type=int, default=5)
    p.add_argument("--dae_learning_rate", type=float, default=0.1)
    p.add_argument("--stacked_layers", default="",
                   help="comma-separated hidden sizes (e.g. '128,64') — use a "
                        "greedy-pretrained stacked DAE (the paper's deep variant) "
                        "instead of the single-layer DAE; the last size becomes "
                        "the embedding dim")
    p.add_argument("--finetune_epochs", type=int, default=0,
                   help="joint fine-tune epochs after stacked pretraining")
    # sessions
    p.add_argument("--n_users", type=int, default=200)
    p.add_argument("--seq_len", type=int, default=12)
    p.add_argument("--p_interest", type=float, default=0.85,
                   help="prob a browsed article comes from the user's interest")
    p.add_argument("--holdout_frac", type=float, default=0.2)
    # user GRU
    p.add_argument("--gru_hidden", type=int, default=0, help="0 = same as embed dim")
    p.add_argument("--gru_epochs", type=int, default=20)
    p.add_argument("--gru_learning_rate", type=float, default=1e-2)
    p.add_argument("--gru_batch_size", type=int, default=64)
    # optional sequence-parallel inference check (virtual or real mesh)
    p.add_argument("--seq_devices", type=int, default=0,
                   help=">0: also run user states through the time-sharded "
                        "pipeline mesh and assert parity")
    return p


def simulate_sessions(categories, n_users, seq_len, rng, p_interest=0.85):
    """Index-level browse simulation. Returns dict of [U, T] index arrays plus the
    per-user interest category [U]."""
    cats = np.unique(categories)
    by_cat = {c: np.where(categories == c)[0] for c in cats}
    browse = np.empty((n_users, seq_len), np.int64)
    pos = np.empty((n_users, seq_len), np.int64)
    neg = np.empty((n_users, seq_len), np.int64)
    interest = rng.choice(cats, size=n_users)
    for u in range(n_users):
        mine = by_cat[interest[u]]
        for t in range(seq_len):
            if rng.uniform() < p_interest:
                browse[u, t] = rng.choice(mine)
            else:
                browse[u, t] = rng.integers(0, len(categories))
            pos[u, t] = rng.choice(mine)  # the next click: in-interest
            other = rng.choice(cats[cats != interest[u]])
            neg[u, t] = rng.choice(by_cat[other])
    return {"browse": browse, "pos": pos, "neg": neg, "interest": interest}


def main(argv=None):
    FLAGS = build_parser().parse_args(argv)
    rng = np.random.default_rng(FLAGS.seed)
    print(__file__ + ": Start")

    # ---- stage 1-2: corpus -> DAE article embeddings
    corpus = articles.synthetic_articles(n_articles=FLAGS.n_articles, seed=FLAGS.seed)
    _, X, _, _ = articles.count_vectorize(
        corpus.main_content, tokenizer=None, stop_words="english",
        max_features=FLAGS.max_features, binary=True)
    categories = corpus.category_publish_name.factorize()[0]

    # shared DAE hyperparameters for both the shallow and stacked paths
    dae_hp = dict(enc_act_func="tanh", dec_act_func="none",
                  loss_func="mean_squared", corr_type="masking", corr_frac=0.3,
                  opt="ada_grad", learning_rate=FLAGS.dae_learning_rate,
                  num_epochs=FLAGS.dae_epochs, batch_size=256, seed=FLAGS.seed,
                  verbose=FLAGS.verbose)
    models_dir, data_dir, logs_dir, _, _ = create_run_directories(
        "gru_user", FLAGS.model_name)
    if FLAGS.stacked_layers:
        from ..models import StackedDenoisingAutoencoder

        layers = [int(s) for s in FLAGS.stacked_layers.split(",") if s.strip()]
        assert layers and all(l > 0 for l in layers), (
            f"--stacked_layers must be positive hidden sizes, got "
            f"{FLAGS.stacked_layers!r}")
        sdae = StackedDenoisingAutoencoder(layers, **dae_hp)
        sdae.fit(X)
        if FLAGS.finetune_epochs > 0:
            sdae.fit_finetune(X, num_epochs=FLAGS.finetune_epochs)
        # pretraining already computed the deepest codes; fine-tuning stales them
        emb = (sdae.fit_representation_ if sdae.fit_representation_ is not None
               else sdae.encode(X))
    else:
        dae = DenoisingAutoencoder(
            algo_name="gru_user", model_name=FLAGS.model_name,
            main_dir=FLAGS.model_name, n_components=FLAGS.n_components,
            triplet_strategy="none", **dae_hp)
        dae.fit(X)
        emb = dae.transform(X, name="article_embeddings", save=False)
    # center before normalizing: bag-of-words corpora share a dominant common
    # component (frequent words in every article) that pushes all codes nearly
    # collinear; removing it is what makes cosine geometry discriminative
    emb = emb - emb.mean(axis=0, keepdims=True)
    emb = emb / (np.linalg.norm(emb, axis=1, keepdims=True) + 1e-12)
    # persist the embeddings the GRU is actually trained/scored against, so the
    # saved artifacts (embeddings + gru params) share one geometry
    np.save(os.path.join(data_dir, "article_embeddings.npy"), emb)

    # ---- stage 3: browse sessions
    sessions = simulate_sessions(categories, FLAGS.n_users, FLAGS.seq_len, rng,
                                 FLAGS.p_interest)
    seq_e = emb[sessions["browse"]]
    pos_e = emb[sessions["pos"]]
    neg_e = emb[sessions["neg"]]
    n_hold = max(1, int(FLAGS.n_users * FLAGS.holdout_frac))
    tr = slice(0, FLAGS.n_users - n_hold)
    te = slice(FLAGS.n_users - n_hold, FLAGS.n_users)

    # ---- stage 4: GRU user model
    assert FLAGS.gru_hidden in (0, emb.shape[1]), (
        f"--gru_hidden must be 0 or equal n_components ({emb.shape[1]}): the "
        "relevance score <state, embed> needs matching dimensions")
    gru = GRUUserModel(
        d_embed=emb.shape[1], d_hidden=FLAGS.gru_hidden or None,
        opt="adam", learning_rate=FLAGS.gru_learning_rate,
        num_epochs=FLAGS.gru_epochs, batch_size=FLAGS.gru_batch_size,
        seed=FLAGS.seed, verbose=FLAGS.verbose)
    gru.fit(seq_e[tr], pos_e[tr], neg_e[tr])

    # ---- stage 5: held-out eval
    import jax.numpy as jnp

    from ..models.gru_user import gru_apply

    states, finals = gru_apply(gru.params, jnp.asarray(seq_e[te]))
    states = np.asarray(states)
    s_pos = np.sum(states * pos_e[te], axis=-1)
    s_neg = np.sum(states * neg_e[te], axis=-1)
    rank_acc = float((s_pos > s_neg).mean())
    # CI over per-user accuracies (decisions within a user share its state
    # trajectory, so user is the independent unit, not the [U, T] decision);
    # undefined at n=1 — report 0.0, not NaN (NaN breaks strict JSON parsers)
    per_user = (s_pos > s_neg).mean(axis=1)
    rank_ci95 = (float(1.96 * per_user.std(ddof=1) / np.sqrt(len(per_user)))
                 if len(per_user) > 1 else 0.0)

    # does the user's state rank their interest category first? Each category
    # is represented by the mean score over up to 5 sampled candidate articles
    # — a single candidate made the metric hostage to one draw's embedding
    # (measured swing ~±0.1 at 500 users)
    cats = np.unique(categories)
    cand_scores = []
    for c in cats:
        pool = np.where(categories == c)[0]
        cand = rng.choice(pool, size=min(5, len(pool)), replace=False)
        cand_scores.append((np.asarray(finals) @ emb[cand].T).mean(axis=1))
    scores = np.stack(cand_scores, axis=1)                 # [U_te, C]
    top1 = cats[scores.argmax(axis=1)]
    cat_acc = float((top1 == sessions["interest"][te]).mean())

    if FLAGS.seq_devices > 0:
        from jax.sharding import Mesh
        import jax

        from ..parallel import pipeline_gru_apply

        n_dev = FLAGS.seq_devices
        mesh = Mesh(np.array(jax.devices()[:n_dev]).reshape(n_dev), ("seq",))
        t_len = seq_e.shape[1]
        assert t_len % n_dev == 0, (
            f"--seq_devices {n_dev} must divide --seq_len {t_len}")
        _, finals_sp = pipeline_gru_apply(
            gru.params, jnp.asarray(seq_e[te]),
            jnp.ones(seq_e[te].shape[:2], jnp.float32), mesh, microbatches=1)
        np.testing.assert_allclose(np.asarray(finals), np.asarray(finals_sp),
                                   atol=1e-4)
        print(f"sequence-parallel({n_dev}) user states: parity ok")

    metrics = {"rank_accuracy": rank_acc, "rank_accuracy_ci95": rank_ci95,
               "category_top1_accuracy": cat_acc,
               "n_users_eval": int(n_hold), "seq_len": FLAGS.seq_len,
               "d_embed": int(emb.shape[1])}
    print(json.dumps(metrics))

    # loadable via GRUUserModel.load (geometry embedded in the npz)
    gru.save(os.path.join(models_dir, "gru_user_params.npz"))
    with open(os.path.join(logs_dir, "user_model_metrics.json"), "w") as f:
        json.dump(metrics, f)
    print(__file__ + ": End")
    return gru, metrics


if __name__ == "__main__":
    main()
