"""Experiment driver for the precomputed-triplet DAE: per-category pos/neg article
mapping -> three aligned matrices -> DenoisingAutoencoderTriplet fit -> same eval tail.

Twin of reference main_autoencoder_triplet.py (flags :16-71, triplet prep :142-156
via similar_articles, fit :240, eval :249-321).

Run: python -m dae_rnn_news_recommendation_tpu.cli.main_autoencoder_triplet \
        --model_name uci_triplet --verbose --synthetic --num_epochs 5
"""

import numpy as np
import pandas as pd

from ..data import articles, io as hio
from ..models import DenoisingAutoencoderTriplet
from ..ops.corruption import decay_noise
from ..utils.config import parse_flags


def main(argv=None):
    FLAGS = parse_flags(argv, triplet_mode=True)
    print(__file__ + ": Start")

    mesh = None
    if FLAGS.model_parallel > 1:
        from ..parallel import get_mesh_2d
        assert FLAGS.n_devices % FLAGS.model_parallel == 0, (
            f"--model_parallel {FLAGS.model_parallel} must divide "
            f"--n_devices {FLAGS.n_devices}")
        mesh = get_mesh_2d(FLAGS.n_devices // FLAGS.model_parallel,
                           FLAGS.model_parallel)

    model = DenoisingAutoencoderTriplet(
        mesh=mesh, seed=FLAGS.seed, model_name=FLAGS.model_name,
        compress_factor=FLAGS.compress_factor, enc_act_func=FLAGS.enc_act_func,
        dec_act_func=FLAGS.dec_act_func, xavier_init=FLAGS.xavier_init,
        corr_type=FLAGS.corr_type, corr_frac=FLAGS.corr_frac,
        loss_func=FLAGS.loss_func, main_dir=FLAGS.main_dir, opt=FLAGS.opt,
        learning_rate=FLAGS.learning_rate, momentum=FLAGS.momentum,
        verbose=FLAGS.verbose, verbose_step=FLAGS.verbose_step,
        num_epochs=FLAGS.num_epochs, batch_size=FLAGS.batch_size, alpha=FLAGS.alpha,
        n_devices=FLAGS.n_devices, compute_dtype=FLAGS.compute_dtype,
        checkpoint_every=FLAGS.checkpoint_every, profile=FLAGS.profile,
        sparse_feed=bool(FLAGS.sparse_feed),
        weight_update_sharding=FLAGS.weight_update_sharding)

    train_row, validate_row = FLAGS.train_row, FLAGS.validate_row

    if FLAGS.synthetic:
        n = int((train_row + validate_row)
                * max(getattr(FLAGS, "synthetic_oversample", 1.0), 1.0))
        article_contents = articles.synthetic_articles(
            n_articles=max(n, 100),
            vocab_size=FLAGS.synthetic_vocab, seed=max(FLAGS.seed, 0))
    else:
        article_contents = articles.read_articles(path=FLAGS.data_path)

    # label engineering (same as the online-mining driver)
    article_contents["label_story"] = pd.factorize(article_contents.story)[0]
    article_contents["label_category_publish_name"] = pd.factorize(
        article_contents.category_publish_name.map(lambda s: s.lstrip("即時")))[0]

    # positive/negative mapping. The reference keys it on category only
    # (similar_articles, datasets/articles.py:83-128), which by construction
    # carries no Story signal: positives are merely same-CATEGORY neighbors,
    # so same-story pairs are pushed no closer than any category pair.
    # --label story (net-new) keys the same recipe on the story column —
    # positive = next article in the same story, negative = random article
    # from a different (or no) story — so the triplet path can carry Story.
    map_key = "story" if FLAGS.label == "story" else "category_publish_name"
    article_contents = articles.similar_articles(
        article_contents, id_colname="article_id",
        cate_colname=map_key, min_cate=2,
        seed=max(FLAGS.seed, 0))
    valid = article_contents[article_contents.valid_triplet_data == 1]
    valid = valid.iloc[: train_row + validate_row]
    if FLAGS.validation and len(valid) <= train_row:
        raise ValueError(
            f"only {len(valid)} valid-triplet rows remain (mapping keyed on "
            f"{map_key!r}) but --train_row {train_row} + --validation needs "
            "more; lower the split sizes or raise --synthetic_oversample "
            "(~35% of synthetic rows carry a story, and min_cate=2 filters "
            "singleton groups)")
    train_row = min(train_row, len(valid))

    content = article_contents.main_content
    org_series = valid.main_content[:train_row]
    pos_series = content.loc[valid.article_id_pos[:train_row]]
    neg_series = content.loc[valid.article_id_neg[:train_row]]

    count_vectorizer, X, X_pos, X_neg = articles.count_vectorize(
        org_series, pos_series, neg_series,
        tokenizer=None, stop_words="english",
        min_df=FLAGS.min_df, max_df=FLAGS.max_df,
        max_features=FLAGS.max_features, binary=False)

    def binarize(m):
        m = m.copy(); m.data = np.ones_like(m.data); return m

    tfidf_transformer, X_tfidf = articles.tfidf_transform(X)
    if FLAGS.input_format == "binary":
        train = {"org": binarize(X), "pos": binarize(X_pos), "neg": binarize(X_neg)}
        trX = binarize(X)
    else:
        train = {"org": X_tfidf,
                 "pos": tfidf_transformer.transform(X_pos),
                 "neg": tfidf_transformer.transform(X_neg)}
        trX = X_tfidf

    validation = None
    if FLAGS.validation and len(valid) > train_row:
        vo = content.loc[valid.article_id[train_row:]]
        vp = content.loc[valid.article_id_pos[train_row:]]
        vn = content.loc[valid.article_id_neg[train_row:]]
        vo_m, vp_m, vn_m = (count_vectorizer.transform(s) for s in (vo, vp, vn))
        if FLAGS.input_format == "binary":
            validation = {"org": binarize(vo_m), "pos": binarize(vp_m),
                          "neg": binarize(vn_m)}
        else:
            validation = {"org": tfidf_transformer.transform(vo_m),
                          "pos": tfidf_transformer.transform(vp_m),
                          "neg": tfidf_transformer.transform(vn_m)}

    print("fit")
    model.fit(train_set=train, validation_set=validation,
              restore_previous_model=FLAGS.restore_previous_model)
    print("fit done")

    # sparse stays sparse: transform() densifies per batch internally, so the
    # full [N, F] array never materializes on host (the main driver's fix)
    X_encoded = model.transform(
        decay_noise(trX, FLAGS.corr_frac),
        name="article_encoded", save=FLAGS.encode_full)
    X_encoded_validate = None
    if validation is not None:
        X_encoded_validate = model.transform(
            decay_noise(validation["org"], FLAGS.corr_frac),
            name="article_encoded_validate", save=FLAGS.encode_full)

    # reference-parity eval tail (main_autoencoder_triplet.py:249-321): all
    # three representations x both splits x both label kinds, shared with the
    # online-mining driver
    from .eval_tail import nn_printout, similarity_eval

    X_bin = binarize(X)
    vo_tfidf = X_bin_validate = None
    n_validate = 0
    if validation is not None:
        # validation['org'] already holds one of the two eval forms of vo_m —
        # reuse it for that branch instead of re-transforming
        if FLAGS.input_format == "binary":
            X_bin_validate = validation["org"]
            vo_tfidf = tfidf_transformer.transform(vo_m)
        else:
            vo_tfidf = validation["org"]
            X_bin_validate = binarize(vo_m)
        n_validate = vo_m.shape[0]
    reps = {"tfidf": (X_tfidf, vo_tfidf),
            "binary_count": (X_bin, X_bin_validate),
            "encoded": (X_encoded, X_encoded_validate)}
    has_vl = validation is not None
    label_dict = {
        lab: {"train": valid[lab][:train_row],
              "validate": valid[lab][train_row:] if has_vl else None}
        for lab in ("label_category_publish_name", "label_story")
    }
    streaming = (FLAGS.streaming_eval
                 or max(trX.shape[0], n_validate) > FLAGS.streaming_eval_threshold)
    sim_cache = {}
    aurocs = similarity_eval(reps, label_dict, model.plot_dir, streaming,
                             sim_cache=sim_cache)
    for k, v in sorted(aurocs.items()):
        print(f"AUROC {k}: {v:.4f}")

    nn_printout(valid.iloc[:train_row], X_encoded, X_bin, streaming,
                sim_cache=sim_cache)

    print(__file__ + ": End")
    return model, aurocs


if __name__ == "__main__":
    main()
