// Native StarSpace-style embedding trainer: the external C++ baseline the
// reference compares against (reference starspace/prepare_starspace_formatted_data.ipynb
// cells 6-7 shell out to Facebook's `starspace train ... -dim 50 -similarity cosine
// -loss hinge -adagrad true -thread 20`; its arg dump is starspace/train.log:1-28).
// The reference does not vendor the binary; this file is a from-scratch native
// equivalent of the trainMode=0 document/label path it uses:
//
//   - a document embeds as the mean of its word embeddings
//   - similarity(doc, label) = cosine
//   - loss = hinge: sum_neg max(0, margin - cos(doc, pos) + cos(doc, neg)),
//     negatives drawn uniformly from the other labels (maxNegSamples)
//   - per-row adagrad updates, hogwild over `threads` std::threads
//   - per-epoch validation error with best-epoch early stopping (patience),
//     matching the reference run's "early stopping loss is 0.018963 / patience 10"
//     (starspace/train.log:115-121)
//
// C ABI only; driven from Python via ctypes (native/__init__.py), wrapped with
// format export + NumPy oracle in baselines/starspace.py.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Model {
  float* word_emb;   // [V, dim]
  float* label_emb;  // [L, dim]
  float* word_g2;    // adagrad accumulators, per row
  float* label_g2;
  int dim;
  int vocab;
  int n_labels;
  float lr;
  float margin;
  int neg;
};

inline void doc_embed(const Model& m, const int32_t* words, int64_t n,
                      float* out) {
  std::memset(out, 0, sizeof(float) * m.dim);
  if (n == 0) return;
  for (int64_t j = 0; j < n; ++j) {
    const float* w = m.word_emb + static_cast<int64_t>(words[j]) * m.dim;
    for (int d = 0; d < m.dim; ++d) out[d] += w[d];
  }
  const float inv = 1.0f / static_cast<float>(n);
  for (int d = 0; d < m.dim; ++d) out[d] *= inv;
}

inline float dot(const float* a, const float* b, int dim) {
  float s = 0.f;
  for (int d = 0; d < dim; ++d) s += a[d] * b[d];
  return s;
}

inline float norm(const float* a, int dim) {
  return std::sqrt(dot(a, a, dim)) + 1e-8f;
}

// d cos(a,b) / d a = b/(|a||b|) - cos * a/|a|^2
inline void cos_grad_a(const float* a, const float* b, int dim, float* out,
                       float* cos_out) {
  const float na = norm(a, dim), nb = norm(b, dim);
  const float c = dot(a, b, dim) / (na * nb);
  *cos_out = c;
  const float inv_ab = 1.0f / (na * nb), inv_aa = c / (na * na);
  for (int d = 0; d < dim; ++d) out[d] = b[d] * inv_ab - a[d] * inv_aa;
}

inline void adagrad_row(float* row, float* g2, const float* grad, int dim,
                        float lr) {
  float gn2 = 0.f;
  for (int d = 0; d < dim; ++d) gn2 += grad[d] * grad[d];
  *g2 += gn2;  // per-row accumulator (StarSpace-style scalar adagrad)
  const float step = lr / std::sqrt(*g2 + 1e-8f);
  for (int d = 0; d < dim; ++d) row[d] -= step * grad[d];
}

// One training example: doc i with positive label y against `neg` sampled
// negatives. Returns the example loss.
float train_example(Model& m, const int32_t* words, int64_t n_words, int32_t y,
                    std::mt19937& rng, std::vector<float>& scratch) {
  if (n_words == 0 || m.n_labels < 2) return 0.f;
  const int dim = m.dim;
  scratch.resize(static_cast<size_t>(dim) * 4);
  float* doc = scratch.data();
  float* gpos = doc + dim;   // d cos(doc,pos)/d doc
  float* gneg = gpos + dim;  // d cos(doc,neg)/d doc for current neg
  float* gdoc = gneg + dim;  // accumulated gradient w.r.t. doc embedding

  doc_embed(m, words, n_words, doc);
  float* pos_row = m.label_emb + static_cast<int64_t>(y) * dim;
  float cos_pos;
  cos_grad_a(doc, pos_row, dim, gpos, &cos_pos);

  std::memset(gdoc, 0, sizeof(float) * dim);
  std::uniform_int_distribution<int> pick(0, m.n_labels - 1);
  float loss = 0.f;
  int active = 0;
  for (int k = 0; k < m.neg; ++k) {
    int yn = pick(rng);
    if (yn == y) yn = (yn + 1) % m.n_labels;
    float* neg_row = m.label_emb + static_cast<int64_t>(yn) * dim;
    float cos_neg;
    cos_grad_a(doc, neg_row, dim, gneg, &cos_neg);
    const float l = m.margin - cos_pos + cos_neg;
    if (l <= 0.f) continue;
    loss += l;
    ++active;
    // d l / d doc = -gpos + gneg ; d l / d pos = -dcos(doc,pos)/dpos ; etc.
    for (int d = 0; d < dim; ++d) gdoc[d] += gneg[d] - gpos[d];
    float grad_label[512];
    float c;
    // gradient w.r.t. the negative label row
    cos_grad_a(neg_row, doc, dim, grad_label, &c);
    adagrad_row(neg_row, m.label_g2 + yn, grad_label, dim, m.lr);
  }
  if (active > 0) {
    float grad_label[512];
    float c;
    cos_grad_a(pos_row, doc, dim, grad_label, &c);
    for (int d = 0; d < dim; ++d) grad_label[d] *= -static_cast<float>(active);
    adagrad_row(pos_row, m.label_g2 + y, grad_label, dim, m.lr);
    // doc gradient distributes over its words: doc = mean(words) so each word
    // row sees gdoc / n_words.
    const float scale = 1.0f / static_cast<float>(n_words);
    std::vector<float> gw(dim);
    for (int64_t j = 0; j < n_words; ++j) {
      const int32_t w = words[j];
      for (int d = 0; d < dim; ++d) gw[d] = gdoc[d] * scale;
      adagrad_row(m.word_emb + static_cast<int64_t>(w) * dim, m.word_g2 + w,
                  gw.data(), dim, m.lr);
    }
  }
  return loss;
}

// Mean hinge loss over a (held-out) set, negatives sampled with a fixed seed so
// the metric is deterministic across calls.
double eval_loss(const Model& m, const int64_t* indptr, const int32_t* indices,
                 int64_t n_docs, const int32_t* labels, int neg, uint64_t seed) {
  if (n_docs == 0) return 0.0;
  std::mt19937 rng(static_cast<uint32_t>(seed));
  std::uniform_int_distribution<int> pick(0, m.n_labels - 1);
  std::vector<float> doc(m.dim), g(m.dim);
  double total = 0.0;
  for (int64_t i = 0; i < n_docs; ++i) {
    const int64_t lo = indptr[i], n = indptr[i + 1] - lo;
    if (n == 0) continue;
    doc_embed(m, indices + lo, n, doc.data());
    float cos_pos;
    cos_grad_a(doc.data(), m.label_emb + static_cast<int64_t>(labels[i]) * m.dim,
               m.dim, g.data(), &cos_pos);
    for (int k = 0; k < neg; ++k) {
      int yn = pick(rng);
      if (yn == labels[i]) yn = (yn + 1) % m.n_labels;
      float cos_neg;
      cos_grad_a(doc.data(), m.label_emb + static_cast<int64_t>(yn) * m.dim,
                 m.dim, g.data(), &cos_neg);
      const float l = m.margin - cos_pos + cos_neg;
      if (l > 0.f) total += l;
    }
  }
  return total / static_cast<double>(n_docs);
}

}  // namespace

extern "C" {

// Train word/label embeddings; returns the best validation error seen (or the
// final train error when no validation set is given). Arrays word_emb [V,dim]
// and label_emb [L,dim] must be pre-initialized by the caller (uniform small
// random, as StarSpace does); they are updated in place, and on early stop the
// best-epoch snapshot is restored into them.
//
// epoch_errors (nullable): float64[epochs], filled with the per-epoch
// validation (or train) error, -1 for epochs not reached (early stop).
double starspace_train(const int64_t* indptr, const int32_t* indices,
                       int64_t n_docs, const int32_t* labels, int vocab,
                       int n_labels, int dim, float lr, float margin, int neg,
                       int epochs, int threads, int patience,
                       const int64_t* val_indptr, const int32_t* val_indices,
                       int64_t n_val, const int32_t* val_labels,
                       float* word_emb, float* label_emb, uint64_t seed,
                       double* epoch_errors) {
  if (dim > 512 || n_docs <= 0 || vocab <= 0 || n_labels <= 0) return -1.0;
  Model m;
  m.word_emb = word_emb;
  m.label_emb = label_emb;
  m.dim = dim;
  m.vocab = vocab;
  m.n_labels = n_labels;
  m.lr = lr;
  m.margin = margin;
  m.neg = neg;
  std::vector<float> word_g2(static_cast<size_t>(vocab), 0.f);
  std::vector<float> label_g2(static_cast<size_t>(n_labels), 0.f);
  m.word_g2 = word_g2.data();
  m.label_g2 = label_g2.data();

  const bool has_val = val_indptr != nullptr && n_val > 0;
  std::vector<float> best_words, best_labels;
  double best_err = 1e30;
  int since_best = 0;

  if (epoch_errors != nullptr)
    for (int e = 0; e < epochs; ++e) epoch_errors[e] = -1.0;

  for (int epoch = 0; epoch < epochs; ++epoch) {
    const int nt = threads < 1 ? 1 : threads;
    std::vector<std::thread> pool;
    std::vector<double> thread_loss(static_cast<size_t>(nt), 0.0);
    const int64_t per = (n_docs + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {
      const int64_t lo = t * per;
      const int64_t hi = std::min<int64_t>(lo + per, n_docs);
      if (lo >= hi) break;
      pool.emplace_back([&, t, lo, hi] {
        std::mt19937 rng(static_cast<uint32_t>(seed + 1315423911ull * (epoch * nt + t + 1)));
        std::vector<float> scratch;
        // hogwild: embedding rows are updated without locks; races are benign
        std::vector<int64_t> order(static_cast<size_t>(hi - lo));
        for (int64_t i = lo; i < hi; ++i) order[static_cast<size_t>(i - lo)] = i;
        std::shuffle(order.begin(), order.end(), rng);
        double loss = 0.0;
        for (int64_t i : order) {
          const int64_t plo = indptr[i];
          loss += train_example(m, indices + plo, indptr[i + 1] - plo, labels[i],
                                rng, scratch);
        }
        thread_loss[static_cast<size_t>(t)] = loss;
      });
    }
    for (auto& th : pool) th.join();

    double err;
    if (has_val) {
      err = eval_loss(m, val_indptr, val_indices, n_val, val_labels, neg, seed);
    } else {
      double s = 0.0;
      for (double v : thread_loss) s += v;
      err = s / static_cast<double>(n_docs);
    }
    if (epoch_errors != nullptr) epoch_errors[epoch] = err;

    if (err < best_err) {
      best_err = err;
      since_best = 0;
      if (has_val) {
        best_words.assign(word_emb,
                          word_emb + static_cast<int64_t>(vocab) * dim);
        best_labels.assign(label_emb,
                           label_emb + static_cast<int64_t>(n_labels) * dim);
      }
    } else if (has_val && ++since_best >= patience && patience > 0) {
      break;  // early stop: restore best snapshot below
    }
  }
  if (has_val && !best_words.empty()) {
    std::memcpy(word_emb, best_words.data(), best_words.size() * sizeof(float));
    std::memcpy(label_emb, best_labels.data(),
                best_labels.size() * sizeof(float));
  }
  return best_err;
}

// embed_doc equivalent (notebook cell 7): mean of word embeddings per csr row.
void starspace_embed_docs(const int64_t* indptr, const int32_t* indices,
                          int64_t n_docs, const float* word_emb, int dim,
                          float* out) {
  for (int64_t i = 0; i < n_docs; ++i) {
    const int64_t lo = indptr[i], n = indptr[i + 1] - lo;
    float* o = out + i * dim;
    std::memset(o, 0, sizeof(float) * dim);
    if (n == 0) continue;
    for (int64_t j = 0; j < n; ++j) {
      const float* w = word_emb + static_cast<int64_t>(indices[lo + j]) * dim;
      for (int d = 0; d < dim; ++d) o[d] += w[d];
    }
    const float inv = 1.0f / static_cast<float>(n);
    for (int d = 0; d < dim; ++d) o[d] *= inv;
  }
}

}  // extern "C"
