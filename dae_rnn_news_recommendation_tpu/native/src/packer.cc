// Native csr -> padded-batch packer: the host-side hot path of the TPU feed.
//
// The reference ships scipy csr to the runtime as a (indices, values, shape)
// triple built row-by-row in Python (reference autoencoder/utils.py:162-180);
// our Python packer (ops/sparse_ingest.py pad_csr_batch) likewise loops over
// rows in the interpreter. At streaming rates (100k+ articles/sec feeds) that
// loop is the bottleneck between the data pipeline and the device, so it is
// implemented natively here: one tight pass over the csr arrays into
// preallocated padded output tiles.
//
// Layout contract (must match ops/sparse_ingest.py):
//   - output indices [n_rows, k], values [n_rows, k] (values omitted in binary
//     mode); rows with nnz > k are truncated to the first k entries
//   - padding slots hold `pad_index` (0 in value mode, F in binary mode) and
//     value 0.0f, so they contribute nothing downstream.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

template <typename OutIdx>
void pack_rows(const int64_t* indptr, const int32_t* indices, const float* data,
               int64_t row_lo, int64_t row_hi, int64_t k, OutIdx pad_index,
               OutIdx* out_indices, float* out_values) {
  for (int64_t i = row_lo; i < row_hi; ++i) {
    const int64_t lo = indptr[i];
    const int64_t n0 = indptr[i + 1] - lo;
    const int64_t n = n0 < k ? n0 : k;
    OutIdx* oi = out_indices + i * k;
    for (int64_t j = 0; j < n; ++j) oi[j] = static_cast<OutIdx>(indices[lo + j]);
    for (int64_t j = n; j < k; ++j) oi[j] = pad_index;
    if (out_values != nullptr) {
      float* ov = out_values + i * k;
      if (data != nullptr)
        std::memcpy(ov, data + lo, sizeof(float) * static_cast<size_t>(n));
      else
        for (int64_t j = 0; j < n; ++j) ov[j] = 1.0f;
      for (int64_t j = n; j < k; ++j) ov[j] = 0.0f;
    }
  }
}

template <typename OutIdx>
void pack_gather_rows(const int64_t* indptr, const int32_t* indices,
                      const float* data, const int64_t* row_ids,
                      int64_t row_lo, int64_t row_hi, int64_t k, OutIdx pad_index,
                      OutIdx* out_indices, float* out_values) {
  for (int64_t i = row_lo; i < row_hi; ++i) {
    const int64_t r = row_ids[i];
    const int64_t lo = indptr[r];
    const int64_t n0 = indptr[r + 1] - lo;
    const int64_t n = n0 < k ? n0 : k;
    OutIdx* oi = out_indices + i * k;
    for (int64_t j = 0; j < n; ++j) oi[j] = static_cast<OutIdx>(indices[lo + j]);
    for (int64_t j = n; j < k; ++j) oi[j] = pad_index;
    if (out_values != nullptr) {
      float* ov = out_values + i * k;
      if (data != nullptr)
        std::memcpy(ov, data + lo, sizeof(float) * static_cast<size_t>(n));
      else
        for (int64_t j = 0; j < n; ++j) ov[j] = 1.0f;
      for (int64_t j = n; j < k; ++j) ov[j] = 0.0f;
    }
  }
}

template <typename OutIdx>
void pack_gather_impl(const int64_t* indptr, const int32_t* indices,
                      const float* data, const int64_t* row_ids, int64_t n_rows,
                      int64_t k, int64_t pad_index, OutIdx* out_indices,
                      float* out_values, int threads) {
  if (threads <= 1 || n_rows < 4096) {
    pack_gather_rows<OutIdx>(indptr, indices, data, row_ids, 0, n_rows, k,
                             static_cast<OutIdx>(pad_index), out_indices,
                             out_values);
    return;
  }
  std::vector<std::thread> pool;
  const int64_t per = (n_rows + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = std::min<int64_t>(lo + per, n_rows);
    if (lo >= hi) break;
    pool.emplace_back([=] {
      pack_gather_rows<OutIdx>(indptr, indices, data, row_ids, lo, hi, k,
                               static_cast<OutIdx>(pad_index), out_indices,
                               out_values);
    });
  }
  for (auto& th : pool) th.join();
}

template <typename OutIdx>
void pack_csr_impl(const int64_t* indptr, const int32_t* indices,
                   const float* data, int64_t n_rows, int64_t k,
                   int64_t pad_index, OutIdx* out_indices, float* out_values,
                   int threads) {
  if (threads <= 1 || n_rows < 4096) {
    pack_rows<OutIdx>(indptr, indices, data, 0, n_rows, k,
                      static_cast<OutIdx>(pad_index), out_indices, out_values);
    return;
  }
  std::vector<std::thread> pool;
  const int64_t per = (n_rows + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = std::min<int64_t>(lo + per, n_rows);
    if (lo >= hi) break;
    pool.emplace_back([=] {
      pack_rows<OutIdx>(indptr, indices, data, lo, hi, k,
                        static_cast<OutIdx>(pad_index), out_indices, out_values);
    });
  }
  for (auto& th : pool) th.join();
}

void densify_rows_range(const int64_t* indptr, const int32_t* indices,
                        const float* data, int64_t row_lo, int64_t row_hi,
                        int64_t n_cols, float* out) {
  for (int64_t i = row_lo; i < row_hi; ++i) {
    float* row = out + i * n_cols;
    std::memset(row, 0, sizeof(float) * static_cast<size_t>(n_cols));
    const int64_t lo = indptr[i], hi = indptr[i + 1];
    if (data != nullptr)
      for (int64_t j = lo; j < hi; ++j) row[indices[j]] = data[j];
    else
      for (int64_t j = lo; j < hi; ++j) row[indices[j]] = 1.0f;
  }
}

}  // namespace

extern "C" {

// csr row block -> dense [n_rows, n_cols] float32 (the dense-batch feed's
// densify loop, data/batcher.py densify_rows). data == nullptr means binary
// csr (stored values all 1.0). Duplicate column entries take last-writer value
// (scipy .todense() sums them; feeds here are vectorizer output with unique
// columns per row, so the difference never materializes).
void densify_csr(const int64_t* indptr, const int32_t* indices,
                 const float* data, int64_t n_rows, int64_t n_cols, float* out,
                 int threads) {
  if (threads <= 1 || n_rows < 256) {
    densify_rows_range(indptr, indices, data, 0, n_rows, n_cols, out);
    return;
  }
  std::vector<std::thread> pool;
  const int64_t per = (n_rows + threads - 1) / threads;
  for (int t = 0; t < threads; ++t) {
    const int64_t lo = t * per;
    const int64_t hi = std::min<int64_t>(lo + per, n_rows);
    if (lo >= hi) break;
    pool.emplace_back(
        [=] { densify_rows_range(indptr, indices, data, lo, hi, n_cols, out); });
  }
  for (auto& th : pool) th.join();
}

// data == nullptr means "stored values are all 1.0" (binary csr).
// out_values == nullptr means binary mode (values not materialized).
void pack_csr_u16(const int64_t* indptr, const int32_t* indices,
                  const float* data, int64_t n_rows, int64_t k,
                  int64_t pad_index, uint16_t* out_indices, float* out_values,
                  int threads) {
  pack_csr_impl<uint16_t>(indptr, indices, data, n_rows, k, pad_index,
                          out_indices, out_values, threads);
}

void pack_csr_u32(const int64_t* indptr, const int32_t* indices,
                  const float* data, int64_t n_rows, int64_t k,
                  int64_t pad_index, uint32_t* out_indices, float* out_values,
                  int threads) {
  pack_csr_impl<uint32_t>(indptr, indices, data, n_rows, k, pad_index,
                          out_indices, out_values, threads);
}

// Gather+pack in one pass: pack rows row_ids[0..n_rows) of the source csr
// directly into the padded tiles — no intermediate csr slice (the scipy
// fancy-index the per-batch feed would otherwise pay).
void pack_csr_gather_u16(const int64_t* indptr, const int32_t* indices,
                         const float* data, const int64_t* row_ids,
                         int64_t n_rows, int64_t k, int64_t pad_index,
                         uint16_t* out_indices, float* out_values, int threads) {
  pack_gather_impl<uint16_t>(indptr, indices, data, row_ids, n_rows, k,
                             pad_index, out_indices, out_values, threads);
}

void pack_csr_gather_u32(const int64_t* indptr, const int32_t* indices,
                         const float* data, const int64_t* row_ids,
                         int64_t n_rows, int64_t k, int64_t pad_index,
                         uint32_t* out_indices, float* out_values, int threads) {
  pack_gather_impl<uint32_t>(indptr, indices, data, row_ids, n_rows, k,
                             pad_index, out_indices, out_values, threads);
}

}  // extern "C"
