"""csr row block -> dense float32, via the native packer (src/packer.cc).

This is the dense-batch feed's hot loop (data/batcher.py densify_rows): the
reference densifies with scipy `.todense()` per batch on one thread
(reference autoencoder/utils.py:55-63 feeds dense slices); the native path
scatters csr rows into a preallocated tile across threads.

Importing this module raises ImportError when the native library is
unavailable (no compiler / build failure), so callers can guard with a plain
try/except at import time and trust a non-None binding at call time.
"""

import ctypes
import os

import numpy as np
import scipy.sparse as sp

from . import as_ptr, load

_lib = load()
if _lib is None or not hasattr(_lib, "densify_csr"):
    raise ImportError("native library unavailable (densify_csr missing)")

_THREADS = min(os.cpu_count() or 1, 8)


def densify_csr_rows(rows, out=None, threads=None):
    """Dense float32 [n, F] copy of a scipy csr block.

    `out` is written in place when its shape/dtype match. The batcher/estimator
    feeds deliberately do NOT pass one: the tile they yield is handed to an
    async device transfer (and, under data.prefetch, produced ahead of the
    consumer), so reusing a persistent tile would mutate a buffer still in
    flight. Pass `out` only when the caller fully consumes the result before
    the next call. Rows with duplicate column entries take the last value
    (vectorizer output never has duplicates; scipy would sum them).
    """
    assert sp.issparse(rows)
    if not sp.isspmatrix_csr(rows):
        rows = rows.tocsr()
    n, f = rows.shape
    if out is None or out.shape != (n, f) or out.dtype != np.float32 \
            or not out.flags.c_contiguous:
        out = np.empty((n, f), np.float32)
    indptr = np.ascontiguousarray(rows.indptr, np.int64)
    indices = np.ascontiguousarray(rows.indices, np.int32)
    data = np.ascontiguousarray(rows.data, np.float32)
    if threads is None:
        # threading pays only on big tiles; small batches stay single-pass
        threads = _THREADS if n * f >= 1 << 22 else 1
    _lib.densify_csr(
        as_ptr(indptr, ctypes.c_int64), as_ptr(indices, ctypes.c_int32),
        as_ptr(data, ctypes.c_float), n, f, as_ptr(out, ctypes.c_float),
        int(threads),
    )
    return out
