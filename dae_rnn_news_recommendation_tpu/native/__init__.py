"""Native (C++) runtime components, loaded via ctypes.

Two components live in one shared library (`_native.so`):

  - csr -> padded-batch packer (src/packer.cc) — the host-side hot path of the
    sparse TPU feed (ops/sparse_ingest.py delegates here when available)
  - StarSpace-style hinge-loss embedding trainer (src/starspace.cc) — the
    native equivalent of the external C++ baseline the reference shells out to
    (reference starspace/prepare_starspace_formatted_data.ipynb cells 6-7)

The library is compiled on demand with g++ (single translation-unit rebuild,
~2s, cached next to the sources) so the repo needs no build step to import.
Every caller must handle `load() is None` (no compiler / build failure) by
falling back to the pure-Python path.
"""

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src")
_LIB_PATH = os.path.join(_HERE, "_native.so")
_SOURCES = ("packer.cc", "starspace.cc")

_lock = threading.Lock()
_lib = None
_failed_mtimes = None  # source mtimes at last failed build (don't respawn g++)


def _build():
    srcs = [os.path.join(_SRC, s) for s in _SOURCES]
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-pthread",
           "-o", _LIB_PATH, *srcs]
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _stale():
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC, s)) > lib_mtime for s in _SOURCES
    )


def _bind(lib):
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    u32p = ctypes.POINTER(ctypes.c_uint32)

    for name, idxp in (("pack_csr_u16", u16p), ("pack_csr_u32", u32p)):
        fn = getattr(lib, name)
        fn.argtypes = [i64p, i32p, f32p, ctypes.c_int64, ctypes.c_int64,
                       ctypes.c_int64, idxp, f32p, ctypes.c_int]
        fn.restype = None

    for name, idxp in (("pack_csr_gather_u16", u16p),
                       ("pack_csr_gather_u32", u32p)):
        fn = getattr(lib, name)
        fn.argtypes = [i64p, i32p, f32p, i64p, ctypes.c_int64, ctypes.c_int64,
                       ctypes.c_int64, idxp, f32p, ctypes.c_int]
        fn.restype = None

    lib.densify_csr.argtypes = [i64p, i32p, f32p, ctypes.c_int64,
                                ctypes.c_int64, f32p, ctypes.c_int]
    lib.densify_csr.restype = None

    lib.starspace_train.argtypes = [
        i64p, i32p, ctypes.c_int64, i32p,            # train docs + labels
        ctypes.c_int, ctypes.c_int, ctypes.c_int,    # vocab, n_labels, dim
        ctypes.c_float, ctypes.c_float, ctypes.c_int,  # lr, margin, neg
        ctypes.c_int, ctypes.c_int, ctypes.c_int,    # epochs, threads, patience
        i64p, i32p, ctypes.c_int64, i32p,            # val docs + labels
        f32p, f32p, ctypes.c_uint64, f64p,           # embs, seed, epoch_errors
    ]
    lib.starspace_train.restype = ctypes.c_double

    lib.starspace_embed_docs.argtypes = [i64p, i32p, ctypes.c_int64, f32p,
                                         ctypes.c_int, f32p]
    lib.starspace_embed_docs.restype = None
    return lib


def _mtimes():
    return tuple(os.path.getmtime(os.path.join(_SRC, s)) for s in _SOURCES)


def load():
    """Return the bound ctypes library, building it if needed; None on failure.

    A failed build is cached against the source mtimes so hot-path callers
    (pad_csr_batch at feed rates) never respawn g++; editing a source retries.
    """
    global _lib, _failed_mtimes
    if _lib is not None:
        return _lib
    if _failed_mtimes is not None and _failed_mtimes == _mtimes():
        return None
    with _lock:
        if _lib is not None:
            return _lib
        try:
            if _stale():
                _build()
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
            _failed_mtimes = None
        except Exception:
            _lib = None
            _failed_mtimes = _mtimes()
    return _lib


def as_ptr(arr, ctype):
    """numpy array -> ctypes pointer (no copy; caller keeps arr alive)."""
    return arr.ctypes.data_as(ctypes.POINTER(ctype))
