"""Shadow scorer: online retrieval-quality measurement for the serving path.

Every quality number the repo had before this module — recall@10, coverage,
quantization error — was an OFFLINE bench figure; the live fleet observed
latency, health, and device time, but never what it actually returned. The
shadow scorer closes that gap: it samples a configurable fraction of live
requests (deterministic every-Nth, the same discipline as
`trace_sample_rate`) and asynchronously re-scores them with the EXACT
(non-IVF, full-scan, fp32-accumulated) path, then compares the exact answer
against what the request was actually served:

  recall@k            |served ∩ exact-top-k| / |exact-top-k|
  rank displacement   mean |served rank − exact rank| over the matched rows
  score delta         mean per-rank score regret (exact − served, clamped ≥ 0)

All three land in the r14 metrics registry (counters + histograms + gauges),
so the SLO monitor can burn on them (`telemetry.quality_slo_specs`) and
`telemetry report --quality` can render them.

Design constraints, in order:

  * OFF THE REQUEST CRITICAL PATH. `offer()` is called by the batcher AFTER
    every primary reply has resolved, and does nothing but a deterministic
    counter check and a `put_nowait` — a full shadow queue drops the sample
    (counted, never silent) rather than ever blocking or reordering a reply.
  * UNDER THE MESH DISPATCH LOCK. The shadow re-score is a device dispatch
    from a background thread; on a sharded service that is a collective, so
    it serializes through `parallel.mesh.dispatch_lock` exactly like the
    batcher, the corpus health gate, and the bench sweeps (the r16 deadlock
    class; meshcheck S1 lints this site).
  * ZERO POST-WARM COMPILES. The exact variants the shadow dispatches are
    compiled inside `RecommendationService.warmup()` (at the shadow's one
    bucket shape), so a sampled request never triggers a live retrace —
    the same contract every degraded serving mode honors.

Per-cell probe-hit attribution: when the served slot carries an IVF index,
each exact-top-k row is mapped to its cell (the replicated `assign` array)
and its cell's occupancy is observed into a hit or a miss histogram —
`ivf_probe_hit_cell_rows` / `ivf_probe_miss_cell_rows` — so a recall loss is
attributable to WHERE the misses live (crowded cells under append skew vs
sparse cells the probe ordering skips).
"""

import queue
import threading
import time

import numpy as np

import jax

from ..parallel.mesh import dispatch_lock

# bounded window of per-sample records kept for summary()/the quality bundle
_SAMPLE_WINDOW = 512

# histogram bucket bounds (upper edges; +inf overflow implicit)
RECALL_BOUNDS = (0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0)
DISPLACEMENT_BOUNDS = (0.0, 0.5, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0)
SCORE_DELTA_BOUNDS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.05, 0.1, 0.25)
CELL_ROWS_BOUNDS = (8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0)


class _Sample:
    __slots__ = ("rid", "query", "indices", "scores", "slot", "k", "coverage")

    def __init__(self, rid, query, indices, scores, slot, k, coverage):
        self.rid = rid
        self.query = query
        self.indices = indices
        self.scores = scores
        self.slot = slot
        self.k = k
        self.coverage = coverage


class ShadowScorer:
    """Asynchronous exact re-scorer attached to one RecommendationService.

    :param service: the owning RecommendationService — source of the exact
        serve variants (`_shadow_fn`), the params, the bucket shapes, and
        the (late-bindable) metrics registry.
    :param rate: fraction of replied requests sampled (deterministic
        every-Nth over the reply sequence: 1.0 = every reply, 0.25 = every
        4th — reproducible across identical request sequences, like
        `trace_sample_rate`).
    :param max_queue: bounded sample queue depth; a full queue DROPS the
        sample (counter `shadow_dropped`) instead of blocking the batcher.
    """

    def __init__(self, service, *, rate=0.25, max_queue=64):
        rate = float(rate)
        assert 0.0 < rate <= 1.0, f"shadow rate must be in (0, 1]: {rate}"
        self.service = service
        self.rate = rate
        self._period = max(1, int(round(1.0 / rate)))
        self._seen = 0            # replies considered (sampling sequence)
        self._q = queue.Queue(maxsize=int(max_queue))
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._offered = 0         # samples enqueued
        self._done = 0            # samples scored, errored — flush() waits
        self._recalls = []        # bounded recall window (summary mean/min)
        self.samples = []         # bounded per-sample records, newest last
        self.counts = {"seen": 0, "sampled": 0, "scored": 0, "dropped": 0,
                       "errors": 0}
        self._occupancy = None    # (slot id, version) -> cell occupancy cache
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"shadow-scorer[{service.name}]")
        self._thread.start()

    # ------------------------------------------------------------ ingestion
    def offer(self, rid, query, indices, scores, slot, k, coverage=1.0):
        """Called by the batcher after the primary replies resolved: decide
        (deterministically) whether this reply is sampled, and if so enqueue
        a host-side copy for the shadow thread. Never blocks: a full queue
        drops the sample and counts the drop."""
        m = self.service.metrics
        with self._lock:
            self._seen += 1
            self.counts["seen"] += 1
            keep = (self._seen - 1) % self._period == 0
        if not keep or self._stop.is_set():
            return False
        sample = _Sample(rid, np.array(query, np.float32, copy=True),
                         np.array(indices, copy=True),
                         np.array(scores, copy=True), slot, int(k),
                         float(coverage))
        if m is not None:
            m.counter("shadow_sampled").inc()
        try:
            self._q.put_nowait(sample)
        except queue.Full:
            with self._lock:
                self.counts["dropped"] += 1
            if m is not None:
                m.counter("shadow_dropped").inc()
            return False
        with self._lock:
            self.counts["sampled"] += 1
            self._offered += 1
        return True

    # --------------------------------------------------------- shadow thread
    def _loop(self):
        while True:
            if self._stop.is_set() and self._q.empty():
                return
            try:
                sample = self._q.get(timeout=0.005)
            except queue.Empty:
                continue
            try:
                self._score(sample)
            # nothing is swallowed silently: a failed shadow re-score (a
            # retired slot's freed buffers, a device fault) is a counted
            # error and the primary path never notices
            except Exception as exc:
                self._record_error(sample, exc)

    def _record_error(self, sample, exc):
        """A failed re-score surfaces as a counted error with the exception
        kept on the sample record — operators see it in summary() and the
        quality bundle; the primary path never notices."""
        m = self.service.metrics
        with self._lock:
            self.counts["errors"] += 1
            self._done += 1
            self.samples.append({"rid": sample.rid, "error":
                                 f"{type(exc).__name__}: {exc}"})
            del self.samples[:-_SAMPLE_WINDOW]
        if m is not None:
            m.counter("shadow_errors").inc()

    def _score(self, sample):
        svc = self.service
        k = sample.k
        fn = svc._shadow_fn(k)
        bucket = svc.buckets[0]
        batch = np.zeros((bucket, sample.query.shape[0]), np.float32)
        batch[0] = sample.query
        slot = sample.slot
        # a background-thread device dispatch: on a sharded service this is
        # a collective program, so it MUST serialize with every other
        # dispatcher in the process (meshcheck S1's contract)
        with dispatch_lock(svc.sharded):
            out = fn(svc.params, slot.emb, slot.valid, slot.scales, batch)
            jax.block_until_ready(out)
        exact_sc = np.asarray(out[0])[0][:k]
        exact_idx = np.asarray(out[1])[0][:k]
        rec = self._compare(sample, exact_idx, exact_sc)
        m = svc.metrics
        if m is not None:
            m.counter("shadow_scored").inc()
            m.counter("shadow_expected").inc(rec["expected"])
            m.counter("shadow_misses").inc(rec["expected"] - rec["hits"])
            m.gauge("shadow_recall").set(rec["recall"])
            m.histogram("shadow_recall", bounds=RECALL_BOUNDS).observe(
                rec["recall"])
            m.histogram("shadow_rank_displacement",
                        bounds=DISPLACEMENT_BOUNDS).observe(
                rec["rank_displacement"])
            m.histogram("shadow_score_delta",
                        bounds=SCORE_DELTA_BOUNDS).observe(rec["score_delta"])
        self._cell_attribution(slot, exact_idx, exact_sc,
                               np.asarray(sample.indices)[:k])
        with self._lock:
            self.counts["scored"] += 1
            self._done += 1
            self._recalls.append(rec["recall"])
            del self._recalls[:-_SAMPLE_WINDOW]
            self.samples.append(rec)
            del self.samples[:-_SAMPLE_WINDOW]
        if m is not None:
            m.gauge("shadow_recall_mean").set(self.recall_mean())

    def _compare(self, sample, exact_idx, exact_sc):
        """Per-request quality record: the exact top-k is the reference
        ranking, the served reply is the candidate. Padding/invalid exact
        rows (non-finite score) don't count toward the denominator — a
        corpus smaller than k can still score 1.0."""
        k = sample.k
        served_idx = np.asarray(sample.indices)[:k].astype(np.int64)
        served_sc = np.asarray(sample.scores)[:k].astype(np.float64)
        finite = np.isfinite(np.asarray(exact_sc, np.float64))
        exact = [int(r) for r, f in zip(exact_idx, finite) if f]
        pos = {r: i for i, r in enumerate(exact)}
        expected = len(exact)
        disps = [abs(i - pos[int(r)]) for i, r in enumerate(served_idx)
                 if int(r) in pos]
        hits = len(disps)
        recall = hits / expected if expected else 1.0
        # per-rank score regret vs the best achievable ordering; clamped at
        # zero so fp jitter in the served direction never reads as "better
        # than exact" and score_delta stays a one-sided quality loss
        n = min(len(exact), served_sc.shape[0])
        regret = [max(0.0, float(exact_sc[i]) - float(served_sc[i]))
                  for i in range(n) if np.isfinite(served_sc[i])]
        return {"rid": sample.rid, "k": k, "expected": expected,
                "hits": hits, "recall": round(recall, 6),
                "rank_displacement": round(float(np.mean(disps))
                                           if disps else 0.0, 6),
                "score_delta": round(float(np.mean(regret))
                                     if regret else 0.0, 8),
                "corpus_version": int(getattr(sample.slot, "version", 0)),
                "coverage": round(sample.coverage, 6)}

    def _cell_attribution(self, slot, exact_idx, exact_sc, served_idx):
        """Observe each exact-top-k row's CELL occupancy into a hit or a
        miss histogram (IVF slots only): a miss in a crowded cell points at
        append skew, a miss in a sparse cell at probe ordering."""
        m = self.service.metrics
        ivf = getattr(slot, "ivf", None)
        if m is None or ivf is None:
            return
        counts, assign = self._cell_occupancy(slot, ivf)
        served = {int(r) for r in np.asarray(served_idx).astype(np.int64)}
        hit = m.histogram("ivf_probe_hit_cell_rows", bounds=CELL_ROWS_BOUNDS)
        miss = m.histogram("ivf_probe_miss_cell_rows",
                           bounds=CELL_ROWS_BOUNDS)
        for r, sc in zip(np.asarray(exact_idx).astype(np.int64), exact_sc):
            if not np.isfinite(float(sc)) or not 0 <= r < assign.shape[0]:
                continue
            occ = float(counts[assign[r]])
            (hit if int(r) in served else miss).observe(occ)

    def _cell_occupancy(self, slot, ivf):
        """Host copies of the slot's row->cell map and per-cell occupancy
        (index.cell_stats — REAL rows only, padding excluded, both
        layouts), cached per (slot, version): one device_get per promoted
        index, not per sample."""
        key = (id(slot), int(getattr(slot, "version", 0)))
        cached = self._occupancy
        if cached is not None and cached[0] == key:
            return cached[1], cached[2]
        from ..index import cell_stats

        counts = np.asarray(cell_stats(ivf)["counts"], np.int64)
        assign = np.asarray(ivf.assign).astype(np.int64)
        self._occupancy = (key, counts, assign)
        return counts, assign

    # ------------------------------------------------------------ lifecycle
    def flush(self, timeout=5.0):
        """Block until every enqueued sample has been scored (or errored) —
        the chaos harnesses call this before evaluating quality SLOs, so an
        assertion never races the shadow thread. Returns True when drained."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                if self._done >= self._offered:
                    return True
            time.sleep(0.002)
        with self._lock:
            return self._done >= self._offered

    def stop(self, timeout=5.0):
        """Drain and join: the shadow thread scores everything already
        queued, then exits."""
        self._stop.set()
        self._thread.join(timeout=timeout)

    # ------------------------------------------------------------ reporting
    def recall_mean(self):
        with self._lock:
            vals = list(self._recalls)
        return round(float(np.mean(vals)), 6) if vals else None

    def recall_min(self):
        with self._lock:
            vals = list(self._recalls)
        return round(float(np.min(vals)), 6) if vals else None

    def summary(self):
        """Manifest/bundle fragment: counts, the recall window stats, and
        the bounded per-sample record tail."""
        with self._lock:
            counts = dict(self.counts)
            samples = list(self.samples)
        return {"rate": self.rate, "period": self._period, "counts": counts,
                "recall_mean": self.recall_mean(),
                "recall_min": self.recall_min(),
                "n_samples": len(samples), "samples": samples[-64:]}
