"""Deadline-aware recommendation service: admission -> microbatch -> reply.

This is `train/pipeline.py`'s bounded-queue machinery run in reverse. The
training feed has one consumer (the step) pulling from a background producer;
serving has many producers (request threads) feeding one background consumer
(the batcher thread) that coalesces requests into shape-bucketed microbatches
for the jitted encode->score->top-k graph (serve/graph.py). The same
discipline carries over: a bounded queue (admission is load shedding, not
buffering), timeout-polled gets (a wedged device can never deadlock the
loop), and stop() that drains and joins.

Request lifecycle — every submitted request ends in EXACTLY ONE of:

  reply   the request rode a microbatch to the device and got its top-k
          (the reply says whether the deadline was met and which degraded
          modes, if any, shaped the answer);
  shed    an explicit admission/queue decision with a reason: queue full,
          deadline provably unmeetable (less than the observed device floor
          remains), deadline expired while queued, or service shutdown;
  error   the device call failed after bounded retries (or a fatal injected
          fault landed); the error text rides the reply.

Nothing times out silently and nothing blocks forever — the chaos-serve soak
(serve/chaos_serve.py) replays seeded fault plans x overload traces and
asserts exactly-one-outcome over every request.

Microbatch flush policy (the deadline-aware part): the batcher fires when the
batch is FULL, when the OLDEST request's deadline slack has shrunk to the
flush threshold (slack-triggered flush — a request is never parked past the
point where the device floor would blow its deadline), or when the batch has
lingered `linger_s` with spare slack (idle latency bound). Under overload
(queue occupancy past the watermark) the service degrades EXPLICITLY rather
than failing implicitly: top-k truncates to `degraded_top_k` (a precompiled
smaller-k variant, not a recompile) and batching coarsens (linger stretches
so dispatches amortize better). Each degraded episode is recorded in
`service.events` and lands in the manifest fragment — degraded modes are
first-class, never silent.
"""

import dataclasses
import queue
import threading
import time

import numpy as np

import jax

from .. import telemetry
from ..analysis.runtime import CompileWatcher
from ..parallel import mesh as _mesh
from ..reliability import faults as _faults
from ..reliability.retry import RetryPolicy
from ..train.pipeline import bucket_sizes
from .graph import make_serve_fn

_LATENCY_WINDOW = 4096  # replies kept for p50/p95 (bounded, like the queue)

_MESH_LOCK = _mesh.MESH_DISPATCH_LOCK
# Process-wide serialization of SHARDED serve dispatches. A shard_map program
# is a collective: all mesh devices must rendezvous on the SAME program. Two
# service threads (fleet replicas share this host's one device mesh)
# dispatching concurrently can interleave their programs' per-device
# participant arrivals and deadlock the rendezvous — so every sharded
# serve-fn call in this process takes this lock. Single-device dispatches
# never touch it. The lock itself lives in parallel/mesh.py (r17): the
# corpus health gate, index refit, bench parity sweeps and the ring AUROC
# dispatch collectives too, and they all must serialize against US.


@dataclasses.dataclass
class Reply:
    """Terminal outcome of one request. status: "ok" | "shed" | "error"."""

    status: str
    indices: object = None    # np [k] int corpus rows (status == "ok")
    scores: object = None     # np [k] f32 cosine scores
    reason: str = ""          # shed/error explanation
    latency_s: float = 0.0    # submit -> resolve wall clock
    deadline_met: bool = False
    degraded: tuple = ()      # subset of ("topk_truncated", "coarse_batching",
    #                           "stale_corpus", "partial_corpus",
    #                           "ivf_unavailable") that shaped this reply
    corpus_version: int = 0
    coverage: float = 1.0     # valid-row fraction the answering slot served;
    # < 1.0 exactly when "partial_corpus" is in `degraded` (a shard is lost
    # and the surviving shards answered)
    request_id: str = ""      # trace id; the fleet router suffixes hops
    # ("/h" hedge twin, "/rN" retry), so the winning attempt is attributable
    timings: dict = dataclasses.field(default_factory=dict)
    # per-hop decomposition in seconds (admit_s, queue_s, batch_form_s,
    # compute_s, resolve_s — plus router_s at the fleet level): consecutive
    # monotonic stamps, so the components SUM to latency_s (± rounding)

    @property
    def ok(self):
        return self.status == "ok"


class _Pending:
    __slots__ = ("query", "deadline", "t_submit", "future", "rid",
                 "t_admit", "t_dequeue", "t_batch", "compute_s")

    def __init__(self, query, deadline, t_submit, rid=""):
        self.query = query
        self.deadline = deadline
        self.t_submit = t_submit
        self.future = ReplyFuture()
        self.rid = rid
        # hop stamps (monotonic), filled as the request moves: admission
        # decision -> queue -> batch formation -> fenced compute
        self.t_admit = None
        self.t_dequeue = None
        self.t_batch = None
        self.compute_s = None


class ReplyFuture:
    """Per-request future: resolved exactly once with a Reply."""

    __slots__ = ("_event", "_reply", "_lock", "_callbacks")

    def __init__(self):
        self._event = threading.Event()
        self._reply = None
        self._lock = threading.Lock()
        self._callbacks = []

    def done(self):
        return self._event.is_set()

    def result(self, timeout=None):
        """The Reply, blocking up to `timeout` (None = forever is for tests
        only; production callers pass their deadline slack)."""
        if not self._event.wait(timeout):
            raise TimeoutError("reply not ready")
        return self._reply

    def add_done_callback(self, fn):
        """Invoke `fn(reply)` when the future resolves — immediately if it
        already has. Callbacks run on the resolving thread (the batcher, or
        the submitter for synchronous sheds) and MUST NOT raise: an exception
        propagates to that thread. This is what lets the fleet router track
        completions without one waiter thread per in-flight request."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self._reply)

    def _set(self, reply):
        with self._lock:
            if self._event.is_set():
                return False
            self._reply = reply
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn(reply)
        return True


class RecommendationService:
    """Admission-controlled, deadline-propagating serving front end.

    :param params: trained DAE params (the encode weights).
    :param config: the model's DAEConfig.
    :param corpus: a serve.corpus.ServingCorpus (swap() at least once before
        submitting, or every request errors with no_corpus).
    :param top_k: articles per reply (compiled into the serve graph).
    :param degraded_top_k: the overload variant (precompiled; <= top_k).
    :param max_batch: microbatch ceiling; buckets halve down from it.
    :param max_inflight: bounded admission queue depth — beyond it, shed.
    :param flush_slack_s: flush when the oldest deadline is this close.
    :param linger_s: idle flush bound — a lone request never waits longer
        than this for companions (stretched under overload: coarse batching).
    :param default_deadline_s: applied when submit() gets no deadline.
    :param overload_watermark: queue-occupancy fraction that enters degraded
        mode.
    :param retry: RetryPolicy for transient device faults on the batch path
        (default: 3 attempts, full jitter, 0.25 s cumulative cap).
    :param sharded: score against a ROW-SHARDED corpus: the serve graphs are
        built with the sharded variants over `mesh`, so corpus capacity
        scales with device count. Build the corpus with
        `ServingCorpus(mesh=mesh)` (same mesh; builds pad N_pad to divide it
        and swaps ride the two-phase shard commit) — or pass an explicit
        `device_put=lambda x: parallel.mesh.shard_rows(x, mesh)` with
        divisible shapes. Shard rows must stay >= top_k. The default (None)
        DERIVES from the corpus: True iff the corpus was built over a mesh
        with more than one device.
    :param mesh: the 1-D mesh for sharded serving (default: the corpus's
        mesh, else all devices via `parallel.mesh.get_mesh()`).
    :param retrieval: "exact" (scan every corpus row) or "ivf" (probe the
        slot's clustered index; the corpus must be built with
        `retrieval="ivf"` so every promoted slot carries one). The default
        (None) follows the corpus's own `retrieval`. Composed with sharded
        serving the graphs route through `make_sharded_ivf_serve_fn` —
        sharded+IVF IS the default configuration on multi-device hosts
        (`serve.corpus.default_corpus`). A slot promoted without an index
        serves through a recorded exact-scoring fallback
        (degraded="ivf_unavailable") instead of erroring.
    :param probes: cells scanned per query under `retrieval="ivf"` — baked
        into the compiled variants, so `warmup()` precompiles one program
        per (bucket, k, probes) and probing depth never recompiles live.
    :param name: service identity — the request-id prefix for locally
        generated ids and the batcher thread's trace-track suffix, so a
        fleet of replicas lands on distinguishable Chrome-trace tracks.
    :param registry: optional telemetry.MetricsRegistry this service
        mutates on the host side (admission, terminals, batcher loop) —
        exact counts, unaffected by trace sampling. None = no metrics.
    :param trace_sample_rate: fraction of `serve/request` terminal spans
        recorded while tracing is enabled (deterministic every-Nth, 1.0 =
        keep all, 0.0 = none). Sampling applies ONLY to that zero-length
        span: batch spans, registry counters, and replies are unaffected.
    :param shadow_rate: fraction of replied requests the shadow scorer
        (serve/shadow.py) re-scores with the exact full-scan path —
        deterministic every-Nth, asynchronous, off the reply critical path.
        0.0 (the default) attaches no shadow scorer; the quality metrics
        land in `registry` and the per-sample records in
        `service.shadow.summary()`.
    :param shadow_queue: bounded shadow sample queue depth; a full queue
        drops samples (counted) rather than ever blocking the batcher.
    """

    def __init__(self, params, config, corpus, *, top_k=10,
                 degraded_top_k=None, max_batch=32, max_inflight=64,
                 flush_slack_s=0.02, linger_s=0.005, default_deadline_s=1.0,
                 overload_watermark=0.75, retry=None, fused=True,
                 sharded=None, mesh=None, retrieval=None, probes=8,
                 name="svc", registry=None, trace_sample_rate=1.0,
                 shadow_rate=0.0, shadow_queue=64):
        assert int(top_k) >= 1 and int(max_batch) >= 1
        if retrieval is None:
            # follow the corpus: its slots carry an index iff it was built
            # with retrieval="ivf", and the serve graphs must match
            retrieval = getattr(corpus, "retrieval", "exact")
        if retrieval not in ("exact", "ivf"):
            raise ValueError(
                f"retrieval must be 'exact' or 'ivf': {retrieval!r}")
        corpus_mesh = getattr(corpus, "mesh", None)
        if sharded is None:
            # derive from the corpus: a mesh with more than one device means
            # the slot arrays land row-sharded, so the serve graphs must be
            # the sharded variants — sharded+IVF is the multi-device default
            sharded = (corpus_mesh is not None
                       and int(np.prod(list(corpus_mesh.shape.values()))) > 1)
        self.params = params
        self.config = config
        self.corpus = corpus
        self.top_k = int(top_k)
        self.degraded_top_k = int(degraded_top_k if degraded_top_k is not None
                                  else max(1, self.top_k // 2))
        assert 1 <= self.degraded_top_k <= self.top_k
        self.max_batch = int(max_batch)
        self.max_inflight = int(max_inflight)
        self.flush_slack_s = float(flush_slack_s)
        self.linger_s = float(linger_s)
        self.default_deadline_s = float(default_deadline_s)
        self.overload_watermark = float(overload_watermark)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, backoff_s=0.002, max_elapsed_s=0.25)
        self.buckets = bucket_sizes(self.max_batch, n_buckets=3,
                                    floor=min(8, self.max_batch))
        self.fused = bool(fused)
        self.sharded = bool(sharded)
        self.retrieval = retrieval
        self.probes = int(probes)
        assert self.probes >= 1
        if self.sharded:
            from ..parallel.mesh import get_mesh
            if mesh is None:
                mesh = corpus_mesh if corpus_mesh is not None else get_mesh()
            self.mesh = mesh
            if self.retrieval == "ivf":
                from .graph import make_sharded_ivf_serve_fn
                self._serve_fns = {
                    k: make_sharded_ivf_serve_fn(config, k, self.probes,
                                                 self.mesh)
                    for k in {self.top_k, self.degraded_top_k}}
            else:
                from .graph import make_sharded_serve_fn
                self._serve_fns = {
                    k: make_sharded_serve_fn(config, k, self.mesh)
                    for k in {self.top_k, self.degraded_top_k}}
        elif self.retrieval == "ivf":
            from .graph import make_ivf_serve_fn
            self.mesh = None
            self._serve_fns = {
                k: make_ivf_serve_fn(config, k, self.probes)
                for k in {self.top_k, self.degraded_top_k}}
        else:
            self.mesh = None
            self._serve_fns = {k: make_serve_fn(config, k, fused=self.fused)
                               for k in {self.top_k, self.degraded_top_k}}
        self._fallback_fns = {}  # lazy exact-scoring variants: the recorded
        # ivf_unavailable fallback when a slot promoted without an index
        self._ivf_unavail_version = None  # last version the fallback event
        # was recorded for (one event per index-less slot, not per dispatch)
        self._warmup_compiles = None   # set by warmup()
        self._post_warm_watcher = None  # counts compiles after warmup() —
        # the serving SLO assumes zero (every (bucket, k) variant is warm)
        self._q = queue.Queue(maxsize=self.max_inflight)
        self._stop = threading.Event()
        self._floor_s = 0.0       # fastest observed device batch (the proof
        # floor for "deadline provably unmeetable"; 0 until warm = admit all)
        self._degraded = False    # inside an overload episode?
        self._latencies = []      # bounded reply-latency window
        self._lock = threading.Lock()
        self.counts = {"submitted": 0, "replied": 0, "shed": 0, "errors": 0,
                       "deadline_missed": 0, "batches": 0}
        self.events = []          # degraded-mode transitions, in order
        self.name = str(name)
        self.metrics = registry
        self.trace_sample_rate = float(trace_sample_rate)
        self._trace_seen = 0      # terminal spans considered (sampling)
        self._rid_n = 0           # locally generated request-id sequence
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"serve-batcher[{self.name}]")
        self._thread.start()
        self.shadow = None
        if float(shadow_rate) > 0.0:
            self.attach_shadow(shadow_rate, max_queue=shadow_queue)

    def attach_shadow(self, rate, *, max_queue=64):
        """Attach (rate > 0) or detach (rate <= 0) the shadow scorer.

        Call only between bursts — the dispatch loop reads ``self.shadow``
        without a lock, so toggling while requests are in flight races the
        offer path. The bench's shadow-overhead leg uses this to run the
        SAME warmed replicas with sampling on and off; detaching stops the
        scorer thread and drains its queue first. Returns the new scorer
        (or None). A re-attach on an already-warm service should be
        followed by warmup() only when the corpus is IVF — the exact
        fallback variants are what the shadow path executes."""
        if self.shadow is not None:
            self.shadow.stop()
            self.shadow = None
        if float(rate) > 0.0:
            from .shadow import ShadowScorer
            self.shadow = ShadowScorer(self, rate=float(rate),
                                       max_queue=int(max_queue))
        return self.shadow

    # ------------------------------------------------------------ admission
    def submit(self, query, deadline_s=None, deadline_at=None,
               request_id=None):
        """Admit one query (dense [F] feature vector). Returns a ReplyFuture
        that ALWAYS resolves — with a reply, an explicit shed, or an error.

        `deadline_at` is an ABSOLUTE `time.monotonic()` deadline and wins
        over `deadline_s`: a hedged or retried re-enqueue passes the original
        request's absolute deadline so the remaining budget SHRINKS with
        elapsed time instead of resetting — a nearly-expired request is shed
        as provably unmeetable here, never re-queued with a fresh full
        timeout (ISSUE 12 deadline-propagation fix).

        `request_id` propagates a caller-assigned trace id (the fleet router
        passes its hop-suffixed attempt ids); None generates one from the
        service name. The id rides the Reply with a per-hop timing record."""
        now = time.monotonic()
        if deadline_at is not None:
            deadline_s = float(deadline_at) - now
        else:
            deadline_s = (self.default_deadline_s if deadline_s is None
                          else float(deadline_s))
        with self._lock:
            self.counts["submitted"] += 1
            self._rid_n += 1
            rid = (str(request_id) if request_id is not None
                   else f"{self.name}-{self._rid_n}")
        p = _Pending(np.asarray(query, np.float32).reshape(-1),
                     now + deadline_s, now, rid=rid)
        m = self.metrics
        if m is not None:
            m.counter("submitted").inc()
        if self._stop.is_set():
            return self._shed(p, "shutdown")
        try:
            # transient admission blips ride the jittered retry policy;
            # anything fatal is an explicit error reply, not a hang
            self.retry.run(_faults.fire, "serve.enqueue",
                           site="serve.enqueue")
        except Exception as exc:
            return self._error(p, f"{type(exc).__name__}: {exc}")
        floor = self._floor_s
        if deadline_s <= 0.0 or (floor > 0.0 and deadline_s < floor):
            # provably unmeetable: the budget is already spent, or the device
            # has never answered a batch faster than `floor` — shedding NOW
            # costs the caller nothing and spares the queue
            return self._shed(p, "deadline_unmeetable")
        # the admission decision is made: stamp BEFORE the enqueue so the
        # batcher can never dequeue an unstamped request (admit_s = decision
        # cost, queue_s starts here)
        p.t_admit = time.monotonic()
        try:
            self._q.put_nowait(p)
        except queue.Full:
            return self._shed(p, "queue_full")
        if m is not None:
            m.gauge("queue_depth").set(self._q.qsize())
        if self._stop.is_set() and not self._thread.is_alive():
            # raced a concurrent stop(): the batcher is gone, so nothing will
            # ever pull this queue again — shed the stragglers explicitly
            # rather than leak an unresolved future
            while True:
                try:
                    self._shed(self._q.get_nowait(), "shutdown")
                except queue.Empty:
                    break
        return p.future

    # ------------------------------------------------------- batcher thread
    def _loop(self):
        pending = []
        while True:
            now = time.monotonic()
            if pending:
                oldest_slack = min(p.deadline for p in pending) - now
                age = now - min(p.t_submit for p in pending)
                linger = self.linger_s * (4.0 if self._degraded else 1.0)
                if (len(pending) >= self.max_batch
                        or oldest_slack <= self.flush_slack_s
                        or age >= linger or self._stop.is_set()):
                    self._dispatch(pending)
                    pending = []
                    continue
                poll = max(0.0005, min(0.005, linger - age,
                                       oldest_slack - self.flush_slack_s))
            else:
                if self._stop.is_set() and self._q.empty():
                    return
                poll = 0.005
            try:
                p = self._q.get(timeout=poll)
                p.t_dequeue = time.monotonic()   # queue wait ends here
                pending.append(p)
            except queue.Empty:
                pass

    def _dispatch(self, pending):
        now = time.monotonic()
        live = []
        for p in pending:
            if p.deadline <= now:
                self._shed(p, "deadline_expired_in_queue")
            else:
                live.append(p)
        if not live:
            return
        degraded = self._note_overload()
        k = self.degraded_top_k if degraded else self.top_k
        slot = self.corpus.active
        if slot is None:
            for p in live:
                self._error(p, "no_corpus")
            return
        ivf_missing = self.retrieval == "ivf" and slot.ivf is None
        tags = []
        if ivf_missing:
            # a slot promoted without an index (e.g. a corpus seeded with
            # retrieval="exact" then fronted by an ivf service) SERVES via
            # the exact-scoring fallback instead of erroring — a recorded
            # first-class degraded mode, one event per index-less version
            tags.append("ivf_unavailable")
            if self._ivf_unavail_version != slot.version:
                self._ivf_unavail_version = slot.version
                self._record_event("ivf_unavailable",
                                   corpus_version=slot.version)
        if degraded:
            tags.append("coarse_batching")
            if k < self.top_k:
                tags.append("topk_truncated")
        if self.corpus.refreshing:
            tags.append("stale_corpus")
        if getattr(slot, "coverage", 1.0) < 1.0:
            tags.append("partial_corpus")  # already-degraded steady state:
            # a shard is quarantined and the surviving shards answer
        b = len(live)
        target = min((s for s in self.buckets if s >= b),
                     default=self.buckets[-1])
        batch = np.zeros((max(target, b), live[0].query.shape[0]), np.float32)
        for i, p in enumerate(live):
            batch[i] = p.query
        serve_fn = (self._fallback_fn(k) if ivf_missing
                    else self._serve_fns[k])
        t0 = time.monotonic()
        for p in live:
            # batch formation ends / fenced compute begins for every rider
            p.t_batch = t0
        try:
            with telemetry.span("serve/batch",
                                args={"n": b, "bucket": int(batch.shape[0]),
                                      "k": k, "degraded": list(tags),
                                      "corpus_version": slot.version}) as sp:
                def call():
                    _faults.fire("serve.batch", n=b)
                    with self._mesh_guard():
                        out = serve_fn(self.params,
                                       *self._slot_args(slot,
                                                        fallback=ivf_missing),
                                       batch)
                        jax.block_until_ready(out)
                    return out

                scores, indices = self.retry.run(call, site="serve.batch")
                sp.fence_on(scores)
        # nothing is swallowed: every request in the batch gets an explicit
        # error Reply carrying this exception, counted in counts["errors"]
        except Exception as exc:
            detail = f"{type(exc).__name__}: {exc}"
            for p in live:
                self._error(p, detail)
            return
        wall = time.monotonic() - t0
        with self._lock:
            self.counts["batches"] += 1
            self._floor_s = wall if self._floor_s == 0.0 else min(
                self._floor_s, wall)
        for p in live:
            p.compute_s = wall   # the shared fenced device wall — every
            # rider paid it; the per-request remainder is resolve_s
        scores = np.asarray(scores)
        indices = np.asarray(indices)
        if not np.all(np.isfinite(scores[:b])):
            # the shard-loss detection path: NaN sorts above every finite
            # cosine in the top-k merge, so a poisoned shard provably shows
            # up here on the first post-loss dispatch
            redo = self._quarantine_and_redispatch(serve_fn, batch, b, slot,
                                                   fallback=ivf_missing)
            if redo is None:
                for p in live:
                    self._error(p, "nonfinite_scores")
                return
            slot, scores, indices = redo
            if (getattr(slot, "coverage", 1.0) < 1.0
                    and "partial_corpus" not in tags):
                tags.append("partial_corpus")
        coverage = float(getattr(slot, "coverage", 1.0))
        m = self.metrics
        if m is not None:
            m.counter("batches").inc()
            m.histogram("batch_compute_ms").observe(wall * 1e3)
            m.gauge("corpus_version").set(slot.version)
            m.gauge("corpus_coverage").set(coverage)
            m.gauge("queue_depth").set(self._q.qsize())
        tags = tuple(tags)
        for i, p in enumerate(live):
            self._reply(p, indices[i], scores[i], tags, slot.version,
                        coverage)
        if self.shadow is not None:
            # strictly AFTER every primary reply resolved: the shadow offer
            # is a counter check + put_nowait, and a full shadow queue drops
            # the sample — the reply path never waits on quality measurement
            for i, p in enumerate(live):
                self.shadow.offer(p.rid, batch[i], indices[i], scores[i],
                                  slot, k, coverage)

    def _quarantine_and_redispatch(self, serve_fn, batch, n, slot,
                                   fallback=False):
        """Nonfinite scores from a sharded corpus mean a shard's buffers
        died under us (the `serve.shard` fault class): quarantine the lost
        shards (`corpus.quarantine_lost_shards` masks their rows invalid,
        drops coverage below 1.0 and blocks swaps), then re-dispatch the
        SAME padded batch against the degraded slot — identical shapes and
        shardings, so it rides the variant warmup() compiled, never a
        recompile. Returns (slot, scores, indices) served by the surviving
        shards, or None when the corpus isn't sharded, nothing was actually
        lost and the slot didn't change under us (a genuine compute fault),
        or the re-dispatch itself failed — the caller turns None into
        explicit error replies for the whole batch."""
        if not self.sharded:
            return None
        try:
            lost = self.corpus.quarantine_lost_shards(
                note="nonfinite dispatch scores")
        # nothing swallowed: returning None routes every request in the
        # batch to an explicit error Reply
        except Exception:
            return None
        fresh = self.corpus.active
        if not lost and fresh is slot:
            return None
        if lost:
            self._record_event(
                "partial_corpus_enter", lost=list(lost),
                coverage=round(float(getattr(fresh, "coverage", 1.0)), 4),
                corpus_version=fresh.version)
        try:
            with self._mesh_guard():
                out = serve_fn(self.params,
                               *self._slot_args(fresh, fallback=fallback),
                               batch)
                jax.block_until_ready(out)
        # same contract: None -> explicit error Replies for the whole batch
        except Exception:
            return None
        scores, indices = np.asarray(out[0]), np.asarray(out[1])
        if not np.all(np.isfinite(scores[:n])):
            return None
        return fresh, scores, indices

    def _note_overload(self):
        """Degraded-mode hysteresis: enter past the watermark, leave when the
        queue empties. Transitions are recorded — never silent."""
        occupancy = self._q.qsize() / max(1, self.max_inflight)
        if not self._degraded and occupancy >= self.overload_watermark:
            self._degraded = True
            self._record_event("degraded_enter", occupancy=round(occupancy, 3),
                               top_k=self.degraded_top_k)
            if self.metrics is not None:
                self.metrics.counter("degraded_enter").inc()
        elif self._degraded and occupancy == 0.0:
            self._degraded = False
            self._record_event("degraded_exit", occupancy=0.0)
        return self._degraded

    def _record_event(self, event, **info):
        with self._lock:
            self.events.append({"event": event, "t": time.monotonic(), **info})

    # ------------------------------------------------------------ terminals
    def _timings(self, p, now):
        """The per-hop decomposition from the stamps `p` collected on its way
        through the service. Consecutive monotonic deltas: whatever hops the
        request reached appear, and `resolve_s` is always the remainder — so
        the components SUM to `now - t_submit` (± 6-decimal rounding) for
        every terminal, including sheds that never left admission."""
        out = {}
        last = p.t_submit
        for key, stamp in (("admit_s", p.t_admit), ("queue_s", p.t_dequeue),
                           ("batch_form_s", p.t_batch)):
            if stamp is None:
                break
            out[key] = stamp - last
            last = stamp
        if p.compute_s is not None:
            out["compute_s"] = p.compute_s
            last = last + p.compute_s
        out["resolve_s"] = max(0.0, now - last)
        return {k: round(v, 6) for k, v in out.items()}

    def _sample_trace(self):
        """Deterministic every-Nth keep decision for the terminal span."""
        rate = self.trace_sample_rate
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        period = max(1, int(round(1.0 / rate)))
        with self._lock:
            self._trace_seen += 1
            return (self._trace_seen - 1) % period == 0

    def _finish(self, p, reply):
        if not p.future._set(reply):
            return p.future  # lost a shed/shed race: first decision stands
        with self._lock:
            key = {"ok": "replied", "shed": "shed", "error": "errors"}
            self.counts[key[reply.status]] += 1
            if reply.status == "ok":
                if not reply.deadline_met:
                    self.counts["deadline_missed"] += 1
                self._latencies.append(reply.latency_s)
                del self._latencies[:-_LATENCY_WINDOW]
        m = self.metrics
        if m is not None:
            # exact, sampling-independent: the registry is the record the
            # SLO monitor burns against, so every terminal lands here
            m.counter({"ok": "replied", "shed": "shed",
                       "error": "errors"}[reply.status]).inc()
            if reply.status == "ok":
                if not reply.deadline_met:
                    m.counter("deadline_missed").inc()
                m.histogram("request_latency_ms").observe(
                    reply.latency_s * 1e3)
            elif reply.status == "shed" and reply.reason:
                m.counter(f"shed.{reply.reason}").inc()
        # a zero-length per-request span: the request's terminal decision
        # lands on the trace timeline next to the batch that produced it
        # (subject to trace_sample_rate — counters above are not)
        if self._sample_trace():
            with telemetry.span("serve/request", fence=False,
                                args={"id": reply.request_id,
                                      "status": reply.status,
                                      "reason": reply.reason,
                                      "latency_ms": round(
                                          reply.latency_s * 1e3, 3),
                                      "timings": reply.timings,
                                      "degraded": list(reply.degraded)}):
                pass
        return p.future

    def _reply(self, p, indices, scores, degraded, version, coverage=1.0):
        now = time.monotonic()
        return self._finish(p, Reply(
            status="ok", indices=indices, scores=scores,
            latency_s=now - p.t_submit, deadline_met=now <= p.deadline,
            degraded=degraded, corpus_version=version,
            coverage=float(coverage), request_id=p.rid,
            timings=self._timings(p, now)))

    def _shed(self, p, reason):
        now = time.monotonic()
        return self._finish(p, Reply(
            status="shed", reason=reason, latency_s=now - p.t_submit,
            request_id=p.rid, timings=self._timings(p, now)))

    def _error(self, p, detail):
        now = time.monotonic()
        return self._finish(p, Reply(
            status="error", reason=detail, latency_s=now - p.t_submit,
            request_id=p.rid, timings=self._timings(p, now)))

    def _mesh_guard(self):
        """The collective-dispatch guard: sharded services serialize their
        device calls through the process-wide mesh dispatch lock (see the
        `_MESH_LOCK` comment); single-device services pay nothing."""
        return _mesh.dispatch_lock(self.sharded)

    def _slot_args(self, slot, fallback=False):
        """Positional slot operands for the compiled serve variants — the
        IVF variants take the slot's cell index as one extra pytree operand;
        `fallback=True` (the ivf_unavailable path) omits it because the
        exact-scoring fallback variants don't take one."""
        if self.retrieval == "ivf" and not fallback:
            return (slot.emb, slot.valid, slot.scales, slot.ivf)
        return (slot.emb, slot.valid, slot.scales)

    def _fallback_fn(self, k):
        """The exact-scoring variant the ivf_unavailable path dispatches to —
        sharded iff the service is, compiled lazily on first use and cached
        (an index-less slot is the exception, not the steady state; warmup()
        pre-warms these instead of the IVF variants when it sees one)."""
        fn = self._fallback_fns.get(k)
        if fn is None:
            if self.sharded:
                from .graph import make_sharded_serve_fn
                fn = make_sharded_serve_fn(self.config, k, self.mesh)
            else:
                fn = make_serve_fn(self.config, k, fused=self.fused)
            self._fallback_fns[k] = fn
        return fn

    def _shadow_fn(self, k):
        """The exact full-scan variant the shadow scorer re-scores with: on
        an exact service this IS the primary variant (same jit cache — zero
        extra compiles); on an IVF service it is the exact-scoring fallback
        family (`_fallback_fn`), sharded iff the service is. warmup()
        pre-compiles these at the shadow's bucket shape whenever a shadow
        scorer is attached, so sampling never retraces live."""
        if self.retrieval == "ivf":
            return self._fallback_fn(k)
        return self._serve_fns[k]

    # ------------------------------------------------------------ lifecycle
    def warmup(self):
        """Compile every (bucket, k) variant — primary AND degraded k, and
        under `retrieval="ivf"` that means one program per (bucket, k,
        probes) since probes is baked into each variant — and seed the
        device floor, so first requests measure dispatch, not tracing.
        One-time, blocking. Compile counts are watched: the warmup total
        lands in `summary()["compiles"]`, and a post-warmup watcher stays
        live so the chaos soak can assert the degraded modes never trigger
        a recompile (they dispatch to variants warmed here)."""
        slot = self.corpus.active
        assert slot is not None, "swap a corpus in before warmup()"
        # an ivf service fronting a slot with no index warms the
        # exact-scoring fallback variants instead — requests serve degraded
        # (ivf_unavailable) rather than erroring, and still without
        # post-warmup compiles
        ivf_missing = self.retrieval == "ivf" and slot.ivf is None
        fns = ({k: self._fallback_fn(k) for k in self._serve_fns}
               if ivf_missing else self._serve_fns)
        # load the autotuner cache BEFORE compiling the serving variants:
        # every kernel config resolves here, once, so post-warm traffic can
        # never see a different tile choice (and with it a recompile) —
        # the r09/r19 zero-post-warm-recompile contract with tuning on
        from .. import tuning

        tuning.prime()
        args = self._slot_args(slot, fallback=ivf_missing)
        f = int(self.config.n_features)
        watcher = CompileWatcher().start()
        try:
            with self._mesh_guard():
                for k, fn in sorted(fns.items()):
                    for b in self.buckets:
                        out = fn(self.params, *args,
                                 np.zeros((b, f), np.float32))
                        jax.block_until_ready(out)
                if self.shadow is not None:
                    # the shadow scorer's exact variants, at its one bucket
                    # shape — on an exact service these hit the jit cache
                    # warmed above; on an IVF service they are the fallback
                    # family, compiled here so a sampled request can never
                    # retrace post-warmup
                    sargs = (slot.emb, slot.valid, slot.scales)
                    for k in sorted({self.top_k, self.degraded_top_k}):
                        out = self._shadow_fn(k)(
                            self.params, *sargs,
                            np.zeros((self.buckets[0], f), np.float32))
                        jax.block_until_ready(out)
                # floor := fastest warm repeat of the smallest variant
                t0 = time.monotonic()
                out = fns[self.top_k](
                    self.params, *args,
                    np.zeros((self.buckets[0], f), np.float32))
                jax.block_until_ready(out)
                floor = time.monotonic() - t0
            # the flush thread may already be folding its own min() into
            # _floor_s under the lock — don't race it with a bare store
            with self._lock:
                self._floor_s = floor
        finally:
            self._warmup_compiles = watcher.stop()
        self._post_warm_watcher = CompileWatcher().start()

    def stop(self, timeout=5.0):
        """Drain and join: the batcher flushes everything already admitted,
        then exits; anything racing into the queue after is shed explicitly."""
        self._stop.set()
        self._thread.join(timeout=timeout)
        if self.shadow is not None:
            # after the batcher: nothing new can be offered, and the shadow
            # thread drains what it already holds before exiting
            self.shadow.stop(timeout=timeout)
        if self._post_warm_watcher is not None:
            self._post_warm_watcher.stop()  # .count survives for summary()
        while True:
            try:
                self._shed(self._q.get_nowait(), "shutdown")
            except queue.Empty:
                break

    def attach_registry(self, registry):
        """Late-bind a MetricsRegistry (bench attaches after construction so
        the bare/instrumented race shares one service build path). Counters
        start from the attach point — they are deltas-over-window material
        for the SLO monitor, so a zero start is fine."""
        self.metrics = registry
        return registry

    # ------------------------------------------------------------ reporting
    def latency_stats(self):
        with self._lock:
            lat = np.asarray(self._latencies, np.float64)
        if lat.size == 0:
            return {"n": 0, "p50_ms": None, "p95_ms": None}
        return {"n": int(lat.size),
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
                "p95_ms": round(float(np.percentile(lat, 95)) * 1e3, 3),
                "mean_ms": round(float(lat.mean()) * 1e3, 3)}

    def summary(self):
        """Manifest fragment: counts, latency percentiles, degraded-mode and
        corpus-swap ledgers, retry events — the never-silent record."""
        with self._lock:
            counts = dict(self.counts)
            events = list(self.events)
        return {"name": self.name, "counts": counts,
                "latency": self.latency_stats(),
                "degraded_events": events,
                "corpus_events": list(self.corpus.events),
                "corpus_ledger": list(self.corpus.ledger),
                "retries": list(self.retry.events),
                "buckets": list(self.buckets), "top_k": self.top_k,
                "degraded_top_k": self.degraded_top_k,
                "sharded": self.sharded, "retrieval": self.retrieval,
                "coverage": round(float(getattr(self.corpus, "coverage",
                                                1.0)), 4),
                "lost_shards": list(getattr(self.corpus, "degraded_shards",
                                            ()) or ()),
                "probes": (self.probes if self.retrieval == "ivf" else None),
                "shadow": (self.shadow.summary() if self.shadow is not None
                           else None),
                "floor_ms": round(self._floor_s * 1e3, 3),
                "tuning": self._tuning_summary(),
                "compiles": {
                    "warmup": self._warmup_compiles,
                    "post_warmup": (self._post_warm_watcher.count
                                    if self._post_warm_watcher is not None
                                    else None)}}

    @staticmethod
    def _tuning_summary():
        """Which tile configs this process's kernels dispatched with and
        where each came from (tuned capture vs hand-picked default) —
        compact: full per-shape resolutions live in the run manifest."""
        try:
            from .. import tuning

            m = tuning.resolution_manifest()
            return {"enabled": m["enabled"], "n_tuned": m["n_tuned"],
                    "n_default": m["n_default"]}
        except Exception:
            return None
