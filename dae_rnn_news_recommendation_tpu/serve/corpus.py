"""Double-buffered HBM-resident serving corpus with a health-gated hot swap.

A production recommender refreshes its article corpus while serving (new
articles arrive continuously; the paper's whole premise is fresh-news
recommendation). The refresh must never take the service down and must never
promote a bad build — so the swap protocol here is:

  1. BUILD the standby slot while the active slot keeps serving: upload the
     new article set with `train/resident.build_resident` and embed it in one
     dispatch (serve/graph.make_corpus_encode_fn). Requests answered during
     the build are tagged `stale_corpus` by the service — a first-class
     degraded mode, recorded, never silent.
  2. HEALTH-GATE the standby before promotion: the sentinel's collapse score
     (telemetry/health.embedding_health — masked mean pairwise cosine) over a
     sample of the new embeddings, plus a finiteness check. A collapsed or
     NaN-poisoned embedding table would serve confidently-wrong results with
     healthy-looking latency; the gate refuses it.
  3. PROMOTE atomically (one reference assignment under the lock) or ROLL
     BACK: any build/gate failure leaves the active slot untouched and
     serving, and appends a `swap_rollback` event to `corpus.events` (which
     the service folds into its manifest fragment).

`reliability/faults.py` fires `serve.swap` at the top of every build, so the
chaos-serve soak can prove the rollback path: an injected swap fault must
leave the OLD corpus serving, version unchanged.
"""

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

CORPUS_DTYPES = ("float32", "bfloat16", "int8")

from .. import telemetry
from ..reliability import faults as _faults
from ..telemetry.health import embedding_health
from ..train.resident import build_resident
from .graph import DEFAULT_BLOCK, block_indices, make_corpus_encode_fn

# refuse to promote an embedding table whose sampled mean pairwise cosine is
# above this: the encoder has collapsed and every query would get the same
# articles (telemetry/health.py uses the same score to flag training runs)
COLLAPSE_CEILING = 0.98

_GATE_SAMPLE = 256  # rows sampled for the collapse gate


def quantize_corpus(emb, dtype):
    """[N_pad, D] f32 unit-norm embeddings -> (stored array, per-row scales).

    float32: stored as-is, scales None. bfloat16: one cast, scales None (the
    rows are unit-norm, so bf16's 8-bit mantissa costs ~3 decimal digits of
    cosine resolution uniformly). int8: symmetric per-row absmax quantization
    — `scale = absmax / 127`, zero rows get scale 1 so dequant stays exact —
    stored with f32 scales the scorer applies AFTER the int8 dot (all
    accumulation in fp32 via `preferred_element_type`; see ops/topk_fused)."""
    if dtype == "float32":
        return emb, None
    if dtype == "bfloat16":
        return emb.astype(jnp.bfloat16), None
    if dtype == "int8":
        absmax = jnp.max(jnp.abs(emb), axis=1)
        scales = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(emb / scales[:, None]), -127, 127)
        return q.astype(jnp.int8), scales
    raise ValueError(f"corpus_dtype must be one of {CORPUS_DTYPES}: {dtype!r}")


def dequantize_rows(emb, scales, rows):
    """First `rows` corpus rows back in f32 (health gate / parity checks)."""
    x = emb[:rows].astype(jnp.float32)
    if scales is not None:
        x = x * scales[:rows, None]
    return x


class CorpusSlot:
    """One immutable buffer: unit-norm embeddings [N_pad, D] on device (at
    the corpus dtype, int8 alongside its per-row scales), a valid-row mask,
    and provenance. Never mutated after build — the service snapshots a
    reference and scores against it lock-free."""

    __slots__ = ("emb", "valid", "scales", "dtype", "n", "version", "note",
                 "built_s")

    def __init__(self, emb, valid, n, version, note, built_s,
                 scales=None, dtype="float32"):
        self.emb = emb
        self.valid = valid
        self.scales = scales
        self.dtype = dtype
        self.n = int(n)
        self.version = int(version)
        self.note = note
        self.built_s = built_s

    def resident_bytes(self):
        """Device bytes held by the scoring matrix (embeddings + scales; the
        valid mask is dtype-invariant and excluded so dtypes compare clean)."""
        return int(self.emb.nbytes) + (
            int(self.scales.nbytes) if self.scales is not None else 0)


class SwapRejected(RuntimeError):
    """The standby build failed its health gate; the active slot still serves."""


class ServingCorpus:
    """Double-buffered corpus: `active` serves while `swap()` builds, gates,
    and promotes (or rolls back). Thread-safe; the swap runs on the caller's
    thread so the microbatcher never blocks on a refresh."""

    def __init__(self, config, *, block=DEFAULT_BLOCK,
                 collapse_ceiling=COLLAPSE_CEILING, device_put=None,
                 corpus_dtype="float32"):
        if corpus_dtype not in CORPUS_DTYPES:
            raise ValueError(
                f"corpus_dtype must be one of {CORPUS_DTYPES}: {corpus_dtype!r}")
        self.config = config
        self.block = int(block)
        self.collapse_ceiling = float(collapse_ceiling)
        self.corpus_dtype = corpus_dtype
        self._device_put = device_put
        self._encode_corpus = make_corpus_encode_fn(config)
        self._lock = threading.Lock()
        self._active = None
        self._version = 0
        self._refreshing = threading.Event()
        self.events = []  # swap / swap_rollback records, in order

    # ------------------------------------------------------------ read side
    @property
    def active(self):
        """The serving slot (None before the first successful swap)."""
        with self._lock:
            return self._active

    @property
    def version(self):
        with self._lock:
            return self._version

    @property
    def refreshing(self):
        """True while a standby build is in flight — the service tags replies
        `stale_corpus` for the duration."""
        return self._refreshing.is_set()

    # ----------------------------------------------------------- swap side
    def swap(self, params, articles, note=""):
        """Build a standby slot from `articles` (dense [N, F] or scipy CSR),
        health-gate it, and promote it. Returns the promoted CorpusSlot.

        On ANY failure (injected serve.swap fault, build error, gate refusal)
        the active slot keeps serving: the failure is recorded as a
        `swap_rollback` event and re-raised only when there is no active slot
        to fall back to (a failed FIRST build has nothing to serve)."""
        t0 = time.monotonic()
        self._refreshing.set()
        try:
            with telemetry.span("serve/corpus_swap", fence=False,
                                args={"note": note}):
                standby = self._build(params, articles, note)
            gate = self._health_gate(standby)
            if not gate["ok"]:
                raise SwapRejected(
                    f"standby corpus failed the health gate: {gate}")
        except Exception as exc:
            with self._lock:
                fallback = self._active
                event = {"event": "swap_rollback", "note": note,
                         "error": f"{type(exc).__name__}: {exc}",
                         "active_version": self._version,
                         "duration_s": round(time.monotonic() - t0, 4)}
                self.events.append(event)
            if fallback is None:
                raise  # nothing to roll back TO: the caller must know
            return fallback
        finally:
            self._refreshing.clear()
        with self._lock:
            self._version += 1
            standby.version = self._version
            self._active = standby
            self.events.append({
                "event": "swap", "note": note, "version": self._version,
                "n_articles": standby.n, "collapse": gate["collapse"],
                "duration_s": round(time.monotonic() - t0, 4)})
        return standby

    def _build(self, params, articles, note):
        _faults.fire("serve.swap", note=note)
        n = int(articles.shape[0])
        resident = build_resident(articles, device_put=self._device_put)
        blocks = block_indices(n, self.block)
        emb = self._encode_corpus(params, resident, blocks)
        emb, scales = quantize_corpus(emb, self.corpus_dtype)
        n_pad = blocks.size
        valid = np.zeros(n_pad, np.float32)
        valid[:n] = 1.0
        put = self._device_put or jax.device_put
        if self._device_put is not None:
            # re-place through the caller's sharder (e.g. mesh.shard_rows):
            # the encode ran wherever jit put it, the slot lives where scoring
            # wants it
            emb = put(emb)
            scales = put(scales) if scales is not None else None
        return CorpusSlot(emb=emb, valid=put(valid), n=n, version=-1,
                          note=note, built_s=time.monotonic(),
                          scales=scales, dtype=self.corpus_dtype)

    def _health_gate(self, slot):
        """Finiteness + collapse score on a sample of the standby embeddings
        (DEQUANTIZED — the gate judges what scoring will actually see, so a
        broken quantization fails here, not in production ranking).
        One deliberate host sync — the swap path is off the request path."""
        sample = dequantize_rows(slot.emb, slot.scales,
                                 min(_GATE_SAMPLE, slot.n))
        finite = bool(jax.device_get(jnp.all(jnp.isfinite(sample))))
        stats = jax.device_get(embedding_health(sample))
        collapse = float(stats["health/embedding_collapse"])
        ok = finite and np.isfinite(collapse) and (
            collapse <= self.collapse_ceiling)
        return {"ok": ok, "finite": finite, "collapse": round(collapse, 6),
                "ceiling": self.collapse_ceiling}
