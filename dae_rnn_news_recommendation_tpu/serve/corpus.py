"""Double-buffered HBM-resident serving corpus with a health-gated hot swap.

A production recommender refreshes its article corpus while serving (new
articles arrive continuously; the paper's whole premise is fresh-news
recommendation). The refresh must never take the service down and must never
promote a bad build — so the swap protocol here is:

  1. BUILD the standby slot while the active slot keeps serving: upload the
     new article set with `train/resident.build_resident` and embed it in one
     dispatch (serve/graph.make_corpus_encode_fn). Requests answered during
     the build are tagged `stale_corpus` by the service — a first-class
     degraded mode, recorded, never silent.
  2. HEALTH-GATE the standby before promotion: the sentinel's collapse score
     (telemetry/health.embedding_health — masked mean pairwise cosine) over a
     sample of the new embeddings, plus a finiteness check. A collapsed or
     NaN-poisoned embedding table would serve confidently-wrong results with
     healthy-looking latency; the gate refuses it.
  3. PROMOTE atomically (one reference assignment under the lock) or ROLL
     BACK: any build/gate failure leaves the active slot untouched and
     serving, and appends a `swap_rollback` event to `corpus.events` (which
     the service folds into its manifest fragment).

`reliability/faults.py` fires `serve.swap` at the top of every build, so the
chaos-serve soak can prove the rollback path: an injected swap fault must
leave the OLD corpus serving, version unchanged.
"""

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import telemetry
from ..reliability import faults as _faults
from ..telemetry.health import embedding_health
from ..train.resident import build_resident
from .graph import DEFAULT_BLOCK, block_indices, make_corpus_encode_fn

# refuse to promote an embedding table whose sampled mean pairwise cosine is
# above this: the encoder has collapsed and every query would get the same
# articles (telemetry/health.py uses the same score to flag training runs)
COLLAPSE_CEILING = 0.98

_GATE_SAMPLE = 256  # rows sampled for the collapse gate


class CorpusSlot:
    """One immutable buffer: unit-norm embeddings [N_pad, D] on device, a
    valid-row mask, and provenance. Never mutated after build — the service
    snapshots a reference and scores against it lock-free."""

    __slots__ = ("emb", "valid", "n", "version", "note", "built_s")

    def __init__(self, emb, valid, n, version, note, built_s):
        self.emb = emb
        self.valid = valid
        self.n = int(n)
        self.version = int(version)
        self.note = note
        self.built_s = built_s


class SwapRejected(RuntimeError):
    """The standby build failed its health gate; the active slot still serves."""


class ServingCorpus:
    """Double-buffered corpus: `active` serves while `swap()` builds, gates,
    and promotes (or rolls back). Thread-safe; the swap runs on the caller's
    thread so the microbatcher never blocks on a refresh."""

    def __init__(self, config, *, block=DEFAULT_BLOCK,
                 collapse_ceiling=COLLAPSE_CEILING, device_put=None):
        self.config = config
        self.block = int(block)
        self.collapse_ceiling = float(collapse_ceiling)
        self._device_put = device_put
        self._encode_corpus = make_corpus_encode_fn(config)
        self._lock = threading.Lock()
        self._active = None
        self._version = 0
        self._refreshing = threading.Event()
        self.events = []  # swap / swap_rollback records, in order

    # ------------------------------------------------------------ read side
    @property
    def active(self):
        """The serving slot (None before the first successful swap)."""
        with self._lock:
            return self._active

    @property
    def version(self):
        with self._lock:
            return self._version

    @property
    def refreshing(self):
        """True while a standby build is in flight — the service tags replies
        `stale_corpus` for the duration."""
        return self._refreshing.is_set()

    # ----------------------------------------------------------- swap side
    def swap(self, params, articles, note=""):
        """Build a standby slot from `articles` (dense [N, F] or scipy CSR),
        health-gate it, and promote it. Returns the promoted CorpusSlot.

        On ANY failure (injected serve.swap fault, build error, gate refusal)
        the active slot keeps serving: the failure is recorded as a
        `swap_rollback` event and re-raised only when there is no active slot
        to fall back to (a failed FIRST build has nothing to serve)."""
        t0 = time.monotonic()
        self._refreshing.set()
        try:
            with telemetry.span("serve/corpus_swap", fence=False,
                                args={"note": note}):
                standby = self._build(params, articles, note)
            gate = self._health_gate(standby)
            if not gate["ok"]:
                raise SwapRejected(
                    f"standby corpus failed the health gate: {gate}")
        except Exception as exc:
            with self._lock:
                fallback = self._active
                event = {"event": "swap_rollback", "note": note,
                         "error": f"{type(exc).__name__}: {exc}",
                         "active_version": self._version,
                         "duration_s": round(time.monotonic() - t0, 4)}
                self.events.append(event)
            if fallback is None:
                raise  # nothing to roll back TO: the caller must know
            return fallback
        finally:
            self._refreshing.clear()
        with self._lock:
            self._version += 1
            standby.version = self._version
            self._active = standby
            self.events.append({
                "event": "swap", "note": note, "version": self._version,
                "n_articles": standby.n, "collapse": gate["collapse"],
                "duration_s": round(time.monotonic() - t0, 4)})
        return standby

    def _build(self, params, articles, note):
        _faults.fire("serve.swap", note=note)
        n = int(articles.shape[0])
        resident = build_resident(articles, device_put=self._device_put)
        blocks = block_indices(n, self.block)
        emb = self._encode_corpus(params, resident, blocks)
        n_pad = blocks.size
        valid = np.zeros(n_pad, np.float32)
        valid[:n] = 1.0
        put = self._device_put or jax.device_put
        return CorpusSlot(emb=emb, valid=put(valid), n=n, version=-1,
                          note=note, built_s=time.monotonic())

    def _health_gate(self, slot):
        """Finiteness + collapse score on a sample of the standby embeddings.
        One deliberate host sync — the swap path is off the request path."""
        sample = slot.emb[:min(_GATE_SAMPLE, slot.n)]
        finite = bool(jax.device_get(jnp.all(jnp.isfinite(sample))))
        stats = jax.device_get(embedding_health(sample))
        collapse = float(stats["health/embedding_collapse"])
        ok = finite and np.isfinite(collapse) and (
            collapse <= self.collapse_ceiling)
        return {"ok": ok, "finite": finite, "collapse": round(collapse, 6),
                "ceiling": self.collapse_ceiling}
