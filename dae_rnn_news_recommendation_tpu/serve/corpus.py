"""Double-buffered HBM-resident serving corpus with a health-gated hot swap.

A production recommender refreshes its article corpus while serving (new
articles arrive continuously; the paper's whole premise is fresh-news
recommendation). The refresh must never take the service down and must never
promote a bad build — so the swap protocol here is:

  1. BUILD the standby slot while the active slot keeps serving: upload the
     new article set with `train/resident.build_resident` and embed it in one
     dispatch (serve/graph.make_corpus_encode_fn). Requests answered during
     the build are tagged `stale_corpus` by the service — a first-class
     degraded mode, recorded, never silent.
  2. HEALTH-GATE the standby before promotion: the sentinel's collapse score
     (telemetry/health.embedding_health — masked mean pairwise cosine) over a
     sample of the new embeddings, plus a finiteness check. A collapsed or
     NaN-poisoned embedding table would serve confidently-wrong results with
     healthy-looking latency; the gate refuses it.
  3. PROMOTE atomically (one reference assignment under the lock) or ROLL
     BACK: any build/gate failure leaves the active slot untouched and
     serving, and appends a `swap_rollback` event to `corpus.events` (which
     the service folds into its manifest fragment).

`reliability/faults.py` fires `serve.swap` at the top of every build, so the
chaos-serve soak can prove the rollback path: an injected swap fault must
leave the OLD corpus serving, version unchanged.

With `retrieval="ivf"` every promoted slot additionally carries a cell-major
clustered index (`slot.ivf`, an `index.IVFCells`): k-means centroids seeded
from the slot's own drift-gate centroid partition the quantized rows into
contiguous cells the fused IVF scorer (`ops/ivf_topk.py`) probes instead of
scanning the whole corpus. The index composes with both swap flavors — a
full swap REFITS the centroids; an incremental swap keeps them and routes
every row (appended ones included) to its nearest existing cell, so churn
never pays a re-clustering. Routing-only updates skew cell occupancy over
time, so each incremental promote updates a staleness counter: `imbalance >
imbalance_max` for `reindex_after` consecutive incremental swaps marks
`reindex_due`, and `reindex()` refits the centroids on the active slot's
rows, riding the same health-gate -> promote -> ledger path as any swap.

On a MESH-SHARDED corpus the index itself shards: cells partition by
centroid across shards (`index.ShardedIVFCells`, shard-major slabs placed
through the corpus's sharder), the centroid scan stays replicated, and the
clustered scorer gathers per shard over only locally-owned probed cells
(`ops.ivf_topk.sharded_ivf_topk`). `default_corpus` makes sharded+IVF the
default configuration on multi-device hosts. Shard loss takes the lost
shard's CELLS with it: quarantine masks those slabs' valid lanes, coverage
reports the row fraction the index still reaches, and recovery restores the
slabs bitwise from the same host mirror as the slot arrays.

MESH-SHARDED slots (rows placed over a 1-D device mesh, pass `mesh=` or a
`device_put=shard_rows` closure) ride the same protocol with a TWO-PHASE
commit: the build/gate/index work is the PREPARE phase — every shard's new
rows, scales and valid mask are staged off to the side (a `swap_prepare`
event marks the window, shard versions staged at a sentinel), and a host
MIRROR of the staged quantized bytes is captured for shard recovery. COMMIT
happens inside `_promote`: all shards' versions are stamped to the new
corpus version in the same lock-held assignment that publishes the slot, so
a concurrent reader either sees the whole old slot or the whole new one —
never mixed shard versions (reliability/ledger.audit_version_ledger audits
the per-promote shard stamps; audit_shard_reads audits live reader
snapshots). A prepare-phase crash discards the entire staged slot — standard
rollback, no shard advances. Shard LOSS is first-class: `inject_shard_loss`
(the `serve.shard` chaos directive) poisons one shard's buffers in place;
`audit_shards` detects it, `quarantine_lost_shards` degrades to
partial-corpus serving (lost rows masked invalid, coverage fraction on the
slot, swaps blocked), and `recover_shards` re-materializes the lost shard
bitwise from the host mirror while surviving shards keep their live buffers.

Corpus churn (refresh/) adds the INCREMENTAL variant of the same protocol:
`swap_incremental` appends freshly-encoded articles to the active slot with
age-based eviction instead of rebuilding the world, runs the identical health
gate over the appended tail, and promotes through the same single-assignment
path — `refresh.swap` is its fault site. Every promote AND every rollback
(full or incremental) appends one record to `corpus.ledger`, the append-only
version ledger the chaos_churn soak audits: versions must be strictly
monotonic, and every promoted record must carry a passing gate. Swaps are
serialized by a non-blocking guard: a second swap attempted while one is in
flight raises `SwapInProgress` deterministically rather than interleaving
slot state (the caller — the churn supervisor — owns retry policy).
"""

import threading
import time

import numpy as np

import jax
import jax.numpy as jnp

CORPUS_DTYPES = ("float32", "bfloat16", "int8")

from .. import telemetry
from ..parallel.mesh import dispatch_lock
from ..reliability import faults as _faults
from ..telemetry.health import embedding_health
from ..train.resident import build_resident
from .graph import DEFAULT_BLOCK, block_indices, make_corpus_encode_fn

# refuse to promote an embedding table whose sampled mean pairwise cosine is
# above this: the encoder has collapsed and every query would get the same
# articles (telemetry/health.py uses the same score to flag training runs)
COLLAPSE_CEILING = 0.98

_GATE_SAMPLE = 256  # rows sampled for the collapse gate

_QUANT_SAMPLE = 64  # rows sampled for the swap-time quantization score error

_STAGED = -2  # shard-version sentinel during the prepare phase: visible only
# on the standby slot (never published), stamped to the real version by the
# lock-held commit in _promote


def quantize_corpus(emb, dtype):
    """[N_pad, D] f32 unit-norm embeddings -> (stored array, per-row scales).

    float32: stored as-is, scales None. bfloat16: one cast, scales None (the
    rows are unit-norm, so bf16's 8-bit mantissa costs ~3 decimal digits of
    cosine resolution uniformly). int8: symmetric per-row absmax quantization
    — `scale = absmax / 127`, zero rows get scale 1 so dequant stays exact —
    stored with f32 scales the scorer applies AFTER the int8 dot (all
    accumulation in fp32 via `preferred_element_type`; see ops/topk_fused)."""
    if dtype == "float32":
        return emb, None
    if dtype == "bfloat16":
        return emb.astype(jnp.bfloat16), None
    if dtype == "int8":
        absmax = jnp.max(jnp.abs(emb), axis=1)
        scales = jnp.where(absmax > 0, absmax / 127.0, 1.0).astype(jnp.float32)
        q = jnp.clip(jnp.round(emb / scales[:, None]), -127, 127)
        return q.astype(jnp.int8), scales
    raise ValueError(f"corpus_dtype must be one of {CORPUS_DTYPES}: {dtype!r}")


def dequantize_rows(emb, scales, rows):
    """First `rows` corpus rows back in f32 (health gate / parity checks)."""
    x = emb[:rows].astype(jnp.float32)
    if scales is not None:
        x = x * scales[:rows, None]
    return x


class CorpusSlot:
    """One immutable buffer: unit-norm embeddings [N_pad, D] on device (at
    the corpus dtype, int8 alongside its per-row scales), a valid-row mask,
    and provenance. Never mutated after build — the service snapshots a
    reference and scores against it lock-free.

    `ages` is a host-side int32 [N_pad]: the corpus version at which each row
    was ingested (-1 for padding), driving age-based eviction on incremental
    swaps. `stats` carries the gate sample's collapse score and centroid —
    the reference the drift gate (telemetry/health.drift_health) compares the
    NEXT refresh batch against.

    Mesh-sharded slots carry four extra fields: `shard_versions` (host int32,
    one entry per shard, all stamped to `version` by the lock-held commit —
    None on single-device slots), `mirror` (host copy of the quantized
    emb/valid/scales bytes, the source `recover_shards` re-materializes a
    lost shard from), `lost` (frozenset of quarantined shard ids) and
    `coverage` (valid-row fraction still served; < 1.0 while degraded — the
    service stamps it on every `partial_corpus` reply)."""

    __slots__ = ("emb", "valid", "scales", "dtype", "n", "version", "note",
                 "built_s", "ages", "stats", "ivf", "shard_versions",
                 "mirror", "lost", "coverage")

    def __init__(self, emb, valid, n, version, note, built_s,
                 scales=None, dtype="float32", ages=None, stats=None,
                 ivf=None, shard_versions=None, mirror=None, lost=frozenset(),
                 coverage=1.0):
        self.emb = emb
        self.valid = valid
        self.scales = scales
        self.dtype = dtype
        self.n = int(n)
        self.version = int(version)
        self.note = note
        self.built_s = built_s
        self.ages = ages
        self.stats = stats or {}
        self.ivf = ivf  # index.IVFCells when the corpus runs retrieval="ivf"
        self.shard_versions = shard_versions
        self.mirror = mirror
        self.lost = frozenset(lost)
        self.coverage = float(coverage)

    def resident_bytes(self):
        """Device bytes held by the scoring matrix (embeddings + scales; the
        valid mask is dtype-invariant and excluded so dtypes compare clean)."""
        return int(self.emb.nbytes) + (
            int(self.scales.nbytes) if self.scales is not None else 0)


class SwapRejected(RuntimeError):
    """The standby build failed its health gate; the active slot still serves."""


class SwapInProgress(RuntimeError):
    """A swap was attempted while another is in flight. Swaps serialize: the
    second caller gets this exception immediately (never a blocked thread,
    never interleaved slot state) and owns the retry decision."""


class ShardedUnsupported(ValueError):
    """A requested feature does not compose with mesh-sharded slots.

    Retained in the exception taxonomy for callers that guard on it; the
    former configuration-time uses (retrieval='ivf' with a mesh) composed in
    r16 and no longer raise. Subclasses ValueError so pre-taxonomy callers
    that caught ValueError keep working."""


def _slot_is_sharded(slot):
    """True when the slot's embedding table spans more than one device —
    the switch that routes swaps through the two-phase prepare -> commit
    (shard staging, host mirror, lock-held version stamp) and arms the
    shard-loss degradation/recovery machinery."""
    sharding = getattr(slot.emb, "sharding", None)
    device_set = getattr(sharding, "device_set", None)
    return bool(device_set) and len(device_set) > 1


class ServingCorpus:
    """Double-buffered corpus: `active` serves while `swap()` builds, gates,
    and promotes (or rolls back). Thread-safe; the swap runs on the caller's
    thread so the microbatcher never blocks on a refresh."""

    def __init__(self, config, *, block=DEFAULT_BLOCK,
                 collapse_ceiling=COLLAPSE_CEILING, device_put=None,
                 mesh=None, corpus_dtype="float32", retrieval="exact",
                 n_cells=None, index_seed=0, index_iters=8, imbalance_max=4.0,
                 reindex_after=3, cell_cap=None, registry=None):
        if corpus_dtype not in CORPUS_DTYPES:
            raise ValueError(
                f"corpus_dtype must be one of {CORPUS_DTYPES}: {corpus_dtype!r}")
        if retrieval not in ("exact", "ivf"):
            raise ValueError(
                f"retrieval must be 'exact' or 'ivf': {retrieval!r}")
        self.mesh = mesh
        self._row_mult = None
        if mesh is not None:
            # slot arrays land row-sharded over the mesh; every build pads
            # N to divide it (graph.block_indices row_multiple). Gather
            # sources whose row count happens not to divide (raw article
            # residents, never scored directly) stay single-device.
            from ..parallel.mesh import shard_rows
            self._row_mult = int(np.prod(list(mesh.shape.values())))
            if device_put is None:
                n_dev = self._row_mult

                def device_put(x, _mesh=mesh, _n=n_dev):
                    def put(leaf):
                        if leaf.shape and leaf.shape[0] % _n == 0:
                            return shard_rows(leaf, _mesh)
                        return jax.device_put(leaf)

                    return jax.tree_util.tree_map(put, x)
        self.config = config
        self.block = int(block)
        self.collapse_ceiling = float(collapse_ceiling)
        self.corpus_dtype = corpus_dtype
        self.retrieval = retrieval
        self.n_cells = None if n_cells is None else int(n_cells)
        self.cell_cap = None if cell_cap is None else int(cell_cap)
        # floor on the uniform IVF cell capacity: pins the index shapes
        # across swaps whose occupancy skews, so the serving variants
        # compiled at warmup keep dispatching (zero-recompile soaks)
        self.index_seed = int(index_seed)
        self.index_iters = int(index_iters)
        self.imbalance_max = float(imbalance_max)
        self.reindex_after = int(reindex_after)
        self._ivf_stale = 0  # consecutive imbalanced incremental promotes
        self._device_put = device_put
        self._encode_corpus = make_corpus_encode_fn(config)
        self._lock = threading.Lock()
        self._swap_busy = threading.Lock()  # serializes swap/swap_incremental
        self._active = None
        self._previous = None  # the slot the last promote displaced — what
        # revert() re-installs when a staged fleet rollout aborts mid-fleet
        self._version = 0
        self._lost = set()  # quarantined shard ids: non-empty blocks every
        # swap flavor until recover_shards() (or a promote that re-places
        # every shard's buffers) heals the corpus
        self._refreshing = threading.Event()
        self.events = []  # swap / swap_rollback records, in order
        self.ledger = []  # append-only version ledger: one record per
        # promote AND per rollback attempt; the chaos_churn soak audits it
        # for version monotonicity + gate coverage
        self.metrics = registry  # optional telemetry.MetricsRegistry: the
        # corpus keeps continuous QUALITY gauges current (cell imbalance /
        # occupancy, staleness since reindex, swap-time quantization score
        # error, live coverage) so degraded modes are quantified, not just
        # flagged — the data source for telemetry.quality_slo_specs()

    def attach_registry(self, registry):
        """Late-bind a MetricsRegistry (mirrors the service's hook, so one
        registry can carry both the serving and the corpus quality gauges).
        Gauges publish from the next swap/index/quarantine event on."""
        self.metrics = registry
        return registry

    # ------------------------------------------------------------ read side
    @property
    def active(self):
        """The serving slot (None before the first successful swap)."""
        with self._lock:
            return self._active

    @property
    def version(self):
        with self._lock:
            return self._version

    @property
    def refreshing(self):
        """True while a standby build is in flight — the service tags replies
        `stale_corpus` for the duration."""
        return self._refreshing.is_set()

    @property
    def degraded_shards(self):
        """Sorted ids of quarantined (lost) shards; empty when fully
        serving. Non-empty blocks every swap flavor — the churn supervisor
        checks this and runs `recover_shards()` before appending."""
        with self._lock:
            return tuple(sorted(self._lost))

    @property
    def coverage(self):
        """Valid-row fraction the active slot still serves (1.0 healthy)."""
        with self._lock:
            return 1.0 if self._active is None else self._active.coverage

    @property
    def ivf_stale_cycles(self):
        """Consecutive incremental promotes whose cell imbalance exceeded
        `imbalance_max` (routing-only updates skew occupancy over time)."""
        with self._lock:
            return self._ivf_stale

    @property
    def reindex_due(self):
        """True when the staleness counter says the centroids should be
        refit — the churn supervisor calls `reindex()` when it sees this."""
        with self._lock:
            return (self.retrieval == "ivf"
                    and self._ivf_stale >= self.reindex_after)

    # ----------------------------------------------------------- swap side
    def swap(self, params, articles, note=""):
        """Build a standby slot from `articles` (dense [N, F] or scipy CSR),
        health-gate it, and promote it. Returns the promoted CorpusSlot.

        On ANY failure (injected serve.swap fault, build error, gate refusal)
        the active slot keeps serving: the failure is recorded as a
        `swap_rollback` event and re-raised only when there is no active slot
        to fall back to (a failed FIRST build has nothing to serve).

        Raises `SwapInProgress` (without touching any state) if another swap
        is already in flight on another thread, and `SwapRejected` while the
        corpus is degraded (a lost shard must be recovered first — swapping
        over a partially-dead mesh would mask the loss)."""
        self._acquire_swap(note)
        try:
            self._reject_if_degraded("swap", note)
            return self._swap_full(params, articles, note)
        finally:
            self._swap_busy.release()

    def _acquire_swap(self, note):
        if not self._swap_busy.acquire(blocking=False):
            with self._lock:
                self.events.append({"event": "swap_rejected_busy",
                                    "note": note,
                                    "active_version": self._version})
            raise SwapInProgress(
                f"a swap is already in flight (rejected: {note!r})")

    def _reject_if_degraded(self, op, note):
        """Swaps are blocked while a shard is quarantined: a promote would
        place fresh buffers on a device the harness just declared dead, and
        an incremental append would dequantize rows through the poisoned
        slot. Recovery (`recover_shards`) is the only legal next move."""
        with self._lock:
            lost = sorted(self._lost)
            if not lost:
                return
            self.events.append({"event": "swap_rejected_degraded", "op": op,
                                "note": note, "lost": lost,
                                "active_version": self._version})
        raise SwapRejected(
            f"{op} blocked while degraded (lost shards {lost}): run "
            "recover_shards() before swapping")

    def _swap_full(self, params, articles, note):
        t0 = time.monotonic()
        self._refreshing.set()
        try:
            with telemetry.span("serve/corpus_swap", fence=False,
                                args={"note": note}):
                standby = self._build(params, articles, note)
            gate = self._health_gate(standby)
            if not gate["ok"]:
                raise SwapRejected(
                    f"standby corpus failed the health gate: {gate}")
            # full rebuild REFITS the centroids, seeded from the gate
            # centroid the line above just stored on the slot
            self._attach_index(standby, refit=True, note=note)
            self._stage_shards(standby, note)
        except Exception as exc:
            return self._rollback("full", note, exc, t0)
        finally:
            self._refreshing.clear()
        return self._promote(standby, gate, "full", note, t0,
                             n_added=standby.n, n_evicted=0)

    def _stage_shards(self, standby, note, base=None):
        """PREPARE phase of the two-phase sharded commit (no-op on
        single-device slots): the staged rows/scales/valid already live off
        to the side on every shard (the standby is invisible until commit);
        here the shard-version vector is staged at the sentinel, and a host
        MIRROR of the staged quantized bytes is captured — the recovery
        source `recover_shards` re-materializes a lost shard from, bitwise.
        Runs inside the swap's try block: any failure here discards the
        whole staged slot (prepare-phase crash -> whole-slot rollback, no
        shard advances)."""
        if not _slot_is_sharded(standby):
            return
        from ..parallel.mesh import shard_spans

        spans = shard_spans(standby.emb)
        if (base is not None and standby.emb is base.emb
                and base.mirror is not None):
            # reindex: the slot bytes are the exact same buffers — copy the
            # mirror dict (never mutate the base's) and refresh only the
            # index entry below (the clustering DID change)
            standby.mirror = dict(base.mirror)
        else:
            standby.mirror = {
                "emb": np.asarray(jax.device_get(standby.emb)),
                "valid": np.asarray(jax.device_get(standby.valid)),
                "scales": (None if standby.scales is None else
                           np.asarray(jax.device_get(standby.scales)))}
        if standby.ivf is not None and hasattr(standby.ivf, "n_shards"):
            # shard-recovery source for the index slabs: centroids/assign
            # are replicated (survive any single shard) and excluded
            standby.mirror["ivf"] = {
                "cell_emb": np.asarray(jax.device_get(standby.ivf.cell_emb)),
                "cell_valid": np.asarray(
                    jax.device_get(standby.ivf.cell_valid)),
                "cell_scales": np.asarray(
                    jax.device_get(standby.ivf.cell_scales))}
        standby.shard_versions = np.full(len(spans), _STAGED, np.int32)
        with self._lock:
            self.events.append({
                "event": "swap_prepare", "note": note,
                "n_shards": len(spans),
                "rows_per_shard": int(spans[0][1] - spans[0][0]),
                "staged_version": self._version + 1})

    def _promote(self, standby, gate, kind, note, t0, *, n_added, n_evicted):
        """The single atomic assignment both swap flavors funnel through:
        version bump + slot reference + event + ledger record, one lock."""
        with self._lock:
            self._previous = self._active
            self._version += 1
            standby.version = self._version
            if standby.ages is None:  # full rebuild: every row is this vintage
                ages = np.full(standby.valid.shape[0], -1, np.int32)
                ages[:standby.n] = self._version
                standby.ages = ages
            else:  # incremental: appended rows were staged with age -1
                standby.ages = np.where(standby.ages == -2, self._version,
                                        standby.ages).astype(np.int32)
            if standby.shard_versions is not None:
                # COMMIT phase of the two-phase sharded swap: every shard's
                # version flips from the staged sentinel to the new corpus
                # version in the same lock-held assignment that publishes
                # the slot — a reader that can see the slot sees ALL shards
                # already stamped, never a mix
                standby.shard_versions = np.full_like(standby.shard_versions,
                                                      self._version)
            self._active = standby
            self._lost = set()  # a promote re-places every shard's buffers,
            # healing any loss that slipped in mid-prepare
            rec = {
                "version": self._version, "kind": kind, "ok": True,
                "gate": gate, "n": standby.n, "n_added": int(n_added),
                "n_evicted": int(n_evicted), "note": note,
                "duration_s": round(time.monotonic() - t0, 4)}
            if standby.shard_versions is not None:
                rec["shards"] = {
                    "n": int(standby.shard_versions.size),
                    "versions": [int(v) for v in standby.shard_versions]}
            self.events.append({
                "event": "swap", "kind": kind, "note": note,
                "version": self._version, "n_articles": standby.n,
                "collapse": gate["collapse"],
                "duration_s": round(time.monotonic() - t0, 4)})
            self.ledger.append(rec)
        m = self.metrics
        if m is not None:
            # the promote is the quality-gauge publish point: whatever slot
            # a reader can see, the gauges already describe
            m.gauge("corpus_version").set(standby.version)
            m.gauge("corpus_coverage").set(standby.coverage)
            q_err = standby.stats.get("quant_error")
            if q_err is not None:
                m.gauge("int8_score_error").set(q_err)
        return standby

    def _rollback(self, kind, note, exc, t0):
        with self._lock:
            fallback = self._active
            detail = {"kind": kind, "note": note,
                      "error": f"{type(exc).__name__}: {exc}",
                      "active_version": self._version,
                      "duration_s": round(time.monotonic() - t0, 4)}
            self.events.append({"event": "swap_rollback", **detail})
            self.ledger.append({"version": self._version, "ok": False,
                                **detail})
        if fallback is None:
            raise exc  # nothing to roll back TO: the caller must know
        return fallback

    def revert(self, note=""):
        """Single-level undo of the last promote: re-install the slot the
        promote displaced and move the active version BACK to that slot's
        number. This is the fleet-rollback primitive (ISSUE 12): a staged
        rollout that fails mid-fleet calls revert() on every replica it
        already promoted, restoring the whole fleet to the pre-canary
        version — at most two corpus versions are ever live, and a failed
        stage collapses the fleet back to one.

        The previous slot was itself health-gated when IT promoted, so no
        re-gating happens here; the record lands in `events` as
        `swap_revert` and in `ledger` with `revert: True` (the shared audit
        accepts a version repeating only after such a record). One level
        only: a second revert without an intervening promote raises
        SwapRejected, and so does a revert before any second promote."""
        self._acquire_swap(note)
        try:
            self._reject_if_degraded("revert", note)
            with self._lock:
                prev, cur = self._previous, self._active
                if prev is None:
                    raise SwapRejected(
                        "no previous slot to revert to (need a promote that "
                        "displaced a serving slot)")
                self._active = prev
                self._version = prev.version
                self._previous = None
                self.events.append({
                    "event": "swap_revert", "note": note,
                    "from_version": cur.version, "version": prev.version})
                self.ledger.append({
                    "version": prev.version, "kind": "revert", "ok": True,
                    "revert": True, "from_version": cur.version,
                    "note": note})
            return prev
        finally:
            self._swap_busy.release()

    def swap_incremental(self, params, new_articles, *, max_rows=None,
                         max_age_versions=None, note="", emb=None):
        """Append `new_articles` (dense [n, F] or scipy CSR) to the ACTIVE
        slot with age-based eviction, health-gate the appended tail, and
        promote — the refresh-path swap. Returns the promoted CorpusSlot.

        Eviction, applied before the append: rows older than
        `max_age_versions` corpus versions are dropped (news articles expire),
        then oldest-first until the combined corpus fits `max_rows`. The
        standby is assembled from the active slot's DEQUANTIZED rows plus the
        freshly-encoded batch, re-quantized at the corpus dtype — so the gate
        judges exactly what scoring will see, same as a full rebuild.

        `emb` short-circuits the encode with precomputed unit-norm [n, D]
        f32 embeddings of `new_articles` — the churn supervisor already
        encoded the batch for its drift check and must not pay (or fault)
        the encode twice.

        On a mesh-sharded slot the append is the same two-phase protocol as
        a sharded full swap: the dequantize -> append -> evict -> requantize
        round trip assembles the staged state, `_stage_shards` captures the
        host mirror, and the re-placement goes back through the corpus's own
        sharder (the `mesh`/`device_put` it was built with) so the standby
        keeps the exact row-sharded topology — the commit then stamps every
        shard's version under the lock.

        `refresh.swap` is the fault site (the full rebuild keeps
        `serve.swap`); rollback semantics are identical to `swap`."""
        self._acquire_swap(note)
        try:
            self._reject_if_degraded("swap_incremental", note)
            t0 = time.monotonic()
            self._refreshing.set()
            try:
                with self._lock:
                    base = self._active
                    version = self._version
                if base is None:
                    raise SwapRejected(
                        "swap_incremental needs an active slot to append to "
                        "(seed the corpus with a full swap first)")
                with telemetry.span("serve/corpus_swap_incremental",
                                    fence=False, args={"note": note}):
                    standby, n_added, n_evicted = self._build_incremental(
                        params, new_articles, base, version, note,
                        max_rows=max_rows, max_age_versions=max_age_versions,
                        emb=emb)
                gate = self._health_gate(standby, tail=True)
                if not gate["ok"]:
                    raise SwapRejected(
                        f"incremental standby failed the health gate: {gate}")
                # keep the centroids: appended rows ROUTE to their nearest
                # existing cell; no re-clustering on the churn path
                self._attach_index(standby, refit=False, base=base, note=note)
                self._stage_shards(standby, note, base=base)
            except Exception as exc:
                return self._rollback("incremental", note, exc, t0)
            finally:
                self._refreshing.clear()
            return self._promote(standby, gate, "incremental", note, t0,
                                 n_added=n_added, n_evicted=n_evicted)
        finally:
            self._swap_busy.release()

    def _build_incremental(self, params, new_articles, base, version, note,
                           *, max_rows, max_age_versions, emb=None):
        _faults.fire("refresh.swap", note=note)
        n_new = int(new_articles.shape[0])
        if emb is not None:
            new_emb = np.asarray(jax.device_get(emb), np.float32)[:n_new]
            assert new_emb.shape[0] == n_new, (new_emb.shape, n_new)
        else:
            resident = build_resident(new_articles,
                                      device_put=self._device_put)
            blocks = block_indices(n_new, self.block)
            with self._dispatch_guard():
                new_emb = np.asarray(jax.device_get(
                    self._encode_corpus(params, resident, blocks)))[:n_new]

        # base is the ACTIVE slot — on a sharded corpus this dequantize is a
        # collective racing the serving threads' dispatches, so it serializes
        with self._dispatch_guard(base):
            old = np.asarray(jax.device_get(
                dequantize_rows(base.emb, base.scales, base.n)))
        ages = (base.ages[:base.n] if base.ages is not None
                else np.full(base.n, max(version, 1), np.int32))
        next_version = version + 1  # promotion will assert this exact bump
        keep = np.ones(base.n, bool)
        if max_age_versions is not None:
            keep &= (next_version - ages) <= int(max_age_versions)
        if max_rows is not None:
            budget = int(max_rows) - n_new
            if budget < 0:
                raise SwapRejected(
                    f"refresh batch ({n_new}) exceeds max_rows ({max_rows})")
            kept_idx = np.flatnonzero(keep)
            if kept_idx.size > budget:  # oldest first, then lowest row index
                order = np.lexsort((kept_idx, ages[kept_idx]))
                keep[kept_idx[order[:kept_idx.size - budget]]] = False
        n_evicted = int(base.n - keep.sum())

        combined = np.concatenate([old[keep], new_emb], axis=0)
        n = combined.shape[0]
        # a sharded base must stay sharded: pad so the standby divides the
        # mesh (inferred from the base slot when the corpus was built with a
        # bare device_put closure instead of mesh=)
        row_mult = self._row_mult
        if row_mult is None and _slot_is_sharded(base):
            row_mult = len(base.emb.sharding.device_set)
        n_pad = block_indices(n, self.block, row_multiple=row_mult).size
        emb_pad = np.zeros((n_pad, combined.shape[1]), np.float32)
        emb_pad[:n] = combined
        # staged age -2 marks the appended rows; _promote stamps them with
        # the version it actually assigns under the lock
        slot_ages = np.full(n_pad, -1, np.int32)
        slot_ages[: base.n - n_evicted] = ages[keep]
        slot_ages[base.n - n_evicted : n] = -2
        valid = np.zeros(n_pad, np.float32)
        valid[:n] = 1.0

        q_emb, scales = quantize_corpus(jnp.asarray(emb_pad),
                                        self.corpus_dtype)
        q_err = self._quant_score_error(emb_pad, q_emb, scales, n)
        put = self._device_put or jax.device_put
        q_emb = put(q_emb)
        scales = put(scales) if scales is not None else None
        return CorpusSlot(
            emb=q_emb, valid=put(valid), n=n, version=-1, note=note,
            built_s=time.monotonic(), scales=scales, dtype=self.corpus_dtype,
            ages=slot_ages,
            stats=(None if q_err is None else {"quant_error": q_err})
            ), n_new, n_evicted

    def _build(self, params, articles, note):
        _faults.fire("serve.swap", note=note)
        n = int(articles.shape[0])
        resident = build_resident(articles, device_put=self._device_put)
        blocks = block_indices(n, self.block, row_multiple=self._row_mult)
        with self._dispatch_guard():
            # the corpus sharder row-shards any resident leaf whose rows
            # divide the mesh, so this encode can be a multi-device program
            raw = self._encode_corpus(params, resident, blocks)
            emb, scales = quantize_corpus(raw, self.corpus_dtype)
            jax.block_until_ready(emb)
        q_err = self._quant_score_error(raw, emb, scales, n)
        n_pad = blocks.size
        valid = np.zeros(n_pad, np.float32)
        valid[:n] = 1.0
        put = self._device_put or jax.device_put
        if self._device_put is not None:
            # re-place through the caller's sharder (e.g. mesh.shard_rows):
            # the encode ran wherever jit put it, the slot lives where scoring
            # wants it
            emb = put(emb)
            scales = put(scales) if scales is not None else None
        return CorpusSlot(emb=emb, valid=put(valid), n=n, version=-1,
                          note=note, built_s=time.monotonic(),
                          scales=scales, dtype=self.corpus_dtype,
                          stats=(None if q_err is None
                                 else {"quant_error": q_err}))

    def _quant_score_error(self, raw, q_emb, scales, n):
        """Swap-time quantization SCORE error: max |pairwise cosine
        difference| between the fp32 embeddings just encoded and their
        stored (quantized, then dequantized) form, over a small row sample.
        Measured entirely on HOST copies — zero device programs, so the
        zero-post-warmup-compile soaks are unaffected (the incremental path
        already host-copies the whole corpus; this is the same discipline).
        float32 corpora skip it: no gauge appears and the quantization SLO
        (`quality-quant-error`) stays silent by absence. Published as gauge
        `int8_score_error` when the slot promotes."""
        if self.corpus_dtype == "float32":
            return None
        m = int(min(_QUANT_SAMPLE, int(n)))
        if m < 2:
            return None
        ref = np.asarray(jax.device_get(raw), np.float32)[:m]
        q = np.asarray(jax.device_get(q_emb)).astype(np.float32)[:m]
        if scales is not None:
            q = q * np.asarray(jax.device_get(scales),
                               np.float32)[:m, None]
        err = np.max(np.abs(ref @ ref.T - q @ q.T))
        return round(float(err), 8)

    def _dispatch_guard(self, *slots):
        """The process-wide collective-dispatch lock (parallel/mesh) when the
        device work about to run touches mesh-sharded arrays. The swap path
        runs on a churn/rollout thread CONCURRENTLY with serving threads
        dispatching against the active slot; a compiled program over sharded
        operands is a collective, and two collectives interleaving their
        per-device rendezvous deadlock (the r16 bug class). Single-device
        corpora return a free nullcontext."""
        sharded = self.mesh is not None or any(
            s is not None and _slot_is_sharded(s) for s in slots)
        return dispatch_lock(sharded)

    def _health_gate(self, slot, tail=False):
        """Finiteness + collapse score on a sample of the standby embeddings
        (DEQUANTIZED — the gate judges what scoring will actually see, so a
        broken quantization fails here, not in production ranking).
        One deliberate host sync — the swap path is off the request path.

        `tail=True` (incremental swaps) samples the NEWEST rows: the old rows
        already passed a gate when their version promoted; the appended tail
        is what could be poisoned. The sample's collapse score and centroid
        are stored on `slot.stats` as the drift reference the next refresh
        batch is compared against (telemetry/health.drift_health)."""
        rows = min(_GATE_SAMPLE, slot.n)
        with self._dispatch_guard(slot):
            if tail:
                sample = dequantize_rows(
                    slot.emb, slot.scales, slot.n)[slot.n - rows:]
            else:
                sample = dequantize_rows(slot.emb, slot.scales, rows)
            host = np.asarray(jax.device_get(sample), np.float32)
            finite = bool(np.all(np.isfinite(host)))
            stats = jax.device_get(embedding_health(sample))
        collapse = float(stats["health/embedding_collapse"])
        ok = finite and np.isfinite(collapse) and (
            collapse <= self.collapse_ceiling)
        norms = np.maximum(np.linalg.norm(host, axis=1, keepdims=True), 1e-12)
        # update, not replace: the build already stashed the swap-time
        # quantization score error under "quant_error" on non-fp32 corpora
        slot.stats.update({"collapse": collapse,
                           "centroid": np.mean(host / norms, axis=0),
                           "gate_rows": rows, "gate_tail": bool(tail)})
        return {"ok": ok, "finite": finite, "collapse": round(collapse, 6),
                "ceiling": self.collapse_ceiling, "rows": rows,
                "tail": bool(tail)}

    # ------------------------------------------------------- clustered index
    def _attach_index(self, slot, *, refit, note, base=None):
        """Build the slot's cell-major IVF index (retrieval="ivf" only).

        `refit=True` runs k-means from scratch, k-means++ seeded with the
        drift-gate centroid `_health_gate` just stored on the slot.
        `refit=False` keeps `base`'s centroids and only re-routes rows to
        their nearest cell — the O(N * n_cells) append path — and advances
        the imbalance staleness counter that eventually flips `reindex_due`.

        Padding rows (valid=0) are assigned like real rows so the IVF
        scorer sees the exact row population the flat scorer sees — the
        bitwise-parity contract at probes = n_cells depends on it.

        On a mesh-sharded slot the index is a shard-major
        `index.ShardedIVFCells`: cells partition by centroid across shards,
        the slab arrays go back through the corpus's own sharder so each
        shard's cells land on its device, and `_stage_shards` mirrors the
        slabs for shard recovery. Attaching runs in the PREPARE phase like
        every other staged array — a failed gate discards the index with
        the slot."""
        if self.retrieval != "ivf":
            return
        from ..index import (assign_cells, build_cells, build_sharded_cells,
                             cell_stats, kmeans_fit)

        n_cells = self.n_cells
        if n_cells is None:  # sqrt(N): the classic IVF scan-balance point
            n_cells = int(round(max(slot.n, 1) ** 0.5))
        n_cells = max(1, min(int(n_cells), max(slot.n, 1)))
        with self._dispatch_guard(slot):
            x = dequantize_rows(slot.emb, slot.scales, slot.emb.shape[0])
            if refit or base is None or base.ivf is None:
                refit = True
                km = kmeans_fit(x, slot.valid, n_cells, seed=self.index_seed,
                                n_iters=self.index_iters,
                                init_centroid=slot.stats.get("centroid"))
                centroids, assign = km.centroids, km.assign
            else:
                centroids = base.ivf.centroids
                assign = assign_cells(x, centroids)
            # capacity rounding multiple: a tuned TPU capture may recommend
            # a larger panel multiple (fewer, longer cell DMAs); defaults to
            # tile_defaults.IVF_CAP_MULTIPLE, and `cap_min` still pins the
            # layout shapes across swaps either way
            from .. import tuning

            cap_multiple = tuning.cap_multiple_hint()
            n_shards = self._row_mult
            if n_shards is None and _slot_is_sharded(slot):
                n_shards = len(slot.emb.sharding.device_set)
            if n_shards is not None and n_shards > 1:
                slot.ivf = build_sharded_cells(
                    slot.emb, slot.valid, slot.scales, centroids, assign,
                    n_shards=n_shards, cap_min=self.cell_cap,
                    cap_multiple=cap_multiple, device_put=self._device_put)
            else:
                slot.ivf = build_cells(slot.emb, slot.valid, slot.scales,
                                       centroids, assign,
                                       cap_min=self.cell_cap,
                                       cap_multiple=cap_multiple)
        st = cell_stats(slot.ivf)
        with self._lock:
            if refit:
                self._ivf_stale = 0
            elif st["imbalance"] > self.imbalance_max:
                self._ivf_stale += 1
            else:
                self._ivf_stale = 0
            self.events.append({
                "event": "ivf_index", "refit": bool(refit), "note": note,
                "n_cells": st["n_cells"], "cell_cap": st["cell_cap"],
                "imbalance": round(st["imbalance"], 4),
                "frac_empty": round(st["frac_empty"], 4),
                "stale_cycles": self._ivf_stale})
            stale = self._ivf_stale
        m = self.metrics
        if m is not None:
            # continuous index-quality gauges: every attach (full build,
            # append re-route, reindex) republishes, so the SLO monitor and
            # `report --quality` always see the index actually serving
            m.gauge("ivf_imbalance").set(st["imbalance"])
            m.gauge("ivf_frac_empty").set(st["frac_empty"])
            m.gauge("ivf_n_cells").set(st["n_cells"])
            m.gauge("ivf_stale_cycles").set(stale)
            occ = m.histogram("ivf_cell_occupancy",
                              bounds=(8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                                      512.0))
            for c in st["counts"]:
                occ.observe(float(c))

    def reindex(self, note=""):
        """Refit the IVF centroids on the ACTIVE slot's rows and promote the
        re-indexed slot through the standard gate -> promote -> ledger path
        (kind="reindex"). The embedding rows are SHARED with the active slot
        — only the clustering is rebuilt — so the gate re-judges the exact
        bytes already serving. Resets the staleness counter.

        This is the background rebuild the churn supervisor schedules when
        `reindex_due` flips: append-routing keeps serving fresh rows cheaply
        while occupancy slowly skews, and this call re-balances the cells
        without re-encoding or re-quantizing anything."""
        if self.retrieval != "ivf":
            raise SwapRejected("reindex() requires retrieval='ivf'")
        self._acquire_swap(note)
        try:
            self._reject_if_degraded("reindex", note)
            t0 = time.monotonic()
            self._refreshing.set()
            try:
                with self._lock:
                    base = self._active
                if base is None:
                    raise SwapRejected(
                        "reindex needs an active slot (swap first)")
                standby = CorpusSlot(
                    emb=base.emb, valid=base.valid, n=base.n, version=-1,
                    note=note, built_s=time.monotonic(), scales=base.scales,
                    dtype=base.dtype,
                    ages=None if base.ages is None else base.ages.copy())
                with telemetry.span("serve/corpus_reindex", fence=False,
                                    args={"note": note}):
                    gate = self._health_gate(standby)
                    if not gate["ok"]:
                        raise SwapRejected(
                            f"reindex standby failed the health gate: {gate}")
                    self._attach_index(standby, refit=True, note=note)
                    self._stage_shards(standby, note, base=base)
            except Exception as exc:
                return self._rollback("reindex", note, exc, t0)
            finally:
                self._refreshing.clear()
            return self._promote(standby, gate, "reindex", note, t0,
                                 n_added=0, n_evicted=0)
        finally:
            self._swap_busy.release()

    # -------------------------------------------------- shard fault tolerance
    def _clone_slot(self, slot, **overrides):
        """A new CorpusSlot sharing every field of `slot` except
        `overrides` — the degraded/recovered views replace one or two
        arrays and keep everything else (version, ages, stats, mirror)
        byte-identical."""
        kw = dict(emb=slot.emb, valid=slot.valid, n=slot.n,
                  version=slot.version, note=slot.note, built_s=slot.built_s,
                  scales=slot.scales, dtype=slot.dtype, ages=slot.ages,
                  stats=slot.stats, ivf=slot.ivf,
                  shard_versions=slot.shard_versions, mirror=slot.mirror,
                  lost=slot.lost, coverage=slot.coverage)
        kw.update(overrides)
        return CorpusSlot(**kw)

    def inject_shard_loss(self, shard_id, note=""):
        """CHAOS HOOK — the executor for the `serve.shard` harness fault
        directive (reliability/faults.HARNESS_SITES). Replaces one shard's
        device buffers with NaN poison in place: same version, same shard
        stamps, no event ordering with swaps — the loss is SILENT until a
        dispatch comes back nonfinite or `audit_shards()` sweeps, exactly
        like a real device dropping its HBM. float32/bfloat16 corpora poison
        the embedding shard; int8 corpora poison the f32 scales shard (int8
        has no NaN, and the scorer multiplies scales back in, so every score
        against the shard goes NaN either way). A sharded IVF index loses
        the same device's slabs with it — the cells the shard owns — so the
        clustered scorer sees the loss exactly like the flat one. Returns
        the poisoned shard id."""
        from ..parallel.mesh import rebuild_shards, shard_spans

        with self._lock:
            slot = self._active
        if slot is None or not _slot_is_sharded(slot):
            raise SwapRejected(
                "inject_shard_loss needs a mesh-sharded active slot")
        spans = shard_spans(slot.emb)
        i = int(shard_id) % len(spans)
        lo, hi, _ = spans[i]
        if slot.scales is not None:
            poison = np.full(hi - lo, np.nan, np.float32)
            emb, scales = slot.emb, rebuild_shards(slot.scales, {i: poison})
        else:
            poison = np.full((hi - lo, int(slot.emb.shape[1])), np.nan,
                             np.float32)
            emb, scales = rebuild_shards(slot.emb, {i: poison}), slot.scales
        ivf = slot.ivf
        if ivf is not None and hasattr(ivf, "n_shards"):
            rows = int(ivf.shard_rows)  # the device's slab rows die with it
            if slot.scales is not None:
                ivf = ivf.replace(cell_scales=rebuild_shards(
                    ivf.cell_scales, {i: np.full(rows, np.nan, np.float32)}))
            else:
                ivf = ivf.replace(cell_emb=rebuild_shards(
                    ivf.cell_emb,
                    {i: np.full((rows, int(ivf.cell_emb.shape[1])), np.nan,
                                np.float32)}))
        poisoned = self._clone_slot(slot, emb=emb, scales=scales, ivf=ivf)
        with self._lock:
            self._active = poisoned
            self.events.append({"event": "shard_lost", "shard": i,
                                "note": note, "version": slot.version})
        inj = _faults.active_injector()
        if inj is not None:
            inj.note("serve.shard", "fatal", shard=i, note=note)
        return i

    def audit_shards(self):
        """Per-shard finiteness sweep of the ACTIVE slot — the shard-level
        arm of the health gate, invoked by the service when a dispatch comes
        back nonfinite (and by the chaos harness directly). Host-copies one
        shard's resident buffers at a time via pure D2H transfers
        (parallel.mesh.shard_host_copies): no compiled program, so the
        serving compile guard stays clean. Off the steady-state request
        path — it runs only on suspected loss."""
        with self._lock:
            slot = self._active
        if slot is None or not _slot_is_sharded(slot):
            return {"sharded": False, "ok": True, "lost": [], "n_shards": 1}
        from ..parallel.mesh import shard_host_copies

        emb_shards = shard_host_copies(slot.emb)
        scale_shards = (shard_host_copies(slot.scales)
                        if slot.scales is not None
                        else [None] * len(emb_shards))
        ivf = slot.ivf
        sharded_ivf = ivf is not None and hasattr(ivf, "n_shards")
        cell_emb_shards = (shard_host_copies(ivf.cell_emb) if sharded_ivf
                           else [None] * len(emb_shards))
        cell_scale_shards = (shard_host_copies(ivf.cell_scales)
                             if sharded_ivf else [None] * len(emb_shards))
        lost = []
        for i, (e, s, ce, cs) in enumerate(zip(emb_shards, scale_shards,
                                               cell_emb_shards,
                                               cell_scale_shards)):
            ok = bool(np.all(np.isfinite(np.asarray(e, np.float32))))
            if ok and s is not None:
                ok = bool(np.all(np.isfinite(s)))
            if ok and ce is not None:  # the device's index slabs die with it
                ok = bool(np.all(np.isfinite(np.asarray(ce, np.float32)))
                          and np.all(np.isfinite(cs)))
            if not ok:
                lost.append(i)
        return {"sharded": True, "ok": not lost, "lost": lost,
                "n_shards": len(emb_shards)}

    def quarantine_lost_shards(self, note=""):
        """Detect lost shards and degrade to PARTIAL-CORPUS serving: the
        lost shards' rows are masked invalid (the scorer's `where` mask
        turns their NaN scores into -inf, so surviving shards keep
        answering), the slot's `coverage` drops below 1.0 (the service
        stamps it on every `partial_corpus` reply), and every swap flavor
        is blocked until `recover_shards()` heals the mesh. Version is
        UNCHANGED — degradation is a serving-state change, not a new
        corpus — recorded in both `events` and the version ledger
        (kind="shard_degraded", ok=False). Returns the sorted lost ids
        (empty when the audit finds nothing, a no-op)."""
        audit = self.audit_shards()
        lost = list(audit["lost"])
        if not lost:
            return []
        from ..parallel.mesh import shard_spans

        with self._lock:
            slot = self._active
        spans = shard_spans(slot.emb)
        mirror = slot.mirror
        assert mirror is not None, (
            "sharded promotes always stage a host mirror (_stage_shards)")
        valid_host = np.asarray(mirror["valid"], np.float32).copy()
        for i in lost:
            valid_host[spans[i][0]:spans[i][1]] = 0.0
        total = float(np.asarray(mirror["valid"], np.float32).sum())
        coverage = float(valid_host.sum()) / max(total, 1.0)
        put = self._device_put or jax.device_put
        ivf = slot.ivf
        if ivf is not None and hasattr(ivf, "n_shards"):
            # a lost shard takes its owned CELLS with it: zero those slabs'
            # valid lanes so the clustered scorer's -inf mask keeps the
            # surviving cells answering, and report coverage as the row
            # fraction the index can still reach (each valid row lives in
            # exactly one cell, so this is the honest serving fraction)
            cv_host = np.asarray(mirror["ivf"]["cell_valid"],
                                 np.float32).copy()
            rows = int(ivf.shard_rows)
            for i in lost:
                cv_host[i * rows:(i + 1) * rows] = 0.0
            cv_total = float(
                np.asarray(mirror["ivf"]["cell_valid"], np.float32).sum())
            coverage = float(cv_host.sum()) / max(cv_total, 1.0)
            ivf = ivf.replace(cell_valid=put(jnp.asarray(cv_host)))
        degraded = self._clone_slot(slot, valid=put(jnp.asarray(valid_host)),
                                    ivf=ivf, lost=frozenset(lost),
                                    coverage=coverage)
        with self._lock:
            self._active = degraded
            self._lost = set(lost)
            self.events.append({
                "event": "shard_degraded", "lost": sorted(lost),
                "coverage": round(coverage, 4), "note": note,
                "version": slot.version})
            self.ledger.append({
                "version": slot.version, "kind": "shard_degraded",
                "ok": False,
                "error": (f"shard loss: {sorted(lost)} quarantined "
                          f"(coverage {coverage:.3f})"),
                "active_version": slot.version,
                "coverage": round(coverage, 4), "note": note})
        m = self.metrics
        if m is not None:
            m.counter("shard_quarantines").inc()
            m.gauge("corpus_coverage").set(coverage)
        return sorted(lost)

    def recover_shards(self, note=""):
        """Re-materialize every quarantined shard from the host mirror and
        return to full-coverage serving — BITWISE: the lost shards' buffers
        are rebuilt from the mirror's exact quantized bytes, the surviving
        shards keep their live device buffers untouched
        (parallel.mesh.rebuild_shards), and the valid mask comes back from
        the mirror, so the healed slot equals the pre-loss slot
        byte-for-byte (the chaos-shard soak asserts it). Version unchanged;
        the ledger records kind="recover" with `recover: True` — the audit
        accepts it only at an already-verified version. Serializes with
        swaps through the same non-blocking guard."""
        self._acquire_swap(note)
        try:
            with self._lock:
                slot = self._active
                lost = sorted(self._lost)
            if slot is None or not _slot_is_sharded(slot):
                raise SwapRejected(
                    "recover_shards needs a mesh-sharded active slot")
            if not lost:
                raise SwapRejected("no lost shards to recover")
            from ..parallel.mesh import rebuild_shards, shard_spans

            mirror = slot.mirror
            spans = shard_spans(slot.emb)
            emb = rebuild_shards(slot.emb, {
                i: mirror["emb"][spans[i][0]:spans[i][1]] for i in lost})
            scales = slot.scales
            if scales is not None:
                scales = rebuild_shards(slot.scales, {
                    i: mirror["scales"][spans[i][0]:spans[i][1]]
                    for i in lost})
            put = self._device_put or jax.device_put
            valid = put(jnp.asarray(np.asarray(mirror["valid"], np.float32)))
            ivf = slot.ivf
            if ivf is not None and hasattr(ivf, "n_shards"):
                # the index heals the same way the slot does: lost slabs
                # re-materialize from the mirror's exact bytes, surviving
                # shards keep their live buffers — bitwise (the chaos-shard
                # soak fingerprints the slabs to prove it)
                m = mirror["ivf"]
                rows = int(ivf.shard_rows)
                lost_slabs = lambda a: {i: a[i * rows:(i + 1) * rows]
                                        for i in lost}
                cell_emb = rebuild_shards(ivf.cell_emb,
                                          lost_slabs(m["cell_emb"]))
                cell_scales = rebuild_shards(ivf.cell_scales,
                                             lost_slabs(m["cell_scales"]))
                ivf = ivf.replace(
                    cell_emb=cell_emb, cell_scales=cell_scales,
                    cell_valid=put(jnp.asarray(
                        np.asarray(m["cell_valid"], np.float32))))
            healed = self._clone_slot(slot, emb=emb, scales=scales,
                                      valid=valid, ivf=ivf, lost=frozenset(),
                                      coverage=1.0)
            with self._lock:
                self._active = healed
                self._lost = set()
                self.events.append({
                    "event": "shard_recovered", "shards": lost,
                    "note": note, "version": slot.version})
                self.ledger.append({
                    "version": slot.version, "kind": "recover", "ok": True,
                    "recover": True, "recovered": lost,
                    "shards": {
                        "n": len(spans),
                        "versions": [int(v) for v in slot.shard_versions]},
                    "note": note})
            reg = self.metrics
            if reg is not None:
                reg.counter("shard_recoveries").inc()
                reg.gauge("corpus_coverage").set(1.0)
            return healed
        finally:
            self._swap_busy.release()


def default_corpus(config, **kw):
    """The default serving corpus for this host: mesh-sharded clustered
    retrieval (`mesh=get_mesh(), retrieval="ivf"`) when more than one device
    is visible, single-device exact otherwise. This is the configuration
    `RecommendationService` and `fleet.ServiceReplica` reach for when the
    caller does not choose — the r16 default flip: on multi-device hosts the
    corpus rows AND the cell index shard across the mesh, so memory per
    device shrinks with the mesh instead of every host holding a full copy.
    Any explicit keyword wins over the derived defaults."""
    if len(jax.devices()) > 1 and "mesh" not in kw and "device_put" not in kw:
        from ..parallel.mesh import get_mesh

        kw["mesh"] = get_mesh()
        kw.setdefault("retrieval", "ivf")
    return ServingCorpus(config, **kw)
