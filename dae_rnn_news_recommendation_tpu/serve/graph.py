"""The serving dataflow: jitted encode -> score -> top-k over a resident corpus.

Two compiled programs, both shaped for the high-latency dispatch link the
training side already engineered around (bench.py:_hard_sync measures
~23-70 ms per host->device round trip over the axon tunnel):

  * `make_corpus_encode_fn` — embeds the WHOLE corpus in one dispatch: a
    `lax.scan` over fixed-size index blocks gathers rows from the HBM-resident
    arrays with the same `jnp.take` gather `train/resident.py` uses for
    one-dispatch epochs, densifies sparse rows on device, encodes, and
    L2-normalizes. The [N_pad, D] embedding matrix never leaves the device —
    it IS the serving corpus (serve/corpus.py double-buffers two of them).

  * `make_serve_fn` — answers one microbatch in one dispatch: encode the
    [B, F] query batch, normalize, score every corpus row by cosine (one
    [B, D] x [D, N] matmul on the MXU), mask padded corpus rows to -inf, and
    `lax.top_k`. `k` is baked into the compiled program (it shapes the
    output), so the service precompiles one variant per (bucket, k) pair —
    the degraded top-k-truncation mode is just a dispatch to the smaller-k
    variant, not a recompile under overload.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .. import telemetry
from ..models import dae_core

# corpus index blocks per scan step: big enough to amortize the gather,
# small enough that (block x F) dense stays far below the step's working set
DEFAULT_BLOCK = 512


def _gather_rows(resident, idx, config):
    """Dense [len(idx), F] rows from a `train.resident.build_resident` dict —
    the resident gather, reused verbatim: `jnp.take` on the resident arrays,
    sparse rows densified on device (ops/sparse_ingest layout)."""
    if "x" in resident:
        return jnp.take(resident["x"], idx, axis=0)
    from ..ops.sparse_ingest import densify_on_device

    ind = jnp.take(resident["indices"], idx, axis=0)
    val = jnp.take(resident["values"], idx, axis=0)
    return densify_on_device(ind, val, config.n_features)


def _normalize(h):
    return h / (jnp.linalg.norm(h, axis=-1, keepdims=True) + 1e-9)


def block_indices(n_rows, block=DEFAULT_BLOCK):
    """[S, block] int32 index blocks covering 0..n_rows-1, tail padded by
    repeating index 0 (the pad rows are masked out of scoring via the valid
    vector, so the duplicate gather is inert)."""
    n_pad = int(-(-max(int(n_rows), 1) // block) * block)
    idx = np.zeros(n_pad, np.int32)
    idx[:n_rows] = np.arange(n_rows, dtype=np.int32)
    return idx.reshape(-1, block)


def make_corpus_encode_fn(config):
    """Jitted whole-corpus embed: (params, resident, idx_blocks [S, block])
    -> unit-norm embeddings [S*block, D], one dispatch for the whole build."""

    def run(params, resident, idx_blocks):
        def body(carry, idx):
            x = _gather_rows(resident, idx, config)
            return carry, _normalize(dae_core.encode(params, x, config))

        _, emb = jax.lax.scan(body, None, idx_blocks)
        return emb.reshape(-1, emb.shape[-1])

    return telemetry.instrument(jax.jit(run), "serve/corpus_encode")


def make_serve_fn(config, k):
    """Jitted microbatch answer: (params, emb [N_pad, D], valid [N_pad],
    queries [B, F]) -> (scores [B, k], indices [B, k]), cosine-ranked."""
    k = int(k)
    assert k >= 1

    def run(params, emb, valid, queries):
        h = _normalize(dae_core.encode(params, queries, config))
        scores = h @ emb.T
        scores = jnp.where(valid[None, :] > 0, scores, -jnp.inf)
        return jax.lax.top_k(scores, k)

    return telemetry.instrument(jax.jit(run), f"serve/topk{k}")
