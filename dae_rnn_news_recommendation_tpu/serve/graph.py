"""The serving dataflow: jitted encode -> fused score/top-k over a resident corpus.

Three compiled programs, all shaped for the high-latency dispatch link the
training side already engineered around (bench.py:_hard_sync measures
~23-70 ms per host->device round trip over the axon tunnel):

  * `make_corpus_encode_fn` — embeds the WHOLE corpus in one dispatch: a
    `lax.scan` over fixed-size index blocks gathers rows from the HBM-resident
    arrays with the same `jnp.take` gather `train/resident.py` uses for
    one-dispatch epochs, densifies sparse rows on device, encodes, and
    L2-normalizes. The [N_pad, D] embedding matrix never leaves the device —
    it IS the serving corpus (serve/corpus.py double-buffers two of them,
    optionally quantized to bf16 or int8).

  * `make_serve_fn` — answers one microbatch in one dispatch: encode the
    [B, F] query batch, normalize, and rank every corpus row by cosine. The
    default (`fused=True`) routes through `ops.topk_fused`: on TPU the corpus
    streams through VMEM in panels and the [B, N] score matrix never touches
    HBM; off-TPU it lowers to the same masked-matmul + `lax.top_k` the r07
    graph ran, bitwise. `fused=False` keeps the r07 materializing path
    compiled and dispatchable — it is the bench baseline the fused kernel is
    gated against, not a deprecated alias. `k` is baked into the compiled
    program, so the service precompiles one variant per (bucket, k) pair —
    the degraded top-k-truncation mode is just a dispatch to the smaller-k
    variant, not a recompile under overload.

  * `make_ivf_serve_fn` — the clustered two-stage variant: encode the query
    batch, then `ops.ivf_topk` probes the corpus's cell-major IVF index
    (`slot.ivf`) instead of scanning every row — centroid scan, top-`probes`
    cell shortlist, fused gather + exact rescore. `k` AND `probes` are baked
    into the compiled program, so the service precompiles one variant per
    (bucket, k, probes) and probing depth never recompiles at request time.

  * `make_sharded_serve_fn` — the same fused scorer over a row-sharded corpus:
    each device holds N/n_dev rows (place them with `parallel.mesh.shard_rows`,
    e.g. via `ServingCorpus(device_put=...)`), computes its local top-k with
    the fused kernel, offsets local indices to global, and one k-way
    `lax.top_k` over the gathered [B, n_dev*k] candidates merges the shards.
    Device order equals global row order, so the merge's positional tie-break
    reproduces single-device index ordering exactly.

  * `make_sharded_ivf_serve_fn` — sharded AND clustered, the default serving
    configuration on multi-device hosts: replicated centroid scan, per-shard
    scalar-prefetch gather over locally-owned probed cells, and the same
    index-exact k-way merge as the sharded exact path.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import _shard_map

from .. import telemetry
from ..models import dae_core
from ..ops.normalize import l2_normalize

# corpus index blocks per scan step: big enough to amortize the gather,
# small enough that (block x F) dense stays far below the step's working set
DEFAULT_BLOCK = 512


def _gather_rows(resident, idx, config):
    """Dense [len(idx), F] rows from a `train.resident.build_resident` dict —
    the resident gather, reused verbatim: `jnp.take` on the resident arrays,
    sparse rows densified on device (ops/sparse_ingest layout)."""
    if "x" in resident:
        return jnp.take(resident["x"], idx, axis=0)
    from ..ops.sparse_ingest import densify_on_device

    ind = jnp.take(resident["indices"], idx, axis=0)
    val = jnp.take(resident["values"], idx, axis=0)
    return densify_on_device(ind, val, config.n_features)


def block_indices(n_rows, block=DEFAULT_BLOCK, row_multiple=None):
    """[S, block] int32 index blocks covering 0..n_rows-1, tail padded by
    repeating index 0 (the pad rows are masked out of scoring via the valid
    vector, so the duplicate gather is inert).

    `row_multiple` additionally rounds the padded total S*block up until it
    divides evenly — the sharded-corpus constraint: `parallel.mesh.shard_rows`
    needs N_pad divisible by the mesh size, which a block multiple alone does
    not guarantee for n_dev > block."""
    n_pad = int(-(-max(int(n_rows), 1) // block) * block)
    if row_multiple is not None:
        m = int(row_multiple)
        assert m >= 1
        lcm = block * m // np.gcd(block, m)
        n_pad = int(-(-n_pad // lcm) * lcm)
    idx = np.zeros(n_pad, np.int32)
    idx[:n_rows] = np.arange(n_rows, dtype=np.int32)
    return idx.reshape(-1, block)


def make_corpus_encode_fn(config):
    """Jitted whole-corpus embed: (params, resident, idx_blocks [S, block])
    -> unit-norm embeddings [S*block, D], one dispatch for the whole build."""

    def run(params, resident, idx_blocks):
        def body(carry, idx):
            with jax.named_scope("corpus/gather"):
                x = _gather_rows(resident, idx, config)
            with jax.named_scope("corpus/encode"):
                return carry, l2_normalize(dae_core.encode(params, x, config))

        _, emb = jax.lax.scan(body, None, idx_blocks)
        return emb.reshape(-1, emb.shape[-1])

    return telemetry.instrument(jax.jit(run), "serve/corpus_encode")


def make_serve_fn(config, k, *, fused=True):
    """Jitted microbatch answer: (params, emb [N_pad, D], valid [N_pad],
    scales [N_pad]|None, queries [B, F]) -> (scores [B, k], indices [B, k]),
    cosine-ranked. `scales` carries the int8 corpus's per-row dequant factors
    (None for float32/bfloat16 corpora)."""
    k = int(k)
    assert k >= 1

    def run(params, emb, valid, scales, queries):
        with jax.named_scope("serve/query_encode"):
            h = l2_normalize(dae_core.encode(params, queries, config))
        if fused:
            # trace-time import: pallas loads only when a fused graph is built
            # (same lazy discipline as ops/__init__'s _PALLAS_EXPORTS)
            from ..ops.topk_fused import topk_fused

            return topk_fused(h, emb, valid, k, scales=scales)
        # the r07 materializing path, kept compiled as the bench baseline:
        # [B, N] scores in HBM, then a full-width top_k over them
        with jax.named_scope("serve/score_materialized"):
            scores = h @ emb.astype(jnp.float32).T
            if scales is not None:
                scores = scores * scales[None, :]
            scores = jnp.where(valid[None, :] > 0, scores, -jnp.inf)
        with jax.named_scope("serve/topk_full"):
            return jax.lax.top_k(scores, k)

    name = f"serve/topk{k}" + ("" if fused else "_unfused")
    return telemetry.instrument(jax.jit(run), name)


def make_ivf_serve_fn(config, k, probes):
    """Jitted clustered microbatch answer: (params, emb [N_pad, D], valid,
    scales, cells, queries [B, F]) -> (scores [B, k], indices [B, k]).

    Same contract as `make_serve_fn` with one extra operand: `cells`, the
    slot's `index.IVFCells` layout (a pytree — it traces like any array
    argument, so a swapped slot with the same cell shapes dispatches the
    already-compiled program). Scoring routes through `ops.ivf_topk`:
    per-query cost is `n_cells` centroids plus `probes` cells' rows instead
    of the whole corpus; `probes = n_cells` reproduces the exact scorer
    bitwise. Indices are ORIGINAL slot row numbers, directly comparable
    with `make_serve_fn` output."""
    k = int(k)
    probes = int(probes)
    assert k >= 1 and probes >= 1

    def run(params, emb, valid, scales, cells, queries):
        with jax.named_scope("serve/query_encode"):
            h = l2_normalize(dae_core.encode(params, queries, config))
        # trace-time import: pallas loads only when a fused graph is built
        from ..ops.ivf_topk import ivf_topk

        return ivf_topk(h, emb, valid, k, cells=cells, probes=probes,
                        scales=scales)

    return telemetry.instrument(jax.jit(run), f"serve/ivf_topk{k}_p{probes}")


def make_sharded_serve_fn(config, k, mesh, axis_name="data"):
    """`make_serve_fn`, but the corpus is row-sharded over `mesh`.

    Expects emb/valid/scales placed with `parallel.mesh.shard_rows` (N_pad
    divisible by the mesh size, shard rows >= k). Each device runs the fused
    kernel over its local rows, local indices are offset by
    `axis_index * shard_rows` to global, and the [B, n_dev*k] gathered
    candidates collapse through one final `lax.top_k` whose positional
    tie-break — device-major, slot-minor — IS ascending global index order,
    so scores and indices match the single-device graph (scores to fp32
    merge roundoff, indices exactly)."""
    k = int(k)
    assert k >= 1

    def run(params, emb, valid, scales, queries):
        with jax.named_scope("serve/query_encode"):
            h = l2_normalize(dae_core.encode(params, queries, config))
        # trace-time import: pallas loads only when a fused graph is built
        from ..ops.topk_fused import topk_sharded

        return topk_sharded(h, emb, valid, k, mesh=mesh,
                            axis_name=axis_name, scales=scales)

    return telemetry.instrument(jax.jit(run), f"serve/topk{k}_sharded")


def make_sharded_ivf_serve_fn(config, k, probes, mesh, axis_name="data"):
    """The clustered scorer over a mesh-sharded corpus: `make_ivf_serve_fn`'s
    contract (operands end `..., cells, queries`) with `cells` a
    `index.ShardedIVFCells` whose slab arrays are row-sharded over `mesh`.

    The centroid scan runs replicated; the scalar-prefetch shortlist gather
    runs per shard over only locally-owned probed cells; per-shard local
    top-k merges with the same axis-offset index-exact k-way merge as
    `make_sharded_serve_fn` (`ops.ivf_topk.sharded_ivf_topk`). Indices are
    ORIGINAL slot row numbers — index-exact vs the unsharded IVF graph at
    matched probes, and vs the exact scorer at `probes = n_cells`."""
    k = int(k)
    probes = int(probes)
    assert k >= 1 and probes >= 1

    def run(params, emb, valid, scales, cells, queries):
        with jax.named_scope("serve/query_encode"):
            h = l2_normalize(dae_core.encode(params, queries, config))
        # trace-time import: pallas loads only when a fused graph is built
        from ..ops.ivf_topk import sharded_ivf_topk

        return sharded_ivf_topk(h, emb, valid, k, cells=cells, probes=probes,
                                mesh=mesh, axis_name=axis_name, scales=scales)

    return telemetry.instrument(
        jax.jit(run), f"serve/ivf_topk{k}_p{probes}_sharded")
