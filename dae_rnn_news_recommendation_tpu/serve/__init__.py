"""Resilient serving: the inference path of the news recommender.

The training side of this repo got the chaos treatment in `reliability/`;
this package gives the SERVING side the same discipline — every request gets
a reply-or-shed decision before its deadline, degraded modes are explicit
and recorded, and the corpus refresh is a health-gated hot swap that rolls
back rather than serving a bad build. Full story in docs/serving.md.

    corpus = ServingCorpus(config)
    corpus.swap(params, articles)          # build + gate + promote
    svc = RecommendationService(params, config, corpus, top_k=10)
    svc.warmup()
    fut = svc.submit(user_vector, deadline_s=0.05)
    reply = fut.result(timeout=0.05)       # .status: ok | shed | error
    svc.stop()
"""

from .chaos_quality import (QUALITY_FAMILIES, QualityPlanResult,
                            chaos_quality_soak, run_quality_plan,
                            run_quality_reference)
from .chaos_serve import (ServePlanResult, ShardPlanResult, chaos_serve_soak,
                          chaos_shard_soak, overload_trace, run_serve_plan,
                          run_shard_plan, serve_fault_plan, shard_fault_plan)
from .corpus import (CORPUS_DTYPES, CorpusSlot, ServingCorpus,
                     ShardedUnsupported, SwapInProgress, SwapRejected,
                     default_corpus, dequantize_rows, quantize_corpus)
from .graph import (block_indices, make_corpus_encode_fn, make_ivf_serve_fn,
                    make_serve_fn, make_sharded_ivf_serve_fn,
                    make_sharded_serve_fn)
from .service import RecommendationService, Reply, ReplyFuture

__all__ = [
    "CORPUS_DTYPES",
    "CorpusSlot",
    "QUALITY_FAMILIES",
    "QualityPlanResult",
    "RecommendationService",
    "Reply",
    "ReplyFuture",
    "ServePlanResult",
    "ServingCorpus",
    "ShardPlanResult",
    "ShardedUnsupported",
    "SwapInProgress",
    "SwapRejected",
    "block_indices",
    "chaos_quality_soak",
    "chaos_serve_soak",
    "chaos_shard_soak",
    "default_corpus",
    "dequantize_rows",
    "make_corpus_encode_fn",
    "make_ivf_serve_fn",
    "make_serve_fn",
    "make_sharded_ivf_serve_fn",
    "make_sharded_serve_fn",
    "overload_trace",
    "quantize_corpus",
    "run_quality_plan",
    "run_quality_reference",
    "run_serve_plan",
    "run_shard_plan",
    "serve_fault_plan",
    "shard_fault_plan",
]
