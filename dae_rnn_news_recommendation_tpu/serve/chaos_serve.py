"""Chaos-serve soak: seeded fault plans x overload traces against the service.

`reliability/chaos.py` proves the TRAINING loop recovers from injected
faults; this module proves the SERVING loop keeps its reply-or-shed promise
under the same discipline. Each seeded plan pairs:

  * a FaultPlan over the serve fire-points (serve.enqueue / serve.batch /
    serve.swap, kinds transient|fatal|preempt) — the round-robin family pick
    guarantees any 6 consecutive seeds cover every serve fault family; and
  * an overload trace — a seeded arrival schedule of request bursts with
    mixed deadlines: generous ones that must be answered, hopeless ones
    (microseconds) that must be shed, plus bursts sized past the admission
    queue so queue_full shedding and the degraded modes actually engage.

Mid-plan, the harness attempts a hot corpus swap. Under an injected
`serve.swap` fault the swap must ROLL BACK: version unchanged, rollback
recorded in `corpus.events`, and a probe request answered by the OLD corpus
afterwards.

A plan passes when:
  * exactly-one-outcome: submitted == replied + shed + errors, and every
    future resolved within the harness deadline (zero deadlocks, zero silent
    drops);
  * fault honesty: a plan that injected faults shows them in the injector
    log, and transient batch faults show absorbed retries;
  * swap honesty: a swap-faulted plan rolled back and kept serving; an
    unfaulted plan promoted to a new version;
  * bounded latency: p95 of answered requests stays under the generous
    deadline even when the plan ran degraded.
"""

import dataclasses
import time

import numpy as np

from ..analysis.runtime import compile_guard
from ..models.dae_core import DAEConfig, init_params
from ..reliability import faults as _faults
from ..reliability.faults import FaultInjector, FaultPlan, FaultSpec
from ..reliability.ledger import audit_outcome_counts
from ..reliability.retry import RetryPolicy
from .corpus import ServingCorpus
from .service import RecommendationService

# CPU-sized service shapes: small enough for tier-1, busy enough to overload
_N_ARTICLES = 96
_N_FEATURES = 24
_N_COMPONENTS = 8

# generous deadline every answered request must honor (CPU dispatch is ~ms;
# the budget absorbs scheduler jitter on a loaded test box)
_SLA_S = 5.0
_HOPELESS_S = 1e-6   # provably unmeetable once the floor is warm
_HARNESS_DEADLINE_S = 60.0


@dataclasses.dataclass
class ServePlanResult:
    seed: int
    ok: bool
    detail: str
    n_submitted: int
    n_replied: int
    n_shed: int
    n_errors: int
    n_unresolved: int
    p95_ms: float
    degraded: bool
    swap_faulted: bool
    swap_rolled_back: bool
    served_after_swap: bool
    n_post_warm_compiles: int
    injected: list
    retries: list
    duration_s: float

    def to_dict(self):
        return dataclasses.asdict(self)


def serve_fault_plan(seed, n_requests):
    """Seeded plan over the serve fire-points. Six families, round-robin on
    the seed (mirrors FaultPlan.generate's discipline for the train sites)."""
    rng = np.random.default_rng(seed)
    # batch faults always land on the FIRST dispatch: the trace guarantees an
    # answerable first burst, so the fault provably fires there — and never
    # on the end-of-plan probe
    batch_at = 1
    families = (
        lambda: (FaultSpec("serve.batch", batch_at, "transient",
                           note="flaky device dispatch"),),
        lambda: (FaultSpec("serve.batch", batch_at, "fatal",
                           note="device fault mid-batch"),),
        lambda: (FaultSpec("serve.enqueue",
                           int(rng.integers(1, max(2, n_requests))),
                           "transient", note="admission blip"),),
        lambda: (FaultSpec("serve.enqueue",
                           int(rng.integers(1, max(2, n_requests))), "fatal",
                           note="admission failure"),),
        lambda: (FaultSpec("serve.swap", 1, "fatal",
                           note="standby build dies -> rollback"),),
        lambda: (FaultSpec("serve.batch", batch_at, "preempt",
                           note="serving task preempted mid-batch"),),
    )
    specs = list(families[seed % len(families)]())
    for _ in range(int(rng.integers(0, 3))):
        specs.append(FaultSpec(
            "serve.batch" if rng.random() < 0.5 else "serve.enqueue",
            int(rng.integers(1, max(2, n_requests))), "transient",
            note="extra transient"))
    return FaultPlan(seed=int(seed), specs=tuple(specs))


def overload_trace(seed, n_requests):
    """Seeded arrival schedule: [(n_burst, deadline_s, gap_s)]. Front-loaded
    bursts overflow the admission queue; a sprinkle of hopeless deadlines
    exercises unmeetable-shedding; the rest must be answered within SLA."""
    rng = np.random.default_rng(1000 + seed)
    trace = []
    left = n_requests
    while left > 0:
        burst = int(min(left, rng.integers(1, 25)))
        # the first burst is always answerable: batch-site faults are planned
        # at the first dispatch and must land on real requests, not the probe
        hopeless = bool(trace) and rng.random() < 0.25
        trace.append((burst, _HOPELESS_S if hopeless else _SLA_S,
                      float(rng.random() * 0.002)))
        left -= burst
    return trace


def _make_service(seed, collapse_ceiling=0.98):
    config = DAEConfig(n_features=_N_FEATURES, n_components=_N_COMPONENTS,
                       enc_act_func="tanh", triplet_strategy="none",
                       corr_type="masking", corr_frac=0.0)
    import jax

    params = init_params(jax.random.PRNGKey(7 + seed), config)
    rng = np.random.default_rng(2000 + seed)
    articles = rng.random((_N_ARTICLES, _N_FEATURES), dtype=np.float32)
    corpus = ServingCorpus(config, block=32,
                           collapse_ceiling=collapse_ceiling)
    corpus.swap(params, articles, note="initial")
    service = RecommendationService(
        params, config, corpus, top_k=5, max_batch=8, max_inflight=16,
        flush_slack_s=0.02, linger_s=0.002, default_deadline_s=_SLA_S,
        retry=RetryPolicy(max_attempts=3, backoff_s=0.001, max_elapsed_s=0.5))
    service.warmup()
    return service, params, articles


def run_serve_plan(seed, n_requests=48, log=None):
    """Execute one fault-plan x overload-trace pair. Returns ServePlanResult."""
    t0 = time.monotonic()
    service, params, articles = _make_service(seed)
    corpus = service.corpus
    plan = serve_fault_plan(seed, n_requests)
    injector = FaultInjector(plan)
    swap_faulted = any(s.site == "serve.swap" for s in plan.specs)
    version_before = corpus.version
    rng = np.random.default_rng(3000 + seed)
    futures = []
    served_after_swap = False
    try:
        # everything past warmup() — the overload trace, the degraded-mode
        # dispatches, the mid-plan hot swap — must hit only the variants the
        # service compiled up front; a recompile here is a latency cliff the
        # SLO never budgeted. Count mode (no max): a violation is reported as
        # a plan problem, not an exception that would mask the trace results.
        with compile_guard() as guard, _faults.install(injector):
            swap_at = len(overload_trace(seed, n_requests)) // 2
            for i, (burst, deadline_s, gap_s) in enumerate(
                    overload_trace(seed, n_requests)):
                for _ in range(burst):
                    q = articles[int(rng.integers(0, _N_ARTICLES))]
                    futures.append(service.submit(q, deadline_s=deadline_s))
                if i == swap_at:
                    # hot swap under fire: fresh articles, old ones keep
                    # serving until promotion (or forever, on rollback)
                    fresh = rng.random((_N_ARTICLES, _N_FEATURES),
                                       dtype=np.float32)
                    corpus.swap(params, fresh, note=f"refresh-{seed}")
                time.sleep(gap_s)
            # post-swap probe OUTSIDE the trace accounting: whatever the swap
            # did, the service must still answer
            probe = service.submit(articles[0], deadline_s=_SLA_S)
            probe_reply = probe.result(timeout=_HARNESS_DEADLINE_S)
            served_after_swap = probe_reply.ok
            futures.append(probe)
            replies, unresolved = [], 0
            harness_deadline = time.monotonic() + _HARNESS_DEADLINE_S
            for f in futures:
                try:
                    replies.append(f.result(
                        timeout=max(0.0, harness_deadline - time.monotonic())))
                except TimeoutError:
                    unresolved += 1  # a deadlock/silent drop — fails the plan
    finally:
        service.stop()
    n_ok = sum(1 for r in replies if r.status == "ok")
    n_shed = sum(1 for r in replies if r.status == "shed")
    n_err = sum(1 for r in replies if r.status == "error")
    ok_lat = [r.latency_s for r in replies if r.status == "ok"]
    p95_ms = (round(float(np.percentile(ok_lat, 95)) * 1e3, 3)
              if ok_lat else 0.0)
    rolled_back = any(e["event"] == "swap_rollback" for e in corpus.events)
    promoted = corpus.version > version_before
    summary = service.summary()
    # exactly-one-outcome, via the shared audit (reliability/ledger.py)
    problems = audit_outcome_counts(summary["counts"]["submitted"], n_ok,
                                    n_shed, n_err, n_unresolved=unresolved)
    if plan.specs and not injector.fired:
        # the mandatory family is planned where it provably lands (batch
        # call 1 / an enqueue within the trace / the mid-plan swap)
        problems.append("plan fired no faults (plan/trace mismatch)")
    if swap_faulted and not rolled_back:
        problems.append("serve.swap fault did not roll back")
    if swap_faulted and promoted:
        problems.append("swap promoted despite injected fault")
    if not swap_faulted and not promoted:
        problems.append("fault-free swap failed to promote")
    if not served_after_swap:
        problems.append("service stopped answering after the swap")
    if ok_lat and p95_ms > _SLA_S * 1e3:
        problems.append(f"p95 {p95_ms} ms blew the {_SLA_S}s SLA")
    if guard.count > 0:
        problems.append(
            f"{guard.count} XLA compiles after warmup — degraded modes must "
            "dispatch to precompiled variants, never retrace")
    result = ServePlanResult(
        seed=int(seed), ok=not problems, detail="; ".join(problems) or "ok",
        n_submitted=summary["counts"]["submitted"], n_replied=n_ok,
        n_shed=n_shed, n_errors=n_err, n_unresolved=unresolved,
        p95_ms=p95_ms, degraded=bool(summary["degraded_events"]),
        swap_faulted=swap_faulted, swap_rolled_back=rolled_back,
        served_after_swap=served_after_swap,
        n_post_warm_compiles=int(guard.count),
        injected=list(injector.fired), retries=list(injector.retries),
        duration_s=round(time.monotonic() - t0, 2))
    if log:
        log(f"serve plan {seed}: {'OK' if result.ok else 'FAIL'} "
            f"({result.n_replied} ok / {result.n_shed} shed / "
            f"{result.n_errors} err, p95 {result.p95_ms} ms) {result.detail}")
    return result


def chaos_serve_soak(n_plans=6, n_requests=48, log=None):
    """Replay `n_plans` seeded plans (seeds 0..n-1; any 6 consecutive seeds
    cover every serve fault family). Returns {"results", "all_ok", ...}."""
    results = [run_serve_plan(seed, n_requests=n_requests, log=log)
               for seed in range(n_plans)]
    n_ok = sum(1 for r in results if r.ok)
    return {"results": results, "n_ok": n_ok, "n_plans": n_plans,
            "all_ok": n_ok == n_plans}
