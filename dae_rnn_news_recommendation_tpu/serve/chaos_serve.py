"""Chaos-serve soak: seeded fault plans x overload traces against the service.

`reliability/chaos.py` proves the TRAINING loop recovers from injected
faults; this module proves the SERVING loop keeps its reply-or-shed promise
under the same discipline. Each seeded plan pairs:

  * a FaultPlan over the serve fire-points (serve.enqueue / serve.batch /
    serve.swap, kinds transient|fatal|preempt) — the round-robin family pick
    guarantees any 6 consecutive seeds cover every serve fault family; and
  * an overload trace — a seeded arrival schedule of request bursts with
    mixed deadlines: generous ones that must be answered, hopeless ones
    (microseconds) that must be shed, plus bursts sized past the admission
    queue so queue_full shedding and the degraded modes actually engage.

Mid-plan, the harness attempts a hot corpus swap. Under an injected
`serve.swap` fault the swap must ROLL BACK: version unchanged, rollback
recorded in `corpus.events`, and a probe request answered by the OLD corpus
afterwards.

A plan passes when:
  * exactly-one-outcome: submitted == replied + shed + errors, and every
    future resolved within the harness deadline (zero deadlocks, zero silent
    drops);
  * fault honesty: a plan that injected faults shows them in the injector
    log, and transient batch faults show absorbed retries;
  * swap honesty: a swap-faulted plan rolled back and kept serving; an
    unfaulted plan promoted to a new version;
  * bounded latency: p95 of answered requests stays under the generous
    deadline even when the plan ran degraded.

The CHAOS-SHARD soak (`chaos_shard_soak` / `run_shard_plan`) is the
mesh-sharded sibling: each seeded plan runs a row-sharded service over every
local device and exercises one shard-fault family — shard lost under load
(detect -> quarantine -> partial_corpus replies -> swaps blocked -> recover),
shard lost mid-swap (the loss lands inside the prepare phase and the commit
heals it), prepare-phase crashes on both swap flavors (whole-slot
rollback, no shard advances), and — r16 — the same under-load loss against
the DEFAULT sharded+IVF configuration: the lost shard takes its owned index
cells with it, quarantine masks those cells, replies carry the index's
honest reachable-row coverage, and `recover_shards()` restores the slabs
bitwise. A concurrent reader thread samples the active
slot's per-shard version stamps the whole time, and a plan passes only when:
exactly-one-outcome holds; `audit_shard_reads` finds zero torn cross-shard
reads; `audit_version_ledger` accepts the promote/degrade/recover records
(uniform shard stamps, <=1 skew); zero post-warmup XLA compiles (loss,
quarantine, degraded serving and recovery all ride warmed variants and pure
transfers); and the final slot is BITWISE equal to a fault-free reference
replay of the same seeded operations — the recovery really is a byte-exact
undo of the loss.
"""

import dataclasses
import threading
import time

import numpy as np

from ..analysis.runtime import compile_guard
from ..models.dae_core import DAEConfig, init_params
from ..reliability import faults as _faults
from ..reliability.faults import FaultInjector, FaultPlan, FaultSpec
from ..reliability.ledger import (OutcomeLedger, audit_outcome_counts,
                                  audit_shard_reads, audit_version_ledger)
from ..reliability.retry import RetryPolicy
from ..train.resident import build_resident
from .corpus import ServingCorpus, SwapRejected
from .graph import block_indices
from .service import RecommendationService

# CPU-sized service shapes: small enough for tier-1, busy enough to overload
_N_ARTICLES = 96
_N_FEATURES = 24
_N_COMPONENTS = 8

# generous deadline every answered request must honor (CPU dispatch is ~ms;
# the budget absorbs scheduler jitter on a loaded test box)
_SLA_S = 5.0
_HOPELESS_S = 1e-6   # provably unmeetable once the floor is warm
_HARNESS_DEADLINE_S = 60.0


@dataclasses.dataclass
class ServePlanResult:
    seed: int
    ok: bool
    detail: str
    n_submitted: int
    n_replied: int
    n_shed: int
    n_errors: int
    n_unresolved: int
    p95_ms: float
    degraded: bool
    swap_faulted: bool
    swap_rolled_back: bool
    served_after_swap: bool
    n_post_warm_compiles: int
    injected: list
    retries: list
    duration_s: float

    def to_dict(self):
        return dataclasses.asdict(self)


def serve_fault_plan(seed, n_requests):
    """Seeded plan over the serve fire-points. Six families, round-robin on
    the seed (mirrors FaultPlan.generate's discipline for the train sites)."""
    rng = np.random.default_rng(seed)
    # batch faults always land on the FIRST dispatch: the trace guarantees an
    # answerable first burst, so the fault provably fires there — and never
    # on the end-of-plan probe
    batch_at = 1
    families = (
        lambda: (FaultSpec("serve.batch", batch_at, "transient",
                           note="flaky device dispatch"),),
        lambda: (FaultSpec("serve.batch", batch_at, "fatal",
                           note="device fault mid-batch"),),
        lambda: (FaultSpec("serve.enqueue",
                           int(rng.integers(1, max(2, n_requests))),
                           "transient", note="admission blip"),),
        lambda: (FaultSpec("serve.enqueue",
                           int(rng.integers(1, max(2, n_requests))), "fatal",
                           note="admission failure"),),
        lambda: (FaultSpec("serve.swap", 1, "fatal",
                           note="standby build dies -> rollback"),),
        lambda: (FaultSpec("serve.batch", batch_at, "preempt",
                           note="serving task preempted mid-batch"),),
    )
    specs = list(families[seed % len(families)]())
    for _ in range(int(rng.integers(0, 3))):
        specs.append(FaultSpec(
            "serve.batch" if rng.random() < 0.5 else "serve.enqueue",
            int(rng.integers(1, max(2, n_requests))), "transient",
            note="extra transient"))
    return FaultPlan(seed=int(seed), specs=tuple(specs))


def overload_trace(seed, n_requests):
    """Seeded arrival schedule: [(n_burst, deadline_s, gap_s)]. Front-loaded
    bursts overflow the admission queue; a sprinkle of hopeless deadlines
    exercises unmeetable-shedding; the rest must be answered within SLA."""
    rng = np.random.default_rng(1000 + seed)
    trace = []
    left = n_requests
    while left > 0:
        burst = int(min(left, rng.integers(1, 25)))
        # the first burst is always answerable: batch-site faults are planned
        # at the first dispatch and must land on real requests, not the probe
        hopeless = bool(trace) and rng.random() < 0.25
        trace.append((burst, _HOPELESS_S if hopeless else _SLA_S,
                      float(rng.random() * 0.002)))
        left -= burst
    return trace


def _make_service(seed, collapse_ceiling=0.98):
    config = DAEConfig(n_features=_N_FEATURES, n_components=_N_COMPONENTS,
                       enc_act_func="tanh", triplet_strategy="none",
                       corr_type="masking", corr_frac=0.0)
    import jax

    params = init_params(jax.random.PRNGKey(7 + seed), config)
    rng = np.random.default_rng(2000 + seed)
    articles = rng.random((_N_ARTICLES, _N_FEATURES), dtype=np.float32)
    corpus = ServingCorpus(config, block=32,
                           collapse_ceiling=collapse_ceiling)
    corpus.swap(params, articles, note="initial")
    service = RecommendationService(
        params, config, corpus, top_k=5, max_batch=8, max_inflight=16,
        flush_slack_s=0.02, linger_s=0.002, default_deadline_s=_SLA_S,
        retry=RetryPolicy(max_attempts=3, backoff_s=0.001, max_elapsed_s=0.5))
    service.warmup()
    return service, params, articles


def run_serve_plan(seed, n_requests=48, log=None):
    """Execute one fault-plan x overload-trace pair. Returns ServePlanResult."""
    t0 = time.monotonic()
    service, params, articles = _make_service(seed)
    corpus = service.corpus
    plan = serve_fault_plan(seed, n_requests)
    injector = FaultInjector(plan)
    swap_faulted = any(s.site == "serve.swap" for s in plan.specs)
    version_before = corpus.version
    rng = np.random.default_rng(3000 + seed)
    futures = []
    served_after_swap = False
    try:
        # everything past warmup() — the overload trace, the degraded-mode
        # dispatches, the mid-plan hot swap — must hit only the variants the
        # service compiled up front; a recompile here is a latency cliff the
        # SLO never budgeted. Count mode (no max): a violation is reported as
        # a plan problem, not an exception that would mask the trace results.
        with compile_guard() as guard, _faults.install(injector):
            swap_at = len(overload_trace(seed, n_requests)) // 2
            for i, (burst, deadline_s, gap_s) in enumerate(
                    overload_trace(seed, n_requests)):
                for _ in range(burst):
                    q = articles[int(rng.integers(0, _N_ARTICLES))]
                    futures.append(service.submit(q, deadline_s=deadline_s))
                if i == swap_at:
                    # hot swap under fire: fresh articles, old ones keep
                    # serving until promotion (or forever, on rollback)
                    fresh = rng.random((_N_ARTICLES, _N_FEATURES),
                                       dtype=np.float32)
                    corpus.swap(params, fresh, note=f"refresh-{seed}")
                time.sleep(gap_s)
            # post-swap probe OUTSIDE the trace accounting: whatever the swap
            # did, the service must still answer
            probe = service.submit(articles[0], deadline_s=_SLA_S)
            probe_reply = probe.result(timeout=_HARNESS_DEADLINE_S)
            served_after_swap = probe_reply.ok
            futures.append(probe)
            replies, unresolved = [], 0
            harness_deadline = time.monotonic() + _HARNESS_DEADLINE_S
            for f in futures:
                try:
                    replies.append(f.result(
                        timeout=max(0.0, harness_deadline - time.monotonic())))
                except TimeoutError:
                    unresolved += 1  # a deadlock/silent drop — fails the plan
    finally:
        service.stop()
    n_ok = sum(1 for r in replies if r.status == "ok")
    n_shed = sum(1 for r in replies if r.status == "shed")
    n_err = sum(1 for r in replies if r.status == "error")
    ok_lat = [r.latency_s for r in replies if r.status == "ok"]
    p95_ms = (round(float(np.percentile(ok_lat, 95)) * 1e3, 3)
              if ok_lat else 0.0)
    rolled_back = any(e["event"] == "swap_rollback" for e in corpus.events)
    promoted = corpus.version > version_before
    summary = service.summary()
    # exactly-one-outcome, via the shared audit (reliability/ledger.py)
    problems = audit_outcome_counts(summary["counts"]["submitted"], n_ok,
                                    n_shed, n_err, n_unresolved=unresolved)
    if plan.specs and not injector.fired:
        # the mandatory family is planned where it provably lands (batch
        # call 1 / an enqueue within the trace / the mid-plan swap)
        problems.append("plan fired no faults (plan/trace mismatch)")
    if swap_faulted and not rolled_back:
        problems.append("serve.swap fault did not roll back")
    if swap_faulted and promoted:
        problems.append("swap promoted despite injected fault")
    if not swap_faulted and not promoted:
        problems.append("fault-free swap failed to promote")
    if not served_after_swap:
        problems.append("service stopped answering after the swap")
    if ok_lat and p95_ms > _SLA_S * 1e3:
        problems.append(f"p95 {p95_ms} ms blew the {_SLA_S}s SLA")
    if guard.count > 0:
        problems.append(
            f"{guard.count} XLA compiles after warmup — degraded modes must "
            "dispatch to precompiled variants, never retrace")
    result = ServePlanResult(
        seed=int(seed), ok=not problems, detail="; ".join(problems) or "ok",
        n_submitted=summary["counts"]["submitted"], n_replied=n_ok,
        n_shed=n_shed, n_errors=n_err, n_unresolved=unresolved,
        p95_ms=p95_ms, degraded=bool(summary["degraded_events"]),
        swap_faulted=swap_faulted, swap_rolled_back=rolled_back,
        served_after_swap=served_after_swap,
        n_post_warm_compiles=int(guard.count),
        injected=list(injector.fired), retries=list(injector.retries),
        duration_s=round(time.monotonic() - t0, 2))
    if log:
        log(f"serve plan {seed}: {'OK' if result.ok else 'FAIL'} "
            f"({result.n_replied} ok / {result.n_shed} shed / "
            f"{result.n_errors} err, p95 {result.p95_ms} ms) {result.detail}")
    return result


def chaos_serve_soak(n_plans=6, n_requests=48, log=None):
    """Replay `n_plans` seeded plans (seeds 0..n-1; any 6 consecutive seeds
    cover every serve fault family). Returns {"results", "all_ok", ...}."""
    results = [run_serve_plan(seed, n_requests=n_requests, log=log)
               for seed in range(n_plans)]
    n_ok = sum(1 for r in results if r.ok)
    return {"results": results, "n_ok": n_ok, "n_plans": n_plans,
            "all_ok": n_ok == n_plans}


# ------------------------------------------------------- chaos-shard soak
# Mesh-sharded serving under shard faults. Shapes stay CONSTANT across a
# plan (append batches keep the corpus at _N_ARTICLES rows, so N_pad never
# moves) — that is what lets the compile guard demand ZERO post-warmup
# compiles while shards die, degrade and recover mid-plan.

_APPEND_ROWS = 32   # divides block=32 and the 8-device mesh; with
# max_rows=_N_ARTICLES every append evicts exactly its own size, so n_pad
# is pinned and every dispatch/swap rides the warmed programs

_SHARD_FAMILIES = (
    "shard-lost-under-load",   # loss while serving: detect -> quarantine ->
    # partial_corpus -> swaps blocked -> recover -> append
    "shard-lost-mid-swap",     # loss lands INSIDE an append's prepare
    # phase; the commit re-places every shard and heals it
    "prepare-crash-append",    # injected refresh.swap fatal: whole-slot
    # rollback, retry promotes
    "prepare-crash-rebuild",   # injected serve.swap fatal on a full
    # rebuild: same rollback contract
    "ivf-shard-lost-under-load",  # ISSUE 16: the default sharded+IVF
    # configuration loses a cell-owning shard under load — quarantine masks
    # the lost CELLS, partial_corpus coverage is the index's reachable-row
    # fraction, recovery restores the slabs bitwise
)

# the IVF family's corpus: few cells at a pinned capacity floor so append
# skew can never move the slab shapes (zero-recompile), probed exhaustively
# so every dispatch provably touches the lost shard's cells
_IVF_CORPUS_KW = {"retrieval": "ivf", "n_cells": 4, "cell_cap": 96}
_IVF_PROBES = 4


@dataclasses.dataclass
class ShardPlanResult:
    seed: int
    family: str
    dtype: str
    ok: bool
    detail: str
    n_submitted: int
    n_replied: int
    n_shed: int
    n_errors: int
    n_partial: int          # replies tagged partial_corpus
    min_coverage: float     # lowest coverage stamped on any reply
    final_version: int
    bitwise_recovered: bool  # final slot == fault-free reference, byte-exact
    n_read_samples: int     # reader-thread shard-stamp snapshots audited
    n_post_warm_compiles: int
    injected: list
    duration_s: float

    def to_dict(self):
        return dataclasses.asdict(self)


def shard_fault_plan(seed):
    """Seeded shard-fault plan: five families, round-robin on the seed (any
    5 consecutive seeds cover every family), alternating float32/int8
    corpora (any 2 consecutive seeds cover both quantization poisons —
    float32 loses an embedding shard, int8 loses its f32 scales shard).

    The loss families plan the `serve.shard` HARNESS directive (a dead
    device never raises in-line — `run_shard_plan` applies it via
    `ServingCorpus.inject_shard_loss`); the two crash families plan in-line
    fatals at the prepare phase of each swap flavor."""
    family = _SHARD_FAMILIES[seed % len(_SHARD_FAMILIES)]
    specs = {
        "shard-lost-under-load": (FaultSpec(
            "serve.shard", 1, "fatal", note="shard HBM lost under load"),),
        "shard-lost-mid-swap": (FaultSpec(
            "serve.shard", 1, "fatal",
            note="shard HBM lost inside the prepare phase"),),
        "prepare-crash-append": (FaultSpec(
            "refresh.swap", 1, "fatal",
            note="append prepare dies -> whole-slot rollback"),),
        "prepare-crash-rebuild": (FaultSpec(
            "serve.swap", 1, "fatal",
            note="rebuild prepare dies -> whole-slot rollback"),),
        "ivf-shard-lost-under-load": (FaultSpec(
            "serve.shard", 1, "fatal",
            note="shard + its owned IVF cells lost under load"),),
    }[family]
    return FaultPlan(seed=int(seed), specs=specs)


class _ShardLossAtPrepare(FaultInjector):
    """Injector that lands the planned shard loss INSIDE the prepare phase:
    the first `refresh.swap` fire (the very top of the staged append) poisons
    one shard of the ACTIVE slot before the hook returns. The swap's base
    snapshot predates the loss and the commit re-places every shard's
    buffers, so the promote itself is the recovery — the family proves a
    mid-prepare loss can neither tear the commit nor survive it."""

    def __init__(self, plan, corpus, shard_id):
        super().__init__(plan)
        self._corpus = corpus
        self._shard = int(shard_id)
        self._armed = True

    def fire(self, site, **info):
        if site == "refresh.swap" and self._armed:
            self._armed = False
            self._corpus.inject_shard_loss(
                self._shard, note="lost mid-swap (prepare phase)")
        super().fire(site, **info)


def _encode_rows(corpus, params, X):
    """Unit-norm [n, D] f32 host embeddings of `X` via the corpus's own
    jitted encoder — computed once per batch so `swap_incremental(emb=...)`
    never pays (or recompiles) the encode inside the compile guard."""
    import jax

    resident = build_resident(X, device_put=corpus._device_put)
    blocks = block_indices(int(X.shape[0]), corpus.block)
    emb = corpus._encode_corpus(params, resident, blocks)
    return np.asarray(jax.device_get(emb), np.float32)[: int(X.shape[0])]


def _slot_fingerprint(slot):
    """Host copy of every byte that defines the slot's serving behavior —
    including the IVF index slabs when the slot carries one, so "recovery is
    bitwise" covers the clustered scorer's entire read set too."""
    import jax

    out = {"n": slot.n, "version": slot.version,
           "emb": np.asarray(jax.device_get(slot.emb)),
           "valid": np.asarray(jax.device_get(slot.valid)),
           "scales": (None if slot.scales is None
                      else np.asarray(jax.device_get(slot.scales))),
           "ages": (None if slot.ages is None
                    else np.asarray(slot.ages))}
    ivf = getattr(slot, "ivf", None)
    for key in ("centroids", "cell_emb", "cell_valid", "cell_scales",
                "row_ids", "assign"):
        out[f"ivf_{key}"] = (None if ivf is None else
                             np.asarray(jax.device_get(getattr(ivf, key))))
    return out


def _fingerprints_equal(a, b):
    if a["n"] != b["n"] or a["version"] != b["version"]:
        return False
    for key in a:
        if key in ("n", "version"):
            continue
        x, y = a[key], b[key]
        if (x is None) != (y is None):
            return False
        if x is not None and not (x.dtype == y.dtype
                                  and np.array_equal(x, y)):
            return False
    return True


def _make_sharded_service(seed, dtype, corpus_kw=None, derive_service=False):
    """Row-sharded service over every local device, fully warmed: serve
    variants (warmup), the append path (one fault-free incremental swap, so
    encode/dequantize/requantize/gate programs for the plan's exact shapes
    are all cached) — everything the plan dispatches after this point must
    be a cache hit. `corpus_kw` adds corpus build knobs (the IVF family's
    clustered index at a pinned cell capacity); `derive_service=True` builds
    the service WITHOUT explicit sharded=/mesh= kwargs, exercising the r16
    default-derivation path under chaos."""
    from ..parallel.mesh import get_mesh
    import jax

    config = DAEConfig(n_features=_N_FEATURES, n_components=_N_COMPONENTS,
                       enc_act_func="tanh", triplet_strategy="none",
                       corr_type="masking", corr_frac=0.0)
    params = init_params(jax.random.PRNGKey(7 + seed), config)
    rng = np.random.default_rng(2000 + seed)
    articles = rng.random((_N_ARTICLES, _N_FEATURES), dtype=np.float32)
    mesh = get_mesh()
    corpus = ServingCorpus(config, block=32, mesh=mesh, corpus_dtype=dtype,
                           **(corpus_kw or {}))
    corpus.swap(params, articles, note="initial")
    service_kw = ({"probes": _IVF_PROBES} if derive_service
                  else {"sharded": True, "mesh": mesh})
    service = RecommendationService(
        params, config, corpus, top_k=5, max_batch=8, max_inflight=16,
        flush_slack_s=0.02, linger_s=0.002, default_deadline_s=_SLA_S,
        retry=RetryPolicy(max_attempts=3, backoff_s=0.001, max_elapsed_s=0.5),
        **service_kw)
    service.warmup()
    batch1 = rng.random((_APPEND_ROWS, _N_FEATURES), dtype=np.float32)
    corpus.swap_incremental(params, batch1,
                            emb=_encode_rows(corpus, params, batch1),
                            max_rows=_N_ARTICLES, note="warm-append")
    return service, params, config, articles, batch1


def _replay_reference(seed, dtype, family, params, config, articles, batch1,
                      batch2, fresh, corpus_kw=None):
    """The fault-free twin: the exact data operations the faulted plan
    performed, on a fresh corpus over the same mesh — its final slot is the
    bitwise target the recovered corpus must hit."""
    from ..parallel.mesh import get_mesh

    corpus = ServingCorpus(config, block=32, mesh=get_mesh(),
                           corpus_dtype=dtype, **(corpus_kw or {}))
    corpus.swap(params, articles, note="initial")
    corpus.swap_incremental(params, batch1,
                            emb=_encode_rows(corpus, params, batch1),
                            max_rows=_N_ARTICLES, note="warm-append")
    if family == "prepare-crash-rebuild":
        corpus.swap(params, fresh, note=f"refresh-{seed}")
    else:
        corpus.swap_incremental(params, batch2,
                                emb=_encode_rows(corpus, params, batch2),
                                max_rows=_N_ARTICLES, note=f"append-{seed}")
    return corpus.active


def run_shard_plan(seed, n_requests=24, log=None):
    """Execute one chaos-shard plan on a row-sharded service. Returns
    ShardPlanResult; see the module docstring for the pass criteria."""
    import jax

    t0 = time.monotonic()
    family = _SHARD_FAMILIES[seed % len(_SHARD_FAMILIES)]
    dtype = ("float32", "int8")[seed % 2]
    plan = shard_fault_plan(seed)
    ivf_family = family.startswith("ivf")
    corpus_kw = dict(_IVF_CORPUS_KW) if ivf_family else None
    service, params, config, articles, batch1 = _make_sharded_service(
        seed, dtype, corpus_kw=corpus_kw, derive_service=ivf_family)
    corpus = service.corpus
    n_shards = len(corpus.active.shard_versions)
    if ivf_family:
        from ..index import cell_shard_owner

        # only a CELL-OWNING shard's loss is visible to the clustered
        # scorer — the index slabs hold their own copy of every row, so a
        # dummy-only shard dying changes no byte the scorer reads. Poison
        # an owner, seed-rotated.
        owners = sorted({int(s) for s in
                         cell_shard_owner(corpus.active.ivf)})
        shard_id = owners[seed % len(owners)]
    else:
        shard_id = seed % n_shards
    if family == "shard-lost-mid-swap":
        injector = _ShardLossAtPrepare(plan, corpus, shard_id)
    else:
        injector = FaultInjector(plan)
    rng = np.random.default_rng(3000 + seed)
    batch2 = rng.random((_APPEND_ROWS, _N_FEATURES), dtype=np.float32)
    fresh = rng.random((_N_ARTICLES, _N_FEATURES), dtype=np.float32)
    led = OutcomeLedger()
    futures = []
    problems = []
    samples = []
    reader_stop = threading.Event()

    def reader():
        # concurrent torn-read probe: snapshot (slot version, per-shard
        # stamps) from OUTSIDE the swap lock while swaps/losses/recoveries
        # run; audit_shard_reads demands every snapshot is uniform
        while not reader_stop.is_set():
            slot = corpus.active
            if slot is not None and slot.shard_versions is not None:
                samples.append({"version": slot.version,
                                "shards": [int(v)
                                           for v in slot.shard_versions]})
            time.sleep(0.0002)

    def burst(n, tag):
        out = []
        for j in range(n):
            q = articles[int(rng.integers(0, _N_ARTICLES))]
            fut = service.submit(q, deadline_s=_SLA_S)
            rid = f"{tag}-{j}"
            led.submit(rid)
            fut.add_done_callback(lambda r, rid=rid: led.resolve(
                rid, r.status,
                coverage=float(getattr(r, "coverage", 1.0)),
                partial="partial_corpus" in tuple(r.degraded or ())))
            out.append(fut)
        futures.extend(out)
        deadline = time.monotonic() + _HARNESS_DEADLINE_S
        return [f.result(timeout=max(0.0, deadline - time.monotonic()))
                for f in out]

    reader_thread = threading.Thread(target=reader, daemon=True,
                                     name="shard-read-probe")
    reader_thread.start()
    per_burst = max(1, n_requests // 3)
    if ivf_family and not (service.sharded and service.retrieval == "ivf"):
        problems.append("kwarg-less service did not derive the sharded+IVF "
                        "default configuration")
    try:
        with compile_guard() as guard, _faults.install(injector):
            replies_a = burst(per_burst, f"s{seed}-pre")
            if family.endswith("shard-lost-under-load"):
                corpus.inject_shard_loss(shard_id, note="lost under load")
                replies_b = burst(per_burst, f"s{seed}-degraded")
                if not corpus.degraded_shards:
                    problems.append("loss never quarantined: no dispatch "
                                    "detected the poisoned shard")
                if not any("partial_corpus" in r.degraded for r in replies_b
                           if r.status == "ok"):
                    problems.append("no post-loss reply tagged "
                                    "partial_corpus")
                if not any(r.status == "ok" and 0.0 < r.coverage < 1.0
                           for r in replies_b):
                    problems.append("no post-loss reply carried a "
                                    "fractional coverage")
                try:
                    corpus.swap_incremental(
                        params, batch2,
                        emb=_encode_rows(corpus, params, batch2),
                        max_rows=_N_ARTICLES, note="must-reject")
                    problems.append("swap_incremental succeeded while "
                                    "degraded (must be blocked)")
                except SwapRejected:
                    pass
                corpus.recover_shards(note="heal after quarantine")
                if not corpus.audit_shards()["ok"]:
                    problems.append("shards still lost after "
                                    "recover_shards()")
                if corpus.coverage != 1.0:
                    problems.append(f"coverage {corpus.coverage} != 1.0 "
                                    "after recovery")
            emb2 = _encode_rows(corpus, params, batch2)
            if family == "prepare-crash-rebuild":
                # first attempt dies at the injected prepare crash and rolls
                # back (the active slot keeps serving); the retry — the spec
                # is exhausted — must promote
                corpus.swap(params, fresh, note=f"refresh-{seed}")
                corpus.swap(params, fresh, note=f"refresh-{seed}")
            else:
                corpus.swap_incremental(params, batch2, emb=emb2,
                                        max_rows=_N_ARTICLES,
                                        note=f"append-{seed}")
                if family == "prepare-crash-append":
                    # first attempt died at the injected prepare crash and
                    # rolled back; replay it fault-free (spec exhausted)
                    corpus.swap_incremental(params, batch2, emb=emb2,
                                            max_rows=_N_ARTICLES,
                                            note=f"append-{seed}")
            replies_c = burst(per_burst, f"s{seed}-post")
            if not all(r.status == "ok" and r.coverage == 1.0
                       and "partial_corpus" not in r.degraded
                       for r in replies_c):
                problems.append("post-recovery burst not served at full "
                                "coverage")
    finally:
        reader_stop.set()
        reader_thread.join(timeout=5.0)
        service.stop()
    if any(r.status != "ok" for r in replies_a):
        problems.append("pre-fault burst had non-ok replies")
    if family == "prepare-crash-rebuild":
        crashed = [rec for rec in corpus.ledger
                   if not rec["ok"] and "injected" in rec.get("error", "")]
        if not crashed:
            problems.append("prepare crash never rolled back in the ledger")
    if family == "prepare-crash-append":
        if not any(not rec["ok"] and "injected" in rec.get("error", "")
                   for rec in corpus.ledger):
            problems.append("prepare crash never rolled back in the ledger")
    if family == "shard-lost-mid-swap":
        if not any(e.get("site") == "serve.shard" for e in injector.fired):
            problems.append("mid-swap loss was never applied")
        if not corpus.audit_shards()["ok"]:
            problems.append("commit did not heal the mid-prepare loss")
    if corpus.version != 3:
        problems.append(f"final version {corpus.version} != 3 "
                        "(initial + warm append + plan swap)")
    problems += led.audit()
    counts = led.counts()
    problems += audit_outcome_counts(
        led.n_submitted, counts.get("ok", 0), counts.get("shed", 0),
        counts.get("error", 0))
    problems += audit_shard_reads(samples)
    _, _, ledger_problems = audit_version_ledger(corpus.ledger)
    problems += ledger_problems
    if guard.count > 0:
        problems.append(
            f"{guard.count} XLA compiles after warmup — shard loss, "
            "degraded serving and recovery must ride warmed programs")
    # the fault-free twin runs OUTSIDE the guard (its fresh corpus compiles
    # its own encoder); bitwise equality is the recovery contract
    reference = _replay_reference(seed, dtype, family, params, config,
                                  articles, batch1, batch2, fresh,
                                  corpus_kw=corpus_kw)
    bitwise = _fingerprints_equal(_slot_fingerprint(corpus.active),
                                  _slot_fingerprint(reference))
    if not bitwise:
        problems.append("final slot differs from the fault-free reference "
                        "(recovery is not bitwise)")
    partial = [r for r in led.records
               if r["status"] == "ok" and r.get("partial")]
    coverages = [r["coverage"] for r in led.records if r["status"] == "ok"]
    result = ShardPlanResult(
        seed=int(seed), family=family, dtype=dtype, ok=not problems,
        detail="; ".join(problems) or "ok",
        n_submitted=led.n_submitted, n_replied=counts.get("ok", 0),
        n_shed=counts.get("shed", 0), n_errors=counts.get("error", 0),
        n_partial=len(partial),
        min_coverage=round(min(coverages), 4) if coverages else 0.0,
        final_version=int(corpus.version), bitwise_recovered=bool(bitwise),
        n_read_samples=len(samples),
        n_post_warm_compiles=int(guard.count),
        injected=list(injector.fired),
        duration_s=round(time.monotonic() - t0, 2))
    if log:
        log(f"shard plan {seed} [{family}/{dtype}]: "
            f"{'OK' if result.ok else 'FAIL'} ({result.n_replied} ok, "
            f"{result.n_partial} partial, min coverage "
            f"{result.min_coverage}) {result.detail}")
    return result


def chaos_shard_soak(n_plans=5, n_requests=24, log=None):
    """Replay `n_plans` seeded chaos-shard plans (seeds 0..n-1; any 5
    consecutive seeds cover every shard family, any 2 both corpus dtypes).
    Returns {"results", "all_ok", ...}."""
    results = [run_shard_plan(seed, n_requests=n_requests, log=log)
               for seed in range(n_plans)]
    n_ok = sum(1 for r in results if r.ok)
    return {"results": results, "n_ok": n_ok, "n_plans": n_plans,
            "all_ok": n_ok == n_plans}
