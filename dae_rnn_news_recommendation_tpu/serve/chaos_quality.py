"""Chaos-quality soak: the both-ways contract for the QUALITY alerts.

r14's chaos soaks pin the LATENCY/health alerts both ways (the injected
family's alert fires, the fault-free reference replay stays silent); this
module gives the retrieval-QUALITY alerts (`telemetry.quality_slo_specs`)
the same discipline. Two fault families, each degrading the one signal its
alert watches:

  * ``cell-owning-shard-loss`` — the default sharded+IVF configuration
    loses a shard that OWNS index cells under load, with the shadow scorer
    sampling every reply. The first post-loss dispatch quarantines the
    shard, the corpus/service publish the shrunken `corpus_coverage`
    gauge, and the ``quality-coverage`` floor alert must fire. The fault
    is visible to quality observability the moment it lands — not at the
    next offline bench.
  * ``churn-drift`` — the serving params have drifted from the params the
    corpus (and its k-means centroids) were built with, and the service
    probes fewer cells than exist. Every query the shadow re-scores with
    the exact full-scan path reveals rows the drifted probe ordering
    skipped: `shadow_misses` burns against `shadow_expected` and the
    ``quality-recall`` burn-rate alert must fire — while coverage stays a
    full 1.0 and the ``quality-coverage`` alert stays silent.

The fault-free reference replay runs each family's exact configuration
MINUS its fault (no shard loss; service params == corpus build params) and
must raise zero quality alerts. The reference's recall silence is
structural, not statistical: queries are corpus rows, and k-means'
assignment is nearest-centroid under the FINAL centroids, so a probe-1
lookup of a row's own embedding lands in the cell that holds the row and
the served top-1 equals the exact top-1 bit-for-bit.

Every plan (faulted and reference) additionally demands ZERO post-warmup
XLA compiles: the shadow re-scores, the quarantine path, and the degraded
serving all ride variants `warmup()` compiled — quality observability
never buys a latency cliff.
"""

import dataclasses
import time

import numpy as np

from ..analysis.runtime import compile_guard
from ..fleet.observability import QUALITY_FAMILY_ALERTS
from ..models.dae_core import DAEConfig, init_params
from ..telemetry.metrics_registry import MetricsRegistry
from ..telemetry.slo import SLOMonitor, quality_slo_specs
from .chaos_serve import _encode_rows
from .corpus import ServingCorpus
from .service import RecommendationService

_N_ARTICLES = 96
_N_FEATURES = 24
_N_COMPONENTS = 8
_SLA_S = 5.0
_HARNESS_DEADLINE_S = 60.0

QUALITY_FAMILIES = tuple(QUALITY_FAMILY_ALERTS)

# the drift family's index: many THIN cells probed shallowly (96 rows over
# 16 cells, probes=1), so stale centroid ordering has plenty of room to
# miss; the loss family probes exhaustively so its recall is IVF==exact
# and coverage is the only degraded signal
_DRIFT_IVF_KW = {"retrieval": "ivf", "n_cells": 16, "cell_cap": 96}
_DRIFT_PROBES = 1
_LOSS_IVF_KW = {"retrieval": "ivf", "n_cells": 4, "cell_cap": 96}
_LOSS_PROBES = 4


@dataclasses.dataclass
class QualityPlanResult:
    seed: int
    family: str
    injected: bool          # False = the fault-free reference replay
    ok: bool
    detail: str
    n_replied: int
    n_scored: int           # shadow samples scored
    recall_mean: float
    min_coverage: float     # lowest corpus_coverage gauge value observed
    alerts: list            # quality alert names fired, in firing order
    n_post_warm_compiles: int
    duration_s: float

    def to_dict(self):
        return dataclasses.asdict(self)


def _drift_query_ids(seed, n_requests):
    """The row ids run_quality_plan's two bursts will submit, in order —
    the same rng stream `_burst` consumes, replicated so the plan
    constructor can judge drift materiality on the EXACT query sequence."""
    rng = np.random.default_rng(3000 + seed)
    per_burst = max(1, int(n_requests) // 2)
    return [int(rng.integers(0, _N_ARTICLES)) for _ in range(2 * per_burst)]


def _predicted_miss_rate(e1, slot, query_ids):
    """Host prediction of the probe-1 recall@1 miss rate for drifted query
    embeddings `e1` against the slot's stored corpus + centroids: a query
    misses when its exact top-1 row does not live in its nearest-centroid
    cell (the single probed cell). This is the same dot-product arithmetic
    the device runs, so the prediction is exact up to fp ties."""
    import jax

    e0 = np.asarray(jax.device_get(slot.emb), np.float32)[: slot.n]
    cents = np.asarray(jax.device_get(slot.ivf.centroids), np.float32)
    assign = np.asarray(jax.device_get(slot.ivf.assign),
                        np.int64)[: slot.n]
    misses = 0
    for i in query_ids:
        q = e1[i]
        exact = int(np.argmax(e0 @ q))
        cell = int(np.argmax(cents @ q))
        rows = np.where(assign == cell)[0]
        served = int(rows[np.argmax(e0[rows] @ q)]) if rows.size else -1
        misses += int(exact != served)
    return misses / max(len(query_ids), 1)


def _material_drift_params(seed, corpus, config, articles, n_requests=24,
                           floor=0.15):
    """Pick drifted serving params whose recall damage provably clears the
    alerting objective (5% miss rate) with margin, on this plan's exact
    query sequence. Independent re-inits drift by luck — most keys land a
    20-60% miss rate, but a benign one can land under the objective and
    would make the plan assert an alert its own fault never earned. Like
    `serve_fault_plan` pinning the batch fault to a dispatch that provably
    happens, the constructor walks a seeded key schedule and takes the
    first candidate whose predicted miss rate clears `floor`."""
    import jax

    ids = _drift_query_ids(seed, n_requests)
    slot = corpus.active
    best = None
    for attempt in range(8):
        cand = init_params(
            jax.random.PRNGKey(9000 + 97 * attempt + seed), config)
        e1 = _encode_rows(corpus, cand, articles)
        rate = _predicted_miss_rate(e1, slot, ids)
        if best is None or rate > best[0]:
            best = (rate, cand)
        if rate >= floor:
            return cand
    return best[1]   # most damaging candidate; the plan audit still
    # demands the alert, so an insufficient drift fails loudly, not silently


def _quality_service(seed, family, injected, registry):
    """Build the family's corpus + shadow-sampling service. The drift
    family's fault is configuration-level (service params != corpus build
    params), so `injected` selects the params; the loss family's fault is
    applied later by the harness."""
    import jax

    config = DAEConfig(n_features=_N_FEATURES, n_components=_N_COMPONENTS,
                       enc_act_func="tanh", triplet_strategy="none",
                       corr_type="masking", corr_frac=0.0)
    build_params = init_params(jax.random.PRNGKey(7 + seed), config)
    rng = np.random.default_rng(2000 + seed)
    articles = rng.random((_N_ARTICLES, _N_FEATURES), dtype=np.float32)
    if family == "cell-owning-shard-loss":
        from ..parallel.mesh import get_mesh

        corpus = ServingCorpus(config, block=32, mesh=get_mesh(),
                               registry=registry, **_LOSS_IVF_KW)
        probes = _LOSS_PROBES
        serve_params = build_params
    else:
        corpus = ServingCorpus(config, block=32, registry=registry,
                               **_DRIFT_IVF_KW)
        probes = _DRIFT_PROBES
        serve_params = build_params
    corpus.swap(build_params, articles, note="initial")
    if family == "churn-drift" and injected:
        # the drift: a refresh cycle updated the model but the corpus (and
        # its centroids) still embed the OLD params' space. Like every
        # chaos plan in this repo, the fault must PROVABLY land — the
        # constructor verifies the candidate drift is material against the
        # plan's exact query sequence before serving a single request
        serve_params = _material_drift_params(seed, corpus, config, articles)
    service = RecommendationService(
        serve_params, config, corpus, top_k=1, max_batch=8, max_inflight=32,
        flush_slack_s=0.02, linger_s=0.002, default_deadline_s=_SLA_S,
        probes=probes, registry=registry, shadow_rate=1.0, shadow_queue=256,
        name=f"quality-{family}")
    service.warmup()
    return service, articles


def _burst(service, articles, rng, n):
    futures = [service.submit(articles[int(rng.integers(0, _N_ARTICLES))],
                              deadline_s=_SLA_S) for _ in range(n)]
    deadline = time.monotonic() + _HARNESS_DEADLINE_S
    return [f.result(timeout=max(0.0, deadline - time.monotonic()))
            for f in futures]


def run_quality_plan(seed, family, n_requests=24, injected=True, log=None):
    """Execute one quality plan (or, with `injected=False`, its fault-free
    reference replay). Returns QualityPlanResult; a plan passes when the
    family's mapped alert fires iff the fault was injected, the untargeted
    alerts stay silent, and nothing recompiled after warmup."""
    assert family in QUALITY_FAMILIES, f"unknown quality family {family!r}"
    t0 = time.monotonic()
    registry = MetricsRegistry(name=f"quality-{family}-{seed}")
    monitor = SLOMonitor(quality_slo_specs())
    service, articles = _quality_service(seed, family, injected, registry)
    corpus = service.corpus
    rng = np.random.default_rng(3000 + seed)
    problems = []
    replies = []
    pre_fired = []
    try:
        with compile_guard() as guard:
            monitor.observe(registry.snapshot())   # pre-traffic baseline
            replies += _burst(service, articles, rng, max(1, n_requests // 2))
            service.shadow.flush(timeout=_HARNESS_DEADLINE_S)
            monitor.observe(registry.snapshot())
            pre_fired = monitor.evaluate()
            if injected and family == "cell-owning-shard-loss":
                # the drift family is degraded from the first request; the
                # loss family must be CLEAN until the fault actually lands
                if pre_fired:
                    problems.append(
                        "quality alert fired before the fault: "
                        f"{[a['slo'] for a in pre_fired]}")
                from ..index import cell_shard_owner

                owners = sorted({int(s) for s in
                                 cell_shard_owner(corpus.active.ivf)})
                corpus.inject_shard_loss(owners[seed % len(owners)],
                                         note="cell-owning shard lost")
            replies += _burst(service, articles, rng, max(1, n_requests // 2))
            if not service.shadow.flush(timeout=_HARNESS_DEADLINE_S):
                problems.append("shadow queue failed to drain")
            monitor.observe(registry.snapshot())
            monitor.evaluate()
    finally:
        service.stop()
    if any(r.status != "ok" for r in replies):
        problems.append("not every request was answered ok")
    shadow = service.shadow.summary()
    if shadow["counts"]["errors"]:
        problems.append(f"{shadow['counts']['errors']} shadow re-score "
                        "errors")
    if not shadow["counts"]["scored"]:
        problems.append("shadow scorer scored nothing")
    alert_names = [a["slo"] for a in monitor.alerts]
    target = QUALITY_FAMILY_ALERTS[family]
    if injected:
        if target not in alert_names:
            problems.append(f"injected {family} never fired {target} "
                            f"(fired: {alert_names or 'nothing'})")
        if family == "churn-drift" and "quality-coverage" in alert_names:
            problems.append("drift fired the coverage alert (coverage "
                            "never dropped)")
    elif alert_names:
        problems.append("fault-free reference fired quality alerts: "
                        f"{alert_names}")
    if "quality-quant-error" in alert_names:
        problems.append("float32 corpus fired the quantization-error "
                        "ceiling (gauge must be absent)")
    if guard.count > 0:
        problems.append(f"{guard.count} XLA compiles after warmup — the "
                        "shadow path must ride warmed variants")
    gauges = registry.snapshot().get("gauges") or {}
    result = QualityPlanResult(
        seed=int(seed), family=family, injected=bool(injected),
        ok=not problems, detail="; ".join(problems) or "ok",
        n_replied=sum(1 for r in replies if r.status == "ok"),
        n_scored=int(shadow["counts"]["scored"]),
        recall_mean=float(shadow["recall_mean"] or 0.0),
        min_coverage=float(gauges.get("corpus_coverage", 1.0)),
        alerts=alert_names,
        n_post_warm_compiles=int(guard.count),
        duration_s=round(time.monotonic() - t0, 2))
    if log:
        mode = "fault" if injected else "reference"
        log(f"quality plan {seed} [{family}/{mode}]: "
            f"{'OK' if result.ok else 'FAIL'} (recall {result.recall_mean}, "
            f"coverage {result.min_coverage}, alerts {alert_names}) "
            f"{result.detail}")
    return result


def run_quality_reference(seed, family, n_requests=24, log=None):
    """The fault-free twin: the family's exact configuration minus its
    fault. Must raise zero quality alerts."""
    return run_quality_plan(seed, family, n_requests=n_requests,
                            injected=False, log=log)


def chaos_quality_soak(n_seeds=1, n_requests=24, log=None):
    """The both-ways quality-alert audit: for each seed, every family runs
    faulted (its mapped alert MUST fire) and as a fault-free reference
    (NO quality alert may fire). Returns {"results", "all_ok", ...}."""
    results = []
    for seed in range(int(n_seeds)):
        for family in QUALITY_FAMILIES:
            results.append(run_quality_plan(seed, family,
                                            n_requests=n_requests, log=log))
            results.append(run_quality_reference(seed, family,
                                                 n_requests=n_requests,
                                                 log=log))
    n_ok = sum(1 for r in results if r.ok)
    return {"results": results, "n_ok": n_ok, "n_plans": len(results),
            "all_ok": n_ok == len(results)}
