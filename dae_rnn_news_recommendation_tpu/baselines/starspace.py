"""StarSpace-equivalent baseline: native C++ trainer + fastText-format export.

The reference benchmarks its DAE embeddings against Facebook's StarSpace C++
binary, invoked out of process on fastText-formatted text files
(reference starspace/prepare_starspace_formatted_data.ipynb: cell 4-5 write
"w1 w2 ... __label__<category>" lines, cell 6 runs `starspace train -trainFile
... -dim 50 -similarity cosine -loss hinge -adagrad true -thread 20`, cell 7
runs `embed_doc`; the argument dump is starspace/train.log:1-28 and the early-
stopped validation error 0.018963 is train.log:115-121).

Here the trainer is an in-repo native component (native/src/starspace.cc,
hogwild adagrad hinge-loss over cosine similarity) driven through ctypes, with
a NumPy implementation of identical semantics as fallback/oracle. The fastText
format export is kept so the artifacts stay interchangeable with the real
binary's.
"""

import dataclasses

import numpy as np
import scipy.sparse as sp

from .. import native


@dataclasses.dataclass
class StarSpaceConfig:
    """Mirrors the knobs the reference passes to the binary (train.log:2-28)."""

    dim: int = 50           # train.log:4
    lr: float = 0.01        # train.log:2
    margin: float = 0.05    # train.log:9
    epochs: int = 50        # notebook cell 6: -epoch 50
    neg: int = 10           # maxNegSamples, train.log:11
    threads: int = 20       # train.log:13
    patience: int = 10      # validationPatience, train.log:21
    seed: int = 0


def _as_csr(docs):
    # only the structure (indptr/indices) is consumed — a doc is its word set —
    # so stored values are never touched or copied
    docs = docs.tocsr()
    return (docs.indptr.astype(np.int64), docs.indices.astype(np.int32),
            docs.shape)


def train_starspace(train_docs, train_labels, val_docs=None, val_labels=None,
                    config=None, force_numpy=False):
    """Train word+label embeddings on bag-of-words csr docs.

    :param train_docs: scipy sparse [N, V]; column = vocabulary word. Stored
        values are ignored (a doc is its set of words, as in the fastText
        format export the reference feeds the binary).
    :param train_labels: int array [N] of label (category) ids
    :param val_docs/val_labels: optional held-out set for early stopping
    :param config: StarSpaceConfig
    :param force_numpy: skip the native library (used by tests as the oracle)
    :return: dict with 'word_emb' [V, dim], 'label_emb' [L, dim],
        'best_val_error', 'epoch_errors' (list, early-stopped tail omitted)
    """
    config = config or StarSpaceConfig()
    if not 0 < config.dim <= 512:
        raise ValueError(f"dim must be in (0, 512], got {config.dim}")
    indptr, indices, (n, vocab) = _as_csr(train_docs)
    labels = np.ascontiguousarray(train_labels, np.int32)
    if labels.size and labels.min() < 0:
        # pd.factorize emits -1 for missing categories; these must be filtered
        # by the caller, not silently indexed (OOB in the native trainer)
        raise ValueError("negative label ids (missing categories?) not allowed")
    n_labels = int(labels.max()) + 1 if labels.size else 0

    rng = np.random.default_rng(config.seed)
    bound = 1.0 / np.sqrt(config.dim)
    word_emb = rng.uniform(-bound, bound,
                           (vocab, config.dim)).astype(np.float32)
    label_emb = rng.uniform(-bound, bound,
                            (n_labels, config.dim)).astype(np.float32)

    has_val = val_docs is not None and val_docs.shape[0] > 0
    if has_val:
        v_indptr, v_indices, _ = _as_csr(val_docs)
        v_labels = np.ascontiguousarray(val_labels, np.int32)
        if v_labels.min() < 0 or int(v_labels.max()) + 1 > n_labels:
            raise ValueError("validation labels outside training label set")
    else:
        v_indptr = v_indices = v_labels = None

    lib = None if force_numpy else native.load()
    epoch_errors = np.full(config.epochs, -1.0)
    if lib is not None:
        import ctypes

        best = lib.starspace_train(
            native.as_ptr(indptr, ctypes.c_int64),
            native.as_ptr(indices, ctypes.c_int32),
            n, native.as_ptr(labels, ctypes.c_int32),
            vocab, n_labels, config.dim, config.lr, config.margin, config.neg,
            config.epochs, config.threads, config.patience,
            native.as_ptr(v_indptr, ctypes.c_int64) if has_val else None,
            native.as_ptr(v_indices, ctypes.c_int32) if has_val else None,
            len(v_labels) if has_val else 0,
            native.as_ptr(v_labels, ctypes.c_int32) if has_val else None,
            native.as_ptr(word_emb, ctypes.c_float),
            native.as_ptr(label_emb, ctypes.c_float),
            config.seed, native.as_ptr(epoch_errors, ctypes.c_double),
        )
        if best < 0:
            raise RuntimeError("native starspace_train rejected its inputs")
    else:
        best = _train_numpy(indptr, indices, labels, n_labels, word_emb,
                            label_emb, config, v_indptr, v_indices, v_labels,
                            epoch_errors)
    return {
        "word_emb": word_emb,
        "label_emb": label_emb,
        "best_val_error": float(best),
        "epoch_errors": [e for e in epoch_errors.tolist() if e >= 0],
    }


def embed_docs(docs, word_emb):
    """`embed_doc` equivalent: mean of word embeddings per csr row."""
    indptr, indices, (n, _) = _as_csr(docs)
    dim = word_emb.shape[1]
    out = np.zeros((n, dim), np.float32)
    lib = native.load()
    if lib is not None:
        import ctypes

        w = np.ascontiguousarray(word_emb, np.float32)
        lib.starspace_embed_docs(
            native.as_ptr(indptr, ctypes.c_int64),
            native.as_ptr(indices, ctypes.c_int32), n,
            native.as_ptr(w, ctypes.c_float), dim,
            native.as_ptr(out, ctypes.c_float))
        return out
    for i in range(n):
        lo, hi = indptr[i], indptr[i + 1]
        if hi > lo:
            out[i] = word_emb[indices[lo:hi]].mean(axis=0)
    return out


def export_fasttext_format(token_lists, labels, path):
    """Write "w1 w2 ... __label__<label>" lines (notebook cells 4-5 format) so
    artifacts stay interchangeable with the real StarSpace binary."""
    with open(path, "w") as f:
        for tokens, label in zip(token_lists, labels):
            f.write(" ".join(str(t) for t in tokens) + f" __label__{label}\n")


def tokens_from_csr(docs, vocabulary=None):
    """Inverse-transform csr rows to token lists (notebook cell 3 uses
    CountVectorizer.inverse_transform); vocabulary maps column -> word."""
    docs = docs.tocsr()
    out = []
    for i in range(docs.shape[0]):
        cols = docs.indices[docs.indptr[i]:docs.indptr[i + 1]]
        out.append([vocabulary[c] if vocabulary is not None else f"w{c}"
                    for c in cols])
    return out


# ---------------------------------------------------------------------------
# NumPy reference implementation — identical semantics to starspace.cc, used
# as the test oracle and as fallback when the native build is unavailable.
# ---------------------------------------------------------------------------

def _cos_and_grad(a, b):
    na = np.sqrt(a @ a) + 1e-8
    nb = np.sqrt(b @ b) + 1e-8
    c = (a @ b) / (na * nb)
    return c, b / (na * nb) - c * a / (na * na)


def _adagrad_row(emb, g2, row, grad, lr):
    g2[row] += grad @ grad
    emb[row] -= lr / np.sqrt(g2[row] + 1e-8) * grad


def _eval_numpy(indptr, indices, labels, word_emb, label_emb, margin, neg,
                seed):
    rng = np.random.RandomState(seed & 0xFFFFFFFF)
    n_labels = label_emb.shape[0]
    total = 0.0
    n = len(labels)
    for i in range(n):
        lo, hi = indptr[i], indptr[i + 1]
        if hi == lo:
            continue
        doc = word_emb[indices[lo:hi]].mean(axis=0)
        cp, _ = _cos_and_grad(doc, label_emb[labels[i]])
        for _ in range(neg):
            yn = rng.randint(0, n_labels)
            if yn == labels[i]:
                yn = (yn + 1) % n_labels
            cn, _ = _cos_and_grad(doc, label_emb[yn])
            total += max(0.0, margin - cp + cn)
    return total / max(n, 1)


def _train_numpy(indptr, indices, labels, n_labels, word_emb, label_emb,
                 config, v_indptr, v_indices, v_labels, epoch_errors):
    """Single-threaded trainer with the same update rule as the native code.

    RNG streams differ from the C++ (std::mt19937 shuffling vs RandomState),
    so runs are statistically — not bitwise — equivalent.
    """
    word_g2 = np.zeros(word_emb.shape[0], np.float32)
    label_g2 = np.zeros(n_labels, np.float32)
    has_val = v_indptr is not None
    best = np.inf
    best_snap = None
    since_best = 0
    n = len(labels)
    rng = np.random.RandomState(config.seed & 0xFFFFFFFF)
    for epoch in range(config.epochs):
        order = rng.permutation(n)
        train_loss = 0.0
        for i in order:
            lo, hi = indptr[i], indptr[i + 1]
            if hi == lo or n_labels < 2:
                continue
            words = indices[lo:hi]
            doc = word_emb[words].mean(axis=0)
            y = labels[i]
            cp, gpos = _cos_and_grad(doc, label_emb[y])
            gdoc = np.zeros_like(doc)
            active = 0
            for _ in range(config.neg):
                yn = rng.randint(0, n_labels)
                if yn == y:
                    yn = (yn + 1) % n_labels
                cn, gneg = _cos_and_grad(doc, label_emb[yn])
                l = config.margin - cp + cn
                if l <= 0:
                    continue
                train_loss += l
                active += 1
                gdoc += gneg - gpos
                _, glab = _cos_and_grad(label_emb[yn], doc)
                _adagrad_row(label_emb, label_g2, yn, glab, config.lr)
            if active:
                _, glab = _cos_and_grad(label_emb[y], doc)
                _adagrad_row(label_emb, label_g2, y, -active * glab, config.lr)
                gw = gdoc / len(words)
                for w in words:
                    _adagrad_row(word_emb, word_g2, int(w), gw, config.lr)
        if has_val:
            err = _eval_numpy(v_indptr, v_indices, v_labels, word_emb,
                              label_emb, config.margin, config.neg,
                              config.seed)
        else:
            err = train_loss / n
        epoch_errors[epoch] = err
        if err < best:
            best, since_best = err, 0
            if has_val:
                best_snap = (word_emb.copy(), label_emb.copy())
        elif has_val:
            since_best += 1
            if config.patience > 0 and since_best >= config.patience:
                break
    if best_snap is not None:
        word_emb[:], label_emb[:] = best_snap
    return best
