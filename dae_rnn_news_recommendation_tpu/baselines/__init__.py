from .starspace import (StarSpaceConfig, embed_docs, export_fasttext_format,
                        train_starspace)

__all__ = ["StarSpaceConfig", "train_starspace", "embed_docs",
           "export_fasttext_format"]
