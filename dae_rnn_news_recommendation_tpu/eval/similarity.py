"""Pairwise similarity — the O(N^2) eval kernel, on device and blockwise.

Twin of reference helpers.py:11-50 (pairwise_similarity): cosine or linear-kernel
(dot-product) similarity with optional l1/l2/max row normalization and a zeroed
diagonal. The reference computes the full N x N matrix in one sklearn call on host;
here row blocks stream through the device so N is bounded by host memory for the
output, not HBM — and on a mesh the ring variant (parallel/ring.py) shards the rows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp


def _normalize_host(x, norm):
    """sklearn.preprocessing.normalize semantics (reference helpers.py:42-43)."""
    if norm == "":
        return x
    if norm == "l2":
        denom = np.sqrt((x * x).sum(axis=1, keepdims=True))
    elif norm == "l1":
        denom = np.abs(x).sum(axis=1, keepdims=True)
    elif norm == "max":
        denom = np.abs(x).max(axis=1, keepdims=True)
    else:
        raise ValueError(f"unknown norm: {norm!r}")
    denom = np.where(denom == 0, 1.0, denom)
    return x / denom


def pairwise_similarity(in_df, norm="", metric="cosine", set_diagonal_zero=True,
                        block_size=2048, mesh=None):
    """Pairwise similarity matrix [N, N] as float32 ndarray.

    :param in_df: ndarray / scipy sparse / list — rows are items
    :param metric: 'cosine' | 'linear kernel' (dot product, reference helpers.py:33)
    :param mesh: optional jax Mesh — uses the ring-allgather collective instead of
        host-blocked streaming (rows must divide the mesh size)
    """
    assert metric in ("cosine", "linear kernel")
    x = in_df.toarray() if sp.issparse(in_df) else np.asarray(in_df, np.float32)
    x = np.asarray(x, np.float32)
    x = _normalize_host(x, norm)

    if mesh is not None:
        from ..parallel.ring import ring_pairwise_similarity

        out = np.asarray(ring_pairwise_similarity(
            jnp.asarray(x), mesh, normalize=(metric == "cosine"),
            set_diagonal_zero=set_diagonal_zero))
        return out

    n = x.shape[0]
    if metric == "cosine" and norm != "l2":  # l2-normed rows are already unit length
        x = _normalize_host(x, "l2")

    xd = jnp.asarray(x)

    @jax.jit
    def block(rows):
        return jnp.matmul(rows, xd.T, precision=jax.lax.Precision.HIGHEST)

    out = np.empty((n, n), np.float32)
    for start in range(0, n, block_size):
        out[start : start + block_size] = np.asarray(block(xd[start : start + block_size]))
    if set_diagonal_zero:
        np.fill_diagonal(out, 0.0)
    return out


def streaming_top1(data, metric="cosine", n_rows=5, block_size=2048):
    """Most-similar item (self excluded) for the first `n_rows` rows, without the
    [N, N] matrix: the query block stays on device while the corpus streams
    through in blocks. Returns (argmax [n_rows] int, score [n_rows] float32).

    Sparse inputs densify one block at a time, so host memory stays O(block * F).
    """
    assert metric in ("cosine", "linear kernel")
    sparse_in = sp.issparse(data)
    x = data.tocsr() if sparse_in else np.asarray(data, np.float32)
    n = x.shape[0]
    n_rows = min(n_rows, n)

    if metric == "cosine":
        if sparse_in:
            inv = np.sqrt(np.asarray(x.multiply(x).sum(axis=1)).ravel())
            inv = 1.0 / np.where(inv == 0, 1.0, inv)
        else:
            x = _normalize_host(x, "l2")

    def rows(start, stop):
        out = np.asarray(x[start:stop].todense(), np.float32) if sparse_in \
            else x[start:stop]
        if sparse_in and metric == "cosine":
            out = out * inv[start:stop, None]
        return jnp.asarray(out)

    q = rows(0, n_rows)

    @jax.jit
    def block_scores(corpus):
        return jnp.matmul(q, corpus.T, precision=jax.lax.Precision.HIGHEST)

    best_idx = np.zeros(n_rows, np.int64)
    best_val = np.full(n_rows, -np.inf, np.float32)
    for start in range(0, n, block_size):
        stop = min(start + block_size, n)
        s = np.array(block_scores(rows(start, stop)))  # writable host copy
        # zero the self slot, exactly like the full-matrix path's zeroed
        # diagonal (so a row whose best off-diagonal score is negative picks
        # itself at 0.0 on both paths — reference helpers.py:47 semantics)
        for i in range(n_rows):
            j = i - start
            if 0 <= j < s.shape[1]:
                s[i, j] = 0.0
        arg = s.argmax(axis=1)
        val = s[np.arange(n_rows), arg]
        upd = val > best_val
        best_idx[upd] = arg[upd] + start
        best_val[upd] = val[upd]
    return best_idx, best_val


def nearest_neighbor_report_from_top1(article_df, embed_top1, count_top1, top=5):
    """Report rows from precomputed (argmax, score) pairs — the streaming path's
    equivalent of nearest_neighbor_report."""
    embed_idx, embed_score = embed_top1
    count_idx, _ = count_top1
    rows = []
    for i in range(min(top, len(embed_idx))):
        rows.append({
            "article": article_df[["category_publish_name", "title"]].iloc[i].to_dict(),
            "most_similar_by_count": article_df[["category_publish_name", "title"]]
                .iloc[int(count_idx[i])].to_dict(),
            "most_similar_by_embedding": article_df[["category_publish_name", "title"]]
                .iloc[int(embed_idx[i])].to_dict(),
            "score": float(embed_score[i]),
        })
    return rows


def nearest_neighbor_report(article_df, sim_embed, sim_count, top=5):
    """Top-similar-article printout rows (reference main_autoencoder.py:352-360):
    for the first `top` articles, the most similar article under the count-vector
    metric and under the learned embedding."""
    count_argmax = np.nanargmax(sim_count, 1)
    embed_argmax = np.nanargmax(sim_embed, 1)
    embed_score = sim_embed[np.arange(len(embed_argmax)), embed_argmax]
    return nearest_neighbor_report_from_top1(
        article_df, (embed_argmax, embed_score), (count_argmax, None), top=top)
