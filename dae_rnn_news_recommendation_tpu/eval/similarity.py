"""Pairwise similarity — the O(N^2) eval kernel, on device and blockwise.

Twin of reference helpers.py:11-50 (pairwise_similarity): cosine or linear-kernel
(dot-product) similarity with optional l1/l2/max row normalization and a zeroed
diagonal. The reference computes the full N x N matrix in one sklearn call on host;
here row blocks stream through the device so N is bounded by host memory for the
output, not HBM — and on a mesh the ring variant (parallel/ring.py) shards the rows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp


def _normalize_host(x, norm):
    """sklearn.preprocessing.normalize semantics (reference helpers.py:42-43)."""
    if norm == "":
        return x
    if norm == "l2":
        denom = np.sqrt((x * x).sum(axis=1, keepdims=True))
    elif norm == "l1":
        denom = np.abs(x).sum(axis=1, keepdims=True)
    elif norm == "max":
        denom = np.abs(x).max(axis=1, keepdims=True)
    else:
        raise ValueError(f"unknown norm: {norm!r}")
    denom = np.where(denom == 0, 1.0, denom)
    return x / denom


def pairwise_similarity(in_df, norm="", metric="cosine", set_diagonal_zero=True,
                        block_size=2048, mesh=None):
    """Pairwise similarity matrix [N, N] as float32 ndarray.

    :param in_df: ndarray / scipy sparse / list — rows are items
    :param metric: 'cosine' | 'linear kernel' (dot product, reference helpers.py:33)
    :param mesh: optional jax Mesh — uses the ring-allgather collective instead of
        host-blocked streaming (rows must divide the mesh size)
    """
    assert metric in ("cosine", "linear kernel")
    x = in_df.toarray() if sp.issparse(in_df) else np.asarray(in_df, np.float32)
    x = np.asarray(x, np.float32)
    x = _normalize_host(x, norm)

    if mesh is not None:
        from ..parallel.ring import ring_pairwise_similarity

        out = np.asarray(ring_pairwise_similarity(
            jnp.asarray(x), mesh, normalize=(metric == "cosine"),
            set_diagonal_zero=set_diagonal_zero))
        return out

    n = x.shape[0]
    if metric == "cosine" and norm != "l2":  # l2-normed rows are already unit length
        x = _normalize_host(x, "l2")

    xd = jnp.asarray(x)

    @jax.jit
    def block(rows):
        return jnp.matmul(rows, xd.T, precision=jax.lax.Precision.HIGHEST)

    out = np.empty((n, n), np.float32)
    for start in range(0, n, block_size):
        out[start : start + block_size] = np.asarray(block(xd[start : start + block_size]))
    if set_diagonal_zero:
        np.fill_diagonal(out, 0.0)
    return out


def nearest_neighbor_report(article_df, sim_embed, sim_count, top=5):
    """Top-similar-article printout rows (reference main_autoencoder.py:352-360):
    for the first `top` articles, the most similar article under the count-vector
    metric and under the learned embedding."""
    count_argmax = np.nanargmax(sim_count, 1)
    embed_argmax = np.nanargmax(sim_embed, 1)
    rows = []
    for i in range(min(top, len(embed_argmax))):
        v = embed_argmax[i]
        rows.append({
            "article": article_df[["category_publish_name", "title"]].iloc[i].to_dict(),
            "most_similar_by_count": article_df[["category_publish_name", "title"]]
                .iloc[count_argmax[i]].to_dict(),
            "most_similar_by_embedding": article_df[["category_publish_name", "title"]]
                .iloc[v].to_dict(),
            "score": float(sim_embed[i, v]),
        })
    return rows
