"""AUROC + distribution plots for related-vs-unrelated article similarity.

Twin of reference helpers.py:53-135 (visualize_scatter, visualize_pairwise_similarity):
labels with value < 0 are treated as missing and masked out; "related" pairs share a
label, "unrelated" pairs differ; the similarity scores of the two populations feed an
ROC curve (AUROC is the headline quality metric) plus a boxplot/scatter panel.

`related_unrelated_auroc` exposes the number without matplotlib so quality checks can
run headless; the visualize_* functions render the reference's two-panel figure.
"""

import numpy as np
import pandas as pd
import scipy.sparse as sparse
from sklearn.metrics import auc, roc_curve


def _plt():
    """Lazy pyplot import: keeps `related_unrelated_auroc` matplotlib-free and avoids
    forcing a backend on importers (headless envs auto-select Agg)."""
    from matplotlib import pyplot as plt

    return plt


def _related_unrelated(labels, sim):
    labels = np.asarray(labels)
    assert labels.shape[0] == sim.shape[0]
    assert sim.shape[0] == sim.shape[1]
    if labels.ndim == 1:
        labels = labels[:, None]
    not_nan = np.squeeze((labels[None, :] >= 0) & (labels[:, None] >= 0))
    eq = np.squeeze(labels[None, :] == labels[:, None])
    related_mask = sparse.coo_matrix(np.tril(eq & not_nan, -1))
    related = sim[related_mask.row, related_mask.col]
    unrelated_mask = sparse.coo_matrix(np.tril(~eq & not_nan, -1))
    unrelated = sim[unrelated_mask.row, unrelated_mask.col]
    return related, unrelated


def related_unrelated_auroc(labels, sim):
    """AUROC of 'same-label pair' vs similarity score (reference helpers.py:99-101)."""
    related, unrelated = _related_unrelated(labels, sim)
    if len(related) == 0 or len(unrelated) == 0:
        return float("nan")
    y = ["Related"] * len(related) + ["Unrelated"] * len(unrelated)
    fpr, tpr, _ = roc_curve(y, np.concatenate([related, unrelated]),
                            pos_label="Related")
    return auc(fpr, tpr)


def visualize_pairwise_similarity(labels, pairwise_similarity_metrics, plot="boxplot",
                                  title=None, figsize=(16, 9), save_path=None,
                                  max_data_limit=int(1e7), **plot_kwargs):
    """ROC panel + boxplot/scatter panel (reference helpers.py:79-135). Returns the
    AUROC."""
    assert plot in ("scatter", "boxplot")
    related, unrelated = _related_unrelated(labels, pairwise_similarity_metrics)

    if len(related) == 0 or len(unrelated) == 0:
        # degenerate label structure (e.g. all labels missing): no curve to draw
        return float("nan")
    y = ["Related"] * len(related) + ["Unrelated"] * len(unrelated)
    fpr, tpr, _ = roc_curve(y, np.concatenate([related, unrelated]),
                            pos_label="Related")
    auroc = auc(fpr, tpr)

    plt = _plt()
    plt.figure(figsize=figsize)
    plt.subplot(121)
    plt.plot(fpr, tpr, color="darkorange", lw=2,
             label=f"ROC curve (area = {auroc:0.2f})")
    plt.plot([0, 1], [0, 1], color="navy", lw=2, linestyle="--")
    plt.xlim([0.0, 1.0])
    plt.ylim([0.0, 1.05])
    plt.xlabel("False Positive Rate")
    plt.ylabel("True Positive Rate")
    plt.legend(loc="lower right")
    if title is not None:
        plt.title("ROC - " + title)

    rng = np.random.default_rng(0)
    if len(related) > max_data_limit:
        related = rng.choice(related, max_data_limit, replace=False)
    if len(unrelated) > max_data_limit:
        unrelated = rng.choice(unrelated, max_data_limit, replace=False)

    plt.subplot(122)
    if plot == "scatter":
        plt.scatter(["Related"] * len(related), related, **plot_kwargs)
        plt.scatter(["Unrelated"] * len(unrelated), unrelated, **plot_kwargs)
    else:
        plt.boxplot([related, unrelated], **plot_kwargs)
        plt.xticks([1, 2], labels=["Related", "Unrelated"])
    if title is not None:
        plt.title(title)

    if save_path is not None:
        plt.savefig(save_path)
    plt.close()
    return auroc


def _box_stats_from_hist(hist, edges, label):
    """matplotlib bxp() stats dict from a binned score population: weighted
    quantiles at bin centers, 1.5-IQR whiskers capped to occupied bins."""
    h = np.asarray(hist, np.float64)
    centers = (np.asarray(edges[:-1]) + np.asarray(edges[1:])) / 2.0
    total = h.sum()
    cum = np.cumsum(h)

    def quantile(q):
        return float(centers[np.searchsorted(cum, q * total)])

    q1, med, q3 = quantile(0.25), quantile(0.5), quantile(0.75)
    iqr = q3 - q1
    occupied = centers[h > 0]
    lo = float(occupied[occupied >= q1 - 1.5 * iqr].min())
    hi = float(occupied[occupied <= q3 + 1.5 * iqr].max())
    return {"label": label, "med": med, "q1": q1, "q3": q3,
            "whislo": lo, "whishi": hi,
            "mean": float((h * centers).sum() / total), "fliers": []}


def roc_points_from_histograms(hist_rel, hist_unrel):
    """(fpr, tpr) curve points from binned related/unrelated score histograms:
    sweeping the threshold down through the bins, tpr/fpr are suffix sums of the
    related/unrelated mass — the exact ROC of the quantized scores."""
    r = np.asarray(hist_rel, np.float64)
    u = np.asarray(hist_unrel, np.float64)
    # counts >= each bin's lower edge, descending threshold order
    r_ge = np.cumsum(r[::-1])[::-1]
    u_ge = np.cumsum(u[::-1])[::-1]
    tpr = np.concatenate([[0.0], r_ge[::-1] / max(r.sum(), 1.0)])
    fpr = np.concatenate([[0.0], u_ge[::-1] / max(u.sum(), 1.0)])
    return fpr, tpr


def visualize_similarity_from_histograms(hist_rel, hist_unrel, edges,
                                         title=None, figsize=(16, 9),
                                         save_path=None):
    """The reference's two-panel ROC+boxplot figure (helpers.py:79-135) rendered
    from streaming_auroc's histograms instead of raw pair scores — the figure the
    scaling-safe eval path produces when the full pair populations never exist.
    Returns the AUROC (exact rank statistic of the binned scores)."""
    from .streaming_auroc import auroc_from_histograms

    r_total = float(np.sum(hist_rel))
    u_total = float(np.sum(hist_unrel))
    if r_total == 0 or u_total == 0:
        return float("nan")
    auroc = auroc_from_histograms(hist_rel, hist_unrel)
    fpr, tpr = roc_points_from_histograms(hist_rel, hist_unrel)

    plt = _plt()
    plt.figure(figsize=figsize)
    plt.subplot(121)
    plt.plot(fpr, tpr, color="darkorange", lw=2,
             label=f"ROC curve (area = {auroc:0.2f})")
    plt.plot([0, 1], [0, 1], color="navy", lw=2, linestyle="--")
    plt.xlim([0.0, 1.0])
    plt.ylim([0.0, 1.05])
    plt.xlabel("False Positive Rate")
    plt.ylabel("True Positive Rate")
    plt.legend(loc="lower right")
    if title is not None:
        plt.title("ROC - " + title)

    ax = plt.subplot(122)
    ax.bxp([_box_stats_from_hist(hist_rel, edges, "Related"),
            _box_stats_from_hist(hist_unrel, edges, "Unrelated")],
           showfliers=False)
    if title is not None:
        plt.title(title)

    if save_path is not None:
        plt.savefig(save_path)
    plt.close()
    return auroc


def visualize_scatter(data_2d, label, title, figsize=(20, 20), save_path=None):
    """2-D scatter colored by label (reference helpers.py:53-76)."""
    plt = _plt()
    plt.figure(figsize=figsize)
    plt.grid()
    codes, uniques = pd.factorize(label)
    nb = max(len(uniques), 1)
    for label_id in np.unique(codes):
        pts = data_2d[codes == label_id]
        plt.scatter(pts[:, 0], pts[:, 1], marker="o",
                    color=plt.cm.gist_ncar((label_id + 1) / float(nb)),
                    linewidth=1, alpha=0.8, label=str(uniques[label_id]))
    plt.legend(loc="best")
    if title is not None:
        plt.title(title)
    if save_path is not None:
        plt.savefig(save_path)
    plt.close()
