"""Streaming related-vs-unrelated AUROC: the O(N^2) eval without the N^2 matrix.

The reference's eval materializes the full pairwise-similarity matrix and hands
every lower-triangle score to sklearn's roc_curve (helpers.py:45, :79-101) — 4 TB
of float32 at N=1M, the scaling wall SURVEY §5.7 names. Here similarity blocks are
computed on device (MXU matmuls over l2-normalized rows), every score is binned
into fixed-width histograms of the related / unrelated populations, and only two
[bins] count vectors ever leave the device. AUROC is then the exact rank statistic
of the binned scores:

    AUROC = P(s_rel > s_unrel) + 0.5 * P(s_rel == s_unrel)
          = sum_k U_k * (R_{>k} + 0.5 * R_k) / (R * U)

so the only approximation is the bin quantization (1e-3-ish at 8k bins over
[-1, 1]; tested against sklearn on dense data).

Counting is exact: histograms accumulate on device in int32 and are flushed to
float64 host totals before the int32 pair budget (2^31) could overflow, so there
is no float32 saturation at any N; the flush cadence also bounds host<->device
syncs at one per ~2^31 pairs instead of one per block pair. Scores falling
outside `value_range` are detected and raised on — silent edge-bin clipping
would quietly bias the statistic.

Pair semantics match eval/plots.py:_related_unrelated exactly: strictly-lower-
triangle pairs, rows with label < 0 excluded, related iff labels equal.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from ..parallel.mesh import _shard_map, dispatch_lock, pcast_varying

_FLUSH_PAIRS = 2**31 - 2**26  # flush device int32 accumulators before overflow


@functools.partial(jax.jit, static_argnames=("bins", "diag"), donate_argnums=(0, 1, 2))
def _block_hists(acc_rel, acc_unrel, acc_oob, xi, xj, li, lj, lo, hi, bins, diag):
    """Accumulate one block pair's related/unrelated score histograms (int32,
    threaded through so nothing syncs per call) plus an out-of-range counter.

    li/lj are [L, block]: the similarity block is label-independent, so all L
    label sets share one MXU matmul sweep (histograms are [L, bins])."""
    s = jnp.matmul(xi, xj.T, precision=jax.lax.Precision.HIGHEST)
    base = jnp.ones(s.shape, bool)
    if diag:  # same block: keep strictly-lower-triangle pairs only
        base = jnp.tril(base, -1)

    idx = jnp.clip(((s - lo) / (hi - lo) * bins).astype(jnp.int32), 0, bins - 1)
    idx = idx.ravel()
    n_labels = li.shape[0]
    for l in range(n_labels):  # static unroll; L is small (label kinds)
        valid = base & (li[l][:, None] >= 0) & (lj[l][None, :] >= 0)
        eq = li[l][:, None] == lj[l][None, :]
        rel = (valid & eq).ravel().astype(jnp.int32)
        unrel = (valid & ~eq).ravel().astype(jnp.int32)
        acc_rel = acc_rel.at[l, idx].add(rel)
        acc_unrel = acc_unrel.at[l, idx].add(unrel)
        oob = valid & ((s < lo) | (s >= hi))
        acc_oob = acc_oob.at[l].add(jnp.sum(oob.astype(jnp.int32)))
    return acc_rel, acc_unrel, acc_oob


def _resolve_value_range(metric, value_range):
    """(lo_req, hi_req, lo, hi): the caller's requested range plus the slightly
    widened binning range so exact endpoints never clip."""
    assert metric in ("cosine", "linear kernel")
    if value_range is None:
        if metric != "cosine":
            raise ValueError("value_range is required for metric='linear kernel' "
                             "(dot products are unbounded)")
        value_range = (-1.0, 1.0)
    lo_req, hi_req = float(value_range[0]), float(value_range[1])
    span = hi_req - lo_req
    return lo_req, hi_req, lo_req - 1e-5 * span, hi_req + 1e-5 * span


def _finalize_histograms(hist_rel, hist_unrel, oob_total, lo_req, hi_req, lo, hi,
                         bins, single, return_histograms):
    """Shared epilogue: OOB guard, per-label AUROCs, optional histogram return."""
    if oob_total.any():
        raise ValueError(
            f"{int(oob_total.max())} pair scores fell outside "
            f"value_range=({lo_req:.6g}, {hi_req:.6g}) — widen it; silently "
            "clipping them into the edge bins would bias the AUROC")
    aurocs = [auroc_from_histograms(hist_rel[l], hist_unrel[l])
              for l in range(hist_rel.shape[0])]
    auroc = aurocs[0] if single else aurocs
    if return_histograms:
        edges = np.linspace(lo, hi, bins + 1)
        if single:
            return auroc, hist_rel[0], hist_unrel[0], edges
        return auroc, hist_rel, hist_unrel, edges
    return auroc


def _remap_label_matrix(labels, n):
    """[L, N] int32 label matrix with each set remapped to contiguous codes
    (equality-only semantics, immune to 64-bit hash labels); negatives stay -1.
    Returns (label_mat, single) where single marks a 1-D `labels` input."""
    label_mat = np.atleast_2d(np.asarray(labels))
    single = np.asarray(labels).ndim == 1
    assert label_mat.shape[1] == n, (label_mat.shape, n)
    remapped = np.full(label_mat.shape, -1, np.int32)
    for l in range(label_mat.shape[0]):
        nonneg = label_mat[l] >= 0
        if nonneg.any():
            remapped[l, nonneg] = np.unique(label_mat[l, nonneg],
                                            return_inverse=True)[1]
    return remapped, single


def auroc_from_histograms(hist_rel, hist_unrel):
    """Exact AUROC of binned scores (ties within a bin count half)."""
    r = np.asarray(hist_rel, np.float64)
    u = np.asarray(hist_unrel, np.float64)
    r_total, u_total = r.sum(), u.sum()
    if r_total == 0 or u_total == 0:
        return float("nan")
    # related counts strictly above each bin
    r_above = r_total - np.cumsum(r)
    return float(np.sum(u * (r_above + 0.5 * r)) / (r_total * u_total))


def streaming_auroc(embeddings, labels, metric="cosine", block=2048, bins=8192,
                    value_range=None, return_histograms=False):
    """Related-vs-unrelated AUROC over all O(N^2) pairs in O(N^2 / block^2) device
    calls and O(bins) memory.

    :param embeddings: [N, D] float array or scipy sparse matrix — sparse rows are
        densified one block at a time, so wide bag-of-words inputs never
        materialize as a dense [N, F] host array either
    :param labels: [N] ints, or a sequence of L such vectors ([L, N]) to score
        several label kinds in ONE pair sweep (the similarity blocks are
        label-independent, so extra label sets are nearly free); < 0 = missing
        (row excluded, reference helpers.py:91-97). Values are remapped to
        contiguous int32 internally, so 64-bit hash labels are safe.
    :param metric: 'cosine' (rows l2-normalized; scores in [-1, 1]) or
        'linear kernel' (raw dot products; pass value_range)
    :param value_range: (lo, hi) score range for binning; required for
        'linear kernel', defaults to (-1, 1) for cosine. Raises if any valid
        pair's score falls outside it.
    :return: auroc float (list of L floats for multiple label sets), or with
        return_histograms: (auroc, hist_related, hist_unrelated, bin_edges) where
        the histograms are [bins] (or [L, bins])
    """
    lo_req, hi_req, lo, hi = _resolve_value_range(metric, value_range)

    sparse_in = sp.issparse(embeddings)
    x = embeddings.tocsr() if sparse_in else np.asarray(embeddings, np.float32)
    n = x.shape[0]

    label_mat, single = _remap_label_matrix(labels, n)
    n_labels = label_mat.shape[0]

    if metric == "cosine":
        if sparse_in:
            inv = np.sqrt(np.asarray(x.multiply(x).sum(axis=1)).ravel())
            inv = 1.0 / np.where(inv == 0, 1.0, inv)
        else:
            denom = np.sqrt((x * x).sum(axis=1, keepdims=True))
            x = x / np.where(denom == 0, 1.0, denom)

    # pad to a block multiple with excluded rows so every device call has one shape
    n_pad = int(-(-n // block) * block)
    label_mat = np.concatenate(
        [label_mat, np.full((n_labels, n_pad - n), -1, np.int32)], axis=1)

    def rows(start):
        """One [block, D] dense float32 row block from sparse input (normalized,
        padded past n with zeros)."""
        assert sparse_in
        stop = min(start + block, n)
        out = np.asarray(x[start:stop].todense(), np.float32)
        if metric == "cosine":
            out *= inv[start:stop, None]
        if stop - start < block:
            out = np.concatenate(
                [out, np.zeros((block - (stop - start), x.shape[1]), np.float32)])
        return jnp.asarray(out)

    ld = jnp.asarray(label_mat)
    hist_rel = np.zeros((n_labels, bins), np.float64)
    hist_unrel = np.zeros((n_labels, bins), np.float64)
    oob_total = np.zeros(n_labels, np.int64)

    def fresh():
        return (jnp.zeros((n_labels, bins), jnp.int32),
                jnp.zeros((n_labels, bins), jnp.int32),
                jnp.zeros(n_labels, jnp.int32))

    # dense inputs go to the device once; sparse inputs densify per row block
    # (column blocks re-densify per pass — memory stays O(block * D) on host)
    xd = None if sparse_in else jnp.asarray(
        np.concatenate([x, np.zeros((n_pad - n, x.shape[1]), np.float32)])
        if n_pad != n else x)

    def block_of(start):
        return rows(start) if sparse_in else xd[start : start + block]

    acc = fresh()
    pairs_in_acc = 0
    for bi in range(0, n_pad, block):
        xi, li = block_of(bi), ld[:, bi : bi + block]
        for bj in range(0, bi + block, block):
            if pairs_in_acc + block * block > _FLUSH_PAIRS:
                hist_rel += np.asarray(acc[0], np.float64)
                hist_unrel += np.asarray(acc[1], np.float64)
                oob_total += np.asarray(acc[2], np.int64)
                acc = fresh()
                pairs_in_acc = 0
            xj = xi if bj == bi else block_of(bj)  # diagonal block already held
            acc = _block_hists(*acc, xi, xj, li,
                               ld[:, bj : bj + block], lo, hi, bins,
                               diag=(bi == bj))
            pairs_in_acc += block * block
    hist_rel += np.asarray(acc[0], np.float64)
    hist_unrel += np.asarray(acc[1], np.float64)
    oob_total += np.asarray(acc[2], np.int64)

    return _finalize_histograms(hist_rel, hist_unrel, oob_total, lo_req, hi_req,
                                lo, hi, bins, single, return_histograms)


_LO_BITS = 20  # ring accumulators: counts split into (hi << 20) + lo int32 pairs


def ring_streaming_auroc(embeddings, labels, mesh, metric="cosine", bins=8192,
                         value_range=None, axis_name="data",
                         return_histograms=False):
    """streaming_auroc distributed over a device mesh with the ppermute ring.

    Row blocks shard over `axis_name` and rotate with ppermute — the causal
    ring-attention schedule: only floor(p/2)+1 hops run (not p), because an
    unordered block pair {i, j} is processed exactly once, by whichever device
    holds it first, with the tile transposed when the travelling block is the
    lower-triangle side. Each step every device does one [n_loc, n_loc] MXU
    matmul + histogram scatter; only [n_loc, D] tiles ride the ring and only
    the [L, bins] histograms are psum'd at the end. Pair semantics, binning,
    and the exact rank statistic match streaming_auroc bit-for-bit (tested);
    counting stays exact at any N via split int32 accumulators (lo 20 bits +
    spill each step, for histograms AND the out-of-range guard), good to 2^51
    pairs per bin.

    :param embeddings: [N, D] dense array (encode first; the mesh path is for
        the post-encode eval, embeddings are narrow). Padded internally to a
        mesh multiple with excluded rows.
    :param labels: as streaming_auroc — [N] or [L, N], < 0 = missing.
    :return: as streaming_auroc.
    """
    lo_req, hi_req, lo, hi = _resolve_value_range(metric, value_range)

    x = np.asarray(embeddings, np.float32)
    n, d = x.shape
    label_mat, single = _remap_label_matrix(labels, n)
    n_labels = label_mat.shape[0]

    if metric == "cosine":
        denom = np.sqrt((x * x).sum(axis=1, keepdims=True))
        x = x / np.where(denom == 0, 1.0, denom)

    n_dev = mesh.shape[axis_name]
    n_pad = int(-(-n // n_dev) * n_dev)
    if n_pad != n:
        x = np.concatenate([x, np.zeros((n_pad - n, d), np.float32)])
        label_mat = np.concatenate(
            [label_mat, np.full((n_labels, n_pad - n), -1, np.int32)], axis=1)
    n_loc = n_pad // n_dev
    assert n_loc * n_loc + (1 << _LO_BITS) < 2**31, (
        f"{n_loc} rows/device overflows the per-step int32 budget; "
        "use a bigger mesh")

    mask_lo = (1 << _LO_BITS) - 1
    half = n_dev // 2
    n_steps = half + 1 if n_dev % 2 == 0 else (n_dev - 1) // 2 + 1
    even = n_dev % 2 == 0

    def local_fn(local, llab):
        # local [n_loc, D]; llab [L, n_loc]
        me = jax.lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        ar = jnp.arange(n_loc)

        def body(s, carry):
            block, blab, lo_h, hi_h, ob_lo, ob_hi = carry
            src = (me - s) % n_dev
            # orientation: the travelling block is the column side when it is
            # the earlier block (src < me, plus the s=0 diagonal); when it is
            # the later block (src > me) the pair {src, me} belongs to src's
            # rows, so the tile is transposed — that pair is then NOT computed
            # again by device src (its mirror step p-s is outside the loop).
            swap = src > me
            scores0 = jnp.matmul(local, block.T,
                                 precision=jax.lax.Precision.HIGHEST)
            scores = jnp.where(swap, scores0.T, scores0)
            row_g = jnp.where(swap, src, me) * n_loc + ar[:, None]   # [n_loc,1]
            col_g = jnp.where(swap, me, src) * n_loc + ar[None, :]   # [1,n_loc]
            rlab = jnp.where(swap, blab, llab)
            clab = jnp.where(swap, llab, blab)
            # even p: the antipodal pair {me, me±p/2} is seen by both ends at
            # s=p/2 — only the lower-half device processes it
            active = jnp.asarray(True) if not even else (
                (s != half) | (me < half))
            idx = jnp.clip(((scores - lo) / (hi - lo) * bins).astype(jnp.int32),
                           0, bins - 1).ravel()
            tri = (row_g > col_g) & active  # strictly-lower-triangle pairs
            oob_m = (scores < lo) | (scores >= hi)
            for l in range(n_labels):  # static unroll; L is small
                valid = tri & (rlab[l][:, None] >= 0) & (clab[l][None, :] >= 0)
                eq = rlab[l][:, None] == clab[l][None, :]
                lo_h = lo_h.at[0, l, idx].add(
                    (valid & eq).ravel().astype(jnp.int32))
                lo_h = lo_h.at[1, l, idx].add(
                    (valid & ~eq).ravel().astype(jnp.int32))
                ob_lo = ob_lo.at[l].add(
                    jnp.sum((valid & oob_m).astype(jnp.int32)))
            # spill so per-bin/per-label lo never exceeds n_loc^2 + 2^20 < 2^31
            hi_h = hi_h + (lo_h >> _LO_BITS)
            lo_h = lo_h & mask_lo
            ob_hi = ob_hi + (ob_lo >> _LO_BITS)
            ob_lo = ob_lo & mask_lo
            block = jax.lax.ppermute(block, axis_name, perm)
            blab = jax.lax.ppermute(blab, axis_name, perm)
            return block, blab, lo_h, hi_h, ob_lo, ob_hi

        lo_h = jnp.zeros((2, n_labels, bins), jnp.int32)
        hi_h = jnp.zeros((2, n_labels, bins), jnp.int32)
        ob_lo = jnp.zeros(n_labels, jnp.int32)
        ob_hi = jnp.zeros(n_labels, jnp.int32)
        # zeros are device-invariant; the loop carry must match the varying
        # values ppermute/scatter produce (same dance as parallel/ring.py)
        lo_h, hi_h, ob_lo, ob_hi = (
            pcast_varying(v, axis_name)
            for v in (lo_h, hi_h, ob_lo, ob_hi))
        carry = jax.lax.fori_loop(0, n_steps, body,
                                  (local, llab, lo_h, hi_h, ob_lo, ob_hi))
        lo_h, hi_h, ob_lo, ob_hi = carry[2:]
        return (jax.lax.psum(lo_h, axis_name), jax.lax.psum(hi_h, axis_name),
                jax.lax.psum(ob_lo, axis_name), jax.lax.psum(ob_hi, axis_name))

    from jax.sharding import PartitionSpec as P

    # the canonical compat alias (parallel/mesh): bare `jax.shard_map` only
    # exists on jax >= 0.6, and this module must import on 0.4.x
    fn = _shard_map(local_fn, mesh=mesh,
                    in_specs=(P(axis_name, None), P(None, axis_name)),
                    out_specs=(P(), P(), P(), P()))
    # the ring program is a collective; an eval sweep runs concurrently with
    # serving threads (fleet soaks, churn rollouts) sharing this host's one
    # mesh, so the dispatch serializes through the process-wide lock exactly
    # like every sharded serve-fn call (see parallel/mesh.MESH_DISPATCH_LOCK)
    with dispatch_lock():
        lo_h, hi_h, ob_lo, ob_hi = fn(jnp.asarray(x), jnp.asarray(label_mat))
        jax.block_until_ready((lo_h, hi_h, ob_lo, ob_hi))
    hist = (np.asarray(lo_h, np.float64)
            + np.asarray(hi_h, np.float64) * float(1 << _LO_BITS))
    hist_rel, hist_unrel = hist[0], hist[1]
    oob = (np.asarray(ob_lo, np.int64)
           + np.asarray(ob_hi, np.int64) * (1 << _LO_BITS))

    return _finalize_histograms(hist_rel, hist_unrel, oob, lo_req, hi_req,
                                lo, hi, bins, single, return_histograms)
