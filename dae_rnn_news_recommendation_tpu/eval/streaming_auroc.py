"""Streaming related-vs-unrelated AUROC: the O(N^2) eval without the N^2 matrix.

The reference's eval materializes the full pairwise-similarity matrix and hands
every lower-triangle score to sklearn's roc_curve (helpers.py:45, :79-101) — 4 TB
of float32 at N=1M, the scaling wall SURVEY §5.7 names. Here similarity blocks are
computed on device (MXU matmuls over l2-normalized rows), every score is binned
into fixed-width histograms of the related / unrelated populations, and only two
[bins] count vectors ever leave the device. AUROC is then the exact rank statistic
of the binned scores:

    AUROC = P(s_rel > s_unrel) + 0.5 * P(s_rel == s_unrel)
          = sum_k U_k * (R_{>k} + 0.5 * R_k) / (R * U)

so the only approximation is the bin quantization (1e-3-ish at 8k bins over
[-1, 1]; tested against sklearn on dense data).

Counting is exact: histograms accumulate on device in int32 and are flushed to
float64 host totals before the int32 pair budget (2^31) could overflow, so there
is no float32 saturation at any N; the flush cadence also bounds host<->device
syncs at one per ~2^31 pairs instead of one per block pair. Scores falling
outside `value_range` are detected and raised on — silent edge-bin clipping
would quietly bias the statistic.

Pair semantics match eval/plots.py:_related_unrelated exactly: strictly-lower-
triangle pairs, rows with label < 0 excluded, related iff labels equal.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

_FLUSH_PAIRS = 2**31 - 2**26  # flush device int32 accumulators before overflow


@functools.partial(jax.jit, static_argnames=("bins", "diag"), donate_argnums=(0, 1, 2))
def _block_hists(acc_rel, acc_unrel, acc_oob, xi, xj, li, lj, lo, hi, bins, diag):
    """Accumulate one block pair's related/unrelated score histograms (int32,
    threaded through so nothing syncs per call) plus an out-of-range counter."""
    s = jnp.matmul(xi, xj.T, precision=jax.lax.Precision.HIGHEST)
    valid = (li[:, None] >= 0) & (lj[None, :] >= 0)
    if diag:  # same block: keep strictly-lower-triangle pairs only
        valid &= jnp.tril(jnp.ones(s.shape, bool), -1)
    eq = li[:, None] == lj[None, :]

    idx = jnp.clip(((s - lo) / (hi - lo) * bins).astype(jnp.int32), 0, bins - 1)
    idx = idx.ravel()
    rel = (valid & eq).ravel().astype(jnp.int32)
    unrel = (valid & ~eq).ravel().astype(jnp.int32)
    acc_rel = acc_rel.at[idx].add(rel)
    acc_unrel = acc_unrel.at[idx].add(unrel)
    oob = valid & ((s < lo) | (s >= hi))
    acc_oob = acc_oob + jnp.sum(oob.astype(jnp.int32))
    return acc_rel, acc_unrel, acc_oob


def auroc_from_histograms(hist_rel, hist_unrel):
    """Exact AUROC of binned scores (ties within a bin count half)."""
    r = np.asarray(hist_rel, np.float64)
    u = np.asarray(hist_unrel, np.float64)
    r_total, u_total = r.sum(), u.sum()
    if r_total == 0 or u_total == 0:
        return float("nan")
    # related counts strictly above each bin
    r_above = r_total - np.cumsum(r)
    return float(np.sum(u * (r_above + 0.5 * r)) / (r_total * u_total))


def streaming_auroc(embeddings, labels, metric="cosine", block=2048, bins=8192,
                    value_range=None, return_histograms=False):
    """Related-vs-unrelated AUROC over all O(N^2) pairs in O(N^2 / block^2) device
    calls and O(bins) memory.

    :param embeddings: [N, D] float array
    :param labels: [N] ints; < 0 = missing (row excluded, reference helpers.py:91-97).
        Values are remapped to contiguous int32 internally, so 64-bit hash labels
        are safe.
    :param metric: 'cosine' (rows l2-normalized; scores in [-1, 1]) or
        'linear kernel' (raw dot products; pass value_range)
    :param value_range: (lo, hi) score range for binning; required for
        'linear kernel', defaults to (-1, 1) for cosine. Raises if any valid
        pair's score falls outside it.
    :return: auroc, or (auroc, hist_related, hist_unrelated, bin_edges)
    """
    assert metric in ("cosine", "linear kernel")
    if value_range is None:
        if metric != "cosine":
            raise ValueError("value_range is required for metric='linear kernel' "
                             "(dot products are unbounded)")
        value_range = (-1.0, 1.0)
    lo, hi = float(value_range[0]), float(value_range[1])
    # widen a hair so binning of exact endpoints is clip-free
    span = hi - lo
    lo, hi = lo - 1e-5 * span, hi + 1e-5 * span

    x = np.asarray(embeddings, np.float32)
    labels = np.asarray(labels)
    n = x.shape[0]
    # remap to contiguous int32: equality-only semantics, immune to 64-bit labels
    nonneg = labels >= 0
    remapped = np.full(n, -1, np.int32)
    if nonneg.any():
        remapped[nonneg] = np.unique(labels[nonneg], return_inverse=True)[1]
    labels = remapped
    if metric == "cosine":
        denom = np.sqrt((x * x).sum(axis=1, keepdims=True))
        x = x / np.where(denom == 0, 1.0, denom)

    # pad to a block multiple with excluded rows so every device call has one shape
    n_pad = int(-(-n // block) * block)
    if n_pad != n:
        x = np.concatenate([x, np.zeros((n_pad - n, x.shape[1]), np.float32)])
        labels = np.concatenate([labels, np.full(n_pad - n, -1, np.int32)])

    xd = jnp.asarray(x)
    ld = jnp.asarray(labels)
    hist_rel = np.zeros(bins, np.float64)
    hist_unrel = np.zeros(bins, np.float64)
    oob_total = 0

    def fresh():
        return (jnp.zeros(bins, jnp.int32), jnp.zeros(bins, jnp.int32),
                jnp.zeros((), jnp.int32))

    acc = fresh()
    pairs_in_acc = 0
    for bi in range(0, n_pad, block):
        xi, li = xd[bi : bi + block], ld[bi : bi + block]
        for bj in range(0, bi + block, block):
            if pairs_in_acc + block * block > _FLUSH_PAIRS:
                hist_rel += np.asarray(acc[0], np.float64)
                hist_unrel += np.asarray(acc[1], np.float64)
                oob_total += int(acc[2])
                acc = fresh()
                pairs_in_acc = 0
            acc = _block_hists(*acc, xi, xd[bj : bj + block], li,
                               ld[bj : bj + block], lo, hi, bins,
                               diag=(bi == bj))
            pairs_in_acc += block * block
    hist_rel += np.asarray(acc[0], np.float64)
    hist_unrel += np.asarray(acc[1], np.float64)
    oob_total += int(acc[2])

    if oob_total:
        raise ValueError(
            f"{oob_total} pair scores fell outside value_range=({lo:.6g}, {hi:.6g})"
            " — widen it; silently clipping them into the edge bins would bias "
            "the AUROC")

    auroc = auroc_from_histograms(hist_rel, hist_unrel)
    if return_histograms:
        edges = np.linspace(lo, hi, bins + 1)
        return auroc, hist_rel, hist_unrel, edges
    return auroc
