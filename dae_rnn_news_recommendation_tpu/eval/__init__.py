from .similarity import (  # noqa: F401
    pairwise_similarity,
    nearest_neighbor_report,
    nearest_neighbor_report_from_top1,
    streaming_top1,
)
from .plots import (  # noqa: F401
    visualize_pairwise_similarity,
    visualize_scatter,
    visualize_similarity_from_histograms,
    roc_points_from_histograms,
    related_unrelated_auroc,
)
from .streaming_auroc import (  # noqa: F401
    auroc_from_histograms,
    ring_streaming_auroc,
    streaming_auroc,
)
