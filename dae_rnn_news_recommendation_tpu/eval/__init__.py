from .similarity import pairwise_similarity, nearest_neighbor_report  # noqa: F401
from .plots import visualize_pairwise_similarity, visualize_scatter, related_unrelated_auroc  # noqa: F401
from .streaming_auroc import streaming_auroc, auroc_from_histograms  # noqa: F401
