"""Fleet-wide corpus rollout: canary -> health gate -> staged fleet swap.

One `ChurnSupervisor` drives the whole fleet's refresh: it is bound to the
CANARY replica's corpus, so every batch rides the full churn discipline
(frozen-vocab vectorize, micro-batch encode, drift gate, incremental swap
with tail health gate) on ONE replica before the fleet ever sees it. A batch
the canary's gates refuse never leaves the canary — the corpus has already
rolled itself back and the rollout aborts with the fleet untouched.

The staged protocol, per `rollout(batch)`:

  1. CANARY: `churn.ingest(batch)` on the canary corpus. Gate refusal or an
     injected swap crash -> corpus-internal rollback -> rollout aborted,
     fleet at the pre-canary version.
  2. PROBE: one pinned request through the router must come back ok from
     the canary's NEW version — the swap gate judges embeddings, the probe
     judges the serving path end to end. A failed probe reverts the canary.
  3. FLEET: the accepted rows are applied to every other live replica, ONE
     AT A TIME — so at any instant the live corpus versions are a subset of
     {v, v+1}: the version-skew bound the router's per-reply version records
     let the chaos soak audit. Dead replicas are skipped (recorded, never
     silently), draining ones too.
  4. ROLLBACK: a failed fleet-stage swap calls `corpus.revert()` on every
     replica already promoted — canary included — restoring the WHOLE fleet
     to the pre-canary version. Reverts re-install a slot that already
     passed its gate; the shared ledger audit accepts the version number
     repeating only after such a revert record.

`stage_hook(stage)` fires at each stage boundary ("canary", "probe",
"fleet:<name>", "done"/"aborted") — the chaos harness uses it to kill a
replica mid-rollout at a deterministic point.

SHARED-CORPUS fleets (r16: every replica fronts the SAME mesh-sharded
`ServingCorpus`) ride the identical protocol with the fleet stage
collapsing: the canary's churn ingest IS the fleet promote — there is one
corpus, promoted exactly once — so stage 3 records the sharing replicas
under `report["shared"]` instead of re-applying, version skew is zero by
construction, and a rollback is a single `revert()` on the one corpus.
"""

import time

from ..refresh import ChurnConfig, ChurnSupervisor


class FleetSupervisor:
    """Owns the fleet's refresh story: one churn supervisor on the canary,
    staged propagation to everyone else.

    :param params: encoder params shared by the fleet.
    :param config: the model's DAEConfig.
    :param replicas: list of fleet.ServiceReplica; the FIRST is the canary.
    :param router: the fleet.Router (for the canary probe).
    :param churn: ChurnConfig for the canary's supervisor.
    :param probe_deadline_s: budget for the canary probe request.
    :param registry: optional telemetry.MetricsRegistry for rollout
        lifecycle counters (rollouts, rollout_aborts, fleet_reverts) —
        zero-tolerance SLO material: an abort or a whole-fleet revert in a
        supposedly fault-free run is an alert, not a log line.
    """

    def __init__(self, params, config, replicas, router, *, churn=None,
                 probe_deadline_s=5.0, registry=None, **churn_kw):
        assert replicas, "a rollout needs at least one replica"
        self.params = params
        self.config = config
        self.replicas = list(replicas)
        self.router = router
        self.canary = replicas[0]
        self.probe_deadline_s = float(probe_deadline_s)
        self.metrics = registry
        churn_kw.setdefault("registry", registry)
        self.churn = ChurnSupervisor(params, config, self.canary.corpus,
                                     churn=churn or ChurnConfig(),
                                     **churn_kw)
        self.history = []   # one report per bootstrap/rollout

    def _shares_canary_corpus(self, replica):
        """True when `replica` fronts the SAME corpus object as the canary —
        the shared-corpus fleet topology, where the canary's promote IS the
        fleet promote for that replica."""
        return replica.corpus is self.canary.corpus

    # ----------------------------------------------------------- bootstrap
    def bootstrap(self, articles, note="bootstrap"):
        """Seed EVERY replica's corpus with the same full build (all at
        version 1); the canary's goes through the churn supervisor so its
        host-side row mirror starts correct. Replicas sharing the canary's
        corpus are already seeded by that one bootstrap — swapping again
        would double-promote the single corpus."""
        self.churn.bootstrap(articles, note=note)
        shared = []
        for r in self.replicas[1:]:
            if self._shares_canary_corpus(r):
                shared.append(r.name)
                continue
            r.corpus.swap(self.params, articles, note=note)
        report = {"action": "bootstrap", "shared": shared,
                  "versions": {r.name: r.corpus.version
                               for r in self.replicas}}
        self.history.append(report)
        return report

    # ------------------------------------------------------------- rollout
    def rollout(self, batch, note="", stage_hook=None, probe_query=None):
        """One staged fleet refresh of `batch`. Returns a report dict with
        `ok`, `stage` reached, per-replica versions, and what (if anything)
        was rolled back. Never raises on gate refusals or injected swap
        faults — those are recorded aborts; programming errors still
        surface."""
        t0 = time.monotonic()
        hook = stage_hook or (lambda stage: None)
        pre = {r.name: r.corpus.version for r in self.replicas}
        report = {"action": "rollout", "note": note, "pre_versions": dict(pre),
                  "skipped": [], "shared": [], "reverted": [], "ok": False,
                  "stage": "canary"}

        def close(ok, detail):
            report["ok"] = ok
            report["detail"] = detail
            report["versions"] = {r.name: r.corpus.version
                                  for r in self.replicas}
            report["duration_s"] = round(time.monotonic() - t0, 4)
            if self.metrics is not None:
                self.metrics.counter("rollouts").inc()
                if not ok:
                    self.metrics.counter("rollout_aborts").inc()
            hook("done" if ok else "aborted")
            self.history.append(report)
            return report

        # 1. canary: full churn discipline on one replica
        hook("canary")
        try:
            canary_rep = self.churn.ingest(batch, note=f"canary:{note}")
        except Exception as exc:
            # a fatal injected churn fault (ingest/encode) dies BEFORE any
            # swap: nothing promoted, nothing to revert
            return close(False, "canary ingest died: "
                                f"{type(exc).__name__}: {exc}")
        report["canary"] = {k: canary_rep.get(k)
                            for k in ("action", "version", "drift")}
        if canary_rep["action"] == "rollback":
            # the corpus already rolled itself back; fleet untouched
            return close(False, "canary swap rolled back: "
                                + str(canary_rep.get("error", "")))
        promoted = [self.canary]

        # 2. probe: the serving path must answer from the new version
        hook("probe")
        probed = self._probe(probe_query)
        report["probe"] = probed
        if not probed["ok"]:
            self._revert(promoted, report, note)
            return close(False, "canary probe failed: " + probed["detail"])

        # 3. fleet, one replica at a time: live versions stay in {v, v+1}
        for r in self.replicas[1:]:
            hook(f"fleet:{r.name}")
            if self._shares_canary_corpus(r):
                # shared corpus: the canary ingest already promoted the one
                # corpus this replica serves from — applying again would
                # double-swap it. NOT added to `promoted`: a rollback must
                # revert the shared corpus exactly once (the canary entry).
                report["shared"].append(r.name)
                continue
            if r.health() == "dead":
                report["skipped"].append(r.name)
                continue
            ok, detail = self._apply(r, batch, canary_rep, note)
            if not ok:
                self._revert(promoted, report, note)
                report["stage"] = f"fleet:{r.name}"
                return close(False, f"fleet swap failed on {r.name}: "
                                    f"{detail} — fleet reverted to "
                                    "pre-canary")
            promoted.append(r)
        report["stage"] = "fleet"
        covered = len(promoted) + len(report["shared"])
        return close(True, "rolled out to "
                           f"{covered}/{len(self.replicas)} replicas"
                           + (f" ({len(report['shared'])} via shared corpus)"
                              if report["shared"] else "")
                           + (f" (skipped dead: {report['skipped']})"
                              if report["skipped"] else ""))

    def _probe(self, probe_query):
        if probe_query is None:
            return {"ok": True, "detail": "no probe query configured",
                    "version": self.canary.corpus.version}
        fut = self.router.submit(probe_query,
                                 deadline_s=self.probe_deadline_s,
                                 pin=self.canary.name)
        try:
            reply = fut.result(timeout=self.probe_deadline_s * 2)
        except TimeoutError:
            return {"ok": False, "detail": "probe future never resolved",
                    "version": self.canary.corpus.version}
        return {"ok": reply.ok,
                "detail": reply.reason or "ok",
                "version": reply.corpus_version}

    def _apply(self, replica, batch, canary_rep, note):
        """Propagate the canary-accepted refresh to one replica. The canary
        path may have been an incremental append OR a fine-tune-then-rebuild
        (drift trip) — the fleet replica mirrors whichever the canary did,
        with the canary's (possibly fine-tuned) params."""
        params = self.churn.params
        corpus = replica.corpus
        before = corpus.version
        try:
            if "finetune" in canary_rep["action"]:
                # the canary fine-tuned and FULL-rebuilt: mirror that with
                # the fine-tuned params over the canary's resident rows
                from ..refresh.churn import _stack
                corpus.swap(params, _stack(self.churn._store),
                            note=f"fleet:{note}")
            else:
                corpus.swap_incremental(
                    params, batch, max_rows=self.churn.churn.max_rows,
                    max_age_versions=self.churn.churn.max_age_versions,
                    note=f"fleet:{note}")
        except Exception as exc:
            return False, f"{type(exc).__name__}: {exc}"
        led = corpus.ledger[-1]
        if not led["ok"] or corpus.version == before:
            return False, led.get("error", "swap did not promote")
        return True, f"v{corpus.version}"

    def _revert(self, promoted, report, note):
        """Restore every already-promoted replica (canary included) to its
        pre-canary slot. Dead replicas can still revert — the corpus is
        independent of the service — so a killed-then-promoted replica does
        not strand a version."""
        if self.metrics is not None:
            self.metrics.counter("fleet_reverts").inc()
        for r in reversed(promoted):
            r.corpus.revert(note=f"rollout-abort:{note}")
            report["reverted"].append(r.name)
        if promoted and promoted[0] is self.canary:
            # the canary's host row mirror advanced with the ingest; a
            # revert means those rows are NOT resident — drop the last block
            # so a later fine-tune-rebuild trains on what actually serves
            if len(self.churn._store) > 1:
                self.churn._store.pop()

    # ----------------------------------------------------------- reporting
    def summary(self):
        return {"n_rollouts": sum(1 for h in self.history
                                  if h.get("action") == "rollout"),
                "versions": {r.name: r.corpus.version
                             for r in self.replicas},
                "canary": self.canary.name,
                "shared_corpus": [r.name for r in self.replicas[1:]
                                  if self._shares_canary_corpus(r)],
                "churn": self.churn.summary()}
