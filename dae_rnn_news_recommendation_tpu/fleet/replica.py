"""One fleet member: a RecommendationService plus health, kill, and lag.

A `ServiceReplica` owns one in-process `RecommendationService` fronting a
`ServingCorpus`. Two corpus topologies compose with the router/rollout
machinery unchanged:

  * PRIVATE (pass `corpus=` per replica, or single-device hosts): the fleet
    is data-parallel, every replica holds a full corpus copy.
  * SHARED (pass the SAME `ServingCorpus` to every replica — the r16
    default on multi-device hosts, where `serve.corpus.default_corpus`
    builds one mesh-sharded IVF corpus): replicas front one sharded corpus,
    so per-replica corpus memory is total/n_replicas instead of a full
    copy, and the rollout supervisor promotes the shared corpus ONCE
    instead of once per replica.

Either way any replica can answer any query and the router is free to
hedge. The wrapper adds the three things a router needs that a bare service
does not expose:

  * HEALTH, derived rather than declared: `health()` folds the service's
    recorded degraded-mode transitions (the microbatcher's degraded_enter/
    degraded_exit events), batcher-thread liveness, and the replica's own
    draining/dead flags into one of "warm" | "degraded" | "draining" |
    "dead". The router routes to warm and degraded replicas (degraded is an
    explicit, bounded service mode — see serve/service.py), drains around
    draining ones, and skips dead ones.

  * KILL that tells the truth: `kill()` marks the replica dead and stops the
    service, which resolves every in-flight future as shed("shutdown") —
    the honest crash simulation. The router sees those sheds and re-enqueues
    the requests on a live replica with the ORIGINAL absolute deadline, so a
    replica death mid-rollout costs latency, never an outcome.

  * DETERMINISTIC LAG for benches and tests: `lag_s` delays every reply's
    resolution by a fixed amount through a bounded delayer queue — a
    reproducible straggler, which is what makes "hedging reduces p99" an
    assertable fact instead of a scheduling accident.

`fleet.replica` fires at admission: transient faults are absorbed by the
service's own retry discipline downstream; a fatal is an explicit error
reply; a preempt KILLS the replica (the whole point of a preemption) and
sheds the request for the router to retry elsewhere.
"""

import queue
import threading
import time

from ..reliability import faults as _faults
from ..serve.corpus import default_corpus
from ..serve.service import RecommendationService, Reply, ReplyFuture

HEALTH_STATES = ("warm", "degraded", "draining", "dead")


class ServiceReplica:
    """One named replica: service + corpus + health + (optional) lag.

    :param name: stable replica id (router ledger + rollout reports use it).
    :param params: encoder params shared across the fleet.
    :param config: the model's DAEConfig.
    :param corpus: the ServingCorpus this replica fronts. Pass the same
        instance to several replicas to share one (sharded) corpus across
        the fleet. None builds this host's default
        (`serve.corpus.default_corpus`: mesh-sharded IVF on multi-device
        hosts, single-device exact otherwise) privately for this replica.
    :param lag_s: fixed extra delay added to every reply's resolution — the
        deterministic straggler knob (0 = none).
    :param registry: optional telemetry.MetricsRegistry shared with the
        inner service — the replica adds its own admission/lifecycle
        counters (replica_admission_transients, replica_kills) on top of
        the service's request metrics.
    :param service_kw: forwarded to RecommendationService (the replica's
        name is forwarded too unless overridden, so request ids and the
        batcher's trace track carry the replica identity).
    """

    def __init__(self, name, params, config, *, corpus=None, lag_s=0.0,
                 registry=None, **service_kw):
        self.name = str(name)
        self.metrics = registry
        self.corpus = corpus if corpus is not None else default_corpus(config)
        service_kw.setdefault("name", self.name)
        service_kw.setdefault("registry", registry)
        self.service = RecommendationService(params, config, self.corpus,
                                             **service_kw)
        self.lag_s = float(lag_s)
        self._dead = threading.Event()
        self._draining = threading.Event()
        self._delayer = None
        if self.lag_s > 0.0:
            # bounded mailbox + timeout-polled gets: the delayer can never
            # deadlock, and stop() drains whatever is still parked
            self._delay_q = queue.Queue(maxsize=1024)
            self._delayer = threading.Thread(
                target=self._delay_loop, daemon=True,
                name=f"replica-{self.name}-delayer")
            self._delayer.start()

    # ------------------------------------------------------------ admission
    def submit(self, query, deadline_s=None, deadline_at=None,
               request_id=None):
        """Admit one query; returns a ReplyFuture that always resolves.
        The router passes `deadline_at` (absolute) so hedges and retries
        spend the ORIGINAL budget, never a fresh one — and `request_id`
        (its hop-suffixed attempt id) so the Reply stays attributable."""
        rid = "" if request_id is None else str(request_id)
        if self._dead.is_set() or self._draining.is_set():
            fut = ReplyFuture()
            fut._set(Reply(status="shed",
                           reason=("replica_dead" if self._dead.is_set()
                                   else "replica_draining"),
                           request_id=rid))
            return fut
        try:
            _faults.fire("fleet.replica", replica=self.name)
        except _faults.SimulatedPreemption:
            # a preemption takes the whole replica down; the request is shed
            # and the router re-enqueues it on a live replica
            self.kill()
            fut = ReplyFuture()
            fut._set(Reply(status="shed", reason="replica_preempted",
                           request_id=rid))
            return fut
        except _faults.TransientFault:
            # admission blip: the replica still takes the request — the
            # service's own enqueue/batch retry discipline is downstream.
            # Counted, because "absorbed" must not mean "invisible": the
            # zero-tolerance fleet.replica SLO spec burns on this counter.
            if self.metrics is not None:
                self.metrics.counter("replica_admission_transients").inc()
        except _faults.InjectedFault as exc:
            fut = ReplyFuture()
            fut._set(Reply(status="error",
                           reason=f"{type(exc).__name__}: {exc}",
                           request_id=rid))
            return fut
        inner = self.service.submit(query, deadline_s=deadline_s,
                                    deadline_at=deadline_at,
                                    request_id=request_id)
        if self._delayer is None:
            return inner
        outer = ReplyFuture()
        release_at = time.monotonic() + self.lag_s

        def park(reply):
            try:
                self._delay_q.put_nowait((release_at, reply, outer))
            except queue.Full:
                outer._set(reply)  # mailbox full: lag is a simulation knob,
                # never a reason to lose an outcome
        inner.add_done_callback(park)
        return outer

    def _delay_loop(self):
        while True:
            try:
                release_at, reply, outer = self._delay_q.get(timeout=0.05)
            except queue.Empty:
                if self._dead.is_set():
                    return
                continue
            if not self._dead.is_set():
                wait = release_at - time.monotonic()
                if wait > 0:
                    time.sleep(wait)
            outer._set(reply)

    # -------------------------------------------------------------- health
    def health(self):
        """Derived health, never a declared one: dead/draining flags first,
        then batcher-thread liveness (a dead batcher means every queued
        request would hang — that replica is dead no matter what it claims),
        then the service's recorded degraded-mode state (the LAST
        degraded_enter/exit transition — the same ledger the manifest
        ships)."""
        if self._dead.is_set():
            return "dead"
        if self._draining.is_set():
            return "draining"
        if not self.service._thread.is_alive():
            return "dead"
        with self.service._lock:
            last = next((e["event"] for e in reversed(self.service.events)
                         if e["event"] in ("degraded_enter", "degraded_exit")),
                        None)
        return "degraded" if last == "degraded_enter" else "warm"

    @property
    def routable(self):
        return self.health() in ("warm", "degraded")

    # ----------------------------------------------------------- lifecycle
    def drain(self):
        """Stop taking new requests; in-flight ones finish normally."""
        self._draining.set()

    def kill(self, timeout=5.0, _clean=False):
        """The crash simulation: mark dead, stop the service (in-flight
        futures resolve as shed("shutdown") — the service's drain-and-join
        contract), and flush the lag mailbox so no outcome is parked
        forever. `_clean` marks a planned shutdown (stop()): same mechanics,
        but it is NOT counted as a kill — the replica_kills counter feeds a
        zero-tolerance SLO spec, and a fault-free run tearing its fleet down
        must stay silent."""
        if self._dead.is_set():
            return
        if not _clean and self.metrics is not None:
            self.metrics.counter("replica_kills").inc()
        self._dead.set()
        self.service.stop(timeout=timeout)
        if self._delayer is not None:
            self._delayer.join(timeout=timeout)
            while True:
                try:
                    _, reply, outer = self._delay_q.get_nowait()
                except queue.Empty:
                    break
                outer._set(reply)

    def stop(self, timeout=5.0):
        """Clean shutdown — same mechanics as kill(), different intent (and
        not counted as a kill)."""
        self.kill(timeout=timeout, _clean=True)

    # ----------------------------------------------------------- reporting
    def attach_registry(self, registry):
        """Late-bind a MetricsRegistry to the replica AND its service."""
        self.metrics = registry
        self.service.attach_registry(registry)
        return registry

    def warmup(self):
        self.service.warmup()

    def summary(self):
        return {"name": self.name, "health": self.health(),
                "lag_s": self.lag_s, "corpus_version": self.corpus.version,
                "service": self.service.summary()}
