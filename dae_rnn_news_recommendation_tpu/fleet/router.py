"""Load-aware, deadline-propagating, hedging router over a replica fleet.

The single-service story (serve/service.py) promised exactly-one-outcome per
request; the router keeps that promise while ADDING the two things that can
break it — hedged duplicates and cross-replica retries:

  DISPATCH   least-outstanding-requests with power-of-two-choices: sample
             two routable replicas, send to the one with fewer requests in
             flight. P2C gets within a constant of ideal load balance with
             O(1) state and, unlike pure least-loaded, never herds a
             thundering burst onto one briefly-idle replica.

  DEADLINES  the router propagates the request's ABSOLUTE deadline
             (`deadline_at`) into every attempt, so a hedge or retry spends
             the REMAINING budget, never a fresh full timeout — a
             nearly-expired request is shed as provably unmeetable by the
             replica's own floor check, and the hedge scheduler refuses to
             hedge it at all (ISSUE 12 deadline-propagation fix).

  HEDGING    after a hedge delay derived from the p95 of recently observed
             reply latencies (clamped to [floor, cap]; "The Tail at Scale"
             discipline: duplicate only the slowest few percent), the
             request is re-issued to a second replica and the FIRST
             completion wins. Exactly-one-outcome is enforced at the
             request record: the first terminal decision resolves the
             caller's future and writes the one ledger record; the loser's
             completion is counted as `hedge_discarded`, never surfaced.
             The hedge budget is bounded (`hedge_burst + hedge_budget_frac
             * submitted`) so overload cannot amplify itself — past the
             budget, hedges are suppressed and counted, never silent.

  RETRIES    a shed that names a replica-local cause (shutdown, kill,
             drain, full queue) or an error reply is re-enqueued on a
             DIFFERENT replica, bounded by `max_retries` and the remaining
             deadline budget. Terminal sheds (deadline_unmeetable,
             deadline_expired_in_queue) are never retried — the deadline
             math already proved them pointless.

`fleet.route` fires at route selection (transients absorbed by the router's
RetryPolicy, fatals are explicit error outcomes); `fleet.hedge` fires at
hedge issuance — ANY injected fault there skips the hedge and records it,
leaving the primary attempt untouched.
"""

import dataclasses
import heapq
import threading
import time

import numpy as np

from ..reliability import faults as _faults
from ..reliability.retry import RetryPolicy
from ..serve.service import Reply, ReplyFuture

_LATENCY_WINDOW = 512   # recent reply latencies kept for the hedge delay

# shed reasons that name a replica-local cause — worth one try elsewhere.
# Deadline sheds are terminal: the budget is spent no matter who serves.
_RETRYABLE_SHEDS = frozenset((
    "shutdown", "queue_full", "replica_dead", "replica_draining",
    "replica_preempted"))


class _FleetRequest:
    """Router-side record of one caller request across all its attempts."""

    __slots__ = ("id", "rid", "query", "deadline_at", "t_submit", "future",
                 "_lock", "inflight", "resolved", "retries", "hedged",
                 "parked", "tried")

    def __init__(self, req_id, query, deadline_at, t_submit):
        self.id = req_id
        self.rid = f"flt-{req_id}"   # trace id; attempts suffix hops:
        #                              retry -> "/rN", hedge twin -> "/h"
        self.query = query
        self.deadline_at = deadline_at
        self.t_submit = t_submit
        self.future = ReplyFuture()
        self._lock = threading.Lock()
        self.inflight = 0
        self.resolved = False
        self.retries = 0
        self.hedged = False
        self.parked = None    # first not-ok (reply, replica) while another
        #                       attempt is still in flight
        self.tried = []       # replica names, attempt order


class Router:
    """Front door of the fleet: submit() returns a ReplyFuture that always
    resolves with exactly one outcome, whatever the replicas do.

    :param replicas: list of fleet.ServiceReplica (data-parallel copies).
    :param default_deadline_s: applied when submit() gets no deadline.
    :param hedge: enable hedged requests (off = pure p2c routing — the
        bench's no-hedge baseline).
    :param hedge_delay_floor_s / hedge_delay_cap_s: clamp on the p95-derived
        hedge delay (floor also serves as the cold-start delay before any
        latency history exists).
    :param hedge_budget_frac: hedges allowed as a fraction of submitted
        requests (plus `hedge_burst` flat) — the overload-amplification
        bound.
    :param max_retries: cross-replica re-enqueues per request.
    :param retry: RetryPolicy absorbing transient fleet.route faults.
    :param seed: p2c sampling seed (deterministic routing for replay).
    :param ledger: optional reliability.ledger.OutcomeLedger the chaos soak
        audits; the router records one submit and exactly one resolve per
        request into it.
    :param registry: optional telemetry.MetricsRegistry for the router's own
        fleet-level metrics (routed/retry/hedge counters, per-replica
        outstanding gauges, fleet latency histogram). None = no metrics.
    """

    def __init__(self, replicas, *, default_deadline_s=1.0, hedge=True,
                 hedge_delay_floor_s=0.005, hedge_delay_cap_s=0.25,
                 hedge_budget_frac=0.1, hedge_burst=4, max_retries=2,
                 retry=None, seed=0, ledger=None, registry=None):
        assert replicas, "a fleet needs at least one replica"
        names = [r.name for r in replicas]
        assert len(set(names)) == len(names), f"duplicate replica names: {names}"
        self.replicas = list(replicas)
        self.by_name = {r.name: r for r in replicas}
        self.default_deadline_s = float(default_deadline_s)
        self.hedge_enabled = bool(hedge)
        self.hedge_delay_floor_s = float(hedge_delay_floor_s)
        self.hedge_delay_cap_s = float(hedge_delay_cap_s)
        self.hedge_budget_frac = float(hedge_budget_frac)
        self.hedge_burst = int(hedge_burst)
        self.max_retries = int(max_retries)
        self.retry = retry if retry is not None else RetryPolicy(
            max_attempts=3, backoff_s=0.001, max_elapsed_s=0.25)
        self.ledger = ledger
        self.metrics = registry
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()          # counts/latencies/records/rng
        self._out_lock = threading.Lock()      # outstanding counters only —
        # never held while acquiring another lock (ordering: req -> _lock/_out)
        self._outstanding = {r.name: 0 for r in replicas}
        self._latencies = []
        self.records = []   # one terminal record per request, resolve order
        self.counts = {"submitted": 0, "replied": 0, "shed": 0, "errors": 0,
                       "routed": 0, "retries": 0, "hedges": 0,
                       "hedge_wins": 0, "hedge_discarded": 0,
                       "hedge_suppressed_budget": 0,
                       "hedge_suppressed_unmeetable": 0,
                       "hedge_suppressed_no_replica": 0, "hedge_faults": 0}
        self._next_id = 0
        self._stop_flag = False
        self._cv = threading.Condition()
        self._heap = []     # (fire_at, req_id, req) hedge schedule
        self._hedge_thread = threading.Thread(
            target=self._hedge_loop, daemon=True, name="fleet-hedger")
        self._hedge_thread.start()

    # ------------------------------------------------------------ admission
    def submit(self, query, deadline_s=None, deadline_at=None, pin=None):
        """Route one query. `deadline_at` (absolute monotonic) wins over
        `deadline_s`; `pin` forces a specific replica by name (the rollout's
        canary probe) and disables hedging/retry for that request."""
        now = time.monotonic()
        if deadline_at is None:
            deadline_at = now + (self.default_deadline_s if deadline_s is None
                                 else float(deadline_s))
        with self._lock:
            self._next_id += 1
            req = _FleetRequest(self._next_id, query, float(deadline_at), now)
            self.counts["submitted"] += 1
        m = self.metrics
        if m is not None:
            # "fleet_" prefix: the per-replica registries already carry
            # submitted/replied/shed at attempt granularity — the aggregate
            # sums by name, so the router's request-granularity outcomes
            # must not fold into them
            m.counter("fleet_submitted").inc()
        if self.ledger is not None:
            self.ledger.submit(req.id, t_submit=now)

        def route_fire():
            try:
                _faults.fire("fleet.route")
            except _faults.TransientFault:
                # absorbed by the retry policy below, but never invisibly:
                # the zero-tolerance fleet.route SLO spec burns on this
                if m is not None:
                    m.counter("route_transient_retries").inc()
                raise
        try:
            self.retry.run(route_fire, site="fleet.route")
        except Exception as exc:
            return self._resolve_direct(
                req, Reply(status="error",
                           reason=f"{type(exc).__name__}: {exc}"))
        if pin is not None:
            replica = self.by_name[pin]
            if not replica.routable:
                return self._resolve_direct(
                    req, Reply(status="shed", reason="pinned_replica_down"))
            self._dispatch(req, replica)
            return req.future
        replica = self._pick()
        if replica is None:
            return self._resolve_direct(
                req, Reply(status="shed", reason="no_replica"))
        self._dispatch(req, replica)
        if self.hedge_enabled:
            fire_at = now + self._hedge_delay()
            with self._cv:
                heapq.heappush(self._heap, (fire_at, req.id, req))
                self._cv.notify()
        return req.future

    # -------------------------------------------------------------- routing
    def _pick(self, exclude=()):
        """P2C over routable replicas: sample two, take the one with fewer
        outstanding requests. One candidate routes directly; none -> None."""
        cands = [r for r in self.replicas
                 if r.name not in exclude and r.routable]
        if not cands:
            return None
        if len(cands) == 1:
            return cands[0]
        with self._lock:
            i, j = self._rng.choice(len(cands), size=2, replace=False)
        with self._out_lock:
            oi = self._outstanding[cands[int(i)].name]
            oj = self._outstanding[cands[int(j)].name]
        return cands[int(i)] if oi <= oj else cands[int(j)]

    def _dispatch(self, req, replica, hop=""):
        """Issue one attempt. `hop` suffixes the request's trace id ("" for
        the primary, "/rN" for a cross-replica retry, "/h" for the hedge
        twin) — all attempts share the parent id, so whichever one wins the
        exactly-one-outcome race stays attributable in traces and ledger."""
        with req._lock:
            if req.resolved:
                return
            req.inflight += 1
            req.tried.append(replica.name)
        with self._out_lock:
            self._outstanding[replica.name] += 1
            out_now = self._outstanding[replica.name]
        with self._lock:
            self.counts["routed"] += 1
        m = self.metrics
        if m is not None:
            m.counter("routed").inc()
            m.gauge(f"outstanding.{replica.name}").set(out_now)
        fut = replica.submit(req.query, deadline_at=req.deadline_at,
                             request_id=req.rid + hop)
        fut.add_done_callback(
            lambda reply: self._on_attempt(req, replica, reply))

    def _on_attempt(self, req, replica, reply):
        """One attempt completed (batcher thread, or inline for synchronous
        sheds). First terminal decision wins; late completions are counted
        as discarded, never double-surfaced."""
        with self._out_lock:
            self._outstanding[replica.name] -= 1
            out_now = self._outstanding[replica.name]
        if self.metrics is not None:
            self.metrics.gauge(f"outstanding.{replica.name}").set(out_now)
        redispatch = None
        outcome = None
        with req._lock:
            req.inflight -= 1
            if req.resolved:
                with self._lock:
                    self.counts["hedge_discarded"] += 1
                return
            if reply.ok:
                outcome = self._mark_resolved(req, reply, replica.name)
            else:
                retryable = (reply.status == "error"
                             or reply.reason in _RETRYABLE_SHEDS)
                if retryable and req.retries < self.max_retries:
                    remaining = req.deadline_at - time.monotonic()
                    cand = (self._pick(exclude=set(req.tried))
                            if remaining > 0 else None)
                    if cand is not None:
                        req.retries += 1
                        redispatch = cand
                if redispatch is None:
                    if req.inflight > 0:
                        # another attempt is still out: park this outcome,
                        # the race is still winnable
                        if req.parked is None:
                            req.parked = (reply, replica.name)
                    else:
                        parked, name = req.parked or (reply, replica.name)
                        outcome = self._mark_resolved(req, parked, name)
        if outcome is not None:
            self._publish(req, *outcome)
            return
        if redispatch is not None:
            with self._lock:
                self.counts["retries"] += 1
            if self.metrics is not None:
                self.metrics.counter("retries").inc()
            self._dispatch(req, redispatch, hop=f"/r{req.retries}")

    # ------------------------------------------------------------- hedging
    def _hedge_delay(self):
        """p95 of recent observed reply latencies, clamped to [floor, cap];
        floor alone before any history exists (cold start)."""
        with self._lock:
            lat = list(self._latencies)
        if not lat:
            return self.hedge_delay_floor_s
        p95 = float(np.percentile(np.asarray(lat, np.float64), 95))
        return min(max(p95, self.hedge_delay_floor_s), self.hedge_delay_cap_s)

    def _hedge_loop(self):
        """One scheduler thread for ALL hedges (never a timer per request):
        pops due entries off the schedule heap under the condition variable,
        issues hedges outside it. Every wait is bounded — a wedged replica
        can stall its own batch, never this loop."""
        while True:
            with self._cv:
                if self._stop_flag:
                    return
                now = time.monotonic()
                due = []
                while self._heap and self._heap[0][0] <= now:
                    due.append(heapq.heappop(self._heap)[2])
                if not due:
                    wait = (0.05 if not self._heap
                            else min(0.05, self._heap[0][0] - now))
                    self._cv.wait(timeout=max(wait, 0.001))
                    continue
            for req in due:
                self._maybe_hedge(req)

    def _maybe_hedge(self, req):
        if req.future.done():
            return
        remaining = req.deadline_at - time.monotonic()
        floor = max((r.service._floor_s for r in self.replicas if r.routable),
                    default=0.0)
        if remaining <= 0.0 or (floor > 0.0 and remaining < floor):
            # provably unmeetable on ANY replica: the primary attempt's own
            # deadline math will shed it — duplicating it would burn a
            # second slot on a lost cause
            with self._lock:
                self.counts["hedge_suppressed_unmeetable"] += 1
            return
        with self._lock:
            budget = (self.hedge_burst
                      + self.hedge_budget_frac * self.counts["submitted"])
            if self.counts["hedges"] >= budget:
                self.counts["hedge_suppressed_budget"] += 1
                return
        try:
            _faults.fire("fleet.hedge", req=req.id)
        except _faults.InjectedFault:
            # any injected hedge fault skips the hedge and records it; the
            # primary attempt is untouched and still owns the outcome
            with self._lock:
                self.counts["hedge_faults"] += 1
            if self.metrics is not None:
                self.metrics.counter("hedge_faults").inc()
            return
        cand = self._pick(exclude=set(req.tried))
        if cand is None:
            with self._lock:
                self.counts["hedge_suppressed_no_replica"] += 1
            return
        with req._lock:
            if req.resolved:
                return
            req.hedged = True
        with self._lock:
            self.counts["hedges"] += 1
        if self.metrics is not None:
            self.metrics.counter("hedges").inc()
        self._dispatch(req, cand, hop="/h")

    # ------------------------------------------------------------ terminals
    def _resolve_direct(self, req, reply):
        with req._lock:
            outcome = self._mark_resolved(req, reply, replica=None)
        self._publish(req, *outcome)
        return req.future

    def _mark_resolved(self, req, reply, replica):
        """The one place a request becomes terminal. Caller holds req._lock;
        only the terminal DECISION happens under it — flipping `resolved`
        and freezing the final reply/record. Publication (resolving the
        caller's future, counters, ledger) is deferred to `_publish` after
        the lock is released: `future._set` wakes waiters and runs caller
        callbacks, and foreign code must never run under a router lock (it
        can call straight back into submit()/summary() and deadlock —
        jaxcheck C5)."""
        assert not req.resolved
        req.resolved = True
        now = time.monotonic()
        latency_s = now - req.t_submit
        # the winning attempt's replica-level timing record, extended with
        # the router's own share (routing decisions, callback plumbing, the
        # time a retried request spent on its losing attempts) as the exact
        # remainder — the fleet decomposition still sums to latency_s
        timings = dict(reply.timings or {})
        timings["router_s"] = round(latency_s - sum(timings.values()), 6)
        final = dataclasses.replace(reply, latency_s=latency_s,
                                    deadline_met=now <= req.deadline_at,
                                    request_id=reply.request_id or req.rid,
                                    timings=timings)
        rec = {"id": req.id, "request_id": final.request_id,
               "status": final.status, "reason": final.reason,
               "replica": replica, "corpus_version": final.corpus_version,
               "hedged": req.hedged, "retries": req.retries,
               "latency_s": round(final.latency_s, 6),
               "timings": timings, "t_resolved": now}
        hedge_win = (final.ok and req.hedged and req.tried
                     and replica != req.tried[0])
        return final, rec, hedge_win

    def _publish(self, req, final, rec, hedge_win):
        """Surface a terminal decision made by `_mark_resolved` — runs with
        NO router/request lock held. Late attempts racing in are already
        turned away by the `resolved` flag, so publication order is safe."""
        req.future._set(final)
        with self._lock:
            key = {"ok": "replied", "shed": "shed", "error": "errors"}
            self.counts[key[final.status]] += 1
            if hedge_win:
                self.counts["hedge_wins"] += 1
            if final.ok:
                self._latencies.append(final.latency_s)
                del self._latencies[:-_LATENCY_WINDOW]
            self.records.append(rec)
        m = self.metrics
        if m is not None:
            m.counter({"ok": "fleet_replied", "shed": "fleet_shed",
                       "error": "fleet_errors"}[final.status]).inc()
            if hedge_win:
                m.counter("hedge_wins").inc()
            if final.ok:
                m.histogram("fleet_latency_ms").observe(final.latency_s * 1e3)
                if not final.deadline_met:
                    m.counter("fleet_deadline_missed").inc()
        if self.ledger is not None:
            self.ledger.resolve(req.id, final.status, **{
                k: v for k, v in rec.items() if k not in ("id", "status")})

    # ----------------------------------------------------------- lifecycle
    def stop(self, timeout=5.0):
        """Stop the hedge scheduler (pending hedges are dropped — their
        primary attempts still resolve through the replicas). Replica
        shutdown belongs to the fleet owner, not the router."""
        with self._cv:
            self._stop_flag = True
            self._heap.clear()
            self._cv.notify()
        self._hedge_thread.join(timeout=timeout)

    # ----------------------------------------------------------- reporting
    def attach_registry(self, registry):
        """Late-bind a MetricsRegistry (bench attaches for the instrumented
        leg of the tracing-overhead race)."""
        self.metrics = registry
        return registry

    def latency_stats(self):
        with self._lock:
            lat = [r["latency_s"] for r in self.records
                   if r["status"] == "ok"]
        if not lat:
            return {"n": 0, "p50_ms": None, "p95_ms": None, "p99_ms": None}
        a = np.asarray(lat, np.float64) * 1e3
        return {"n": int(a.size),
                "p50_ms": round(float(np.percentile(a, 50)), 3),
                "p95_ms": round(float(np.percentile(a, 95)), 3),
                "p99_ms": round(float(np.percentile(a, 99)), 3),
                "mean_ms": round(float(a.mean()), 3)}

    def summary(self):
        with self._lock:
            counts = dict(self.counts)
        with self._out_lock:
            outstanding = dict(self._outstanding)
        return {"counts": counts, "latency": self.latency_stats(),
                "hedge_delay_s": round(self._hedge_delay(), 6),
                "outstanding": outstanding,
                "replicas": {r.name: r.health() for r in self.replicas},
                "retries": list(self.retry.events)}
