"""Chaos soak for the serving fleet: Zipf replay x faults x a mid-rollout.

Each seeded plan drives a 3-replica fleet (canary + peer + a deterministic
straggler) through a Zipf session-replay trace with a staged corpus rollout
fired MID-TRACE, one fault family injected per plan:

    seed % 6   family
    --------   -------------------------------------------------------------
       0       fleet.kill     harness kills a non-canary replica exactly at
                              its own fleet-rollout stage: its in-flight
                              requests shed, the router re-enqueues them
                              elsewhere, the rollout records it as skipped
       1       refresh.swap   fatal at call 1 — the CANARY's swap dies, its
                              corpus rolls itself back, the rollout aborts
                              with the fleet untouched at the pre-canary
                              version
       2       refresh.swap   fatal at call 2 — canary promotes, the FIRST
                              fleet-stage swap dies, and the supervisor
                              reverts the whole fleet (canary included) to
                              the pre-canary version
       3       fleet.route    transient at admission — the router's own
                              RetryPolicy absorbs it, no outcome impact
       4       fleet.hedge    fatal at hedge issuance — the hedge is skipped
                              and counted, the primary attempt untouched
       5       fleet.replica  transient at replica admission — absorbed

Fleet-wide invariants audited after every plan, whatever was injected:

  * EXACTLY-ONE: every submitted request resolves exactly once, fleet-wide —
    across hedges, retries, replica death, and rollback. The router records
    into a shared OutcomeLedger; `ledger.audit()` catches lost requests and
    double outcomes, `audit_outcome_counts` catches aggregate leaks.
  * VERSION SKEW <= 2: the distinct corpus versions observed across all ok
    replies stay within {v, v+1} — the staged one-replica-at-a-time rollout
    keeps the fleet within one version of itself at all times.
  * ROLLOUT HONESTY per family: family 1 leaves every corpus at the
    pre-canary version with a rollback recorded; family 2 leaves every LIVE
    corpus at the pre-canary version via explicit reverts; fault-free
    families advance every live replica exactly one version.
  * per-replica version ledgers replay clean under the shared
    `audit_version_ledger` (reverts allowed — that is the rollback story).
  * SLO ATTRIBUTION: every run carries per-replica metric registries and a
    burn-rate SLOMonitor (telemetry/slo.py); the injected family's
    zero-tolerance alert (fleet/observability.FAMILY_ALERTS) must FIRE,
    and `run_fleet_reference` — the fault-free twin of the same trace and
    rollout — proves the whole spec set stays SILENT when nothing is wrong.
  * TIMING HONESTY: every resolved request's per-hop timing decomposition
    (reply.timings: admit/queue/batch/compute/resolve + router share) sums
    back to its observed latency.
"""

import dataclasses
import time

import numpy as np

from ..models.dae_core import DAEConfig, init_params
from ..refresh import ChurnConfig
from ..reliability import faults as _faults
from ..reliability.faults import FaultInjector, FaultPlan, FaultSpec
from ..reliability.ledger import (OutcomeLedger, audit_outcome_counts,
                                  audit_version_ledger)
from ..reliability.retry import RetryPolicy
from ..serve.corpus import ServingCorpus
from ..telemetry.metrics_registry import MetricsRegistry, aggregate
from ..telemetry.slo import SLOMonitor, serving_slo_specs
from .loadgen import make_session_trace, replay_trace
from .observability import (FAMILY_ALERTS, dump_fleet_observability,
                            fleet_fault_slo_specs, fleet_registries)
from .replica import ServiceReplica
from .rollout import FleetSupervisor
from .router import Router

_N_FEATURES = 24
_N_COMPONENTS = 8
_N_ARTICLES = 96
_N_REPLICAS = 3
_SLA_S = 5.0
_STRAGGLER_LAG_S = 0.03
_HARNESS_DEADLINE_S = 60.0


@dataclasses.dataclass
class FleetPlanResult:
    seed: int
    ok: bool
    detail: str
    family: int
    n_submitted: int
    n_replied: int
    n_shed: int
    n_errors: int
    n_unresolved: int
    n_hedges: int
    n_hedge_wins: int
    n_retries: int
    p99_ms: float
    versions_seen: list
    rollout_ok: bool
    rollout_stage: str
    reverted: list
    skipped: list
    injected: list
    slo_alerts: list
    duration_s: float

    def to_dict(self):
        return dataclasses.asdict(self)


def fleet_fault_plan(seed, n_requests):
    """Seeded plan over the fleet fire-points, round-robin on the seed.
    Family 0 is a HARNESS directive (fleet.kill has no in-code fire point:
    the harness kills the replica and records it via injector.note)."""
    rng = np.random.default_rng(seed)
    families = (
        lambda: (),   # fleet.kill: applied by run_fleet_plan's stage hook
        lambda: (FaultSpec("refresh.swap", 1, "fatal",
                           note="canary swap dies -> fleet untouched"),),
        lambda: (FaultSpec("refresh.swap", 2, "fatal",
                           note="fleet-stage swap dies -> fleet revert"),),
        lambda: (FaultSpec("fleet.route",
                           int(rng.integers(1, max(2, n_requests // 2))),
                           "transient", note="route-selection blip"),),
        lambda: (FaultSpec("fleet.hedge", 1, "fatal",
                           note="hedge issuance dies -> hedge skipped"),),
        lambda: (FaultSpec("fleet.replica",
                           int(rng.integers(1, max(2, n_requests // 2))),
                           "transient", note="replica admission blip"),),
    )
    return FaultPlan(seed=int(seed),
                     specs=tuple(families[seed % len(families)]()))


def _make_fleet(seed):
    """3 tiny replicas sharing params/articles, each with its OWN corpus
    (data-parallel full copies); the last is a deterministic straggler so
    hedging has a tail to cut."""
    config = DAEConfig(n_features=_N_FEATURES, n_components=_N_COMPONENTS,
                       enc_act_func="tanh", triplet_strategy="none",
                       corr_type="masking", corr_frac=0.0)
    import jax

    params = init_params(jax.random.PRNGKey(7 + seed), config)
    rng = np.random.default_rng(2000 + seed)
    articles = rng.random((_N_ARTICLES, _N_FEATURES), dtype=np.float32)
    replicas = []
    for i in range(_N_REPLICAS):
        corpus = ServingCorpus(config, block=32)
        replicas.append(ServiceReplica(
            f"r{i}", params, config, corpus=corpus,
            registry=MetricsRegistry(f"replica-r{i}"),
            lag_s=_STRAGGLER_LAG_S if i == _N_REPLICAS - 1 else 0.0,
            top_k=5, max_batch=8, max_inflight=16, flush_slack_s=0.02,
            linger_s=0.002, default_deadline_s=_SLA_S,
            retry=RetryPolicy(max_attempts=3, backoff_s=0.001,
                              max_elapsed_s=0.5)))
    return replicas, params, config, articles


def _fleet_slo_monitor():
    """The chaos harness's monitor: generic serving objectives (thresholds
    loose enough that the harness's deliberately bursty trace cannot flake
    the fault-free reference) + one zero-tolerance spec per fault family."""
    return SLOMonitor(serving_slo_specs(deadline_miss_max=0.2, shed_max=0.2,
                                        p95_ms_max=4000.0)
                      + fleet_fault_slo_specs())


def _observe(monitor, regs):
    """One aggregate snapshot into the monitor's ring."""
    monitor.observe(aggregate([m.snapshot() for m in regs]))


def run_fleet_plan(seed, n_requests=48, log=None, dump_path=None):
    """Execute one fault-plan x Zipf-trace x mid-trace-rollout run.
    `dump_path` (optional) writes the joined fleet observability bundle
    (fleet_observability.json shape) there before returning — the
    `telemetry report --fleet` input."""
    t0 = time.monotonic()
    family = seed % 6
    replicas, params, config, articles = _make_fleet(seed)
    ledger = OutcomeLedger()
    router = Router(replicas, default_deadline_s=_SLA_S, seed=seed,
                    hedge_delay_floor_s=0.002, hedge_delay_cap_s=0.05,
                    ledger=ledger, registry=MetricsRegistry("router"))
    sup = FleetSupervisor(
        params, config, replicas, router,
        registry=MetricsRegistry("supervisor"),
        churn=ChurnConfig(microbatch=32, drift_centroid_max=1.0,
                          drift_collapse_max=1.0))
    monitor = _fleet_slo_monitor()
    regs = fleet_registries(router=router, replicas=replicas, supervisor=sup)
    plan = fleet_fault_plan(seed, n_requests)
    injector = FaultInjector(plan)
    rng = np.random.default_rng(3000 + seed)
    victim = replicas[-1] if family == 0 else None

    def stage_hook(stage):
        # the mid-rollout crash: the victim dies exactly when the rollout
        # reaches it, so the supervisor must skip it and the router must
        # re-home its in-flight requests
        if victim is not None and stage == f"fleet:{victim.name}":
            injector.note("fleet.kill", "preempt", replica=victim.name)
            victim.kill()

    trace = make_session_trace(seed, n_requests, _N_ARTICLES,
                               mean_gap_s=0.002, deadline_s=_SLA_S,
                               deadline_spread=0.2)
    half = len(trace) // 2
    pre_versions = None
    try:
        sup.bootstrap(articles)
        for r in replicas:
            r.warmup()
        # SLO baseline BEFORE any traffic or fault: the burn windows must
        # see the fault-family counters move from zero
        _observe(monitor, regs)
        with _faults.install(injector):
            pre_versions = {r.name: r.corpus.version for r in replicas}
            pairs = replay_trace(router, articles, trace[:half])
            fresh = rng.random((32, _N_FEATURES), dtype=np.float32)
            report = sup.rollout(fresh, note=f"plan-{seed}",
                                 stage_hook=stage_hook,
                                 probe_query=articles[0])
            pairs += replay_trace(router, articles, trace[half:])
            replies, unresolved = [], 0
            harness_deadline = time.monotonic() + _HARNESS_DEADLINE_S
            for _, f in pairs:
                try:
                    replies.append(f.result(
                        timeout=max(0.0, harness_deadline - time.monotonic())))
                except TimeoutError:
                    unresolved += 1  # a lost request — fails the plan
        # evaluate BEFORE teardown: stop() sheds stragglers as "shutdown",
        # and those planned sheds must not pollute the SLO record
        _observe(monitor, regs)
        monitor.evaluate()
    finally:
        router.stop()
        for r in replicas:
            r.stop()
    summary = router.summary()
    counts = summary["counts"]
    problems = list(ledger.audit())
    problems += audit_outcome_counts(
        counts["submitted"], counts["replied"], counts["shed"],
        counts["errors"], n_unresolved=unresolved)
    if unresolved:
        problems.append(f"{unresolved} futures never resolved")
    # version-skew bound: ok replies may span at most TWO corpus versions —
    # the staged rollout never lets the fleet diverge further
    versions_seen = sorted({r["corpus_version"] for r in router.records
                            if r["status"] == "ok"})
    if len(versions_seen) > 2:
        problems.append(f"version skew: ok replies spanned {versions_seen}")
    problems += _audit_rollout(family, report, pre_versions, replicas, victim)
    if not injector.fired:
        problems.append("plan fired no faults (plan/trace mismatch)")
    for r in replicas:
        _, _, led_problems = audit_version_ledger(r.corpus.ledger,
                                                  allow_revert=True)
        problems += [f"{r.name}: {p}" for p in led_problems]
    # SLO attribution: the injected family's zero-tolerance alert must have
    # fired (other alerts MAY fire — a kill also sheds, a revert also
    # aborts; the contract is attribution, and the fault-free reference
    # replay proves the silent side)
    alert_names = [a["slo"] for a in monitor.alerts]
    expected_alert = FAMILY_ALERTS[family]
    if expected_alert not in alert_names:
        problems.append(f"SLO alert '{expected_alert}' did not fire for "
                        f"family {family} (fired: {alert_names or 'none'})")
    # per-request timing honesty: every resolved request's hop decomposition
    # sums back to its observed latency (rounding tolerance only — the
    # stamps are consecutive monotonic reads)
    for rec in router.records:
        gap = abs(sum(rec["timings"].values()) - rec["latency_s"])
        if gap > 1e-3:
            problems.append(f"request {rec['request_id']}: timings sum off "
                            f"by {gap * 1e3:.3f} ms")
            break
    result = FleetPlanResult(
        seed=int(seed), ok=not problems, detail="; ".join(problems) or "ok",
        family=family, n_submitted=counts["submitted"],
        n_replied=counts["replied"], n_shed=counts["shed"],
        n_errors=counts["errors"], n_unresolved=unresolved,
        n_hedges=counts["hedges"], n_hedge_wins=counts["hedge_wins"],
        n_retries=counts["retries"],
        p99_ms=summary["latency"]["p99_ms"] or 0.0,
        versions_seen=[int(v) for v in versions_seen],
        rollout_ok=bool(report["ok"]), rollout_stage=report["stage"],
        reverted=list(report["reverted"]), skipped=list(report["skipped"]),
        injected=list(injector.fired), slo_alerts=alert_names,
        duration_s=round(time.monotonic() - t0, 2))
    if dump_path is not None:
        dump_fleet_observability(dump_path, router=router, replicas=replicas,
                                 supervisor=sup, monitor=monitor,
                                 ledger=ledger,
                                 extra={"plan": result.to_dict()})
    if log:
        log(f"fleet plan {seed} (family {family}): "
            f"{'OK' if result.ok else 'FAIL'} ({result.n_replied} ok / "
            f"{result.n_shed} shed / {result.n_errors} err, "
            f"{result.n_hedges} hedges, p99 {result.p99_ms} ms) "
            f"{result.detail}")
    return result


def _audit_rollout(family, report, pre_versions, replicas, victim):
    """Family-specific honesty checks on the rollout report and the fleet's
    final corpus versions."""
    problems = []
    now = {r.name: r.corpus.version for r in replicas}
    if family == 1:
        if report["ok"]:
            problems.append("canary swap fault did not abort the rollout")
        if report.get("canary", {}).get("action") != "rollback":
            problems.append("canary corpus did not record a rollback")
        if now != pre_versions:
            problems.append(f"fleet moved despite canary abort: "
                            f"{pre_versions} -> {now}")
    elif family == 2:
        if report["ok"]:
            problems.append("fleet-stage swap fault did not abort the rollout")
        if not report["reverted"]:
            problems.append("fleet-stage abort reverted nothing")
        if now != pre_versions:
            problems.append(f"fleet not restored to pre-canary versions: "
                            f"{pre_versions} -> {now}")
    else:
        if not report["ok"]:
            problems.append(f"fault-free rollout failed: {report['detail']}")
        for r in replicas:
            if victim is not None and r.name == victim.name:
                if r.name not in report["skipped"]:
                    problems.append(f"killed replica {r.name} not recorded "
                                    "as skipped")
                if now[r.name] != pre_versions[r.name]:
                    problems.append(f"killed replica {r.name} advanced "
                                    "anyway")
            elif now[r.name] != pre_versions[r.name] + 1:
                problems.append(
                    f"{r.name} at v{now[r.name]}, expected "
                    f"v{pre_versions[r.name] + 1} after a clean rollout")
    return problems


def run_fleet_reference(seed, n_requests=48, log=None):
    """The fault-free twin of `run_fleet_plan`: same fleet shape, same Zipf
    trace, same mid-trace rollout — NO injector, no kill. The SLO monitor
    must stay completely silent; any alert here means a spec burns on
    normal operation and its signal under faults is noise. Returns a dict
    with `ok`, `alerts`, and the fleet counts."""
    t0 = time.monotonic()
    replicas, params, config, articles = _make_fleet(seed)
    ledger = OutcomeLedger()
    router = Router(replicas, default_deadline_s=_SLA_S, seed=seed,
                    hedge_delay_floor_s=0.002, hedge_delay_cap_s=0.05,
                    ledger=ledger, registry=MetricsRegistry("router"))
    sup = FleetSupervisor(
        params, config, replicas, router,
        registry=MetricsRegistry("supervisor"),
        churn=ChurnConfig(microbatch=32, drift_centroid_max=1.0,
                          drift_collapse_max=1.0))
    monitor = _fleet_slo_monitor()
    regs = fleet_registries(router=router, replicas=replicas, supervisor=sup)
    rng = np.random.default_rng(3000 + seed)
    trace = make_session_trace(seed, n_requests, _N_ARTICLES,
                               mean_gap_s=0.002, deadline_s=_SLA_S,
                               deadline_spread=0.2)
    half = len(trace) // 2
    unresolved = 0
    try:
        sup.bootstrap(articles)
        for r in replicas:
            r.warmup()
        _observe(monitor, regs)
        pairs = replay_trace(router, articles, trace[:half])
        fresh = rng.random((32, _N_FEATURES), dtype=np.float32)
        report = sup.rollout(fresh, note=f"reference-{seed}",
                             probe_query=articles[0])
        pairs += replay_trace(router, articles, trace[half:])
        harness_deadline = time.monotonic() + _HARNESS_DEADLINE_S
        for _, f in pairs:
            try:
                f.result(timeout=max(0.0,
                                     harness_deadline - time.monotonic()))
            except TimeoutError:
                unresolved += 1
        _observe(monitor, regs)
        monitor.evaluate()
    finally:
        router.stop()
        for r in replicas:
            r.stop()
    problems = list(ledger.audit())
    if unresolved:
        problems.append(f"{unresolved} futures never resolved")
    if not report["ok"]:
        problems.append(f"fault-free rollout failed: {report['detail']}")
    if monitor.alerts:
        problems.append("SLO alerts fired in a fault-free run: "
                        f"{[a['slo'] for a in monitor.alerts]}")
    out = {"seed": int(seed), "ok": not problems,
           "detail": "; ".join(problems) or "ok",
           "alerts": list(monitor.alerts),
           "counts": dict(router.counts),
           "duration_s": round(time.monotonic() - t0, 2)}
    if log:
        log(f"fleet reference {seed}: {'OK' if out['ok'] else 'FAIL'} "
            f"({out['detail']})")
    return out


def chaos_fleet_soak(seeds=(0, 1, 2, 3, 4, 5), n_requests=48, log=None):
    """Replay the seeded plans (any 6 consecutive seeds cover every fleet
    fault family). Returns {"results", "all_ok", ...}."""
    results = [run_fleet_plan(seed, n_requests=n_requests, log=log)
               for seed in seeds]
    n_ok = sum(1 for r in results if r.ok)
    return {"results": results, "n_ok": n_ok, "n_plans": len(results),
            "all_ok": n_ok == len(results)}
