"""Replicated serving fleet: routing, hedging, and staged corpus rollout.

One `RecommendationService` (serve/) answers queries with bounded admission,
deadline-aware microbatching, and exactly-one-outcome futures. This package
scales that contract OUT — N in-process replicas, each with a full
data-parallel corpus copy — without weakening it:

    replicas = [ServiceReplica(f"r{i}", params, config) for i in range(3)]
    router = Router(replicas, ledger=OutcomeLedger())
    sup = FleetSupervisor(params, config, replicas, router)
    sup.bootstrap(articles)                  # every corpus at version 1
    fut = router.submit(query, deadline_s=0.25)   # p2c route + hedge
    sup.rollout(fresh_batch)                 # canary -> probe -> fleet

  * `replica.ServiceReplica` — one service + corpus + DERIVED health
    (warm/degraded/draining/dead from the microbatcher's own degraded-mode
    records), honest kill(), and a deterministic-lag straggler knob.
  * `router.Router` — least-outstanding power-of-two-choices dispatch,
    ABSOLUTE-deadline propagation into every attempt, p95-derived hedged
    requests with a bounded hedge budget, cross-replica retries; exactly one
    outcome per request whatever the replicas do.
  * `rollout.FleetSupervisor` — ONE ChurnSupervisor on the canary drives the
    fleet-wide refresh: canary swap -> pinned serving probe -> staged
    per-replica swap (live versions always within {v, v+1}), with whole-fleet
    revert to the pre-canary version on any failure.
  * `loadgen` — Zipf session-replay traces shared by the bench and the soak.
  * `chaos_fleet` — seeded fault plans (fleet.route / fleet.hedge /
    fleet.replica / refresh.swap / harness fleet.kill) replayed over a
    mid-trace rollout, audited with reliability/ledger.py.

Design notes and diagrams: docs/serving.md ("Serving fleet");
fault-site table: docs/reliability.md.
"""

from .chaos_fleet import (FleetPlanResult, chaos_fleet_soak, fleet_fault_plan,
                          run_fleet_plan, run_fleet_reference)
from .loadgen import make_session_trace, replay_trace
from .observability import (FAMILY_ALERTS, QUALITY_FAMILY_ALERTS,
                            dump_fleet_observability,
                            dump_quality_observability, fleet_fault_slo_specs,
                            fleet_observability_bundle, fleet_registries,
                            quality_observability_bundle)
from .replica import HEALTH_STATES, ServiceReplica
from .rollout import FleetSupervisor
from .router import Router

__all__ = [
    "HEALTH_STATES", "ServiceReplica", "Router", "FleetSupervisor",
    "make_session_trace", "replay_trace",
    "FleetPlanResult", "fleet_fault_plan", "run_fleet_plan",
    "run_fleet_reference", "chaos_fleet_soak",
    "FAMILY_ALERTS", "QUALITY_FAMILY_ALERTS", "fleet_fault_slo_specs",
    "fleet_registries", "fleet_observability_bundle",
    "dump_fleet_observability", "quality_observability_bundle",
    "dump_quality_observability",
]
