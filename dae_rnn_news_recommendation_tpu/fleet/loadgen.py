"""Zipf session-replay load generation for the serving fleet.

Real news traffic is not uniform: a handful of breaking stories soak up most
of the reads, with a long tail of archival lookups. A load test that draws
queries uniformly misses exactly the regime that stresses a fleet — hot-key
concentration (every replica answering the same few articles) punctuated by
cold-tail queries whose embeddings share nothing with the cache-warm ones.

`make_session_trace` builds a deterministic trace of SESSIONS: each session
is one simulated reader issuing a short burst of requests (a user skimming a
story cluster), with

  * article popularity ~ Zipf(a): request i reads article rank r with
    P(r) ∝ r^-a over a seeded random rank permutation, so the hot set is
    seeded, not positional;
  * per-session bursts: session length geometric-ish (1..max_burst), gaps
    WITHIN a session short, gaps BETWEEN sessions longer — arrivals are
    bursty the way real readers are;
  * per-request deadlines: a base SLA with a seeded spread, so some requests
    are tight and shed-eligible under load.

The trace is a plain list of dicts — `replay_trace` feeds it through a
Router at (optionally time-compressed) recorded offsets and returns the
futures in submit order; bench and the chaos soak share both halves so the
traffic shape under measurement is the traffic shape under fault injection.
"""

import time

import numpy as np


def make_session_trace(seed, n_requests, n_articles, *, zipf_a=1.3,
                       max_burst=6, mean_gap_s=0.004, deadline_s=5.0,
                       deadline_spread=0.5):
    """Deterministic Zipf session-replay trace.

    :param seed: trace seed — same seed, same trace, bit for bit.
    :param n_requests: total requests across all sessions.
    :param n_articles: corpus size; article ids drawn in [0, n_articles).
    :param zipf_a: Zipf exponent (>1); larger = more head-heavy.
    :param max_burst: max requests per session.
    :param mean_gap_s: mean inter-SESSION gap; intra-session gaps are ~10x
        shorter.
    :param deadline_s: base per-request deadline.
    :param deadline_spread: fractional spread of deadlines around the base
        (0.5 -> uniform in [0.5, 1.5] * deadline_s).
    :returns: list of {"t": offset_s, "article": id, "session": s,
        "deadline_s": d}, sorted by t.
    """
    rng = np.random.default_rng(seed)
    # seeded rank->article permutation: the hot head is a random subset of
    # the corpus, not "the first few rows"
    perm = rng.permutation(n_articles)
    ranks = rng.zipf(float(zipf_a), size=n_requests)
    articles = perm[np.minimum(ranks - 1, n_articles - 1)]
    lo = 1.0 - float(deadline_spread) / 2.0
    deadlines = float(deadline_s) * rng.uniform(lo, lo + deadline_spread,
                                                size=n_requests)
    trace, t, i, session = [], 0.0, 0, 0
    while i < n_requests:
        burst = int(rng.integers(1, max_burst + 1))
        for _ in range(min(burst, n_requests - i)):
            trace.append({"t": round(t, 6), "article": int(articles[i]),
                          "session": session,
                          "deadline_s": float(deadlines[i])})
            t += float(rng.exponential(mean_gap_s / 10.0))
            i += 1
        t += float(rng.exponential(mean_gap_s))
        session += 1
    return trace


def replay_trace(router, articles, trace, *, speedup=1.0):
    """Feed a trace through a Router at its recorded offsets.

    :param router: fleet.Router (anything with submit(query, deadline_s=)).
    :param articles: (N, F) article matrix the trace's ids index into.
    :param trace: output of make_session_trace.
    :param speedup: >1 compresses time (offsets divided by it); inf-like
        values degenerate to as-fast-as-possible.
    :returns: list of (entry, ReplyFuture) in submit order.
    """
    out = []
    t0 = time.monotonic()
    for entry in trace:
        due = t0 + entry["t"] / float(speedup)
        wait = due - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        fut = router.submit(articles[entry["article"]],
                            deadline_s=entry["deadline_s"])
        out.append((entry, fut))
    return out
