"""Fleet observability: the fault-family SLO catalog and the joined bundle.

Two jobs live here, both consumers of the pieces built elsewhere
(telemetry/metrics_registry, telemetry/slo, the router's per-request
records):

  * `fleet_fault_slo_specs()` — one ZERO-TOLERANCE SLO spec per injectable
    fleet fault family, each burning on the counter that family (and only
    that family) increments. `FAMILY_ALERTS` maps chaos_fleet's
    `seed % 6` family number to the alert that must fire under it: that is
    the contract the chaos soak audits (alert fires under the fault,
    stays silent in the fault-free reference replay).

  * `fleet_observability_bundle()` / `dump_fleet_observability()` — the one
    JSON artifact (`fleet_observability.json`) that `telemetry report
    --fleet` joins: per-request router records (request id + timing
    decomposition), every registry snapshot + the fleet aggregate, the SLO
    monitor's specs/alerts, the rollout history, and the outcome ledger's
    counts — all keyed so a request id found in a trace can be followed
    into the table.
"""

import json
import os

from ..telemetry.metrics_registry import aggregate
from ..telemetry.slo import SLOSpec

# chaos_fleet fault family number -> the alert that must fire under it
FAMILY_ALERTS = {
    0: "replica-kills",
    1: "rollout-aborts",
    2: "fleet-reverts",
    3: "route-transients",
    4: "hedge-faults",
    5: "replica-admission-transients",
}

# chaos_quality fault family -> the QUALITY alert that must fire under it
# (telemetry/slo.quality_slo_specs names). Cell-owning-shard loss shrinks
# live coverage — the quarantine masks the rows out of both the exact
# shadow and the IVF shortlist, so recall is unaffected but coverage drops
# below its floor. Churn drift leaves the service scoring with perturbed
# params against centroids built at the old ones: the IVF probe ordering
# degrades while the exact full-scan shadow does not, and the recall
# burn-rate fires. The soak audits both directions: the injected family's
# alert fires, the fault-free reference replay stays silent.
QUALITY_FAMILY_ALERTS = {
    "cell-owning-shard-loss": "quality-coverage",
    "churn-drift": "quality-recall",
}


def fleet_fault_slo_specs(window_s=3600.0):
    """One zero-tolerance spec per fleet fault family. Objective 0.0 means
    ANY occurrence inside the window is an infinite burn — these events
    (an unplanned kill, a rollout abort, a whole-fleet revert, an absorbed
    transient) must never happen in a healthy run, so one is an alert.
    The window is generous by default: a chaos plan is seconds long and
    the baseline must predate its first fault."""
    zero = dict(short_window_s=float(window_s), long_window_s=float(window_s),
                fast_burn=1.0, slow_burn=1.0)
    return (
        SLOSpec("replica-kills", "rate_max", 0.0,
                numerator="replica_kills", **zero),
        SLOSpec("rollout-aborts", "rate_max", 0.0,
                numerator="rollout_aborts", **zero),
        SLOSpec("fleet-reverts", "rate_max", 0.0,
                numerator="fleet_reverts", **zero),
        SLOSpec("route-transients", "rate_max", 0.0,
                numerator="route_transient_retries", **zero),
        SLOSpec("hedge-faults", "rate_max", 0.0,
                numerator="hedge_faults", **zero),
        SLOSpec("replica-admission-transients", "rate_max", 0.0,
                numerator="replica_admission_transients", **zero),
    )


def fleet_registries(router=None, replicas=(), supervisor=None):
    """The distinct MetricsRegistry objects a fleet carries (router,
    replicas, supervisor), deduplicated by identity — components may share
    one registry, and a shared one must be snapshotted (and aggregated)
    exactly once."""
    regs = []
    for obj in (router, *replicas, supervisor):
        m = getattr(obj, "metrics", None)
        if m is not None and all(m is not seen for seen in regs):
            regs.append(m)
    return regs


def fleet_observability_bundle(router=None, replicas=(), supervisor=None,
                               monitor=None, ledger=None, extra=None,
                               memory=True):
    """Join the fleet's observability surfaces into one serializable dict —
    the `report --fleet` input. Every section is optional and None-safe:
    whatever the run actually wired shows up, nothing crashes on absence.

    `memory=True` additionally samples per-device `memory_stats()` HBM
    gauges (devprof.sample_memory) into the FIRST fleet registry before it
    is snapshotted — the device-memory-growth SLO's data source — and
    carries the raw snapshot under `"memory"`. Where the backend exports no
    memory stats (CPU) the section is `{}` and no gauges appear, so the
    growth spec stays silent by absence."""
    regs = fleet_registries(router=router, replicas=replicas,
                            supervisor=supervisor)
    mem_snap = {}
    if memory:
        from ..telemetry import devprof

        mem_snap = devprof.sample_memory(regs[0] if regs else None)
    snaps = [m.snapshot() for m in regs]
    bundle = {
        "requests": (list(router.records) if router is not None else []),
        "registries": snaps,
        "aggregate": aggregate(snaps) if snaps else None,
        "slo": monitor.summary() if monitor is not None else None,
        "rollout": (list(supervisor.history)
                    if supervisor is not None else []),
        "ledger": ({"n_submitted": ledger.n_submitted,
                    "counts": ledger.counts(),
                    "problems": list(ledger.audit())}
                   if ledger is not None else None),
        "memory": mem_snap,
    }
    if extra:
        bundle.update(extra)
    return bundle


def dump_fleet_observability(path, **bundle_kw):
    """Write the bundle as JSON (atomic tmp+rename, like every other
    artifact dump in this repo) and return `path`. Dropped as
    `fleet_observability.json` next to a trace, `telemetry report`
    auto-detects it."""
    bundle = fleet_observability_bundle(**bundle_kw)
    return _dump_json(path, bundle)


def quality_observability_bundle(service=None, corpus=None, monitor=None,
                                 registry=None, extra=None):
    """Join the retrieval-quality surfaces into one serializable dict —
    the `report --quality` input. Same philosophy as the fleet bundle:
    every section optional and None-safe, pass-by-absence all the way
    down.

    Sections: the shadow scorer's sample window + counters (from
    `service.shadow`), the corpus ledger tail + live coverage, the shared
    registry snapshot (shadow recall histograms, corpus/IVF quality
    gauges), and the quality SLO monitor's specs/alert history."""
    regs = []
    for m in (registry, getattr(service, "metrics", None),
              getattr(corpus, "metrics", None)):
        if m is not None and all(m is not seen for seen in regs):
            regs.append(m)
    snaps = [m.snapshot() for m in regs]
    shadow = getattr(service, "shadow", None)
    bundle = {
        "shadow": shadow.summary() if shadow is not None else None,
        "corpus": ({"coverage": corpus.coverage,
                    "ledger": list(corpus.ledger)[-64:]}
                   if corpus is not None else None),
        "registries": snaps,
        "aggregate": aggregate(snaps) if snaps else None,
        "slo": monitor.summary() if monitor is not None else None,
    }
    if extra:
        bundle.update(extra)
    return bundle


def dump_quality_observability(path, **bundle_kw):
    """Write the quality bundle as JSON and return `path`. Dropped as
    `quality_observability.json` next to a trace, `telemetry report
    --quality` auto-detects it."""
    bundle = quality_observability_bundle(**bundle_kw)
    return _dump_json(path, bundle)


def _dump_json(path, bundle):
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(bundle, f, indent=2, default=str)
    os.replace(tmp, path)
    return path
