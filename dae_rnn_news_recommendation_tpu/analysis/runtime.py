"""Runtime companion to the static rules: a compile-budget guard.

R4 catches recompile *hazards* syntactically; `compile_guard` pins the actual
count at runtime. XLA backend compiles are the multi-second events that wreck
step-time claims (the round-5 ragged-scan tail recompiled inside a timed
section), and `jax.monitoring` exposes each one as a duration event — so a
test can wrap a workload and assert "this path compiles at most N variants":

    with compile_guard(max_compiles=len(buckets)) as guard:
        for batch in feed:
            params, opt_state, metrics = step(params, opt_state, key, batch)
    assert guard.count <= len(buckets)

The guard raises `CompileBudgetExceeded` on exit when the budget is blown
(not mid-run: listeners fire inside jax's dispatch path, where raising would
corrupt unrelated state). Guards nest; each counts independently.
"""

import contextlib
import threading

# the event jax's dispatch layer records around every backend_compile call
# (jax._src.dispatch / pxla both funnel through this name)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


class CompileBudgetExceeded(AssertionError):
    """More XLA backend compiles happened under a guard than budgeted."""


class CompileWatcher:
    """Counts XLA backend-compile events while active.

    Listener registration in `jax.monitoring` is append-only in older jax
    releases, so the callback stays registered but no-ops once `stop()` has
    run; where the private unregister hook exists we use it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self._registered = False
        self.count = 0
        self.events = []  # durations (secs) of each compile seen

    def _listener(self, event, duration_secs, **kwargs):
        if event != BACKEND_COMPILE_EVENT:
            return
        with self._lock:
            if self._active:
                self.count += 1
                self.events.append(duration_secs)

    def start(self):
        import jax.monitoring

        with self._lock:
            self.count = 0
            self.events = []
            self._active = True
        if not self._registered:
            jax.monitoring.register_event_duration_secs_listener(
                self._listener)
            self._registered = True
        return self

    def stop(self):
        with self._lock:
            self._active = False
        if self._registered:
            try:
                from jax._src import monitoring as _m

                _m._unregister_event_duration_listener_by_callback(
                    self._listener)
                self._registered = False
            except Exception:
                pass  # stays registered but inactive; harmless
        return self.count


@contextlib.contextmanager
def compile_guard(max_compiles=None):
    """Context manager asserting an upper bound on XLA compiles inside it.

    `max_compiles=None` just counts (inspect `.count` after). Any overrun
    raises `CompileBudgetExceeded` on exit with the observed count and the
    per-compile durations, which usually identify the shape that retraced.
    """
    watcher = CompileWatcher()
    watcher.start()
    try:
        yield watcher
    finally:
        count = watcher.stop()
        if max_compiles is not None and count > max_compiles:
            durs = ", ".join(f"{d:.3f}s" for d in watcher.events)
            raise CompileBudgetExceeded(
                f"{count} XLA backend compiles observed, budget was "
                f"{max_compiles} (durations: {durs}) — an input shape or "
                "Python-scalar arg is varying across calls; see jaxcheck R4")
