"""CLI: python -m dae_rnn_news_recommendation_tpu.analysis [paths] [--json]

No paths: analyzes the self-clean contract set (the package + bench.py +
evidence/). Exit codes: 0 clean, 1 unsuppressed findings, 2 usage error.
"""

import argparse
import json
import re
import sys

from .core import RULES, analyze_paths, default_targets, repo_root


def _rule_sort_key(rule_id):
    """R2 before R10, rule families grouped (R* then C*)."""
    m = re.match(r"([A-Za-z]+)(\d+)$", rule_id)
    return (m.group(1), int(m.group(2))) if m else (rule_id, 0)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m dae_rnn_news_recommendation_tpu.analysis",
        description="jaxcheck: JAX tracing-hygiene, sync-fence and donation "
        "static analysis (rules: %s)" % ", ".join(sorted(RULES)))
    parser.add_argument("paths", nargs="*",
                        help="files or directories to analyze (default: the "
                        "package, bench.py, and evidence/)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit a JSON report (findings + suppressed with "
                        "reasons) instead of text")
    parser.add_argument("--select", metavar="RULES", default=None,
                        help="comma-separated rule ids or family letters to "
                        "run (e.g. 'R1,C3', 'S', 'R,C,S'); a bare family "
                        "letter selects every rule with that prefix; "
                        "default: all registered rules")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog (id: title) and exit")
    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code == 0 else 2

    if args.list_rules:
        for rule_id in sorted(RULES, key=_rule_sort_key):
            print(f"{rule_id}: {RULES[rule_id][0]}")
        return 0

    select = None
    if args.select is not None:
        tokens = {s.strip() for s in args.select.split(",") if s.strip()}
        families = {re.match(r"([A-Za-z]+)\d+$", r).group(1) for r in RULES}
        select, unknown = set(), []
        for tok in tokens:
            if tok in RULES:
                select.add(tok)
            elif tok in families:   # family letter: every rule it prefixes
                select.update(r for r in RULES
                              if re.match(r"([A-Za-z]+)\d+$", r).group(1)
                              == tok)
            else:
                unknown.append(tok)
        if not tokens or unknown:
            what = ", ".join(sorted(unknown)) if unknown else "(empty)"
            print(f"jaxcheck: --select names unknown rule(s): {what} "
                  f"(try --list-rules)", file=sys.stderr)
            return 2

    if args.paths:
        root, targets = repo_root(), args.paths
    else:
        root, targets = default_targets()
    findings, suppressed, n_files = analyze_paths(targets, root=root,
                                                  select=select)
    if n_files == 0:
        print("jaxcheck: no Python files found under the given paths",
              file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps({
            "files_analyzed": n_files,
            "findings": [f.to_json() for f in findings],
            "suppressed": [f.to_json() for f in suppressed],
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        tail = (f"jaxcheck: {len(findings)} finding(s) in {n_files} files"
                if findings else
                f"jaxcheck: clean ({n_files} files, "
                f"{len(suppressed)} reasoned suppression(s))")
        print(tail, file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
