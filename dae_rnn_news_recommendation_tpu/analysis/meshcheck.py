"""meshcheck rules S1-S5 — whole-program mesh/SPMD collective checkers (see
docs/jaxcheck.md for the catalog with in-repo examples).

r16 made sharded+IVF the default serving configuration and paid for it with a
deadlock class neither the R rules (per-file tracing hygiene) nor the C rules
(threading) could see: a `shard_map` program is a COLLECTIVE — every mesh
device must rendezvous on the same program — so two threads dispatching
concurrently can interleave their per-device participant arrivals and hang
the process. The fix was the process-wide mesh dispatch lock
(`parallel/mesh.MESH_DISPATCH_LOCK`); this module is the lint family that
keeps that invariant (and four more SPMD invariants) enforced ahead of
execution.

The rules ride the threadcheck `ProjectIndex` (project.py) extended here
with a mesh/SPMD index built lazily per project:

  * shard_map construction sites — `jax.shard_map` and the canonical
    `_shard_map` compat alias (parallel/mesh.py) — with the mapped callable
    resolved to its def, the in/out specs, and the axis names they bind;
  * the sharded-callable closure: functions that DISPATCH a shard_map
    program when called (`topk_sharded`, `sharded_ivf_topk`, training step
    closures), functions that FACTORY one (`make_sharded_serve_fn` returns
    `jit(run)` where `run` dispatches), and the names/attributes bound from
    factory calls (`self._serve_fns = {k: make_sharded_ivf_serve_fn(...)}`);
  * collective calls (`psum/pmean/all_gather/ppermute/axis_index/...`) with
    their axis-name operand;
  * `NamedSharding`/`PartitionSpec` constructions and the project's mesh
    axis vocabulary (the `MESH_AXIS_NAMES` tuple in parallel/mesh.py).

Like every jaxcheck rule these are heuristic by construction: callable
identity is nominal (bare-name and `self.attr` resolution, the same
convention as the C rules' lock keys), bodies are analyzed lexically, and
anything a rule cannot see carries a reasoned `# jaxcheck: disable=...`.
"""

import ast

from .core import rule
from . import project
from .concurrency import (_FUNC_DEFS, _make_keyer, _resolve_call, _units,
                          _walk_held)
from .rules import call_name, dotted, names_in

_SHARD_MAP_TAILS = {"shard_map", "_shard_map"}
_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
                "pshuffle", "all_to_all", "psum_scatter", "axis_index",
                "axis_size", "pcast"}
# collectives whose output is identical on every shard — the only producers
# that justify a replicated P() out_spec (S5)
_REDUCING = {"psum", "pmean", "pmax", "pmin", "all_gather"}
# axis-name operand position (default 1: psum(x, axis_name), ppermute(x,
# axis_name, perm), pcast(v, (axis,), to=...))
_AXIS_ARG_POS = {"axis_index": 0, "axis_size": 0}
_SPEC_TAILS = {"P", "PartitionSpec"}
_JIT_TAILS = {"jit", "pjit"}
# the sanctioned guard idioms S1 recognizes as holding the mesh dispatch
# lock when used as a `with` context: parallel/mesh.dispatch_lock() and the
# service/corpus wrappers that delegate to it
_GUARD_CALL_TAILS = {"dispatch_lock", "mesh_guard", "_mesh_guard",
                     "dispatch_guard", "_dispatch_guard"}
_HOST_NP_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "onp.asarray", "onp.array", "np.flatnonzero",
                  "numpy.flatnonzero", "np.nonzero", "numpy.nonzero",
                  "np.concatenate", "numpy.concatenate", "np.stack",
                  "numpy.stack"}
_DEVICE_MOVERS = {"jax.device_put", "device_put", "jax.device_get",
                  "device_get"}

MESH_KEY = "mesh:dispatch"


def _tail(name):
    return name.split(".")[-1] if name else None


def _is_shard_map_call(node):
    return isinstance(node, ast.Call) and \
        _tail(call_name(node)) in _SHARD_MAP_TAILS


def _own_nodes(fn):
    """All AST nodes of `fn`'s body outside nested function defs/lambdas —
    the unit-exclusive view (nested defs are their own units)."""
    out = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_DEFS + (ast.Lambda,)):
                continue
            out.append(child)
            visit(child)

    visit(fn)
    return out


def _kwarg(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _mesh_keyer(owner, mod, index):
    """The C-family lock keyer extended with the mesh dispatch guard: a
    `with dispatch_lock():` / `with self._mesh_guard():` context — or a
    mesh-named lock (`_MESH_LOCK`, `MESH_DISPATCH_LOCK`) — all collapse to
    the one `mesh:dispatch` key, because they ARE one process-wide lock."""
    base = _make_keyer(owner, mod, index)

    def keyer(expr):
        if isinstance(expr, ast.Call):
            if _tail(call_name(expr)) in _GUARD_CALL_TAILS:
                return MESH_KEY
            return None
        key = base(expr)
        if key is not None:
            parts = set(key.split(".")[-1].split(":")[-1].lower()
                        .strip("_").split("_"))
            if "mesh" in parts:
                return MESH_KEY
        return key

    return keyer


def _mesh_entries(index, mod):
    """Per-function entry-held sets under the mesh keyer (the C-family
    `_module_entries` with mesh-guard awareness): a helper only ever called
    under `with self._mesh_guard():` is analyzed with the guard held."""
    cached = index._cache.get(("mesh_entries", mod.relpath))
    if cached is not None:
        return cached
    units = _units(mod)
    entry = {id(node): frozenset() for _, node in units}
    for _ in range(2):
        acc = {}
        for owner, node in units:
            keyer = _mesh_keyer(owner, mod, index)
            nodes, _ = _walk_held(node, keyer, entry[id(node)])
            for n, held in nodes:
                if not isinstance(n, ast.Call):
                    continue
                callee = _resolve_call(n, owner, mod)
                if callee is not None and id(callee) in entry:
                    prev = acc.get(id(callee))
                    acc[id(callee)] = held if prev is None else (prev & held)
        entry = {k: frozenset(acc.get(k) or frozenset()) for k in entry}
    index._cache[("mesh_entries", mod.relpath)] = (units, entry)
    return units, entry


# ------------------------------------------------------------- mesh index

class ShardMapSite:
    """One shard_map construction: the call, its resolved mapped callable
    (a FunctionDef/Lambda or None), and the axis names its specs bind."""

    __slots__ = ("call", "relpath", "body", "in_spec_elts", "out_spec_elts",
                 "spec_literals", "spec_vars")

    def __init__(self, call, relpath, body, in_spec, out_spec):
        self.call = call
        self.relpath = relpath
        self.body = body
        self.in_spec_elts = _spec_elts(in_spec)
        self.out_spec_elts = _spec_elts(out_spec)
        self.spec_literals, self.spec_vars = set(), set()
        for expr in (in_spec, out_spec):
            if expr is None:
                continue
            for node in ast.walk(expr):
                if not (isinstance(node, ast.Call)
                        and _tail(call_name(node)) in _SPEC_TAILS):
                    continue
                for arg in node.args:
                    items = arg.elts if isinstance(arg, ast.Tuple) else [arg]
                    for item in items:
                        if isinstance(item, ast.Constant) and \
                                isinstance(item.value, str):
                            self.spec_literals.add(item.value)
                        elif isinstance(item, ast.Name):
                            self.spec_vars.add(item.id)


def _spec_elts(expr):
    """The per-operand spec expressions: a tuple literal's elements, a
    single spec applied to every operand (list of one marker), or None when
    the spec expression is absent/opaque."""
    if expr is None:
        return None
    if isinstance(expr, ast.Tuple):
        return list(expr.elts)
    return [expr]


def _spec_is_replicated(elt):
    """True for `P()` / `P(None, None)` — an out_spec claiming the body's
    output is identical on every shard."""
    if not (isinstance(elt, ast.Call)
            and _tail(call_name(elt)) in _SPEC_TAILS):
        return False
    if elt.keywords:
        return False
    return all(isinstance(a, ast.Constant) and a.value is None
               for a in elt.args)


def _spec_has_axis(elt):
    """True when a spec element names at least one mesh axis (a string
    literal or an axis variable) — i.e. the operand differs per shard."""
    if elt is None:
        return True
    for node in ast.walk(elt):
        if isinstance(node, ast.Call) and \
                _tail(call_name(node)) in _SPEC_TAILS:
            for arg in node.args:
                items = arg.elts if isinstance(arg, ast.Tuple) else [arg]
                for item in items:
                    if not (isinstance(item, ast.Constant)
                            and item.value is None):
                        return True
            return False
    return True   # opaque spec expression: conservatively per-shard


def _local_defs(scope, mod_tree):
    """name -> FunctionDef, innermost-first: defs inside `scope` shadow
    same-named defs elsewhere in the module."""
    defs = {}
    for node in ast.walk(mod_tree):
        if isinstance(node, _FUNC_DEFS):
            defs.setdefault(node.name, node)
    if scope is not None:
        for node in ast.walk(scope):
            if isinstance(node, _FUNC_DEFS) and node is not scope:
                defs[node.name] = node
    return defs


def _resolve_mapped(call, scope, mod_tree):
    """The FunctionDef/Lambda the shard_map maps, or None. A lambda that
    just forwards to a local function (`lambda p, b, k: local_loss(p, b,
    k)`) resolves to that function — dp.py's donation idiom."""
    if not call.args:
        return None
    arg = call.args[0]
    defs = _local_defs(scope, mod_tree)
    if isinstance(arg, ast.Call) and \
            _tail(call_name(arg)) == "partial" and arg.args:
        arg = arg.args[0]
    if isinstance(arg, ast.Lambda):
        if isinstance(arg.body, ast.Call) and \
                isinstance(arg.body.func, ast.Name) and \
                arg.body.func.id in defs:
            return defs[arg.body.func.id]
        return arg
    if isinstance(arg, ast.Name):
        return defs.get(arg.id)
    return None


def _resolve_spec(expr, scope):
    """A spec passed as a bare name resolves to its assignment in the
    enclosing function (`p_specs = {...}; in_specs=(p_specs, ...)`)."""
    if not isinstance(expr, ast.Name) or scope is None:
        return expr
    for node in ast.walk(scope):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == expr.id
                for t in node.targets):
            return node.value
    return expr


class MeshIndex:
    """Project-wide mesh/SPMD facts, cached on ProjectIndex._cache."""

    def __init__(self):
        self.sites = []              # [ShardMapSite]
        self.by_mod = {}             # relpath -> [ShardMapSite]
        self.call_site = {}          # id(Call inside a body) -> ShardMapSite
        self.dispatcher_names = set()   # calling one dispatches a collective
        self.dispatcher_ids = set()     # id(FunctionDef) of the same
        self.factory_names = set()      # calling one RETURNS a sharded callable
        self.class_sharded_attrs = {}   # id(ClassIndex) -> {attr}
        self.vocab = None               # mesh axis vocabulary, or None


def mesh_index(index):
    cached = index._cache.get("mesh")
    if cached is not None:
        return cached
    mi = MeshIndex()
    facts = []   # (mod, owner, fn, own, constructs, bound_sm, call_tails)
    for mod in index.modules.values():
        for owner, fn in _units(mod):
            own = _own_nodes(fn)
            constructs = [n for n in own if _is_shard_map_call(n)]
            bound_sm = set()
            for n in own:
                if isinstance(n, ast.Assign) and _is_shard_map_call(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            bound_sm.add(t.id)
            call_tails = set()
            for n in own:
                if isinstance(n, ast.Call):
                    if isinstance(n.func, ast.Name):
                        call_tails.add(n.func.id)
            facts.append((mod, owner, fn, own, constructs, bound_sm,
                          call_tails))
            for c in constructs:
                body = _resolve_mapped(c, fn, mod.tree)
                in_spec = _resolve_spec(
                    _kwarg(c, "in_specs")
                    or (c.args[2] if len(c.args) > 2 else None), fn)
                out_spec = _resolve_spec(
                    _kwarg(c, "out_specs")
                    or (c.args[3] if len(c.args) > 3 else None), fn)
                site = ShardMapSite(c, mod.relpath, body, in_spec, out_spec)
                mi.sites.append(site)
                mi.by_mod.setdefault(mod.relpath, []).append(site)
                trees = [body] if body is not None else []
                if c.args and isinstance(c.args[0], ast.Lambda):
                    trees.append(c.args[0])
                for tree in trees:
                    for n in ast.walk(tree):
                        if isinstance(n, ast.Call):
                            mi.call_site.setdefault(id(n), site)
        if mi.vocab is None:
            mi.vocab = _module_vocab(mod)

    # dispatcher seed: a unit that CALLS a shard_map program it built —
    # `shard_map(...)(args)` immediately, or via a local binding
    for mod, owner, fn, own, constructs, bound_sm, _tails in facts:
        direct = any(
            isinstance(n, ast.Call)
            and (_is_shard_map_call(n.func)
                 or (isinstance(n.func, ast.Name) and n.func.id in bound_sm))
            for n in own)
        if direct:
            mi.dispatcher_names.add(fn.name)
            mi.dispatcher_ids.add(id(fn))
    # propagate dispatcher-ness: bare-name calls of a dispatcher, and a
    # parent whose NESTED def dispatches (the training-step shape: `step`
    # hands `loss_of` to value_and_grad) — unless the parent returns the
    # nested callable instead of running it (then it's a factory, below)
    for _ in range(3):
        for mod, owner, fn, own, _c, _b, call_tails in facts:
            if id(fn) in mi.dispatcher_ids:
                continue
            hit = bool(call_tails & mi.dispatcher_names)
            if not hit:
                for node in ast.walk(fn):
                    if node is not fn and isinstance(node, _FUNC_DEFS) and \
                            id(node) in mi.dispatcher_ids and \
                            not _returns_name(own, node.name):
                        hit = True
                        break
            if hit:
                mi.dispatcher_names.add(fn.name)
                mi.dispatcher_ids.add(id(fn))

    # factories: units returning a sharded callable — a shard_map
    # construction, `jit(dispatcher)`, a dispatcher def, or (transitively)
    # another factory's result
    for _ in range(3):
        for mod, owner, fn, own, constructs, bound_sm, _tails in facts:
            if fn.name in mi.factory_names:
                continue
            if _returns_sharded(own, bound_sm, mi):
                mi.factory_names.add(fn.name)

    # class attributes bound from factory calls anywhere in the class body:
    # `self._serve_fns = {k: make_sharded_ivf_serve_fn(...) for ...}`
    for mod in index.modules.values():
        for ci in mod.classes:
            attrs = set()
            for node in ast.walk(ci.node):
                if not isinstance(node, ast.Assign):
                    continue
                if not _value_is_sharded(node.value, mi, ci):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        attrs.add(t.attr)
            if attrs:
                mi.class_sharded_attrs[id(ci)] = attrs

    index._cache["mesh"] = mi
    return mi


def _returns_name(own, name):
    for n in own:
        if isinstance(n, ast.Return) and n.value is not None:
            if name in names_in(n.value):
                return True
    return False


def _returns_sharded(own, bound_sm, mi):
    bound_fact = set()
    for n in own:
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) and \
                isinstance(n.value.func, ast.Name) and \
                n.value.func.id in mi.factory_names:
            for t in n.targets:
                if isinstance(t, ast.Name):
                    bound_fact.add(t.id)
    for n in own:
        if not isinstance(n, ast.Return) or n.value is None:
            continue
        for sub in ast.walk(n.value):
            if _is_shard_map_call(sub):
                return True
            if isinstance(sub, ast.Name) and \
                    sub.id in (bound_sm | bound_fact
                               | mi.dispatcher_names):
                return True
            if isinstance(sub, ast.Call):
                name = call_name(sub)
                if name in mi.factory_names or \
                        (_tail(name) in _JIT_TAILS and sub.args
                         and isinstance(sub.args[0], ast.Name)
                         and sub.args[0].id in mi.dispatcher_names):
                    return True
    return False


def _value_is_sharded(expr, mi, owner):
    """True when an assigned expression produces a shard_map-built callable
    (or a collection of them): a construction without immediate call, or a
    call of a factory (bare name or `self.method`)."""
    for sub in ast.walk(expr):
        if _is_shard_map_call(sub):
            return True
        if isinstance(sub, ast.Call):
            f = sub.func
            if isinstance(f, ast.Name) and f.id in mi.factory_names:
                return True
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self" \
                    and f.attr in mi.factory_names:
                return True
    return False


def _module_vocab(mod):
    """The `MESH_AXIS_NAMES = ("data", ...)` tuple, when this module
    declares one (parallel/mesh.py in the real project; fixtures may carry
    their own)."""
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "MESH_AXIS_NAMES"
                for t in stmt.targets):
            if isinstance(stmt.value, (ast.Tuple, ast.List)):
                vals = [e.value for e in stmt.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)]
                if vals:
                    return set(vals)
    return None


# ------------------------------------------------------------------- S1

@rule("S1", "shard_map dispatch from a thread-reachable site without the "
      "mesh dispatch lock")
def check_s1(ctx):
    """A shard_map program is a collective: all mesh devices rendezvous on
    the SAME program, so two threads dispatching concurrently can interleave
    their per-device participant arrivals and deadlock the process — the
    exact bug r16 hit when fleet replicas began sharing one sharded corpus.
    This rule flags any call of a shard_map-built callable (direct dispatch,
    a dispatcher function like `topk_sharded`, or a name/attribute bound
    from a factory like `make_sharded_serve_fn`) from a thread-reachable
    unit — a method of a thread-shared class (threadcheck's notion: owns a
    lock or spawns/receives threads) or a function used as a Thread target —
    without holding the mesh dispatch lock. The sanctioned idiom is
    `parallel/mesh.dispatch_lock()` (or a wrapper delegating to it:
    `service._mesh_guard`, `corpus._dispatch_guard`), tracked through the
    call graph like the C rules track locks."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    mi = mesh_index(index)
    if not (mi.sites or mi.dispatcher_names or mi.factory_names):
        return []
    target_tails = {t.split(".")[-1] for t in index.thread_target_names}
    units, entry = _mesh_entries(index, mod)
    parents = _parents_map(mod)
    out = []
    for owner, fn in units:
        reachable = (owner is not None and owner.is_thread_shared()) or \
            fn.name in target_tails
        if not reachable:
            continue
        local_sharded = _scope_sharded_names(fn, parents, mi, owner)
        keyer = _mesh_keyer(owner, mod, index)
        nodes, _ = _walk_held(fn, keyer, entry[id(fn)])
        for n, held in nodes:
            if not isinstance(n, ast.Call) or MESH_KEY in held:
                continue
            desc = _dispatch_desc(n, mi, owner, local_sharded)
            if desc is None:
                continue
            out.append(ctx.finding(
                n, f"{desc} from thread-reachable "
                f"`{_unit_name(owner, fn)}` without the mesh dispatch lock "
                "— concurrent shard_map programs interleave their "
                "per-device rendezvous and deadlock (the r16 bug class); "
                "wrap the call in `with parallel.mesh.dispatch_lock():`"))
    return out


def _parents_map(mod):
    """id(FunctionDef) -> enclosing FunctionDef chain, innermost first."""
    parents = {}

    def visit(node, chain):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_DEFS):
                parents[id(child)] = chain
                visit(child, [child] + chain)
            else:
                visit(child, chain)

    visit(mod.tree, [])
    return parents


def _scope_sharded_names(fn, parents, mi, owner):
    """Local names bound to sharded callables in `fn` or any enclosing
    function (a closure dispatching `serve_fn` bound by its parent)."""
    names = set()
    for scope in [fn] + parents.get(id(fn), []):
        for n in _own_nodes(scope):
            if isinstance(n, ast.Assign) and \
                    _binding_is_sharded(n.value, mi, owner):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _binding_is_sharded(expr, mi, owner):
    if _value_is_sharded(expr, mi, owner):
        return True
    # `serve_fn = self._serve_fns[k]` — indexing into a sharded collection
    attrs = mi.class_sharded_attrs.get(id(owner), set()) if owner else set()
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Attribute) and \
                isinstance(sub.value, ast.Name) and \
                sub.value.id == "self" and sub.attr in attrs:
            return True
    return False


def _dispatch_desc(call, mi, owner, local_sharded):
    """Human-readable description when `call` dispatches a shard_map-built
    callable, else None."""
    f = call.func
    if _is_shard_map_call(f):
        return "direct `shard_map(...)(...)` dispatch"
    base = f.value if isinstance(f, ast.Subscript) else f
    if isinstance(base, ast.Name):
        if base.id in local_sharded:
            return f"dispatch of sharded callable `{base.id}`"
        if isinstance(f, ast.Name) and f.id in mi.dispatcher_names:
            return f"call of shard_map dispatcher `{f.id}`"
    if isinstance(base, ast.Attribute) and \
            isinstance(base.value, ast.Name) and base.value.id == "self" \
            and owner is not None:
        attrs = mi.class_sharded_attrs.get(id(owner), set())
        if base.attr in attrs:
            return f"dispatch of sharded callable `self.{base.attr}`"
        meth = owner.methods.get(base.attr)
        if isinstance(f, ast.Attribute) and meth is not None and \
                id(meth) in mi.dispatcher_ids:
            return f"call of shard_map dispatcher `self.{base.attr}`"
    return None


def _unit_name(owner, fn):
    return f"{owner.name}.{fn.name}" if owner is not None else fn.name


# ------------------------------------------------------------------- S2

@rule("S2", "collective under control flow divergent across shards")
def check_s2(ctx):
    """Inside a shard_map body every shard runs the same Python trace — but
    a collective nested under an `if`/`while` (trace-time divergence if the
    predicate is a concrete per-shard value) or under a `lax.cond` branch
    predicated on per-shard data makes shards DISAGREE on whether the
    rendezvous happens: the shards that enter wait forever for the shards
    that don't. Taint is seeded from the mapped function's per-shard
    operands (parameters whose in_spec names a mesh axis; replicated `P()`
    operands are shard-invariant and exempt) and follows assignments.
    Uniform predicates — closure config, static shapes — never fire."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    mi = mesh_index(index)
    out, seen = [], set()
    for site in mi.by_mod.get(mod.relpath, ()):
        body = site.body
        if body is None:
            continue
        tainted = _body_taint(site, per_shard_only=True)
        for node in ast.walk(body):
            if isinstance(node, (ast.If, ast.While)):
                if not (names_in(node.test) & tainted):
                    continue
                for stmt in node.body + node.orelse:
                    for sub in ast.walk(stmt):
                        if _collective_tail(sub) and id(sub) not in seen:
                            seen.add(id(sub))
                            out.append(ctx.finding(
                                sub, f"collective `{call_name(sub)}` under "
                                "a branch predicated on per-shard data "
                                f"(line {node.lineno}) — shards disagreeing "
                                "on the predicate skip the rendezvous and "
                                "the rest hang; hoist the collective out or "
                                "make the predicate shard-invariant"))
            elif isinstance(node, ast.Call) and \
                    _tail(call_name(node)) in ("cond", "switch") and \
                    (call_name(node) or "").split(".")[0] in ("jax", "lax"):
                if not node.args or not (names_in(node.args[0]) & tainted):
                    continue
                branches = node.args[1:]
                defs = _local_defs(body, mod.tree)
                for br in branches:
                    tree = br if isinstance(br, ast.Lambda) else \
                        defs.get(br.id) if isinstance(br, ast.Name) else None
                    if tree is None:
                        continue
                    for sub in ast.walk(tree):
                        if _collective_tail(sub) and id(sub) not in seen:
                            seen.add(id(sub))
                            out.append(ctx.finding(
                                sub, f"collective `{call_name(sub)}` inside "
                                "a `lax.cond`/`switch` branch whose "
                                "predicate is per-shard data (line "
                                f"{node.lineno}) — only the shards taking "
                                "this branch rendezvous; compute both "
                                "branches and `where`-select, or psum the "
                                "predicate first"))
    return out


def _collective_tail(node):
    return isinstance(node, ast.Call) and \
        _tail(call_name(node)) in _COLLECTIVES


def _body_taint(site, per_shard_only=False):
    """Names carrying per-shard (or, with per_shard_only=False, any traced
    operand) data inside the mapped body: seeded from its parameters —
    positionally matched against in_specs when resolvable — plus nested-def
    parameters, propagated through assignments and loop targets."""
    body = site.body
    params = []
    args = getattr(body, "args", None)
    if args is not None:
        params = [a.arg for a in args.args + args.posonlyargs
                  + args.kwonlyargs]
    tainted = set()
    elts = site.in_spec_elts
    for i, p in enumerate(params):
        if per_shard_only and elts is not None:
            elt = elts[i] if len(elts) > 1 and i < len(elts) else elts[0]
            if not _spec_has_axis(elt):
                continue
        tainted.add(p)
    for node in ast.walk(body):
        if isinstance(node, _FUNC_DEFS) and node is not body:
            for a in node.args.args:
                tainted.add(a.arg)
    for _ in range(2):
        for node in ast.walk(body):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
                src = node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets, src = [node.target], node.value
            elif isinstance(node, ast.For):
                targets, src = [node.target], node.iter
            else:
                continue
            if src is None or not (names_in(src) & tainted):
                continue
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        tainted.add(sub.id)
    return tainted


# ------------------------------------------------------------------- S3

@rule("S3", "collective axis name not bound by the enclosing shard_map / "
      "unknown mesh axis")
def check_s3(ctx):
    """A collective names the mesh axis it reduces over; an axis the
    enclosing shard_map's specs never bind — or a string outside the
    project's mesh vocabulary (`parallel/mesh.MESH_AXIS_NAMES`) — is a typo
    XLA only reports at trace time, from whichever call site traces first.
    Matching is nominal and deliberately conservative: literal collective
    axes are judged against literal spec axes, variable axes against spec
    variables; mixed or unresolvable specs stay silent. PartitionSpec
    constructions are vocabulary-checked too."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    mi = mesh_index(index)
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        tail = _tail(name)
        if tail in _COLLECTIVES:
            axis = _kwarg(node, "axis_name")
            if axis is None:
                pos = _AXIS_ARG_POS.get(tail, 1)
                axis = node.args[pos] if len(node.args) > pos else None
            if axis is None:
                continue
            items = axis.elts if isinstance(axis, ast.Tuple) else [axis]
            site = mi.call_site.get(id(node))
            for item in items:
                lit = item.value if (isinstance(item, ast.Constant) and
                                     isinstance(item.value, str)) else None
                if site is not None:
                    ok_lit = (lit is not None and site.spec_literals
                              and not site.spec_vars
                              and lit not in site.spec_literals)
                    ok_var = (isinstance(item, ast.Name)
                              and site.spec_vars and not site.spec_literals
                              and item.id not in site.spec_vars)
                    if ok_lit or ok_var:
                        shown = lit if lit is not None else item.id
                        bound = sorted(site.spec_literals
                                       or site.spec_vars)
                        out.append(ctx.finding(
                            node, f"`{name}` names axis `{shown}` but the "
                            "enclosing shard_map's specs bind "
                            f"{', '.join(f'`{b}`' for b in bound)} — an "
                            "unbound axis fails at trace time from "
                            "whichever caller traces first"))
                        continue
                if lit is not None and mi.vocab is not None and \
                        lit not in mi.vocab:
                    out.append(ctx.finding(
                        node, f"`{name}` names axis '{lit}', not in the "
                        "mesh axis vocabulary "
                        f"({', '.join(sorted(mi.vocab))}) — no mesh in "
                        "this project binds it (MESH_AXIS_NAMES, "
                        "parallel/mesh.py)"))
        elif tail in _SPEC_TAILS and mi.vocab is not None:
            for arg in node.args:
                items = arg.elts if isinstance(arg, ast.Tuple) else [arg]
                for item in items:
                    if isinstance(item, ast.Constant) and \
                            isinstance(item.value, str) and \
                            item.value not in mi.vocab:
                        out.append(ctx.finding(
                            node, f"PartitionSpec names axis "
                            f"'{item.value}', not in the mesh axis "
                            "vocabulary "
                            f"({', '.join(sorted(mi.vocab))}) — arrays "
                            "placed with it can never match a mesh axis"))
    return out


# ------------------------------------------------------------------- S4

@rule("S4", "host-side work captured in a shard_map body")
def check_s4(ctx):
    """`device_put`/`device_get`, `np.` materialization of traced values, or
    host-list construction inside the mapped function runs per-trace on
    TRACERS: it either breaks tracing outright or pins a host round-trip
    into every dispatch of the collective — the generalization of the
    `r1_ivf_cell_lists` hazard from jit bodies to shard_map bodies. Static
    `np` arithmetic on Python ints (tile shapes) is untouched: only calls
    whose arguments involve the body's traced operands fire; device
    transfers fire unconditionally (there is no device to move to/from
    inside the mapped program)."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    mi = mesh_index(index)
    out, seen = [], set()
    for site in mi.by_mod.get(mod.relpath, ()):
        body = site.body
        if body is None:
            continue
        tainted = _body_taint(site, per_shard_only=False)
        for node in ast.walk(body):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            name = call_name(node)
            arg_names = set()
            for a in list(node.args) + [kw.value for kw in node.keywords]:
                arg_names |= names_in(a)
            if name in _DEVICE_MOVERS:
                seen.add(id(node))
                out.append(ctx.finding(
                    node, f"`{name}` inside a shard_map body — the mapped "
                    "function runs per shard under trace; device placement "
                    "belongs to the caller (specs/shardings), not the "
                    "body"))
            elif name in _HOST_NP_CALLS and (arg_names & tainted):
                seen.add(id(node))
                out.append(ctx.finding(
                    node, f"`{name}` materializes a traced per-shard value "
                    "on the host inside a shard_map body — this breaks "
                    "tracing or pins a host sync into every collective "
                    "dispatch; keep the body device-only (jnp/lax)"))
            elif name in ("list", "tuple") and (arg_names & tainted):
                seen.add(id(node))
                out.append(ctx.finding(
                    node, f"host `{name}(...)` of a traced per-shard value "
                    "inside a shard_map body — iterating a tracer "
                    "materializes it element-wise on the host; use jnp "
                    "ops on the whole array"))
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "tolist" and \
                    (names_in(node.func.value) & tainted):
                seen.add(id(node))
                out.append(ctx.finding(
                    node, "`.tolist()` on a traced per-shard value inside "
                    "a shard_map body — host materialization under trace; "
                    "keep the body device-only"))
    return out


# ------------------------------------------------------------------- S5

@rule("S5", "out_spec claims replication for an output the body never "
      "reduces")
def check_s5(ctx):
    """An `out_specs` entry of `P()` promises the runtime that the body's
    corresponding output is IDENTICAL on every shard — the runtime then
    reads one shard's buffer and calls it the answer. Only a reducing
    collective (`psum`/`pmean`/`pmax`/`pmin`/`all_gather`) makes that true;
    a per-shard value returned through `P()` silently serves shard 0's
    partial result. This is the static twin of shard_map's `check_rep`
    (which the Pallas paths must disable — `check_rep=False` — because
    pallas_call carries no replication rule, leaving exactly this hole).
    Outputs whose return expression contains, or derives by assignment
    from, a reducing collective pass; everything else under a replicated
    spec fires."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    mi = mesh_index(index)
    out = []
    for site in mi.by_mod.get(mod.relpath, ()):
        body, elts = site.body, site.out_spec_elts
        if body is None or elts is None or isinstance(body, ast.Lambda):
            continue
        rep = [i for i, e in enumerate(elts) if _spec_is_replicated(e)]
        if not rep:
            continue
        reduced = _reduced_names(body)
        for ret in _own_nodes(body):
            if not isinstance(ret, ast.Return) or ret.value is None:
                continue
            v = ret.value
            if isinstance(v, ast.Tuple) and len(v.elts) == len(elts):
                exprs = [(i, v.elts[i]) for i in rep]
            elif len(elts) == 1:
                exprs = [(0, v)]
            else:
                continue   # opaque return shape: stay silent
            for i, e in exprs:
                if _expr_reduced(e, reduced):
                    continue
                out.append(ctx.finding(
                    ret, f"out_specs position {i} claims `P()` "
                    "(replicated) but the returned value is never reduced "
                    "with a collective — the runtime will serve one "
                    "shard's partial result as the answer; psum/pmean it "
                    "(or shard the out_spec)"))
    return out


def _reduced_names(body):
    """Names (transitively) assigned from a reducing collective."""
    reduced = set()
    for _ in range(2):
        for node in ast.walk(body):
            if not isinstance(node, ast.Assign):
                continue
            ok = any(isinstance(s, ast.Call)
                     and _tail(call_name(s)) in _REDUCING
                     for s in ast.walk(node.value))
            ok = ok or bool(names_in(node.value) & reduced)
            if not ok:
                continue
            for t in node.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        reduced.add(sub.id)
    return reduced


def _expr_reduced(expr, reduced_names):
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and \
                _tail(call_name(sub)) in _REDUCING:
            return True
    return bool(names_in(expr) & reduced_names)
