"""jaxcheck rules R1-R14 — AST checkers for the JAX hazard classes this repo
has been bitten by (see docs/jaxcheck.md for the catalog with in-repo
examples of each).

Every rule is heuristic by construction: Python is too dynamic for proof, so
each checker aims for the precision sweet spot where true findings from this
codebase's real bug history are caught (tests/fixtures/jaxcheck plants one of
each) while the repo's legitimate patterns pass without noise. Anything a
rule cannot see (a guard in a caller, a fence inside an imported helper) is
handled with a reasoned `# jaxcheck: disable=...` at the site — the reason
requirement keeps those honest.
"""

import ast

# ---------------------------------------------------------------- helpers

_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}
_SCAN_NAMES = {"lax.scan", "jax.lax.scan"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
# call prefixes whose results live on device (R1 dataflow seeds)
_DEVICE_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "lax.", "jax.nn.",
                    "jax.random.", "jax.scipy.", "jax.ops.")
_HOST_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray",
                       "numpy.array", "onp.asarray", "onp.array"}
_DEVICE_GET = {"jax.device_get", "device_get"}
_TIMER_CALLS = {"time.time", "time.perf_counter"}
_FENCE_ATTRS = {"block_until_ready"}
_FENCE_NAMES = {"_hard_sync"} | _DEVICE_GET
_STACK_NAMES = {"np.stack", "jnp.stack", "numpy.stack", "jax.numpy.stack"}
_KEY_MAKERS = {"jax.random.PRNGKey", "random.PRNGKey", "jr.PRNGKey",
               "jax.random.key", "jax.random.fold_in", "random.fold_in"}
_KEY_SPLITS = {"jax.random.split", "random.split", "jr.split"}

from .core import rule  # noqa: E402  (registry lives in core)


def dotted(node):
    """'jax.random.split' for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def call_name(node):
    return dotted(node.func) if isinstance(node, ast.Call) else None


def assign_target_names(stmt):
    """Every dotted name (re)bound by this statement's targets."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.For):
        targets = [stmt.target]
    names = set()
    for t in targets:
        for node in ast.walk(t):
            d = dotted(node)
            if d:
                names.add(d)
    return names


def names_in(node):
    """All dotted names loaded anywhere under `node`."""
    found = set()
    for n in ast.walk(node):
        d = dotted(n)
        if d:
            found.add(d)
    return found


def func_defs(tree):
    """name -> list of FunctionDef nodes (module-wide, any nesting)."""
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    return defs


def body_lists(root):
    """Every statement list under `root` (function/loop/if/with/try bodies),
    without descending into nested function defs."""
    out = []

    def visit(node):
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(node, field, None)
            if isinstance(stmts, list) and stmts and \
                    isinstance(stmts[0], ast.stmt):
                out.append(stmts)
                for s in stmts:
                    if not isinstance(s, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        visit(s)
        handlers = getattr(node, "handlers", None)
        if handlers:
            for h in handlers:
                visit(h)

    visit(root)
    return out


# ------------------------------------------------------------- jit index

def traced_roots(tree):
    """Function/lambda nodes whose bodies run under trace: @jit-decorated,
    passed to jax.jit(...), or carried by lax.scan. Plus the transitive
    closure of same-module functions they call (host-sync is a bug anywhere
    *reachable* from traced code)."""
    defs = func_defs(tree)
    direct, seen = [], set()

    def add(node):
        if node is not None and id(node) not in seen:
            seen.add(id(node))
            direct.append(node)

    def resolve(arg):
        if isinstance(arg, ast.Lambda):
            return arg
        if isinstance(arg, ast.Name) and arg.id in defs:
            return defs[arg.id][0]
        return None

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                d = dotted(dec) or call_name(dec)
                if d in _JIT_NAMES:
                    add(node)
                elif isinstance(dec, ast.Call) and \
                        dotted(dec.func) in _PARTIAL_NAMES and dec.args and \
                        dotted(dec.args[0]) in _JIT_NAMES:
                    add(node)
        elif isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in _JIT_NAMES and node.args:
                add(resolve(node.args[0]))
            elif name in _SCAN_NAMES and node.args:
                add(resolve(node.args[0]))

    # transitive closure over same-module calls (weak contexts: no param
    # assumptions, just "this body may run under trace")
    closure = []
    work = list(direct)
    while work:
        fn = work.pop()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = node.func.id
                for d in defs.get(callee, []):
                    if id(d) not in seen:
                        seen.add(id(d))
                        closure.append(d)
                        work.append(d)
    return direct, closure


# ------------------------------------------------------------------- R1

@rule("R1", "host-sync call reachable inside jit-traced code")
def check_r1(ctx):
    direct, closure = traced_roots(ctx.tree)
    out = []
    for root in direct + closure:
        out.extend(_r1_walk_root(ctx, root))
    return out


def _involves(node, device_vals):
    return bool(names_in(node) & device_vals)


def _is_device_call(node, device_vals):
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name and (name.startswith(_DEVICE_PREFIXES)):
        return True
    # method call on a device value (h.sum(), x.astype(...))
    if isinstance(node.func, ast.Attribute) and \
            _involves(node.func.value, device_vals):
        return True
    return False


def _r1_walk_root(ctx, root):
    findings = []
    device_vals = set()

    def value_is_device(value):
        if _is_device_call(value, device_vals):
            return True
        if isinstance(value, (ast.BinOp, ast.UnaryOp, ast.Subscript,
                              ast.IfExp, ast.Tuple, ast.List)):
            return _involves(value, device_vals)
        if isinstance(value, ast.Name) and value.id in device_vals:
            return True
        return False

    def check_call(node):
        name = call_name(node)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("item", "tolist") and not node.args:
            findings.append(ctx.finding(
                node, f".{node.func.attr}() forces a device->host sync; "
                "inside traced code it breaks tracing (and under async "
                "dispatch it stalls the pipeline) — return the array and "
                "fetch on host"))
        elif name in _HOST_MATERIALIZERS:
            findings.append(ctx.finding(
                node, f"{name}(...) materializes on host inside traced code "
                "— use jnp equivalents so the value stays a tracer"))
        elif name in _DEVICE_GET:
            findings.append(ctx.finding(
                node, "jax.device_get inside traced code is a host sync — "
                "hoist it out of the jitted/scanned function"))
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in _FENCE_ATTRS:
            findings.append(ctx.finding(
                node, ".block_until_ready() has no meaning under trace — "
                "it is a host-side fence; remove it from traced code"))
        elif name in ("float", "int", "bool", "complex") and node.args and \
                _involves(node.args[0], device_vals):
            findings.append(ctx.finding(
                node, f"{name}() on a traced value concretizes it "
                "(ConcretizationTypeError at trace time, or a silent host "
                "sync) — keep it an array"))

    def check_test(stmt, test):
        if isinstance(test, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return  # `x is None` never calls __bool__ on a tracer
        if _involves(test, device_vals):
            findings.append(ctx.finding(
                stmt, "branching on a traced value calls __bool__ on a "
                "tracer (TracerBoolConversionError) — use lax.cond/jnp.where "
                "or hoist the predicate to host"))

    def visit(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node is not root:
            return  # nested defs are separate closure entries
        if isinstance(node, ast.Call):
            check_call(node)
        if isinstance(node, (ast.If, ast.While)):
            check_test(node, node.test)
        elif isinstance(node, ast.Assert):
            check_test(node, node.test)
        if isinstance(node, ast.Assign) and value_is_device(node.value):
            for t in node.targets:
                for n in ast.walk(t):
                    d = dotted(n)
                    if d:
                        device_vals.add(d)
        for child in ast.iter_child_nodes(node):
            visit(child)

    if isinstance(root, ast.Lambda):
        visit(root.body)
    else:
        for stmt in root.body:
            visit(stmt)
    return findings


# ------------------------------------------------------------------- R2

def _r2_scope(relpath):
    import os

    base = os.path.basename(relpath)
    parts = relpath.replace("\\", "/").split("/")
    # devprof: the device timer itself lives by the same fencing law it
    # enforces on bench/evidence code; tuning: the autotuner's candidate
    # race is a timed region like any bench leg (its measure loop must go
    # through devprof.measure, never a bare perf_counter pair)
    return base.startswith("bench") or "evidence" in parts \
        or "devprof" in base or "tuning" in parts \
        or base.startswith("r2_tuning")


@rule("R2", "timed region without a fetch fence", scope=_r2_scope)
def check_r2(ctx):
    """time.time()/perf_counter() deltas in bench/evidence code must have a
    device fetch between start and read, or the timer measures dispatch, not
    compute (the round-5 `block_until_ready`-lies lesson). Watchdog/deadline
    arithmetic uses time.monotonic() in this repo and is exempt by that
    convention."""
    fence_fns = _fence_functions(ctx.tree)
    out = []
    roots = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))]
    seen_lines = set()
    for root in roots:
        for stmts in body_lists(root):
            out.extend(_r2_scan_body(ctx, stmts, fence_fns, seen_lines))
    return out


def _fence_functions(tree):
    """Local function names whose bodies fence directly (one hop): calling
    them inside a timed region counts as fencing it."""
    fences = set()
    for name, nodes in func_defs(tree).items():
        for fn in nodes:
            for node in ast.walk(fn):
                if _is_fence_call(node):
                    fences.add(name)
    return fences


def _is_fence_call(node, fence_fns=()):
    if not isinstance(node, ast.Call):
        return False
    name = call_name(node)
    if name in _FENCE_NAMES or name in fence_fns:
        return True
    if name and name.split(".")[-1] in ("device_get", "block_until_ready",
                                        "device_fence"):
        return True
    # devprof.measure is a fence: every timed iteration ends with a
    # device_fence on the call's result (telemetry/devprof.py)
    if name and name.split(".")[-1] == "measure" and "devprof" in name:
        return True
    if _is_fenced_span_call(node):
        return True
    return isinstance(node.func, ast.Attribute) and \
        node.func.attr in _FENCE_ATTRS


# telemetry/ entry points whose presence means a region ends with a real
# device fetch (tracer.py: span exit runs device_fence unless fence=False,
# instrument() fences each call on its result unless fence_result=False)
_SPAN_FENCES = {"span", "instrument", "fence_on"}


def _is_fenced_span_call(node):
    name = call_name(node)
    if not name:
        return False
    short = name.split(".")[-1]
    if short not in _SPAN_FENCES:
        return False
    if short == "span":
        return _const(_kw(node, "fence"), True) is not False
    if short == "instrument":
        return _const(_kw(node, "fence_result"), True) is not False
    return True  # sp.fence_on(x): nominates the fence target explicitly


def _timer_reads(stmt, timers):
    """Timer names read as `time.X() - t0` anywhere in this statement."""
    reads = set()
    for node in ast.walk(stmt):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) and \
                call_name(node.left) in _TIMER_CALLS:
            d = dotted(node.right)
            if d in timers:
                reads.add(d)
    return reads


def _r2_scan_body(ctx, stmts, fence_fns, seen_lines):
    out = []
    timers = {}  # name -> index of the start statement
    for i, stmt in enumerate(stmts):
        for name in _timer_reads(stmt, timers):
            region = stmts[timers[name][0] + 1: i + 1]
            fenced = any(_is_fence_call(n, fence_fns)
                         for s in region for n in ast.walk(s))
            if not fenced and stmt.lineno not in seen_lines:
                seen_lines.add(stmt.lineno)
                out.append(ctx.finding(
                    stmt, f"timed region ({name} started at line "
                    f"{timers[name][1]}) is read without a device fetch "
                    "fence — under async dispatch the delta measures "
                    "enqueue, not compute; end the region with "
                    "_hard_sync/jax.device_get"))
            del timers[name]
        if isinstance(stmt, ast.Assign) and \
                call_name(stmt.value) in _TIMER_CALLS:
            for t in stmt.targets:
                d = dotted(t)
                if d:
                    timers[d] = (i, stmt.lineno)
    return out


# ------------------------------------------------------------------- R3

# factories in this repo that return jitted callables with donated argnums;
# positions are of the *returned* callable's signature
_DONATING_FACTORIES = {
    "make_train_step": "train_step",   # (params, opt_state, key, batch)
    "make_epoch_fn": "epoch",          # (params, opt_state, key, ...)
    "make_parallel_train_step": "pstep",
    "make_moe_train_step": "pstep",
}


def _kw(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _const(node, default=None):
    return node.value if isinstance(node, ast.Constant) else default


def _donated_positions(call):
    """Donated argnums for the callable produced by `call`, else None."""
    name = call_name(call)
    if name is None:
        return None
    short = name.split(".")[-1]
    if name in _JIT_NAMES:
        argnums = _kw(call, "donate_argnums")
        if isinstance(argnums, (ast.Tuple, ast.List)):
            pos = tuple(_const(e) for e in argnums.elts)
            if all(isinstance(p, int) for p in pos):
                return pos
        elif isinstance(argnums, ast.Constant) and \
                isinstance(argnums.value, int):
            return (argnums.value,)
        return None
    if short in _DONATING_FACTORIES:
        if _const(_kw(call, "donate"), True) is False:
            base = ()
        else:
            base = (0, 1)
        if short == "make_train_step" and \
                _const(_kw(call, "donate_batch"), False) is True:
            base = base + (3,)
        return base or None
    return None


def scope_walk(root):
    """Walk `root` without crossing into nested function definitions, so a
    name bound in one function never leaks into another scope's analysis."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _donating_callables(root):
    """dotted-name -> donated positions, from assignments in THIS scope only
    (covers `step = make_train_step(...)` and
    `self._train_step = jax.jit(f, donate_argnums=...)`). Scoping matters:
    two functions can both name their step `step` with different donation
    settings."""
    out = {}
    for node in scope_walk(root):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions(node.value)
            if pos:
                for t in node.targets:
                    d = dotted(t)
                    if d:
                        out[d] = pos
    return out


@rule("R3", "use-after-donate")
def check_r3(ctx):
    module_donators = _donating_callables(ctx.tree)
    out = []
    for root in [ctx.tree] + [n for n in ast.walk(ctx.tree)
                              if isinstance(n, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))]:
        if root is ctx.tree:
            donators = module_donators
        else:
            donators = {**module_donators, **_donating_callables(root)}
        if not donators:
            continue
        body = root.body if hasattr(root, "body") else []
        if body and isinstance(body[0], ast.stmt):
            out.extend(_r3_scan(ctx, body, donators, stale={}))
    # findings can repeat when a body is reachable from module+function walk;
    # dedupe on (line, message)
    uniq = {}
    for f in out:
        uniq[(f.line, f.message)] = f
    return list(uniq.values())


def _donations_in(stmt, donators):
    """(donated_name, call_line) pairs for donating calls in this statement,
    excluding names immediately rebound by the statement's own targets."""
    rebound = assign_target_names(stmt)
    found = []
    for node in ast.walk(stmt):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in donators:
                for pos in donators[name]:
                    if pos < len(node.args):
                        d = dotted(node.args[pos])
                        if d and d not in rebound:
                            found.append((d, node.lineno))
    return found


def _r3_scan(ctx, stmts, donators, stale):
    """Linear scan of one body: donated-and-not-rebound names become stale;
    a later load of a stale name is use-after-donate. Loop bodies: a name
    donated inside the loop must be rebound inside it, or iteration 2 passes
    a deleted buffer."""
    out = []
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # separate scope (closures over donated refs are rare)
        # loads happen before this statement's own donations take effect
        if stale:
            call_funcs = {dotted(n.func) for n in ast.walk(stmt)
                          if isinstance(n, ast.Call)}
            for name in sorted(names_in(stmt) & set(stale)):
                if name in call_funcs:
                    continue  # calling the step again is not reading a buffer
                out.append(ctx.finding(
                    stmt, f"`{name}` was donated at line {stale[name]} and "
                    "read here — the buffer may already be deleted/aliased "
                    "by XLA; copy what you need before the donating call or "
                    "drop the donation"))
                del stale[name]
        if isinstance(stmt, (ast.For, ast.While)):
            loop_donated = {}
            body_out = _r3_scan(ctx, stmt.body, donators, loop_donated)
            out.extend(body_out)
            rebound_in_loop = set()
            for s in ast.walk(stmt):
                rebound_in_loop |= assign_target_names(s)
            for name, line in loop_donated.items():
                if name in rebound_in_loop:
                    stale[name] = line  # stale after the loop exits
                else:
                    out.append(ctx.finding(
                        line, f"`{name}` is donated inside this loop but "
                        "never rebound in the loop body — the next "
                        "iteration passes an already-deleted buffer"))
            continue
        for name, line in _donations_in(stmt, donators):
            stale[name] = line
        for name in assign_target_names(stmt):
            stale.pop(name, None)
    return out


# ------------------------------------------------------------------- R4

def _jitted_callables(tree):
    """dotted-name -> set of static positional indices, for names assigned
    from jax.jit(...) or a known step factory."""
    out = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            name = call_name(call)
            if name is None:
                continue
            is_jit = name in _JIT_NAMES
            is_factory = name.split(".")[-1] in _DONATING_FACTORIES or \
                name.split(".")[-1] in ("make_eval_step", "make_encode_fn")
            if not (is_jit or is_factory):
                continue
            statics = set()
            argnums = _kw(call, "static_argnums")
            if isinstance(argnums, (ast.Tuple, ast.List)):
                statics = {_const(e) for e in argnums.elts}
            elif isinstance(argnums, ast.Constant):
                statics = {argnums.value}
            for t in node.targets:
                d = dotted(t)
                if d:
                    out[d] = statics
    return out


def _scalar_of(expr, var):
    """True when `expr` is a bare Python scalar built from `var` and
    constants (i, i+1, 2*i...) — the shape/hash changes every iteration."""
    if isinstance(expr, ast.Name):
        return expr.id == var
    if isinstance(expr, ast.Constant):
        return False  # constants alone are cached after the first call
    if isinstance(expr, ast.BinOp):
        return ((_scalar_of(expr.left, var) or _scalar_of(expr.right, var))
                and all(isinstance(s, (ast.Name, ast.Constant, ast.BinOp,
                                       ast.UnaryOp))
                        for s in (expr.left, expr.right)))
    if isinstance(expr, ast.UnaryOp):
        return _scalar_of(expr.operand, var)
    return False


@rule("R4", "recompile hazard")
def check_r4(ctx):
    jitted = _jitted_callables(ctx.tree)
    out = []
    # R4a: jitted callable fed a per-iteration Python scalar
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.For):
            continue
        it = call_name(node.iter)
        if it == "range":
            loop_vars = [node.target.id] if isinstance(node.target, ast.Name) \
                else []
        elif it == "enumerate" and isinstance(node.target, ast.Tuple) and \
                node.target.elts and isinstance(node.target.elts[0], ast.Name):
            loop_vars = [node.target.elts[0].id]
        else:
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and call_name(sub) in jitted:
                statics = jitted[call_name(sub)]
                for pos, arg in enumerate(sub.args):
                    if pos in statics:
                        continue
                    for var in loop_vars:
                        if _scalar_of(arg, var):
                            out.append(ctx.finding(
                                sub, f"jitted callable `{call_name(sub)}` "
                                f"receives the Python loop scalar `{var}` at "
                                f"position {pos} — every iteration retraces "
                                "and recompiles; mark it static_argnums, "
                                "pass a device array, or hoist it"))
    # R4b: stacking variable-bound list slices feeds jit/scan a shape that
    # goes ragged on the tail group (the round-5 bench recompile)
    for fn in [ctx.tree] + [n for n in ast.walk(ctx.tree)
                            if isinstance(n, (ast.FunctionDef,
                                              ast.AsyncFunctionDef))]:
        has_guard = _has_mod_assert(fn)
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                    node is not fn:
                continue
            if isinstance(node, ast.Call) and call_name(node) in _STACK_NAMES \
                    and node.args and _is_ragged_slice_source(node.args[0]):
                if not has_guard:
                    out.append(ctx.finding(
                        node, "stacking variable-bound list slices: a ragged "
                        "tail group changes the stacked leading dim and "
                        "recompiles any jit/scan consuming it — assert "
                        "divisibility, pad to a bucket "
                        "(train/pipeline.bucket_pad), or drop the tail "
                        "explicitly"))
    # dedupe (module walk + per-function walk can see the same node)
    uniq = {}
    for f in out:
        uniq[(f.line, f.message)] = f
    return list(uniq.values())


def _has_mod_assert(fn):
    for n in ast.walk(fn):
        if isinstance(n, ast.Assert):
            for b in ast.walk(n.test):
                if isinstance(b, ast.BinOp) and isinstance(b.op, ast.Mod):
                    return True
    return False


def _is_ragged_slice_source(arg):
    """`feeds[g:g+group]` directly, or a comprehension over such slices."""

    def var_slice(node):
        return (isinstance(node, ast.Subscript) and
                isinstance(node.slice, ast.Slice) and
                any(b is not None and not isinstance(b, ast.Constant)
                    for b in (node.slice.lower, node.slice.upper)))

    if var_slice(arg):
        return True
    if isinstance(arg, (ast.ListComp, ast.GeneratorExp)):
        return var_slice(arg.elt)
    return False


# ------------------------------------------------------------------- R5

@rule("R5", "PRNG key reused without split")
def check_r5(ctx):
    out = []
    roots = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))]
    for root in roots:
        body = getattr(root, "body", [])
        if body and isinstance(body[0], ast.stmt):
            state = _KeyState()
            if isinstance(root, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for a in root.args.args + root.args.kwonlyargs:
                    if _looks_like_key(a.arg):
                        state.keys.add(a.arg)
            out.extend(_r5_scan(ctx, body, state, loop_vars=set()))
    uniq = {}
    for f in out:
        uniq[(f.line, f.message)] = f
    return list(uniq.values())


def _looks_like_key(name):
    """Parameters named like PRNG keys are tracked as keys on entry."""
    return name in ("key", "rng", "rng_key", "prng_key", "keys") or \
        name.endswith("_key")


class _KeyState:
    def __init__(self):
        self.keys = set()      # names known to hold PRNG keys / key arrays
        self.used = {}         # key id -> line of first consumption


def _key_ids_in_call(call, state, loop_vars):
    """Key ids consumed by this call: bare key names, or subscripts of a key
    array (`keys[0]`); subscripts indexed by a loop variable vary per
    iteration and get a per-iteration id of None (exempt)."""
    ids = []
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        d = dotted(arg)
        if d and d in state.keys:
            ids.append(d)
        elif isinstance(arg, ast.Subscript):
            base = dotted(arg.value)
            if base in state.keys:
                idx_names = names_in(arg.slice)
                if idx_names & loop_vars:
                    continue  # keys[i] in a loop: a fresh key each pass
                ids.append(ast.unparse(arg))
    return ids


def _r5_consume(ctx, node, state, loop_vars):
    """Mark keys consumed by calls under `node`; reconsumption is a finding."""
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            for key_id in _key_ids_in_call(sub, state, loop_vars):
                if key_id in state.used:
                    out.append(ctx.finding(
                        sub, f"PRNG key `{key_id}` consumed again "
                        f"(first used at line {state.used[key_id]}) "
                        "without an intervening jax.random.split — "
                        "both consumers draw identical randomness"))
                else:
                    state.used[key_id] = sub.lineno
    return out


def _r5_scan(ctx, stmts, state, loop_vars):
    out = []
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(stmt, ast.If):
            # exclusive branches never both run: consumptions in one arm must
            # not count against the other (the corrupt() dispatch pattern)
            out.extend(_r5_consume(ctx, stmt.test, state, loop_vars))
            survivors = []
            for arm in (stmt.body, stmt.orelse):
                branch = _KeyState()
                branch.keys = set(state.keys)
                branch.used = dict(state.used)
                out.extend(_r5_scan(ctx, arm, branch, loop_vars))
                # an arm ending in return/raise never falls through: its
                # consumptions don't exist on the path that continues (the
                # `if t == "x": return f(key)` dispatch chain)
                if not (arm and isinstance(arm[-1], (ast.Return, ast.Raise,
                                                     ast.Break,
                                                     ast.Continue))):
                    survivors.append(branch)
            if survivors:
                state.keys = set.union(*[b.keys for b in survivors])
                merged = {}
                for b in reversed(survivors):
                    merged.update(b.used)
                state.used = merged
            continue
        if isinstance(stmt, (ast.For, ast.While)):
            inner_loop_vars = set(loop_vars)
            if isinstance(stmt, ast.For):
                inner_loop_vars |= names_in(stmt.target)
            before_used = dict(state.used)
            body_findings = _r5_scan(ctx, stmt.body, state, inner_loop_vars)
            out.extend(body_findings)
            rebound = set()
            for s in ast.walk(stmt):
                rebound |= assign_target_names(s)
            for key_id, line in state.used.items():
                if key_id in before_used:
                    continue  # consumed before the loop, not by it
                base = key_id.split("[")[0]
                if base not in rebound and key_id not in rebound:
                    out.append(ctx.finding(
                        line, f"PRNG key `{key_id}` is consumed inside this "
                        "loop but never re-split/rebound in the loop body — "
                        "every iteration draws the same randomness"))
            continue
        # consumption first (uses in this statement see the pre-state)
        out.extend(_r5_consume(ctx, stmt, state, loop_vars))
        # then (re)bindings: a fresh value clears the used mark
        targets = assign_target_names(stmt)
        for t in targets:
            state.used.pop(t, None)
            state.used = {k: v for k, v in state.used.items()
                          if k.split("[")[0] != t}
        if isinstance(stmt, ast.Assign):
            vname = call_name(stmt.value)
            if vname in _KEY_MAKERS or vname in _KEY_SPLITS:
                state.keys |= targets
            elif isinstance(stmt.value, (ast.Name, ast.Subscript)):
                d = dotted(stmt.value) or dotted(
                    getattr(stmt.value, "value", None))
                if d and d.split("[")[0] in {k.split("[")[0]
                                             for k in state.keys}:
                    state.keys |= targets  # alias of a key keeps key-ness
    return out


# ------------------------------------------------------------------- R6

@rule("R6", "fence=False span wrapping un-fenced device work")
def check_r6(ctx):
    """A `telemetry.span(..., fence=False)` declares "this region is
    host-only, its duration needs no device fence". If the span body then
    dispatches device work (jnp/lax calls, or a call to a known jitted
    callable) without any fence of its own, the span's recorded duration
    measures enqueue — the exact lie R2 catches for raw timers, recurring
    through the telemetry API. Fix: drop fence=False (spans fence by
    default), nominate a target with sp.fence_on(out), or end the body
    with jax.device_get."""
    jitted = set(_jitted_callables(ctx.tree))
    fence_fns = _fence_functions(ctx.tree)
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        for item in node.items:
            call = item.context_expr
            if not isinstance(call, ast.Call):
                continue
            name = call_name(call)
            if not name or name.split(".")[-1] != "span":
                continue
            if _const(_kw(call, "fence"), True) is not False:
                continue  # default-fenced span: clean by construction
            has_device = has_fence = False
            for stmt in node.body:
                for sub in ast.walk(stmt):
                    if _is_fence_call(sub, fence_fns):
                        has_fence = True
                    elif isinstance(sub, ast.Call):
                        cn = call_name(sub)
                        if cn and (cn.startswith(_DEVICE_PREFIXES) or
                                   cn in jitted):
                            has_device = True
            if has_device and not has_fence:
                out.append(ctx.finding(
                    call, "span(..., fence=False) wraps device work with no "
                    "fence in the body — the recorded duration measures "
                    "enqueue, not compute; drop fence=False, call "
                    "sp.fence_on(out), or end with jax.device_get"))
    return out


# ------------------------------------------------------------------- R7

_HOST_SCALAR_CASTS = {"float", "int", "bool"}


def _r7_conversions(ctx, node, tainted):
    """Findings for host conversions of tainted names anywhere under `node`
    (one expression or one simple statement). Comprehensions over a tainted
    container taint their element variables (`float(v) for k, v in
    metrics.items()` is still a per-step sync)."""
    if not tainted:
        return []
    local = set(tainted)
    for sub in ast.walk(node):
        if isinstance(sub, (ast.ListComp, ast.SetComp, ast.DictComp,
                            ast.GeneratorExp)):
            for gen in sub.generators:
                if names_in(gen.iter) & local:
                    local |= names_in(gen.target)
    out = []
    fix = ("accumulate the device metrics and fetch once per epoch with "
           "jax.device_get")
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        if isinstance(sub.func, ast.Attribute) and \
                sub.func.attr in ("item", "tolist") and \
                names_in(sub.func.value) & local:
            out.append(ctx.finding(
                sub, f"per-step `.{sub.func.attr}()` on a jitted-step output "
                f"inside the training loop blocks on the device every "
                f"iteration — {fix}"))
            continue
        name = call_name(sub)
        if name in (_HOST_SCALAR_CASTS | _HOST_MATERIALIZERS) and sub.args \
                and names_in(sub.args[0]) & local:
            out.append(ctx.finding(
                sub, f"per-step `{name}()` on a jitted-step output inside "
                f"the training loop forces a device sync every iteration, "
                f"stalling async dispatch — {fix}"))
    return out


def _r7_scan(ctx, stmts, jitted, tainted):
    """Linear taint scan over one loop body. Seeds: an Assign whose value
    calls a jitted callable with carried state (some target name is also an
    argument — `params, opt_state, metrics = step(params, opt_state, ...)`),
    the signature of an async-dispatch training loop. Assignment from
    jax.device_get is the sanctioned batched fetch and clears its targets."""
    out = []
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(stmt, (ast.For, ast.While, ast.If, ast.With, ast.Try)):
            headers = []
            if isinstance(stmt, ast.For):
                headers = [stmt.iter]
            elif isinstance(stmt, (ast.While, ast.If)):
                headers = [stmt.test]
            elif isinstance(stmt, ast.With):
                headers = [i.context_expr for i in stmt.items]
            for h in headers:
                out.extend(_r7_conversions(ctx, h, tainted))
            inner = tainted
            if isinstance(stmt, ast.For) and names_in(stmt.iter) & tainted:
                # iterating a tainted container taints the loop variable
                inner = tainted | names_in(stmt.target)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    out.extend(_r7_scan(ctx, sub, jitted, inner))
            for h in getattr(stmt, "handlers", None) or []:
                out.extend(_r7_scan(ctx, h.body, jitted, inner))
            continue
        out.extend(_r7_conversions(ctx, stmt, tainted))
        if not isinstance(stmt, ast.Assign):
            continue
        value = stmt.value
        targets = assign_target_names(stmt)
        vname = call_name(value)
        short = vname.split(".")[-1] if vname else None
        if vname in _DEVICE_GET or short == "device_get":
            tainted -= targets  # the sanctioned once-per-epoch fetch
        elif vname in jitted:
            arg_names = set()
            for a in value.args:
                d = dotted(a)
                if d:
                    arg_names.add(d)
            if targets & arg_names:
                tainted |= targets  # carried state: async pipeline to protect
            else:
                tainted -= targets
        elif names_in(value) & tainted:
            tainted |= targets  # propagation through plain rebinding
        else:
            tainted -= targets
    return out


@rule("R7", "per-step host conversion of jitted-step outputs in a training "
            "loop")
def check_r7(ctx):
    """A loop that threads state through a jitted step
    (`params, opt_state, metrics = step(params, opt_state, key, batch)`)
    runs ahead of the device: the returned metrics are async futures.
    Converting them to host values (`float()`, `int()`, `np.asarray`,
    `.item()`, `.tolist()`) INSIDE the loop forces a device->host sync every
    step — the stall the in-graph sentinel (telemetry/health.py) exists to
    avoid. Fix: append the device metrics to a list and `jax.device_get`
    the whole list once per epoch (that assignment clears the taint here);
    the health flags ride the same fetch for free."""
    jitted = set(_jitted_callables(ctx.tree))
    if not jitted:
        return []
    out = []
    roots = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))]
    for root in roots:
        for node in scope_walk(root):
            if isinstance(node, (ast.For, ast.While)):
                out.extend(_r7_scan(ctx, node.body, jitted, set()))
    uniq = {}
    for f in out:
        uniq[(f.line, f.message)] = f
    return list(uniq.values())


# ------------------------------------------------------------------- R8

# binary ops that broadcast their operands (materializing the result shape)
_R8_BROADCAST_OPS = (ast.BitAnd, ast.BitOr, ast.Mult, ast.Add, ast.Sub)


def _r8_sig(node, env):
    """Broadcast signature of an expression: the frozenset of `None`
    positions in a rank-3 `x[..., None, ...]` subscript (descending unary
    ops and name bindings), or None when the expression is not a rank-3
    expand. `{2}` means `x[:, :, None]`; `{0, 1}` means `x[None, None, :]`.
    Only proper subsets count — a 3-slot subscript with zero or three
    `None`s is not an expand-for-broadcast."""
    if isinstance(node, ast.UnaryOp):
        return _r8_sig(node.operand, env)
    if isinstance(node, ast.Name):
        return env.get(node.id)
    if isinstance(node, ast.Subscript):
        sl = node.slice
        if isinstance(sl, ast.Tuple) and len(sl.elts) == 3:
            pos = frozenset(i for i, e in enumerate(sl.elts)
                            if isinstance(e, ast.Constant) and e.value is None)
            if 0 < len(pos) < 3:
                return frozenset(pos)
    return None


def _r8_scan_root(ctx, root, seen_lines):
    """Flag broadcasting combinations of rank-3 expands with DIFFERENT
    None-position signatures — the exact idiom whose result is the full
    [B, B, B] cube (`a[:, :, None] op b[:, None, :]`). Same-signature
    combinations (no new axis materialized) and rank-2 expands pass."""
    out = []
    env = {}
    nodes = sorted((n for n in scope_walk(root)
                    if isinstance(n, (ast.Assign, ast.BinOp, ast.Compare))),
                   key=lambda n: (n.lineno, n.col_offset))
    for node in nodes:
        if isinstance(node, ast.Assign):
            # thread signatures through simple rebinds (i_ne_j = ne[:, :, None])
            if len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                env[node.targets[0].id] = _r8_sig(node.value, env)
            continue
        if isinstance(node, ast.BinOp):
            if not isinstance(node.op, _R8_BROADCAST_OPS):
                continue
            pairs = [(node.left, node.right)]
        else:  # Compare
            if len(node.ops) != 1 or len(node.comparators) != 1:
                continue
            pairs = [(node.left, node.comparators[0])]
        for left, right in pairs:
            ls, rs = _r8_sig(left, env), _r8_sig(right, env)
            if ls is not None and rs is not None and ls != rs and \
                    node.lineno not in seen_lines:
                seen_lines.add(node.lineno)
                out.append(ctx.finding(
                    node,
                    "broadcasting rank-3 expands with different axis "
                    "signatures materializes the [B, B, B] cube — O(B^3) "
                    "memory that caps the mined batch (256 GiB at B=4096 "
                    "f32). Compute it in anchor tiles instead: "
                    "ops/triplet_blockwise.py (XLA scan, O(B^2)) or the "
                    "Pallas kernels (VMEM tiles), via "
                    "train/step.py mine_triplets(mining_impl=...)."))
    return out


@rule("R8", "full [B,B,B] triplet cube materialized by rank-3 broadcasting")
def check_r8(ctx):
    """The O(B^3) mining footprint this repo migrated away from (ISSUE 5):
    `d = -dp[:, :, None] + dp[:, None, :]` and its mask twin allocate B^3
    elements in one op. Fine as the dense reference oracle at small B;
    fatal at large-batch mining. The heuristic is purely syntactic —
    two rank-3 expand subscripts with different `None` positions combined
    by a broadcasting operator — so legitimate tiled slabs (a static
    anchor-tile leading axis, or a VMEM tile inside a kernel) carry a
    reasoned `# jaxcheck: disable=R8` at the site."""
    out = []
    seen = set()
    roots = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))]
    for root in roots:
        out.extend(_r8_scan_root(ctx, root, seen))
    return out


# ------------------------------------------------------------------- R9

_BROAD_EXC = {"Exception", "BaseException"}
# a handler that calls any of these (by dotted-name substring) is "recording":
# the failure reaches an operator through warnings, logging, or telemetry
_R9_RECORD_TOKENS = ("warn", "record", "note", "log", "dump", "telemetry",
                     "print")


def _r9_broad(handler):
    """Bare `except:`, or a clause naming Exception/BaseException."""
    t = handler.type
    if t is None:
        return "except:"
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        d = dotted(e)
        if d and d.split(".")[-1] in _BROAD_EXC:
            return f"except {d}"
    return None


def _r9_surfaces(handler):
    """True when the handler re-raises or records: any Raise statement, or a
    call whose dotted name suggests warnings/logging/telemetry."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            name = (call_name(node) or "").lower()
            if any(tok in name for tok in _R9_RECORD_TOKENS):
                return True
    return False


def _contains_loop(stmts):
    """A For/While anywhere in these statements, not crossing nested defs."""
    for s in stmts:
        for node in ast.walk(s):
            if isinstance(node, (ast.For, ast.While)):
                return True
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not s:
                break
    return False


@rule("R9", "broad except swallows errors in a training/feed loop")
def check_r9(ctx):
    """A broad handler (`except Exception` / `except BaseException` / bare
    `except`) inside a loop — or wrapping one — that neither re-raises nor
    records is a silent-truncation bug factory: a dead feed worker or a
    failed step vanishes and the fit 'completes' on partial data (the exact
    failure class reliability/ exists to make loud). Legitimate
    surface-on-the-consumer sites (a worker thread parking the exception for
    the consuming iterator to re-raise) carry a reasoned
    `# jaxcheck: disable=R9` — the handler cannot re-raise on its own thread.
    Diagnostics-must-never-kill handlers pass by calling a recording API
    (warnings.warn, logger.*, recorder.note_*, telemetry.*)."""
    out = []

    def visit(node, in_loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            in_loop = False  # the body runs when called, not per-iteration
        if isinstance(node, ast.Try):
            relevant = in_loop or _contains_loop(node.body)
            for h in node.handlers:
                broad = _r9_broad(h)
                if relevant and broad and not _r9_surfaces(h):
                    out.append(ctx.finding(
                        h, f"`{broad}` in a training/feed loop neither "
                        "re-raises nor records — a swallowed error here "
                        "silently truncates the feed or fit; re-raise, "
                        "narrow the clause, record it (warnings/logging/"
                        "telemetry), or carry a reasoned disable at a "
                        "surface-on-consumer site"))
        for child in ast.iter_child_nodes(node):
            visit(child, in_loop or isinstance(node, (ast.For, ast.While)))

    visit(ctx.tree, in_loop=False)
    return out


# ------------------------------------------------------------------- R10

# exact dotted names (pickle.loads must not drag json.loads along)
_R10_EXACT = {"pickle.loads", "pickle.load", "marshal.loads"}
# unambiguous short names: flagged whatever module alias they hang off
# (zlib/bz2/lzma/gzip .decompress, np.unpackbits, and the wire codec's
# host-side entry points)
_R10_SHORT = {"decompress", "unpackbits", "unpack_wire_host",
              "pack_csr_wire"}


def _r10_is_host_decode(node):
    name = call_name(node)
    if not name:
        return None
    if name in _R10_EXACT or name.split(".")[-1] in _R10_SHORT:
        return name
    return None


@rule("R10", "host-side per-batch decompression in a feed/training loop")
def check_r10(ctx):
    """Decoding compressed payloads on the host once per batch (zlib/bz2/
    lzma/gzip decompress, pickle loads, np.unpackbits, or the wire codec's
    host-side unpack/pack) serializes the feed on host CPU: the decode sits
    on the critical path between batches, exactly the stall the compressed
    wire format exists to remove — pack ONCE on the host at ingest, ship the
    packed words, and expand on device inside the jitted step
    (ops/wire.unpack_wire in train/step.materialize_x). Flagged inside
    For/While loops and inside generator bodies (a generator's body re-runs
    per yielded batch). Legitimate per-batch host pack sites — a codec
    accounting sweep in bench code, a golden-reference comparison in a test
    harness — carry a reasoned `# jaxcheck: disable=R10`."""
    out = []
    seen = set()

    def flag(node):
        name = _r10_is_host_decode(node)
        if name and node.lineno not in seen:
            seen.add(node.lineno)
            out.append(ctx.finding(
                node, f"`{name}` runs host-side per batch in this "
                "feed/training loop — the decode serializes the feed on "
                "host CPU; pack once at ingest and expand on device in the "
                "jitted step (ops/wire.unpack_wire), or hoist the decode "
                "out of the loop"))

    def is_generator(fn):
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.Yield, ast.YieldFrom)):
                return True
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                stack.extend(ast.iter_child_nodes(n))
        return False

    def visit(node, hot):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a generator body re-executes per yielded item: per-batch
            hot = is_generator(node)
        elif isinstance(node, ast.Lambda):
            hot = False
        if hot and isinstance(node, ast.Call):
            flag(node)
        for child in ast.iter_child_nodes(node):
            visit(child, hot or isinstance(node, (ast.For, ast.While)))

    visit(ctx.tree, hot=False)
    return out


# ------------------------------------------------------------------- R11

_R11_QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
                    "Queue", "LifoQueue", "PriorityQueue"}
_R11_THREAD_CTORS = {"threading.Thread", "Thread"}


def _r11_bindings(tree):
    """Dotted targets bound to queue / thread constructors anywhere in the
    file ('q', 'self._q', ...), plus every unbounded-queue construction."""
    queues, threads, unbounded = set(), set(), []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        call = node.value
        name = call_name(call)
        if name is None:
            continue
        targets = {dotted(t) for t in node.targets} - {None}
        if name in _R11_QUEUE_CTORS:
            queues |= targets
            if not call.args and _kw(call, "maxsize") is None:
                unbounded.append(call)
        elif name in _R11_THREAD_CTORS:
            threads |= targets
    return queues, threads, unbounded


def _r11_has_timeout(call):
    """True when the get()/join() is bounded: a timeout (kw or the
    positional slot after `block`) or a non-blocking block=False."""
    if _kw(call, "timeout") is not None:
        return True
    block = _kw(call, "block")
    if block is not None and _const(block, True) is False:
        return True
    if call.args:
        if len(call.args) >= 2:        # get(block, timeout)
            return True
        return _const(call.args[0], None) is not None  # join(5) / get(False)
    return False


@rule("R11", "unbounded queue / blocking get-join without timeout in a "
      "serve/feed loop")
def check_r11(ctx):
    """Serving and feed loops live or die by bounded waits. An unbounded
    `queue.Queue()` turns overload into silent unbounded buffering (every
    queued request already missed its deadline by the time it's served —
    admission control needs `maxsize` to shed instead). A bare blocking
    `.get()` in a worker/consumer loop deadlocks the loop forever when the
    other side dies without its sentinel landing (kill -9, interpreter
    teardown); `.join()` without a timeout does the same at shutdown. The
    repo's discipline (train/pipeline.py, serve/service.py): bounded queues,
    timeout-polled gets with a liveness check, join(timeout=...). Flagged:
    queue constructions without maxsize anywhere; `.get()` without
    timeout/block=False on a queue-bound name inside a For/While loop;
    `.join()` without a timeout on a queue- or thread-bound name anywhere.
    A deliberately unbounded internal queue (e.g. a result mailbox that is
    provably drained) carries a reasoned `# jaxcheck: disable=R11`."""
    queues, threads, unbounded = _r11_bindings(ctx.tree)
    out = []
    for call in unbounded:
        out.append(ctx.finding(
            call, f"`{call_name(call)}()` without maxsize is an unbounded "
            "buffer: overload queues work instead of shedding it, and every "
            "parked item ages past its deadline — bound it and make the "
            "producer handle Full explicitly"))

    def visit(node, in_loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            in_loop = False  # the body runs when called, not per-iteration
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            recv = dotted(node.func.value)
            if (node.func.attr == "get" and in_loop and recv in queues
                    and not _r11_has_timeout(node)):
                out.append(ctx.finding(
                    node, f"blocking `{recv}.get()` without a timeout in "
                    "this loop: if the producer dies without its sentinel "
                    "landing, the consumer hangs forever — poll with "
                    "get(timeout=...) and check producer liveness on Empty"))
            elif (node.func.attr == "join" and recv in queues | threads
                    and not _r11_has_timeout(node)):
                out.append(ctx.finding(
                    node, f"`{recv}.join()` without a timeout blocks "
                    "shutdown forever if the other side is wedged — join "
                    "with a timeout and surface the failure"))
        for child in ast.iter_child_nodes(node):
            visit(child, in_loop or isinstance(node, (ast.For, ast.While)))

    visit(ctx.tree, in_loop=False)
    return out


# ------------------------------------------------------------------- R12

_R12_LOW_DTYPES = {"bfloat16", "int8", "float16", "int4",
                   "float8_e4m3fn", "float8_e5m2"}
_R12_DTYPE_CTORS = {"jnp.dtype", "jax.numpy.dtype", "np.dtype",
                    "numpy.dtype"}
_R12_MATMUL_CALLS = {"jnp.matmul", "jnp.dot", "jnp.einsum", "jnp.tensordot",
                     "jax.numpy.matmul", "jax.numpy.dot", "jax.numpy.einsum",
                     "jax.numpy.tensordot", "lax.dot", "lax.dot_general",
                     "jax.lax.dot", "jax.lax.dot_general"}


def _r12_dtype_is_low(node, low_dtype_names):
    """True when a dtype expression may name a sub-fp32 type: a low literal
    (`jnp.bfloat16`, `"int8"`) or a variable bound from `jnp.dtype(...)` in
    this scope (a config-driven compute dtype is *statically maybe-low*; R12
    treats maybe as yes — the escape hatches are an explicit
    `preferred_element_type` or a reasoned disable)."""
    if node is None:
        return False
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and node.value in _R12_LOW_DTYPES
    d = dotted(node)
    if d is None:
        return False
    return d.split(".")[-1] in _R12_LOW_DTYPES or d in low_dtype_names


def _r12_scope_evidence(root):
    """(low_dtype_names, low_value_names) bound in THIS scope only.

    low_dtype_names: names assigned from `jnp.dtype(<non-constant>)` or
    `jnp.dtype("<low literal>")` — the repo's `dt = jnp.dtype(
    config.compute_dtype)` idiom lands here.
    low_value_names: names assigned from `<expr>.astype(<maybe-low dtype>)`
    or from a call carrying a `dtype=<maybe-low>` keyword (densify/ones/...
    builders that materialize directly in the compute dtype).

    Two passes, because `scope_walk` order is not source order: dtype
    bindings must be complete before value bindings consult them."""
    assigns = [n for n in scope_walk(root)
               if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call)]
    low_dtypes, low_values = set(), set()
    for node in assigns:
        call = node.value
        if call_name(call) in _R12_DTYPE_CTORS and call.args:
            arg = call.args[0]
            if (not isinstance(arg, ast.Constant)
                    or _r12_dtype_is_low(arg, low_dtypes)):
                low_dtypes.update(d for t in node.targets
                                  if (d := dotted(t)))
    for node in assigns:
        call = node.value
        if ((isinstance(call.func, ast.Attribute)
             and call.func.attr == "astype" and call.args
             and _r12_dtype_is_low(call.args[0], low_dtypes))
                or _r12_dtype_is_low(_kw(call, "dtype"), low_dtypes)):
            low_values.update(d for t in node.targets if (d := dotted(t)))
    return low_dtypes, low_values


def _r12_operand_low(node, low_dtypes, low_values):
    """True when a matmul operand visibly carries a maybe-low dtype: an
    inline `.astype(low)` (possibly behind a `.T`/`.mT` transpose) or a name
    bound from one in this scope."""
    while isinstance(node, ast.Attribute) and node.attr in ("T", "mT"):
        node = node.value
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype" and node.args):
        return _r12_dtype_is_low(node.args[0], low_dtypes)
    d = dotted(node)
    return d is not None and d in low_values


@rule("R12", "low-precision matmul without preferred_element_type")
def check_r12(ctx):
    """A bf16/int8-input matmul accumulates (and returns) in the input dtype
    unless told otherwise: on TPU the MXU takes bf16/int8 operands but only
    keeps its fp32 accumulator when the HLO dot carries
    `preferred_element_type=f32`. Without it, `jnp.matmul(x.astype(bf16), w)`
    rounds every partial sum to 8 mantissa bits — a silent recall cliff at
    serving k (the int8 corpus path is only rank-preserving because
    ops/topk_fused accumulates f32). Flagged: `jnp.matmul/dot/einsum/
    tensordot` and `lax.dot/dot_general` calls where an operand is visibly
    cast to (or built in) a maybe-sub-fp32 dtype — including the repo's
    `dt = jnp.dtype(config.compute_dtype)` binding idiom — and no
    `preferred_element_type` keyword is present; plus the `@` operator on
    such operands, which cannot carry the keyword at all. Sites where narrow
    accumulation IS the contract (e.g. dae_core's compute-dtype parity with
    the reference model) carry a reasoned `# jaxcheck: disable=R12`."""
    out = []
    scopes = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.Lambda))]
    for root in scopes:
        low_dtypes, low_values = _r12_scope_evidence(root)

        def low(arg, _ld=low_dtypes, _lv=low_values):
            return _r12_operand_low(arg, _ld, _lv)

        for node in scope_walk(root):
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.MatMult)
                    and (low(node.left) or low(node.right))):
                out.append(ctx.finding(
                    node, "`@` on a low-precision operand accumulates in "
                    "that dtype and the operator cannot carry "
                    "preferred_element_type — rewrite as jnp.matmul(..., "
                    "preferred_element_type=jnp.float32)"))
            elif (isinstance(node, ast.Call)
                    and call_name(node) in _R12_MATMUL_CALLS
                    and _kw(node, "preferred_element_type") is None
                    and any(low(a) for a in node.args)):
                out.append(ctx.finding(
                    node, f"`{call_name(node)}` with a low-precision "
                    "operand and no preferred_element_type: partial sums "
                    "round to the input dtype — pass preferred_element_type"
                    "=jnp.float32 (or carry a reasoned disable where narrow "
                    "accumulation is the numerical contract)"))
    return out


# ------------------------------------------------------------------- R13

_R13_WALL = {"time.time"}
# identifier parts that mark a name as deadline/timeout state (matched on
# underscore-split parts, not substrings: `send`/`pending` stay clean)
_R13_TOKENS = {"deadline", "deadlines", "timeout", "timeouts", "expire",
               "expires", "expiry", "due", "cutoff", "until"}


def _r13_deadline_name(name):
    if not name:
        return False
    parts = name.lower().replace(".", "_").split("_")
    return bool(set(parts) & _R13_TOKENS)


def _r13_wall_call(node):
    """The first time.time() Call under `node`, else None."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and call_name(sub) in _R13_WALL:
            return sub
    return None


@rule("R13", "wall-clock time.time() in deadline/timeout arithmetic")
def check_r13(ctx):
    """Deadline and timeout arithmetic in the serve/feed/refresh loops must
    use time.monotonic(): time.time() is WALL clock — NTP steps, leap
    smearing, and manual clock changes move it backwards or jump it forward,
    so a deadline derived from it fires early, late, or never (a request
    that never sheds, a watchdog that kills a healthy worker). Flagged:
    assignments of time.time() arithmetic to deadline-ish names
    (`deadline = time.time() + budget`), comparisons against deadline-ish
    names (`while time.time() < deadline`), elapsed-vs-limit comparisons
    (`time.time() - t0 > timeout_s`), and deadline-ish keyword arguments fed
    from time.time(). Plain wall-clock TIMESTAMPS (log/manifest `ts` fields,
    `train_time` durations, tfevents filenames) are not deadline state and
    pass; a genuine wall-clock deadline contract (e.g. an absolute cron-like
    due time from an external system) carries a reasoned
    `# jaxcheck: disable=R13`."""
    out = []
    seen = set()

    def flag(node, what):
        if node.lineno in seen:
            return
        seen.add(node.lineno)
        out.append(ctx.finding(
            node, f"{what} uses wall-clock time.time() — NTP steps/clock "
            "jumps make the deadline fire early, late, or never; use "
            "time.monotonic() for intervals (keep time.time() only for "
            "log/manifest timestamps)"))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign):
            wall = _r13_wall_call(node.value)
            if wall and any(_r13_deadline_name(d)
                            for d in assign_target_names(node)):
                flag(node, "deadline/timeout assignment")
        elif isinstance(node, ast.Compare):
            sides = [node.left] + node.comparators
            wall_sides = [s for s in sides if _r13_wall_call(s)]
            if not wall_sides:
                continue
            names = set()
            for s in sides:
                if s not in wall_sides:
                    names |= names_in(s)
            elapsed = any(isinstance(s, ast.BinOp) and
                          isinstance(s.op, ast.Sub) for s in wall_sides)
            if elapsed or any(_r13_deadline_name(n) for n in names):
                flag(node, "deadline/timeout comparison")
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg and _r13_deadline_name(kw.arg) and \
                        _r13_wall_call(kw.value):
                    flag(node, f"`{kw.arg}=` argument")
    return out


# ------------------------------------------------------------------- R14

_R14_MUTATORS = {"inc", "observe", "set"}
_R14_FACTORIES = {"counter", "gauge", "histogram"}
# identifier parts that mark a receiver as metric state (underscore-split
# parts, not substrings: `self._stop.set()` carries no metric token and
# stays clean)
_R14_TOKENS = {"metric", "metrics", "counter", "counters", "gauge", "gauges",
               "histogram", "histograms", "registry", "meter"}


def _r14_metric_name(name):
    if not name:
        return False
    parts = name.lower().replace(".", "_").split("_")
    return bool(set(parts) & _R14_TOKENS)


@rule("R14", "metric/counter mutation inside jit-traced code")
def check_r14(ctx):
    """Telemetry mutation (`registry.counter(...).inc()`, `gauge.set(...)`,
    `histogram.observe(...)`) inside jit-traced code is a silent lie: the
    Python side effect runs ONCE at trace time and never again, so after the
    first call the counter freezes while the compiled computation keeps
    executing — the registry reports one batch served however many millions
    ran. (A mutation that also READS a traced value forces a mid-graph host
    sync on top.) Metrics belong on the host side of the dispatch boundary —
    serve/service.py increments around its jitted step, never inside.
    Flagged inside any traced root (and the same-module functions it calls):
    `.inc()/.observe()/.set()` chained straight off a registry factory
    (`m.counter("x").inc()`), on a name bound from a factory in the same
    scope (`c = m.counter("x"); ...; c.inc()`), or on a metric-ish dotted
    name (`self.metrics.*`, `shed_counter`)."""
    out = []
    seen = set()
    direct, closure = traced_roots(ctx.tree)

    def flag(node, what):
        if node.lineno in seen:
            return
        seen.add(node.lineno)
        out.append(ctx.finding(
            node, f"{what} mutates a metric inside jit-traced code — the "
            "Python side effect runs once at TRACE time, so the metric "
            "freezes while the compiled function keeps executing; record "
            "metrics on the host side of the dispatch boundary"))

    for root in direct + closure:
        bound = set()
        for node in scope_walk(root):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Attribute) and \
                    node.value.func.attr in _R14_FACTORIES:
                for t in node.targets:
                    d = dotted(t)
                    if d:
                        bound.add(d)
        for node in scope_walk(root):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _R14_MUTATORS):
                continue
            recv = node.func.value
            if isinstance(recv, ast.Call) and \
                    isinstance(recv.func, ast.Attribute) and \
                    recv.func.attr in _R14_FACTORIES:
                flag(node, f"`.{node.func.attr}()` chained off a registry "
                     "factory")
                continue
            d = dotted(recv)
            if d and (d in bound or _r14_metric_name(d)):
                flag(node, f"`{d}.{node.func.attr}()`")
    return out
