"""threadcheck project index: whole-program facts for the C-rule family.

The R-rules (rules.py) are pure per-file AST and deliberately so — each
hazard they catch is visible inside one module. The concurrency rules
(concurrency.py) are not that lucky: a lock acquired in `serve/corpus.py`
can be re-entered via a callback registered in `fleet/rollout.py`, and
whether a class is "thread-shared" depends on who spawns threads at it.
This module builds the cross-file context those rules consume:

  * per-class inventory — which `self.X` attributes hold locks / condition
    variables / events / queues / threads (assigned from their `threading.*`
    or `queue.*` constructors anywhere in the class), which methods exist,
    and whether the class spawns threads;
  * thread-spawn sites — every `threading.Thread(...)` construction in the
    project with its daemon-ness, binding, and target; a method named as a
    `target=` is marked on its owning class;
  * an intra-package call graph — `self.method()` calls resolved to the
    same class, bare-name calls resolved to same-module functions — good
    enough to follow lock-holding across helper methods.

The index is built lazily per "project": for a file inside a package
(`__init__.py` chain), the whole top-level package is parsed and indexed
once per process; for a standalone file (fixtures, tmp files), the project
is just that file. Parsing is `ast` only — like the rest of jaxcheck, the
index never imports the code it describes.
"""

import ast
import os

from .core import iter_python_files

LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock",
              "threading.Condition", "Condition", "threading.Semaphore",
              "threading.BoundedSemaphore"}
EVENT_CTORS = {"threading.Event", "Event"}
QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
               "queue.SimpleQueue", "Queue", "LifoQueue", "PriorityQueue",
               "SimpleQueue"}
THREAD_CTORS = {"threading.Thread", "Thread"}

# identifier parts that make a name lock-like even without a visible
# constructor (a lock received as a parameter keeps its naming convention)
_LOCKISH_PARTS = {"lock", "mutex", "cv", "cond"}


def _call_name(node):
    if not isinstance(node, ast.Call):
        return None
    return _dotted(node.func)


def _dotted(node):
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _kw(call, name):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def name_is_lockish(name):
    """'_lock', 'swap_lock', '_cv' — underscore-split part matching, so
    `blocked`/`clock` never qualify."""
    parts = set(name.lower().strip("_").split("_"))
    return bool(parts & _LOCKISH_PARTS)


class ThreadSpawn:
    """One `threading.Thread(...)` construction site."""

    __slots__ = ("module", "line", "daemon", "target", "binding", "call")

    def __init__(self, module, call, binding):
        self.module = module
        self.line = call.lineno
        self.call = call
        daemon = _kw(call, "daemon")
        self.daemon = (isinstance(daemon, ast.Constant)
                       and daemon.value is True)
        self.target = _dotted(_kw(call, "target")) if _kw(call, "target") \
            else None
        self.binding = binding   # dotted assign target ('t', 'self._thread')


class ClassIndex:
    """Lock/attribute/thread inventory for one class."""

    def __init__(self, name, module, node):
        self.name = name
        self.module = module
        self.node = node
        self.methods = {}        # method name -> FunctionDef
        self.lock_attrs = set()  # self.X = threading.Lock()/RLock()/Condition()
        self.event_attrs = set()
        self.queue_attrs = set()
        self.thread_attrs = set()
        self.spawns_thread = False
        self.thread_targets = set()  # own methods used as Thread target=

    def is_thread_shared(self):
        """A class that allocates its own lock has declared itself shared
        between threads; spawning a thread at one of its methods does too."""
        return bool(self.lock_attrs) or self.spawns_thread \
            or bool(self.thread_targets)


class ModuleIndex:
    def __init__(self, path, relpath, tree):
        self.path = path
        self.relpath = relpath
        self.tree = tree
        self.classes = []
        self.functions = {}      # module-level name -> FunctionDef
        self.module_locks = set()    # module-level `x = threading.Lock()`
        self.spawns = []             # [ThreadSpawn]
        self._scan()

    def _scan(self):
        for stmt in self.tree.body:
            if isinstance(stmt, ast.ClassDef):
                self.classes.append(self._scan_class(stmt))
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign):
                if _call_name(stmt.value) in LOCK_CTORS:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            self.module_locks.add(t.id)
        # thread spawns anywhere in the module (incl. nested functions):
        # bound constructions keep their assign target, the rest (e.g.
        # `threading.Thread(...).start()`) are recorded unbound
        bound = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and \
                    _call_name(node.value) in THREAD_CTORS:
                bound[id(node.value)] = _dotted(node.targets[0])
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) and _call_name(node) in THREAD_CTORS:
                self.spawns.append(
                    ThreadSpawn(self.relpath, node, bound.get(id(node))))

    def _scan_class(self, node):
        ci = ClassIndex(node.name, self.relpath, node)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci.methods[stmt.name] = stmt
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                ctor = _call_name(sub.value)
                for t in sub.targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    if ctor in LOCK_CTORS:
                        ci.lock_attrs.add(t.attr)
                    elif ctor in EVENT_CTORS:
                        ci.event_attrs.add(t.attr)
                    elif ctor in QUEUE_CTORS:
                        ci.queue_attrs.add(t.attr)
                    elif ctor in THREAD_CTORS:
                        ci.thread_attrs.add(t.attr)
            if isinstance(sub, ast.Call) and _call_name(sub) in THREAD_CTORS:
                ci.spawns_thread = True
                target = _kw(sub, "target")
                td = _dotted(target) if target is not None else None
                if td and td.startswith("self."):
                    ci.thread_targets.add(td.split(".", 1)[1])
        return ci


class ProjectIndex:
    """All modules of one project, with cross-file lookup tables."""

    def __init__(self, files, root=None):
        self.modules = {}            # relpath -> ModuleIndex
        self.classes = {}            # class name -> [ClassIndex]
        self.thread_target_names = set()   # every dotted Thread target=
        for path in files:
            relpath = os.path.relpath(path, root) if root else \
                os.path.basename(path)
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, SyntaxError):
                continue
            mod = ModuleIndex(path, relpath, tree)
            self.modules[relpath] = mod
            for ci in mod.classes:
                self.classes.setdefault(ci.name, []).append(ci)
            for spawn in mod.spawns:
                if spawn.target:
                    self.thread_target_names.add(spawn.target)
        # a method named as a thread target from ANOTHER file still marks
        # its class thread-shared (`Thread(target=corpus.refresh_loop)`)
        tails = {t.split(".")[-1] for t in self.thread_target_names}
        for cls_list in self.classes.values():
            for ci in cls_list:
                if tails & set(ci.methods):
                    ci.thread_targets |= tails & set(ci.methods)
        self._cache = {}             # scratch space for rule-level passes

    def module_for(self, path):
        """ModuleIndex for an absolute file path (relpaths differ between
        the analyzer's root and the index's — the path is the stable key)."""
        ap = os.path.abspath(path)
        for mod in self.modules.values():
            if os.path.abspath(mod.path) == ap:
                return mod
        return None

    def class_index(self, module_relpath, class_name):
        for ci in self.classes.get(class_name, ()):
            if ci.module == module_relpath:
                return ci
        lst = self.classes.get(class_name)
        return lst[0] if lst else None

    def lock_attr_names(self):
        """Union of every known lock attribute name across the project —
        lets `req._lock` (receiver of unknown type) be recognized as a lock
        because SOME indexed class declares `_lock`."""
        names = set()
        for lst in self.classes.values():
            for ci in lst:
                names |= ci.lock_attrs
        return names


def _project_top(path):
    """Top-most package directory containing `path`, or None when the file
    is not inside a package (fixtures, tmp files, bench.py)."""
    d = os.path.dirname(os.path.abspath(path))
    top = None
    while os.path.exists(os.path.join(d, "__init__.py")):
        top = d
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return top


_INDEX_CACHE = {}


def index_for(ctx):
    """ProjectIndex for the project containing `ctx.path` — the whole
    top-level package when the file lives in one, else the file alone.
    Cached per process (one CLI/pytest run sees a stable tree)."""
    top = _project_top(ctx.path)
    if top is None:
        key = os.path.abspath(ctx.path)
        if key not in _INDEX_CACHE:
            _INDEX_CACHE[key] = ProjectIndex([ctx.path])
        return _INDEX_CACHE[key]
    key = os.path.realpath(top)
    if key not in _INDEX_CACHE:
        files = list(iter_python_files([top]))
        _INDEX_CACHE[key] = ProjectIndex(files, root=os.path.dirname(top))
    return _INDEX_CACHE[key]
