"""jaxcheck core: findings, suppression parsing, rule registry, file walking.

Design notes
------------
* Pure `ast` — the analyzer never imports the code it checks, so it can walk
  fixture files with planted violations (and broken code) safely, and runs in
  milliseconds inside tier-1.
* Rules are registered via the `@rule` decorator and receive a `FileContext`;
  each returns a list of `Finding`s. A rule may declare a path `scope`
  predicate (R2 only makes sense for bench/evidence timing code).
* Suppressions: `# jaxcheck: disable=R3 (reason)` on the offending line, or
  standalone on the line directly above it. The reason is MANDATORY — a
  disable without one is reported as rule `SUP` and cannot itself be
  suppressed (otherwise `disable=SUP` would launder reasonless disables).
"""

import ast
import dataclasses
import io
import os
import re
import tokenize

# rule id -> (title, checker, scope_predicate_or_None); populated by @rule
RULES = {}

_SUPPRESS_RE = re.compile(
    r"#\s*jaxcheck:\s*disable=([A-Za-z0-9_,\s]+?)\s*(?:\((.*)\))?\s*$")


def rule(rule_id, title, scope=None):
    """Register a checker. `scope(relpath) -> bool` limits which files the
    rule sees (None = every file)."""

    def register(fn):
        RULES[rule_id] = (title, fn, scope)
        return fn

    return register


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def location(self):
        return f"{self.path}:{self.line}"

    def render(self):
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def to_json(self):
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    line: int          # line the comment sits on
    rules: tuple       # rule ids, or ("all",)
    reason: str

    def covers(self, finding_line, rule_id):
        # a suppression comment governs its own line and the line below it
        # (the standalone-comment-above style)
        if finding_line not in (self.line, self.line + 1):
            return False
        return "all" in self.rules or rule_id in self.rules


class FileContext:
    """Everything a rule needs about one file: source, AST, repo-relative
    path, and per-line suppressions."""

    def __init__(self, path, relpath, source, tree):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.suppressions = parse_suppressions(source)
        self.current_rule = None  # set by analyze_file around each checker

    def finding(self, node_or_line, message, rule_id=None):
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule_id or self.current_rule, self.relpath, line,
                       message)


def parse_suppressions(source):
    """Token-aware: only real COMMENT tokens register — a disable quoted
    inside a docstring (this package's own docs show the syntax) is prose,
    not a suppression. Falls back to line-matching only if tokenization
    fails (the file already parsed as AST, so it essentially never does)."""
    out = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        candidates = [(tok.start[0], tok.string) for tok in tokens
                      if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        candidates = list(enumerate(source.splitlines(), start=1))
    for i, text in candidates:
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        ids = tuple(s.strip() for s in m.group(1).split(",") if s.strip())
        reason = (m.group(2) or "").strip()
        out.append(Suppression(line=i, rules=ids, reason=reason))
    return out


def _suppression_findings(ctx):
    """Rule SUP: every disable must carry a non-empty parenthesized reason."""
    out = []
    for sup in ctx.suppressions:
        if not sup.reason:
            out.append(ctx.finding(
                sup.line,
                "jaxcheck suppression without a reason — write "
                "`# jaxcheck: disable=<RULE> (why this is safe)`",
                rule_id="SUP"))
        unknown = [r for r in sup.rules if r != "all" and r not in RULES]
        if unknown:
            out.append(ctx.finding(
                sup.line,
                f"suppression names unknown rule(s): {', '.join(unknown)}",
                rule_id="SUP"))
    return out


def _unused_suppression_findings(ctx, used, select):
    """Rule SUP: a reasoned disable whose rule did not fire on its lines is
    stale — the code was fixed (or the disable never matched) and the
    comment now silences nothing but reviewer attention. Only rules that
    actually RAN are judged: a rule excluded by `--select` or a scope
    predicate proves nothing about the disable. `disable=all` is exempt
    (it documents intent, not one rule's firing)."""
    out = []
    for sup in ctx.suppressions:
        if not sup.reason:
            continue   # already a SUP finding; unknown-rule ids likewise
        for rule_id in sup.rules:
            if rule_id == "all" or rule_id not in RULES:
                continue
            if select is not None and rule_id not in select:
                continue
            scope = RULES[rule_id][2]
            if scope is not None and not scope(ctx.relpath):
                continue
            if (sup.line, rule_id) in used:
                continue
            out.append(ctx.finding(
                sup.line,
                f"unused suppression: {rule_id} does not fire here — "
                "delete the stale disable",
                rule_id="SUP"))
    return out


def analyze_file(path, root=None, select=None):
    """Run every applicable rule on one file.

    `select` (a set of rule ids) restricts which registered rules run —
    None means all. Returns (findings, suppressed) — `findings` are
    actionable (exit-code relevant), `suppressed` carry their reasons for
    the JSON report.
    """
    relpath = os.path.relpath(path, root) if root else path
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding("AST", relpath, e.lineno or 1,
                        f"file does not parse: {e.msg}")], []
    ctx = FileContext(path, relpath, source, tree)

    raw = []
    for rule_id, (_, checker, scope) in RULES.items():
        if select is not None and rule_id not in select:
            continue
        if scope is not None and not scope(relpath):
            continue
        ctx.current_rule = rule_id
        raw.extend(checker(ctx))
    ctx.current_rule = None
    # SUP findings are generated outside the registry so they can never be
    # masked by a scope predicate or another suppression
    sup_findings = _suppression_findings(ctx)

    findings, suppressed = [], []
    used = set()   # (suppression line, rule id) pairs that silenced something
    for f in sorted(raw, key=lambda f: (f.line, f.rule)):
        sup = next((s for s in ctx.suppressions if s.covers(f.line, f.rule)),
                   None)
        if sup is not None and sup.reason:
            f.suppressed = True
            f.suppress_reason = sup.reason
            suppressed.append(f)
            used.add((sup.line, f.rule))
        else:
            findings.append(f)
    findings.extend(sup_findings)
    findings.extend(_unused_suppression_findings(ctx, used, select))
    findings.sort(key=lambda f: (f.line, f.rule))
    return findings, suppressed


_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "build", "results"}


def iter_python_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS
                                 and not d.startswith(".")
                                 and d != "fixtures")
            for name in sorted(filenames):
                if name.endswith(".py"):
                    yield os.path.join(dirpath, name)


def repo_root():
    """The repo checkout containing this package (package dir's parent)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_targets():
    """The self-clean contract's file set: the package, bench.py, and
    evidence/ (tests and their planted-violation fixtures excluded)."""
    root = repo_root()
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = [pkg]
    for extra in ("bench.py", "evidence"):
        p = os.path.join(root, extra)
        if os.path.exists(p):
            targets.append(p)
    return root, targets


def analyze_paths(paths, root=None, select=None):
    """Analyze every .py under `paths`. Returns (findings, suppressed,
    n_files)."""
    findings, suppressed = [], []
    n = 0
    for path in iter_python_files(paths):
        n += 1
        f, s = analyze_file(path, root=root, select=select)
        findings.extend(f)
        suppressed.extend(s)
    return findings, suppressed, n


# importing the rule modules registers them (kept last: all import helpers
# from here; concurrency and meshcheck additionally import from rules)
from . import rules  # noqa: E402,F401
from . import concurrency  # noqa: E402,F401
from . import meshcheck  # noqa: E402,F401
