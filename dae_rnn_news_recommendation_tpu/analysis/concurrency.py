"""threadcheck rules C1-C5 — cross-file concurrency checkers for the serving
fleet (see docs/jaxcheck.md for the catalog with in-repo examples).

Where R1-R14 are per-file, these rules consume the whole-program index
(project.py): per-class lock inventories, thread-spawn sites, and an
intra-package call graph good enough to follow lock-holding through helper
methods (`self._resolve(...)` called under `req._lock` analyzes `_resolve`
with that lock held). Like every jaxcheck rule they are heuristic by
construction — lock identity is nominal (`ClassName.attr` for `self.X`,
`receiver.attr` for other objects, `global:name` for module-level locks),
manual `.acquire()`/`.release()` pairs are out of scope (only `with lock:`
regions are tracked), and anything the rules cannot see carries a reasoned
`# jaxcheck: disable=...` at the site.
"""

import ast
import os

from .core import rule
from . import project
from .project import name_is_lockish
from .rules import (dotted, call_name, _kw, _const, _r11_bindings,
                    _r11_has_timeout)

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


# ----------------------------------------------------------- lock tracking

def _make_keyer(owner, mod, index):
    """expr -> lock key or None. Keys are nominal: `Class.attr` for self
    attributes, `recv.attr` for other receivers, `global:name` for bare
    names — the same textual convention across files, so a lock threaded
    through modules keeps one identity."""
    known = index.lock_attr_names()

    def keyer(expr):
        if isinstance(expr, ast.Attribute):
            recv = dotted(expr.value)
            attr = expr.attr
            if recv == "self":
                if owner is not None and (attr in owner.lock_attrs
                                          or name_is_lockish(attr)):
                    return f"{owner.name}.{attr}"
                if owner is None and name_is_lockish(attr):
                    return f"self.{attr}"
                return None
            if recv is not None and (attr in known or name_is_lockish(attr)):
                return f"{recv}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if expr.id in mod.module_locks or name_is_lockish(expr.id):
                return f"global:{expr.id}"
        return None

    return keyer


def _walk_held(func_node, keyer, entry_held=frozenset()):
    """Walk one function body tracking `with <lock>:` regions lexically.

    Returns (nodes, acquires): `nodes` is [(node, held)] for every AST node
    outside nested function defs; `acquires` is [(key, expr, held_before)]
    for every recognized lock acquisition. `entry_held` seeds locks the
    caller proved held at every call site (the call-graph propagation)."""
    nodes, acquires = [], []

    def visit(node, held):
        if isinstance(node, _FUNC_DEFS + (ast.Lambda,)):
            return  # nested defs run later, not here — analyzed as own units
        if isinstance(node, (ast.With, ast.AsyncWith)):
            cur = held
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    nodes.append((sub, cur))
                key = keyer(item.context_expr)
                if key is not None:
                    acquires.append((key, item.context_expr, cur))
                    cur = cur | {key}
            for stmt in node.body:
                visit(stmt, cur)
            return
        nodes.append((node, held))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in func_node.body:
        visit(stmt, frozenset(entry_held))
    return nodes, acquires


def _units(mod):
    """Every function in the module as (owner_class_or_None, FunctionDef):
    class methods (and closures inside them — `self` still means the class)
    first, then module-level functions and their closures."""
    seen, out = set(), []
    for ci in mod.classes:
        for node in ast.walk(ci.node):
            if isinstance(node, _FUNC_DEFS) and id(node) not in seen:
                seen.add(id(node))
                out.append((ci, node))
    for node in ast.walk(mod.tree):
        if isinstance(node, _FUNC_DEFS) and id(node) not in seen:
            seen.add(id(node))
            out.append((None, node))
    return out


def _resolve_call(call, owner, mod):
    """Callee FunctionDef for `self.m(...)` (same class) or `f(...)` (same
    module), else None — the intra-package call graph's resolution step."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self" and owner is not None:
        return owner.methods.get(f.attr)
    if isinstance(f, ast.Name):
        return mod.functions.get(f.id)
    return None


def _module_entries(index, mod):
    """(units, entry): for each function, the set of locks provably held at
    EVERY intra-module call site (intersection semantics — a public method
    reachable without the lock gets the empty set). Two propagation rounds
    cover helper-calls-helper chains."""
    cached = index._cache.get(("entries", mod.relpath))
    if cached is not None:
        return cached
    units = _units(mod)
    entry = {id(node): frozenset() for _, node in units}
    for _ in range(2):
        acc = {}
        for owner, node in units:
            keyer = _make_keyer(owner, mod, index)
            nodes, _ = _walk_held(node, keyer, entry[id(node)])
            for n, held in nodes:
                if not isinstance(n, ast.Call):
                    continue
                callee = _resolve_call(n, owner, mod)
                if callee is not None and id(callee) in entry:
                    prev = acc.get(id(callee))
                    acc[id(callee)] = held if prev is None else (prev & held)
        entry = {k: frozenset(acc.get(k) or frozenset()) for k in entry}
    index._cache[("entries", mod.relpath)] = (units, entry)
    return units, entry


def _lock_names(held):
    return ", ".join(f"`{k}`" for k in sorted(held))


# ------------------------------------------------------------------- C1

@rule("C1", "attribute written under a lock in one method but bare in "
      "another of a thread-shared class")
def check_c1(ctx):
    """A class that allocates its own `threading.Lock` has declared itself
    shared between threads; from then on, an attribute written under `with
    self._lock:` in one method and bare in another is a data race waiting
    for the interleaving chaos soaks never hit — the bare write can tear a
    read-modify-write or publish half-initialized state. `__init__` writes
    are exempt (construction happens-before the threads), as are attributes
    never written under a lock at all (the class evidently considers them
    single-writer). The inference follows the call graph: a helper only
    ever called under the lock counts as locked."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    out = []
    units, entry = _module_entries(index, mod)
    by_owner = {}
    for owner, node in units:
        if owner is not None:
            by_owner.setdefault(id(owner), []).append(node)
    for ci in mod.classes:
        if not ci.lock_attrs:
            continue
        keyer = _make_keyer(ci, mod, index)
        writes = {}   # attr -> {"locked": [...], "bare": [...]}
        init_funcs = {id(ci.methods.get(m)) for m in
                      ("__init__", "__new__", "__post_init__")
                      if ci.methods.get(m) is not None}
        for node in by_owner.get(id(ci), ()):
            if id(node) in init_funcs:
                continue
            nodes, _ = _walk_held(node, keyer, entry[id(node)])
            for n, held in nodes:
                if not isinstance(n, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign)):
                    continue
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    attr = _self_attr_of(t)
                    if attr is None:
                        continue
                    bucket = writes.setdefault(
                        attr, {"locked": [], "bare": []})
                    kind = "locked" if held else "bare"
                    bucket[kind].append((n, node.name, held))
        for attr, b in sorted(writes.items()):
            if not b["locked"] or not b["bare"]:
                continue
            ln, lmeth, lheld = b["locked"][0]
            for n, meth, _ in b["bare"]:
                out.append(ctx.finding(
                    n, f"`self.{attr}` is written under "
                    f"{_lock_names(lheld)} in `{ci.name}.{lmeth}` (line "
                    f"{ln.lineno}) but bare here in `{meth}` — a "
                    "thread-shared class must guard every write of a "
                    "lock-protected attribute"))
    return out


def _self_attr_of(target):
    """'x' for `self.x = ...` and `self.x[k] = ...` targets, else None."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and target.value.id == "self":
        return target.attr
    return None


# ------------------------------------------------------------------- C2

def _lock_graph(index):
    """Project-global acquires-while-holding graph: edge A -> B for every
    site acquiring B with A held (lexically or via the call graph's
    entry-held propagation). Cached on the index."""
    cached = index._cache.get("lock_graph")
    if cached is not None:
        return cached
    edges, sites = {}, {}
    for mod in index.modules.values():
        units, entry = _module_entries(index, mod)
        for owner, node in units:
            keyer = _make_keyer(owner, mod, index)
            _, acquires = _walk_held(node, keyer, entry[id(node)])
            for key, expr, held in acquires:
                for h in held:
                    if h == key:
                        continue
                    edges.setdefault(h, set()).add(key)
                    sites.setdefault((h, key), []).append(
                        (os.path.abspath(mod.path), mod.relpath,
                         expr.lineno))
    index._cache["lock_graph"] = (edges, sites)
    return edges, sites


def _reaches(edges, src, dst, _seen=None):
    if _seen is None:
        _seen = set()
    if src == dst:
        return True
    if src in _seen:
        return False
    _seen.add(src)
    return any(_reaches(edges, nxt, dst, _seen)
               for nxt in edges.get(src, ()))


@rule("C2", "lock-order inversion in the acquires-while-holding graph")
def check_c2(ctx):
    """Cycle search over the project-global acquires-while-holding graph:
    one code path takes A then B while another takes B then ... then A.
    Two threads, one in each order, deadlock — the classic inversion no
    single file shows, which is why this rule rides the whole-program index
    and the call graph (a helper that takes B counts against every caller
    holding A). Keys are nominal, so `req._lock -> Router._lock` in
    fleet/router.py and the reverse order in another module still collide."""
    index = project.index_for(ctx)
    edges, sites = _lock_graph(index)
    here = os.path.abspath(ctx.path)
    out, seen = [], set()
    for (a, b), locs in sorted(sites.items()):
        if not _reaches(edges, b, a):
            continue
        reverse = sites.get((b, a))
        via = (f"the opposite order is taken at "
               f"{reverse[0][1]}:{reverse[0][2]}" if reverse else
               f"`{b}` reaches `{a}` through intermediate locks")
        for path, _, line in locs:
            if path != here or (a, b, line) in seen:
                continue
            seen.add((a, b, line))
            out.append(ctx.finding(
                line, f"lock-order inversion: `{b}` acquired while holding "
                f"`{a}`, but {via} — one thread in each order deadlocks; "
                "pick one global order"))
    return out


# ------------------------------------------------------------------- C3

_DEVICE_SYNC_CALLS = {"jax.block_until_ready", "block_until_ready",
                      "jax.device_get", "device_get"}
_FUTURE_PARTS = {"fut", "future", "futures", "promise"}


def _parts(name):
    return set(name.lower().strip("_").split("_"))


@rule("C3", "blocking call or device sync while holding a lock")
def check_c3(ctx):
    """An untimed `Event.wait` / `Queue.get` / `Thread.join` /
    `future.result`, or a device sync (`block_until_ready`, `device_get`)
    inside a `with lock:` body pins the lock for the full wait: every other
    acquirer stalls behind a wait that may never end, and if the thing being
    waited on needs the same lock to make progress the wait IS the deadlock.
    Device syncs are the serving-stack special: a swap that fetches under
    the corpus lock blocks every reader for the full transfer. Waits on the
    held condition variable itself are exempt (`cv.wait` releases it), as
    are timed waits (bounded stall, surfaced by the caller). Queue/thread
    receivers are binding-aware (R11's tables) so `dict.get` never trips."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    queues, threads, _ = _r11_bindings(mod.tree)
    units, entry = _module_entries(index, mod)
    out = []
    for owner, node in units:
        keyer = _make_keyer(owner, mod, index)
        nodes, _ = _walk_held(node, keyer, entry[id(node)])
        for n, held in nodes:
            if not held or not isinstance(n, ast.Call):
                continue
            desc = _blocking_desc(n, keyer, held, queues, threads,
                                  owner)
            if desc is None:
                continue
            out.append(ctx.finding(
                n, f"{desc} while holding {_lock_names(held)} — the lock "
                "is pinned for the full wait, stalling every other "
                "acquirer; move the wait outside the lock or bound it "
                "with a timeout"))
    return out


def _blocking_desc(call, keyer, held, queues, threads, owner):
    """Human-readable description when `call` blocks indefinitely or forces
    a device sync, else None."""
    name = call_name(call)
    if name in _DEVICE_SYNC_CALLS:
        return f"device sync `{name}(...)`"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv = dotted(call.func.value)
    if attr == "block_until_ready":
        return f"device sync `{recv}.block_until_ready()`"
    if _r11_has_timeout(call):
        return None
    if attr == "wait":
        # waiting on the held cv itself releases it — the sanctioned shape
        if keyer(call.func.value) in held:
            return None
        return f"untimed `{recv}.wait()`"
    if attr == "get" and recv in queues:
        return f"untimed `{recv}.get()`"
    if attr == "join" and recv in queues | threads:
        return f"untimed `{recv}.join()`"
    if attr == "result" and recv is not None and \
            (_parts(recv.split(".")[-1]) & _FUTURE_PARTS):
        return f"untimed `{recv}.result()`"
    return None


# ------------------------------------------------------------------- C4

@rule("C4", "started non-daemon thread with no join/stop on any path")
def check_c4(ctx):
    """A `threading.Thread` started without `daemon=True` and never joined
    anywhere in its module leaks: interpreter shutdown blocks on it forever
    (non-daemon threads are waited on at exit), and in tests the leaked
    worker outlives its fixture and corrupts the next one. The repo's
    discipline is daemon threads joined-with-timeout in `stop()`; this rule
    flags the construction site when neither escape hatch exists. Daemon-ness
    also counts when assigned post-construction (`t.daemon = True`)."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    started, joined, daemonized = set(), set(), set()
    chained_start = set()   # id of ctor Call in Thread(...).start()
    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            recv = dotted(n.func.value)
            if n.func.attr == "start":
                if recv is not None:
                    started.add(recv)
                elif isinstance(n.func.value, ast.Call):
                    chained_start.add(id(n.func.value))
            elif n.func.attr == "join" and recv is not None:
                joined.add(recv)
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Attribute) and t.attr == "daemon" \
                        and _const(n.value) is True:
                    d = dotted(t.value)
                    if d is not None:
                        daemonized.add(d)
    out = []
    for spawn in mod.spawns:
        if spawn.daemon:
            continue
        b = spawn.binding
        if b is not None and b in daemonized:
            continue
        is_started = (b in started if b is not None
                      else id(spawn.call) in chained_start)
        if not is_started:
            continue
        if b is not None and b in joined:
            continue
        out.append(ctx.finding(
            spawn.call, "non-daemon `threading.Thread` is started but "
            "never joined anywhere in this module — interpreter shutdown "
            "blocks on it forever; pass daemon=True or join it with a "
            "timeout on every path"))
    return out


# ------------------------------------------------------------------- C5

_RESOLVE_ATTRS = {"set_result", "set_exception", "_set"}
_CALLBACK_PARTS = {"callback", "callbacks", "cb", "cbs", "hook", "hooks",
                   "listener", "listeners"}
_REGISTRATION_PREFIXES = ("add", "remove", "register", "unregister",
                          "subscribe")


@rule("C5", "future resolved / callbacks invoked while holding a lock")
def check_c5(ctx):
    """Resolving a request future (`set_result`, `set_exception`, this
    repo's `ReplyFuture._set`) or invoking user callbacks while holding a
    router/corpus lock hands YOUR lock to arbitrary foreign code: a waiter
    woken by the resolution — or the callback itself — can call straight
    back into the component and re-acquire the lock (instant deadlock), or
    simply run slow user code under it. The sanctioned shape is
    `serve/service.py`'s `ReplyFuture._set`: swap the callback list out
    under the lock, invoke after releasing it. The check follows the call
    graph, so a `_resolve_locked` helper only ever called under `req._lock`
    is analyzed with that lock held."""
    index = project.index_for(ctx)
    mod = index.module_for(ctx.path)
    if mod is None:
        return []
    units, entry = _module_entries(index, mod)
    out = []
    for owner, node in units:
        keyer = _make_keyer(owner, mod, index)
        nodes, _ = _walk_held(node, keyer, entry[id(node)])
        cb_vars = _callback_loop_vars(node)
        for n, held in nodes:
            if not held or not isinstance(n, ast.Call):
                continue
            desc = _resolving_desc(n, cb_vars)
            if desc is None:
                continue
            out.append(ctx.finding(
                n, f"{desc} while holding {_lock_names(held)} — the woken "
                "waiter or callback can re-enter this component and "
                "re-acquire the lock; snapshot under the lock, resolve/"
                "invoke after releasing it"))
    return out


def _callback_loop_vars(func_node):
    """Loop variables iterating something callback-named (`for cb in
    callbacks:`) — calling one is a callback invocation."""
    vars_ = set()
    for n in ast.walk(func_node):
        if isinstance(n, ast.For) and isinstance(n.target, ast.Name):
            it = dotted(n.iter)
            if it and (_parts(it.split(".")[-1]) & _CALLBACK_PARTS):
                vars_.add(n.target.id)
    return vars_


def _resolving_desc(call, cb_vars):
    f = call.func
    if isinstance(f, ast.Attribute):
        if f.attr in _RESOLVE_ATTRS:
            return f"future resolution `{dotted(f.value)}.{f.attr}(...)`"
        parts = _parts(f.attr)
        if (parts & _CALLBACK_PARTS) and \
                not f.attr.startswith(_REGISTRATION_PREFIXES):
            return f"callback invocation `{dotted(f.value)}.{f.attr}(...)`"
        return None
    if isinstance(f, ast.Name):
        if f.id in cb_vars:
            return f"callback invocation `{f.id}(...)`"
        if (_parts(f.id) & _CALLBACK_PARTS) and \
                not f.id.startswith(_REGISTRATION_PREFIXES):
            return f"callback invocation `{f.id}(...)`"
    return None
