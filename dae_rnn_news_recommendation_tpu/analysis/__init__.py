"""jaxcheck — static analysis for the silent JAX hazard classes this repo has
actually been bitten by, plus a runtime compile-budget guard.

Round 5's hardest lessons were all invisible in review: `block_until_ready`
lying under the axon tunnel inflated bench claims 5x (fixed by fetch fences),
a ragged `lax.scan` tail recompiled inside a timed section, and buffer
donation (`train/step.make_train_step(donate_batch=True)`) opened the
use-after-donate bug class. Each is a *graph-level* invariant a human can't
reliably eyeball across a growing tree — the same observation that motivates
graph-level checking in large training systems (TF system paper §4; XLA's own
donation/aliasing verifier). jaxcheck encodes them as review-time rules:

    R1  host-sync calls reachable inside jit-traced code
    R2  timed regions in bench/evidence code without a fetch fence
    R3  use-after-donate on donated arguments
    R4  recompile hazards (per-iteration Python scalars, ragged stacking)
    R5  PRNG key reuse without an intervening split

The serving fleet added a second invisible-in-review bug class — cross-file
concurrency. The threadcheck family (concurrency.py) rides the same
registry but consumes a whole-program index (project.py: lock inventories,
thread spawns, intra-package call graph):

    C1  attribute written under a lock in one method but bare in another
    C2  lock-order inversion across the acquires-while-holding graph
    C3  blocking call / device sync while holding a lock
    C4  started non-daemon thread with no join/stop on any path
    C5  future resolved / callbacks invoked while holding a lock

Making sharded+IVF the serving default (r16) surfaced a third class neither
family could see: mesh/SPMD invariants. A shard_map program is a collective
— every device must rendezvous on the same program — so concurrent
dispatches from threads deadlock; collectives under per-shard control flow
hang; replicated out_specs on unreduced values silently serve one shard's
partial answer. The meshcheck family (meshcheck.py) extends the project
index with shard_map construction sites, the sharded-callable closure, and
collective/axis inventories:

    S1  shard_map dispatch from a thread-reachable site without the mesh
        dispatch lock (parallel/mesh.dispatch_lock — the r16 deadlock class)
    S2  collective under control flow divergent across shards
    S3  collective axis unbound by the enclosing shard_map / outside the
        mesh axis vocabulary (parallel/mesh.MESH_AXIS_NAMES)
    S4  host-side work (device transfers, np. materialization, host lists)
        captured in a shard_map body
    S5  out_specs claiming P() (replicated) for an output the body never
        collectively reduces — the static twin of check_rep, which the
        Pallas paths must disable

CLI:    python -m dae_rnn_news_recommendation_tpu.analysis [paths] [--json]
        [--select C1,C3] [--select S] [--select R,C,S] [--list-rules]
        (no paths: the package + bench.py + evidence/; exit 0 = clean)
Runtime: `compile_guard(max_compiles=N)` — a context manager counting XLA
        backend compiles via `jax.monitoring`, so tests can pin an upper
        bound on jit variants (e.g. the pipelined feed's shape buckets).

Suppressions are first-class but must carry a reason:

    x = donated_batch["x"]  # jaxcheck: disable=R3 (copied out before the step)

A reasonless disable is itself reported (rule SUP). Rule catalog with
in-repo examples: docs/jaxcheck.md.
"""

from .core import (Finding, analyze_file, analyze_paths, default_targets,
                   iter_python_files, RULES)
from .runtime import CompileBudgetExceeded, CompileWatcher, compile_guard

__all__ = [
    "Finding", "analyze_file", "analyze_paths", "default_targets",
    "iter_python_files", "RULES",
    "CompileBudgetExceeded", "CompileWatcher", "compile_guard",
]
