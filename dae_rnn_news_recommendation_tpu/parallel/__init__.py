"""Parallelism layer — net-new vs the reference, which is single-process TF1 with no
distribution at all (SURVEY §2.1: "Parallelism strategies implemented in the
reference: NONE"). Designed per the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert the collectives over ICI.

  mesh.py — device mesh construction (1-D data, 2-D data x model)
  dp.py   — data-parallel (+ optional feature-sharded) jit train/eval steps;
            'global' triplet mining sees the full global batch (XLA all_gathers the
            [B, D] embeddings — cheap on ICI), 'shard' mines per shard via shard_map
  ring.py — ring-allgather blockwise pairwise similarity (the O(N^2) eval kernel,
            sharded by rows, blocks rotated over the ring with ppermute)
  seq.py  — sequence/context parallelism: the GRU user-model recurrence pipelined
            over a time-sharded mesh (GPipe along T; only [Bm, H] states cross
            devices), exact-semantics and differentiable
  pp.py   — pipeline parallelism: the stacked DAE's equal-width hidden tower,
            one layer per 'stage' device, GPipe microbatch schedule,
            differentiable
  ep.py   — expert parallelism: Switch-style mixture-of-denoisers, one expert DAE
            per device over an 'expert' mesh axis, top-1 routing with static
            capacity and all_to_all dispatch/return, load-balance aux loss;
            oracle-tested against the dense all-experts path
  mining.py — anchor-partitioned GLOBAL triplet mining for shard_map contexts:
            each device mines its own rows as anchors against the gathered
            codes (1/P of the batch_all cube per device), psums complete the
            cross-anchor reductions; exact square-oracle semantics
"""

from .mesh import (  # noqa: F401
    get_mesh,
    get_mesh_2d,
    initialize_multihost,
    row_sharding,
    shard_rows,
)
from .dp import (  # noqa: F401
    make_parallel_train_step,
    make_parallel_eval_step,
    param_shardings,
    batch_shardings,
)
from .feed import batch_spec, put_replicated, put_sharded_batch  # noqa: F401
from .ring import ring_pairwise_similarity  # noqa: F401
from .seq import pipeline_gru_apply  # noqa: F401
from .pp import pipeline_stack_encode, stack_tower_params  # noqa: F401
from .ep import (  # noqa: F401
    make_moe_encode_fn,
    make_moe_train_step,
    moe_forward_dense,
    moe_init_params,
    moe_loss_and_metrics,
)
from .mining import (  # noqa: F401
    sharded_batch_all_triplet_loss,
    sharded_batch_hard_triplet_loss,
)
