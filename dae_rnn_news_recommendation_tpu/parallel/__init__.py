"""Parallelism layer — net-new vs the reference, which is single-process TF1 with no
distribution at all (SURVEY §2.1: "Parallelism strategies implemented in the
reference: NONE"). Designed per the scaling-book recipe: pick a mesh, annotate
shardings, let XLA insert the collectives over ICI.

  mesh.py — device mesh construction (1-D data, 2-D data x model)
  dp.py   — data-parallel (+ optional feature-sharded) jit train/eval steps;
            'global' triplet mining sees the full global batch (XLA all_gathers the
            [B, D] embeddings — cheap on ICI), 'shard' mines per shard via shard_map
  ring.py — ring-allgather blockwise pairwise similarity (the O(N^2) eval kernel,
            sharded by rows, blocks rotated over the ring with ppermute)
  seq.py  — sequence/context parallelism: the GRU user-model recurrence pipelined
            over a time-sharded mesh (GPipe along T; only [Bm, H] states cross
            devices), exact-semantics and differentiable
  pp.py   — pipeline parallelism: the stacked DAE's equal-width hidden tower,
            one layer per 'stage' device, GPipe microbatch schedule,
            differentiable

(Expert parallelism has no counterpart here: this model family has no MoE layers —
every parallelism axis the DAE/GRU architecture admits is covered.)
"""

from .mesh import get_mesh, get_mesh_2d, initialize_multihost  # noqa: F401
from .dp import (  # noqa: F401
    make_parallel_train_step,
    make_parallel_eval_step,
    param_shardings,
    batch_shardings,
)
from .feed import batch_spec, put_replicated, put_sharded_batch  # noqa: F401
from .ring import ring_pairwise_similarity  # noqa: F401
from .seq import pipeline_gru_apply  # noqa: F401
from .pp import pipeline_stack_encode, stack_tower_params  # noqa: F401
