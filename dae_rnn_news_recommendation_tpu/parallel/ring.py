"""Ring-allgather blockwise pairwise similarity.

The reference's eval computes an O(N^2) cosine-similarity matrix on host
(helpers.py:45, SURVEY §5.7 names this the repo's long-context analog). Here the row
blocks are sharded over the mesh and rotated around the ring with `ppermute` — the
same communication pattern as ring attention: at step s each device multiplies its
local block [n_local, D] against the block that has travelled s hops, so every device
only ever holds two [n_local, D] tiles + its [n_local, N] output stripe, and the
N x N matrix never materializes on one device. Comms and compute overlap across steps
on TPU (ppermute rides ICI while the MXU does the current block).

Also usable for *global* blockwise triplet mining when B x B no longer fits
(SURVEY §7, "blockwise/chunked pairwise-distance computation").
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.normalize import l2_normalize
from .mesh import _shard_map, pcast_varying


def _l2_normalize_rows(x):
    return l2_normalize(x, axis=1)


def ring_pairwise_similarity(embeddings, mesh, axis_name="data", normalize=True,
                             set_diagonal_zero=True):
    """Full [N, N] similarity computed blockwise over the mesh.

    :param embeddings: [N, D] array (N divisible by mesh size; pad + mask upstream)
    :param normalize: l2-normalize rows first (cosine); False gives raw dot products
    :return: [N, N] similarity, sharded by rows over `axis_name`
    """
    n_dev = mesh.shape[axis_name]
    n = embeddings.shape[0]
    assert n % n_dev == 0, f"N={n} not divisible by mesh size {n_dev}"

    def local_fn(local):  # local: [n_local, D]
        if normalize:
            local = _l2_normalize_rows(local)
        n_local = local.shape[0]
        me = jax.lax.axis_index(axis_name)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]  # ring: shift blocks right

        def body(s, carry):
            block, out = carry
            # the block currently held started at device (me - s) mod n_dev,
            # so it owns output columns [(me - s) * n_local, ...)
            src = (me - s) % n_dev
            tile = jnp.matmul(local, block.T, precision=jax.lax.Precision.HIGHEST)
            out = jax.lax.dynamic_update_slice(out, tile, (0, src * n_local))
            block = jax.lax.ppermute(block, axis_name, perm)
            return block, out

        out = jnp.zeros((n_local, n), local.dtype)
        # zeros are device-invariant; mark them varying over the mesh axis so the
        # loop carry type matches the ppermute-updated value
        out = pcast_varying(out, axis_name)
        _, out = jax.lax.fori_loop(0, n_dev, body, (local, out))
        return out

    fn = _shard_map(local_fn, mesh=mesh, in_specs=P(axis_name, None),
                       out_specs=P(axis_name, None))
    sim = fn(embeddings)
    if set_diagonal_zero:
        sim = sim * (1.0 - jnp.eye(n, dtype=sim.dtype))
    return sim
