"""Multi-host batch placement: process-local rows -> one global sharded batch.

The reference's only transport is the in-process feed_dict copy (SURVEY §5.8);
on a multi-host TPU deployment each process must load ITS OWN slice of the
batch and hand jit a global jax.Array. These helpers wrap that assembly so the
parallel train/eval steps (parallel/dp.py) work unchanged from 1 chip to a
multi-host pod:

  * single process: a plain device_put with the batch's NamedShardings;
  * multi process: jax.make_array_from_process_local_data stitches each
    process's local rows into the global row-sharded array (row keys), or the
    replicated value every process holds (scalars, params, opt state).

Each process passes only its local rows for row-sharded keys — the global
batch never materializes on any single host.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .dp import _key_spec


def batch_spec(key, data_axis="data", model_axis=None):
    """PartitionSpec for one batch key (rows over data, features over model;
    sparse-ingest [B, K] pairs never shard their nnz axis)."""
    return _key_spec(key, data_axis, model_axis)


def put_sharded_batch(local_batch, mesh, data_axis="data", model_axis=None):
    """Assemble a global on-mesh batch from this process's local rows.

    :param local_batch: dict of host arrays. Under multi-process, row-keyed
        entries hold only THIS process's rows (global row count = local rows x
        process_count, rows ordered by process index); scalars hold the same
        value on every process.
    :return: dict of global jax.Arrays ready for the parallel train/eval steps.
    """
    multi = jax.process_count() > 1
    out = {}
    for k, v in local_batch.items():
        sharding = NamedSharding(mesh, batch_spec(k, data_axis, model_axis))
        if multi:
            out[k] = jax.make_array_from_process_local_data(sharding, v)
        else:
            out[k] = jax.device_put(v, sharding)
    return out


def put_replicated(tree, mesh):
    """Replicate a pytree (params / opt state) over the mesh; every process
    must pass the same host values."""
    rep = NamedSharding(mesh, P())
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda v: jax.make_array_from_process_local_data(rep, v), tree)
    return jax.tree_util.tree_map(lambda v: jax.device_put(v, rep), tree)
