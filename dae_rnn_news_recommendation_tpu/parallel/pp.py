"""Pipeline parallelism (pp) for the stacked DAE's hidden tower.

Completes the parallelism set (dp/tp in dp.py+mesh.py, sp in seq.py): the deep
variant's equal-width hidden layers (models/stacked.py; the paper's deep stack) are
placed one-per-device along a 'stage' mesh axis and microbatches flow through the
classic GPipe schedule — at step s, device d runs layer d on microbatch s-d, then
hands the [Bm, D] activations one ICI hop to device d+1 with `ppermute`.

Scope and shape rules, honestly stated:
  - stages must be equal-width (D -> D): JAX shards a stacked [L, D, D] parameter
    pytree over the mesh, which requires homogeneous layer shapes. The F -> D
    input layer is different-shaped by nature, so (as with embedding layers in
    classic PP) it runs replicated BEFORE the pipelined tower — use
    `stack_tower_params` to split a trained StackedDenoisingAutoencoder
    accordingly.
  - forward is differentiable end to end (static trip count -> scan -> AD through
    ppermute), so a reconstruction/triplet loss on the deepest codes trains the
    tower through the pipeline.

Each layer applies the paper's modified encoder H = act(H W + bh) - act(bh)
(reference autoencoder.py:389 at every depth, like models/stacked.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..models.dae_core import resolve_activation
from .mesh import _shard_map, pcast_varying


def stack_tower_params(sdae):
    """Split a fitted StackedDenoisingAutoencoder into (input_layer_params,
    stacked tower params {"W": [L, D, D], "bh": [L, D]}, enc_act_func). Requires
    >= 2 layers, all hidden layers after the first sharing one width. Thread the
    returned activation into pipeline_stack_encode — the codes are silently wrong
    under a different activation."""
    assert sdae.params, "fit the stack first"
    assert len(sdae.params) >= 2, (
        "a pipeline tower needs at least 2 layers (input layer + >=1 stage); "
        f"got {len(sdae.params)}")
    widths = {p["W"].shape[1] for p in sdae.params[1:]}
    assert len({p["W"].shape[0] for p in sdae.params[1:]} | widths) <= 1, (
        "pipeline stages must be equal-width (D -> D); got layer shapes "
        f"{[tuple(p['W'].shape) for p in sdae.params]}")
    tower = {
        "W": jnp.stack([p["W"] for p in sdae.params[1:]]),
        "bh": jnp.stack([p["bh"] for p in sdae.params[1:]]),
    }
    return sdae.params[0], tower, sdae.enc_act_func


def pipeline_stack_encode(tower, x, mesh, act, axis_name="stage",
                          microbatches=None):
    """Encode [B, D] inputs through L equal-width layers, layer l on mesh device l.

    :param tower: {"W": [L, D, D], "bh": [L, D]} — L must equal mesh[axis_name]
    :param x: [B, D] activations out of the (replicated) input layer
    :param act: the stack's enc_act_func (required — stack_tower_params returns it)
    :return: [B, D] deepest codes, replicated
    """
    n_dev = mesh.shape[axis_name]
    l, d, d2 = tower["W"].shape
    assert d == d2, "pipeline stages must be square (D -> D)"
    assert l == n_dev, f"{l} layers need a {l}-device '{axis_name}' axis, got {n_dev}"
    b = x.shape[0]
    m_micro = n_dev if microbatches is None else int(microbatches)
    assert m_micro >= 1 and b % m_micro == 0, (b, m_micro)
    bm = b // m_micro
    act_fn = resolve_activation(act)

    def local_fn(tower_l, x_all):
        # tower_l: {"W": [1, D, D], "bh": [1, D]} — this device's layer
        stage = jax.lax.axis_index(axis_name)
        w, bh = tower_l["W"][0], tower_l["bh"][0]
        x_m = x_all.reshape(m_micro, bm, d)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

        def layer(h):
            return act_fn(h @ w + bh) - act_fn(bh)

        def body(s, carry):
            recv, out = carry
            m = s - stage
            active = (m >= 0) & (m < m_micro)
            mc = jnp.clip(m, 0, m_micro - 1)
            # stage 0 consumes the input microbatch; later stages consume the
            # activations handed over by the previous stage
            h_in = jnp.where(stage == 0,
                             jax.lax.dynamic_index_in_dim(x_m, mc, 0, False),
                             recv)
            h_out = layer(h_in)
            upd = jax.lax.dynamic_update_index_in_dim(out, h_out, mc, 0)
            out = jnp.where(active & (stage == n_dev - 1), upd, out)
            recv = jax.lax.ppermute(h_out, axis_name, perm)
            return recv, out

        recv = pcast_varying(jnp.zeros((bm, d), x_all.dtype), axis_name)
        out = pcast_varying(jnp.zeros((m_micro, bm, d), x_all.dtype), axis_name)
        _, out = jax.lax.fori_loop(0, m_micro + n_dev - 1, body, (recv, out))
        # codes exist on the last stage only; psum replicates them
        return jax.lax.psum(out, axis_name).reshape(b, d)

    fn = _shard_map(
        local_fn, mesh=mesh,
        in_specs=({"W": P(axis_name, None, None), "bh": P(axis_name, None)}, P()),
        out_specs=P(),
    )
    return fn(tower, x)
