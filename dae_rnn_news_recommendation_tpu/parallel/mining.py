"""Anchor-partitioned global triplet mining for shard_map contexts.

`ops/triplet.py` mines a square batch: the batch_all path materializes the full
[B, B, B] distance/mask cube. Under data- or expert-parallel shard_map with GLOBAL
mining semantics, naively all_gathering the codes and calling those functions would
replicate that cube on every device — E-way redundant compute and per-device memory
cubic in the GLOBAL batch.

These variants partition the work by ANCHOR instead: each device mines only its own
B_local rows as anchors against the gathered [B, D] codes — a [B_local, B, B] slice
(batch_all) or [B_local, B] matrix (batch_hard) — and the cross-anchor reductions
(loss numerator/denominator, per-row participation counts, summary means) complete
with psums over the mesh axis. The results are EXACTLY the global-batch semantics of
ops/triplet.py (same arithmetic, associativity aside): `tests/test_sharded_mining.py` asserts
equality against the square oracle on the virtual 8-device mesh.

Returns mirror ops/triplet.py, except data_weight is returned for the LOCAL rows
only ([B_local] — which is precisely what the caller's local reconstruction term
needs; a row's participation as positive/negative on other devices' anchors arrives
through the psum).

Must be called inside shard_map over `axis_name`, with every shard holding the same
gathered (labels, codes, row_valid) and its own contiguous row block (the layout
`jax.lax.all_gather(..., tiled=True)` produces from a row-sharded batch).
"""

import jax
import jax.numpy as jnp

_EPS = 1e-16


def _anchor_block(b_local, b_global, axis_name):
    """Global row indices of this shard's anchors (contiguous tiled layout)."""
    start = jax.lax.axis_index(axis_name) * b_local
    return start, start + jnp.arange(b_local)


def sharded_batch_all_triplet_loss(labels, encode_local, encode, axis_name,
                                   pos_triplets_only=False, row_valid=None):
    """Global-batch batch_all mining, this shard computing its anchors only.

    :param labels: [B] gathered labels (identical on every shard)
    :param encode_local: [B_local, D] this shard's codes
    :param encode: [B, D] gathered codes (identical on every shard)
    :param row_valid: [B] gathered validity mask (or None)
    :return: (loss, data_weight_local [B_local], fraction, num_pos, extras) —
        scalars are global (identical on every shard).
    """
    dtype = encode.dtype
    b_local, b = encode_local.shape[0], encode.shape[0]
    start, a_idx = _anchor_block(b_local, b, axis_name)
    valid = (jnp.ones(b, dtype=bool) if row_valid is None
             else row_valid.astype(bool))
    valid_a = jax.lax.dynamic_slice_in_dim(valid, start, b_local)
    labels_a = jax.lax.dynamic_slice_in_dim(labels, start, b_local)

    dp = jnp.matmul(encode_local, encode.T,
                    precision=jax.lax.Precision.HIGHEST)  # [B_local, B]
    # jaxcheck: disable=R8 (anchor-sliced slab [B_local,B,B] — the shard axis already tiles the cube)
    dist = -dp[:, :, None] + dp[:, None, :]  # [B_local, B, B]

    # triplet mask, anchor axis sliced (ops/triplet.py:58 semantics)
    g_idx = jnp.arange(b)
    a_ne = a_idx[:, None] != g_idx[None, :]             # [B_local, B] a != j
    p_ne_n = ~jnp.eye(b, dtype=bool)
    # jaxcheck: disable=R8 (anchor-sliced slab [B_local,B,B] — the shard axis already tiles the cube)
    distinct = a_ne[:, :, None] & a_ne[:, None, :] & p_ne_n[None, :, :]
    label_eq = labels_a[:, None] == labels[None, :]     # [B_local, B]
    # jaxcheck: disable=R8 (anchor-sliced slab [B_local,B,B] — the shard axis already tiles the cube)
    valid_labels = label_eq[:, :, None] & (~label_eq[:, None, :])
    # jaxcheck: disable=R8 (anchor-sliced slab [B_local,B,B] — the shard axis already tiles the cube)
    all_valid = (valid_a[:, None, None] & valid[None, :, None]
                 & valid[None, None, :])
    valid_mask = (distinct & valid_labels & all_valid).astype(dtype)

    num_valid = jax.lax.psum(jnp.sum(valid_mask), axis_name)
    pos_mask = (valid_mask * dist > _EPS).astype(dtype)
    num_pos = jax.lax.psum(jnp.sum(pos_mask), axis_name)

    if pos_triplets_only:
        mask, num = pos_mask, num_pos
    else:
        mask, num = valid_mask, num_valid

    loss = (jax.lax.psum(jnp.sum(jax.nn.softplus(dist) * mask), axis_name)
            / jnp.maximum(num, _EPS))

    # participation (ops/triplet.py:111): as anchor (local axis) + as positive
    # (axis 1 of somebody's slice) + as negative (axis 2) — the cross-anchor
    # counts psum into [B] vectors, then slice back to local rows
    as_anchor = jnp.sum(mask, axis=(1, 2))                     # [B_local]
    as_pos = jax.lax.psum(jnp.sum(mask, axis=(0, 2)), axis_name)   # [B]
    as_neg = jax.lax.psum(jnp.sum(mask, axis=(0, 1)), axis_name)   # [B]
    data_weight = as_anchor + jax.lax.dynamic_slice_in_dim(
        as_pos + as_neg, start, b_local)

    fraction = num_pos / jnp.maximum(num_valid, _EPS)
    return loss, data_weight, fraction, num_pos, {}


def sharded_batch_hard_triplet_loss(labels, encode_local, encode, axis_name,
                                    row_valid=None):
    """Global-batch batch_hard mining, this shard's anchors only — [B_local, B]
    working set. Keeps the reference quirks (zero-masked hardest-neg max,
    float-equality tie double-count) exactly as ops/triplet.py:119."""
    dtype = encode.dtype
    b_local, b = encode_local.shape[0], encode.shape[0]
    start, a_idx = _anchor_block(b_local, b, axis_name)
    valid = (jnp.ones(b, dtype=bool) if row_valid is None
             else row_valid.astype(bool))
    valid_a = jax.lax.dynamic_slice_in_dim(valid, start, b_local)
    validf = valid.astype(dtype)
    validf_a = valid_a.astype(dtype)
    labels_a = jax.lax.dynamic_slice_in_dim(labels, start, b_local)

    dp = jnp.matmul(encode_local, encode.T,
                    precision=jax.lax.Precision.HIGHEST)  # [B_local, B]

    g_idx = jnp.arange(b)
    a_ne = a_idx[:, None] != g_idx[None, :]
    label_eq = labels_a[:, None] == labels[None, :]
    both_valid = valid_a[:, None] & valid[None, :]
    mask_ap = (a_ne & label_eq & both_valid).astype(dtype)
    mask_an = ((~label_eq) & both_valid).astype(dtype)

    neg_inf = jnp.asarray(-jnp.inf, dtype)
    max_row = jnp.max(jnp.where(valid[None, :], dp, neg_inf), axis=1,
                      keepdims=True)
    max_row = jnp.where(jnp.isfinite(max_row), max_row, jnp.zeros_like(max_row))
    hardest_pos = jnp.min(dp + max_row * (1.0 - mask_ap), axis=1, keepdims=True)
    hardest_neg = jnp.max(mask_an * dp, axis=1, keepdims=True)

    dist = jnp.maximum(hardest_neg - hardest_pos, 0.0)     # [B_local, 1]
    count = (dist > 0.0).astype(dtype) * validf_a[:, None]

    eq_pos = (dp == hardest_pos).astype(dtype) * validf[None, :]
    eq_neg = (dp == hardest_neg).astype(dtype) * validf[None, :]
    hit_pos = jax.lax.psum(jnp.sum(count * eq_pos, axis=0), axis_name)  # [B]
    hit_neg = jax.lax.psum(jnp.sum(count * eq_neg, axis=0), axis_name)  # [B]
    data_weight = jnp.squeeze(count, axis=1) + jax.lax.dynamic_slice_in_dim(
        hit_pos + hit_neg, start, b_local)

    total = jax.lax.psum(jnp.sum(count), axis_name)
    loss = (jax.lax.psum(jnp.sum(jax.nn.softplus(dist) * count), axis_name)
            / jnp.maximum(total, _EPS))
    n_rows = jax.lax.psum(jnp.sum(validf_a), axis_name)
    fraction = total / jnp.maximum(n_rows, 1.0)

    extras = {
        "hardest_positive_dotproduct":
            jax.lax.psum(jnp.sum(hardest_pos[:, 0] * validf_a), axis_name)
            / jnp.maximum(n_rows, 1.0),
        "hardest_negative_dotproduct":
            jax.lax.psum(jnp.sum(hardest_neg[:, 0] * validf_a), axis_name)
            / jnp.maximum(n_rows, 1.0),
    }
    return loss, data_weight, fraction, total, extras
