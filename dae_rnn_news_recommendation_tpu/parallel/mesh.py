"""Device mesh construction.

On a TPU slice the mesh axes map onto the ICI torus (jax.make_mesh picks a good
device order); on CPU tests the same code runs over
--xla_force_host_platform_device_count virtual devices. Multi-host: jax.devices()
spans all hosts after jax.distributed.initialize, so the same mesh code scales from
one chip to a full pod — collectives ride ICI within a slice and DCN across slices.
"""

import jax
import numpy as np


def get_mesh(n_devices=None, axis_name="data", devices=None):
    """1-D data-parallel mesh over the first n_devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    assert n <= len(devices), f"want {n} devices, have {len(devices)}"
    return jax.sharding.Mesh(np.asarray(devices[:n]), (axis_name,))


def get_mesh_2d(data_parallel, model_parallel, axis_names=("data", "model"),
                devices=None):
    """2-D mesh: batch sharded over `data`, features (the wide F axis of W) over
    `model` — the layout for max_features=50k configs (BASELINE.json config 3) where a
    replicated [F, D] W wastes HBM and the encode matmul wants feature-sharded tiles."""
    devices = list(devices if devices is not None else jax.devices())
    n = data_parallel * model_parallel
    assert n <= len(devices), f"want {n} devices, have {len(devices)}"
    grid = np.asarray(devices[:n]).reshape(data_parallel, model_parallel)
    return jax.sharding.Mesh(grid, axis_names)
