"""Device mesh construction.

On a TPU slice the mesh axes map onto the ICI torus (jax.make_mesh picks a good
device order); on CPU tests the same code runs over
--xla_force_host_platform_device_count virtual devices. Multi-host: jax.devices()
spans all hosts after jax.distributed.initialize, so the same mesh code scales from
one chip to a full pod — collectives ride ICI within a slice and DCN across slices.
"""

import contextlib
import threading

import jax
import numpy as np

try:  # jax >= 0.6 re-homed shard_map; 0.4.x only has the experimental name
    from jax.experimental.shard_map import shard_map as _shard_map
except ImportError:  # pragma: no cover
    _shard_map = jax.shard_map

# The replicated->varying cast has been renamed twice: jax >= 0.8 spells it
# `lax.pcast(..., to="varying")`, 0.6-0.7 `lax.pvary`, and 0.4.x only has the
# rewrite primitive `shard_map.pbroadcast`. Loop carries seeded with
# device-invariant zeros must be cast before ppermute/scatter results (which
# ARE varying) replace them, so every ring/pipeline body routes through this
# one alias instead of version-guessing locally.
if hasattr(jax.lax, "pcast"):  # pragma: no cover

    def pcast_varying(x, axis_name):
        """Cast a replicated value to per-device varying on `axis_name`."""
        return jax.lax.pcast(x, (axis_name,), to="varying")

elif hasattr(jax.lax, "pvary"):  # pragma: no cover

    def pcast_varying(x, axis_name):
        """Cast a replicated value to per-device varying on `axis_name`."""
        return jax.lax.pvary(x, (axis_name,))

else:
    from jax.experimental.shard_map import pbroadcast as _smap_pbroadcast

    def pcast_varying(x, axis_name):
        """Cast a replicated value to per-device varying on `axis_name`."""
        return _smap_pbroadcast(x, (axis_name,))

# Every axis name any mesh in this package binds. meshcheck (analysis/
# meshcheck.py rule S3) reads this tuple as the project's axis vocabulary:
# a collective naming an axis outside it is a typo that XLA only reports at
# trace time, from whichever call site happens to trace first.
MESH_AXIS_NAMES = ("data", "model", "seq", "stage", "expert")

MESH_DISPATCH_LOCK = threading.Lock()
# Process-wide serialization of multi-device collective dispatches. A
# shard_map program is a collective: all mesh devices must rendezvous on the
# SAME program. Two threads (fleet replicas, the churn/rollout thread, an
# eval sweep) dispatching concurrently can interleave their programs'
# per-device participant arrivals and deadlock the rendezvous. Every sharded
# dispatch in this process — serve fns, corpus health gates and index refits
# over mesh-sharded slots, the ring AUROC — takes this lock via
# dispatch_lock(). Single-device dispatches never touch it.


def dispatch_lock(sharded=True):
    """The collective-dispatch guard: `with dispatch_lock(sharded):` around
    any call of a shard_map-built (or jit-over-sharded-arrays) program.
    Returns the process-wide `MESH_DISPATCH_LOCK` when `sharded`, else a free
    nullcontext — callers pass their "am I on a mesh" predicate and the
    single-device path pays nothing. This is the one sanctioned idiom
    meshcheck rule S1 recognizes as holding the mesh dispatch lock."""
    return MESH_DISPATCH_LOCK if sharded else contextlib.nullcontext()


def initialize_multihost(coordinator_address=None, num_processes=None,
                         process_id=None, **kwargs):
    """Bring up the multi-host runtime (jax.distributed) so jax.devices() spans
    every host of a multi-slice/multi-host deployment; the mesh constructors below
    then scale unchanged from one chip to a pod (collectives ride ICI inside a
    slice, DCN across slices).

    This is the TPU-native replacement for the distributed backend the reference
    never had (SURVEY §5.8: no NCCL/MPI/tf.distribute — its only transport was the
    in-process feed_dict copy). All arguments default to JAX's environment
    auto-detection (TPU pods populate them via the metadata server); pass them
    explicitly for manual CPU/GPU clusters.

    Safe to call unconditionally from drivers: no-ops when already initialized,
    and degrades to single-process when nothing was passed and the environment
    carries no cluster metadata (auto-detection raises there). Explicit arguments
    always surface their errors.
    """
    explicit = (coordinator_address is not None or num_processes is not None
                or process_id is not None or bool(kwargs))
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id, **kwargs)
    except RuntimeError as e:
        # tolerate "already initialized" always; a bare call may also hit
        # "must be called before backend init" on a warm single process
        if explicit and "already" not in str(e).lower():
            raise
    except Exception:
        if explicit:
            raise
        # bare call on a single host: no coordinator to find — run single-process
    return jax.process_index(), jax.process_count()


def get_mesh(n_devices=None, axis_name="data", devices=None):
    """1-D data-parallel mesh over the first n_devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = n_devices or len(devices)
    assert n <= len(devices), f"want {n} devices, have {len(devices)}"
    return jax.sharding.Mesh(np.asarray(devices[:n]), (axis_name,))


def row_sharding(mesh, axis_name="data"):
    """NamedSharding splitting axis 0 over `axis_name`, rest replicated —
    the layout for any [N, ...] corpus-like array scored shard-locally
    (serve/graph.make_sharded_serve_fn)."""
    return jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(axis_name))


def shard_rows(x, mesh, axis_name="data"):
    """Place `x` (array or pytree of arrays) with rows sharded over the mesh.

    Generalizes the 1-D data mesh from batch sharding to RESIDENT-array
    sharding: pass as `ServingCorpus(device_put=...)` and the corpus
    embeddings, valid mask and int8 scales all land row-sharded, so corpus
    capacity scales with device count. Axis 0 must divide the mesh size
    (serve/graph pads N to the corpus block, which covers any pow-2 mesh)."""
    n_dev = int(mesh.shape[axis_name])
    sharding = row_sharding(mesh, axis_name)

    def put(leaf):
        assert leaf.shape[0] % n_dev == 0, (
            f"axis 0 ({leaf.shape[0]}) not divisible by mesh size {n_dev}")
        return jax.device_put(leaf, sharding)

    return jax.tree_util.tree_map(put, x)


def _sorted_shards(arr, axis=0):
    """The array's addressable shards in ascending row order — shard i of the
    returned list holds rows shard_spans(arr)[i]. Row-sharded layouts from
    `shard_rows` keep device order == global row order, so this is also mesh
    device order."""
    def start(shard):
        idx = shard.index[axis] if shard.index else slice(None)
        return 0 if idx.start is None else int(idx.start)

    return sorted(arr.addressable_shards, key=start)


def shard_spans(arr, axis=0):
    """[(row_start, row_stop, device)] per shard of a row-sharded array, in
    ascending row order. Shard ids used across serve/corpus (loss injection,
    degradation, recovery) are indices into this list."""
    spans = []
    n = int(arr.shape[axis])
    for shard in _sorted_shards(arr, axis):
        idx = shard.index[axis] if shard.index else slice(None)
        lo = 0 if idx.start is None else int(idx.start)
        hi = n if idx.stop is None else int(idx.stop)
        spans.append((lo, hi, shard.device))
    return spans


def shard_host_copies(arr, axis=0):
    """One host np array per shard, in ascending row order. Pure D2H
    transfers of the existing buffers — no compiled program, so the
    chaos-serve compile guard (zero post-warmup XLA compiles) stays clean
    when the shard audit sweeps the corpus."""
    return [np.asarray(shard.data) for shard in _sorted_shards(arr, axis)]


def rebuild_shards(arr, replacements, axis=0):
    """A new array with the same shape/sharding as `arr`, where shard i's
    device buffer is replaced by `replacements[i]` (a host array of the
    shard's shape) and every other shard REUSES `arr`'s live buffer —
    no cross-device copy, no host round-trip for the survivors.

    This is the device-buffer surgery both halves of shard fault tolerance
    ride: `inject_shard_loss` swaps one shard for a poisoned buffer, and
    `recover_shards` swaps the lost shard back in from the host mirror while
    the surviving shards keep their exact bytes (the bitwise-recovery
    contract the chaos-shard soak asserts)."""
    shards = _sorted_shards(arr, axis)
    bufs = []
    for i, shard in enumerate(shards):
        if i in replacements:
            new = np.asarray(replacements[i])
            assert new.shape == shard.data.shape, (
                f"shard {i}: replacement shape {new.shape} != "
                f"{shard.data.shape}")
            bufs.append(jax.device_put(new.astype(arr.dtype), shard.device))
        else:
            bufs.append(shard.data)
    return jax.make_array_from_single_device_arrays(
        arr.shape, arr.sharding, bufs)


def get_mesh_2d(data_parallel, model_parallel, axis_names=("data", "model"),
                devices=None):
    """2-D mesh: batch sharded over `data`, features (the wide F axis of W) over
    `model` — the layout for max_features=50k configs (BASELINE.json config 3) where a
    replicated [F, D] W wastes HBM and the encode matmul wants feature-sharded tiles."""
    devices = list(devices if devices is not None else jax.devices())
    n = data_parallel * model_parallel
    assert n <= len(devices), f"want {n} devices, have {len(devices)}"
    grid = np.asarray(devices[:n]).reshape(data_parallel, model_parallel)
    return jax.sharding.Mesh(grid, axis_names)
