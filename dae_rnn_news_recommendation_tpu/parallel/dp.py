"""Data-parallel (+ optional feature-sharded) training over a jax Mesh.

This replaces the distribution layer the reference never had (SURVEY §5.8: its only
"transport" is the host->TF feed_dict copy). Two mining scopes:

  - 'global' (default): the whole train step is jitted with sharding annotations —
    batch rows sharded over the mesh `data` axis, params replicated (or W
    feature-sharded over `model`). XLA partitions the wide [B,F]x[F,D] matmuls and
    inserts the collectives itself; the [B,D] pairwise dot-product in the triplet ops
    induces an all_gather of embeddings over ICI (B x D is small — the cheap-comms
    choice, SURVEY §7.7), so mining semantics are EXACTLY the single-device global
    batch: same triplets, same loss, any mesh size.

  - 'shard': shard_map runs the whole objective per shard (mining sees only local
    rows — different semantics, zero mining comms), then pmean's cost/grads. This is
    the throughput choice when the global batch is huge.

Gradient reduction: in 'global' mode XLA derives the psum from the sharding
annotations; in 'shard' mode we pmean explicitly inside shard_map.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import telemetry
from ..telemetry.health import sentinel_metrics
from ..train.step import grads_and_metrics, loss_and_metrics
from .mesh import _shard_map, get_mesh  # noqa: F401  (get_mesh re-exported for the estimator)

_ROW_MATRICES = ("x", "x_corr", "org", "pos", "neg", "org_corr", "pos_corr",
                 "neg_corr")
# sparse-ingest pairs are [B, K] where K is the padded-nnz axis, NOT the
# feature axis — they shard over data only, never over a model axis
_ROW_NNZ = ("indices", "values", "org_indices", "org_values",
            "pos_indices", "pos_values", "neg_indices", "neg_values")
_ROW_VECTORS = ("labels", "labels2", "row_valid")


def param_shardings(mesh, model_axis=None):
    """Pytree of NamedShardings for DAE params: replicated by default; with a
    `model` axis, W's feature rows and bv are sharded over it."""
    if model_axis is None:
        rep = NamedSharding(mesh, P())
        return {"W": rep, "bh": rep, "bv": rep}
    return {
        "W": NamedSharding(mesh, P(model_axis, None)),
        "bh": NamedSharding(mesh, P()),
        "bv": NamedSharding(mesh, P(model_axis)),
    }


def opt_state_shardings(opt_state, mesh, data_axis="data"):
    """Cross-replica weight-update sharding (the XLA data-parallel optimization
    of arXiv:2004.13336, ZeRO-1 style): optimizer accumulators shard their
    leading axis over the DATA axis, so per-device optimizer memory scales 1/N
    and XLA lowers the gradient all-reduce + update into reduce_scatter ->
    sharded update -> all_gather (same bytes on the wire as the all-reduce, the
    update math computed once per shard instead of N times).

    Leaves whose leading dim doesn't divide by the axis size (scalars like
    optax counts, small biases on awkward meshes) stay replicated — sharding is
    per-leaf, purely a layout annotation, and changes no math."""
    n = mesh.shape[data_axis]

    def leaf_sharding(leaf):
        if getattr(leaf, "ndim", 0) >= 1 and leaf.shape[0] % n == 0 and \
                leaf.shape[0] > 0:
            return NamedSharding(mesh, P(data_axis,
                                         *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(leaf_sharding, opt_state)


def _key_spec(k, data_axis="data", model_axis=None):
    """PartitionSpec for one batch key."""
    if k in _ROW_MATRICES:
        return P(data_axis, model_axis)
    if k in _ROW_NNZ:
        return P(data_axis, None)
    if k in _ROW_VECTORS:
        return P(data_axis)
    return P()  # scalars (corr_min/corr_max)


def batch_shardings(mesh, keys, data_axis="data", model_axis=None):
    """Shardings for a batch dict: rows over `data`, features over `model` (if any)."""
    return {k: NamedSharding(mesh, _key_spec(k, data_axis, model_axis))
            for k in keys}


def make_parallel_train_step(config, optimizer, mesh, mining_scope="global",
                             loss_fn=loss_and_metrics, data_axis="data",
                             model_axis=None, donate=True,
                             weight_update_sharding=False, health=True,
                             accum_steps=1):
    """Returns step(params, opt_state, key, batch) -> (params, opt_state, metrics).

    Inputs may be ordinary host arrays; jit's in_shardings place them on the mesh.

    :param weight_update_sharding: shard optimizer state over the data axis
        (opt_state_shardings) — 'global' mining scope on a 1-D data mesh only
        (with a model axis the state follows W's own sharding instead).
    :param health: merge the numeric sentinel (telemetry/health.py) into the
        returned metrics. Norms are over the GLOBAL grads/updates in both
        mining scopes (the sentinel runs outside shard_map, after the update),
        so the flags mean the same thing on any mesh.
    :param accum_steps: microbatch gradient accumulation inside the jitted
        step (train/step.py grads_and_metrics) — 'global' mining scope only.
        Each microbatch keeps its rows sharded over the data axis (the
        [accum, B/accum, ...] reshape splits the leading axis, so XLA keeps
        row ownership; global mining all_gathers one microbatch's embeddings
        at a time). 'shard' raises: its objective lives inside shard_map
        where the batch split would need per-shard replication of the scan —
        the estimator falls back to accum_steps=1 there WITH a recorded
        reason (models/estimator.py), never silently.
    """
    if mining_scope == "global":
        if weight_update_sharding and model_axis is not None:
            raise ValueError("weight_update_sharding shards opt state over the "
                             "data axis; with a model axis the state already "
                             "shards with W — use one or the other")
        return telemetry.instrument(
            _make_global_step(config, optimizer, mesh, loss_fn, data_axis,
                              model_axis, donate,
                              weight_update_sharding=weight_update_sharding,
                              health=health, accum_steps=accum_steps),
            "train/step")
    if mining_scope == "shard":
        if weight_update_sharding:
            raise ValueError("weight_update_sharding requires the jit/global "
                             "path (XLA derives the reduce_scatter); "
                             "mining_scope='shard' runs inside shard_map")
        if accum_steps > 1:
            raise ValueError(
                "accum_steps > 1 requires mining_scope='global' (the shard "
                "objective runs inside shard_map; splitting the batch there "
                "changes local-mining semantics per microbatch). The "
                "estimator records this fallback in the run manifest.")
        return telemetry.instrument(
            _make_shard_step(config, optimizer, mesh, loss_fn, data_axis,
                             donate, health=health),
            "train/step")
    raise ValueError(f"unknown mining_scope: {mining_scope!r}")


def _make_global_step(config, optimizer, mesh, loss_fn, data_axis, model_axis,
                      donate, weight_update_sharding=False, health=True,
                      accum_steps=1):
    def step(params, opt_state, key, batch):
        with jax.named_scope("dp/grads"):
            cost, metrics, grads = grads_and_metrics(loss_fn, config, params,
                                                     batch, key, accum_steps)
        with jax.named_scope("dp/update"):
            updates, opt_state = optimizer.update(grads, opt_state, params)
            if health:
                metrics = {**metrics,
                           **sentinel_metrics(cost, grads, updates, params)}
            params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                            updates)
        return params, opt_state, metrics

    p_sh = param_shardings(mesh, model_axis)
    rep = NamedSharding(mesh, P())
    cache = {}

    def wrapper(params, opt_state, key, batch):
        sig = tuple(sorted(batch.keys()))
        if sig not in cache:
            b_sh = batch_shardings(mesh, sig, data_axis, model_axis)
            if weight_update_sharding:
                o_sh = opt_state_shardings(opt_state, mesh, data_axis)
            else:
                o_sh = jax.tree_util.tree_map(lambda _: rep, opt_state)
            cache[sig] = jax.jit(
                step,
                in_shardings=(p_sh, o_sh, rep, b_sh),
                out_shardings=(p_sh, o_sh, None),
                donate_argnums=(0, 1) if donate else (),
            )
        return cache[sig](params, opt_state, key, batch)

    return wrapper


def _make_shard_step(config, optimizer, mesh, loss_fn, data_axis, donate,
                     health=True):
    n_shards = mesh.devices.size

    def local_loss(params, batch, keys):
        # runs per shard inside shard_map; keys is this shard's key slice
        cost, metrics = loss_fn(params, batch, keys[0], config)
        cost = jax.lax.pmean(cost, data_axis)
        metrics = {k: jax.lax.pmean(v, data_axis) for k, v in metrics.items()}
        # metrics are diagnostics riding the grad trace as aux outputs; cut
        # them out of differentiation so shard_map's transpose never sees
        # their symbolic-Zero cotangents (jax 0.4.x chokes on the mix)
        return cost, jax.lax.stop_gradient(metrics)

    def _specs(batch):
        return {k: _key_spec(k, data_axis) for k in batch}

    def step(params, opt_state, key, batch):
        keys = jax.random.split(key, n_shards)

        def loss_of(p):
            cost, metrics = _shard_map(
                lambda p_, b_, k_: local_loss(p_, b_, k_),
                mesh=mesh,
                in_specs=(P(), _specs(batch), P(data_axis)),
                out_specs=(P(), P()),
            )(p, batch, keys)
            return cost, metrics

        with jax.named_scope("dp/grads_sharded"):
            (cost, metrics), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
        with jax.named_scope("dp/update"):
            updates, opt_state = optimizer.update(grads, opt_state, params)
            if health:
                # outside shard_map: grads are already pmean'd, so these are
                # global-norm flags — identical semantics to the 'global' scope
                metrics = {**metrics,
                           **sentinel_metrics(cost, grads, updates, params)}
            params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                            updates)
        return params, opt_state, metrics

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def _clean_feed(batch, config):
    """Validation feeds the clean set as the 'corrupted' input (reference
    autoencoder.py:300-304). Sparse-ingest batches densify on device first."""
    from ..train.step import materialize_x

    batch = materialize_x(dict(batch), config)
    if "org" in batch:
        for n in ("org", "pos", "neg"):
            batch[f"{n}_corr"] = batch[n]
    else:
        batch["x_corr"] = batch["x"]
    return batch


def make_parallel_eval_step(config, mesh, mining_scope="global",
                            loss_fn=loss_and_metrics, data_axis="data",
                            model_axis=None):
    """Validation step matching the TRAIN mining scope: under 'shard' the
    objective runs per shard inside shard_map (validation mines the same local
    populations training optimizes); under 'global' mining sees the full batch.
    A scope mismatch here would make validation triplet metrics measure a
    different objective than the one being trained."""
    if mining_scope == "shard":
        def local_metrics(params, batch):
            _, metrics = loss_fn(params, batch, jax.random.PRNGKey(0), config)
            return {k: jax.lax.pmean(v, data_axis) for k, v in metrics.items()}

        @jax.jit
        def shard_eval(params, batch):
            batch = _clean_feed(batch, config)
            specs = {k: _key_spec(k, data_axis) for k in batch}
            return _shard_map(
                local_metrics, mesh=mesh, in_specs=(P(), specs), out_specs=P(),
            )(params, batch)

        return telemetry.instrument(shard_eval, "train/eval_step")

    if mining_scope != "global":
        raise ValueError(f"unknown mining_scope: {mining_scope!r}")

    def eval_step(params, batch):
        _, metrics = loss_fn(params, _clean_feed(batch, config), jax.random.PRNGKey(0),
                             config)
        return metrics

    p_sh = param_shardings(mesh, model_axis)
    cache = {}

    def wrapper(params, batch):
        sig = tuple(sorted(batch.keys()))
        if sig not in cache:
            b_sh = batch_shardings(mesh, sig, data_axis, model_axis)
            cache[sig] = jax.jit(eval_step, in_shardings=(p_sh, b_sh),
                                 out_shardings=None)
        return cache[sig](params, batch)

    return telemetry.instrument(wrapper, "train/eval_step")
