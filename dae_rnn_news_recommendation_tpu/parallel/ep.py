"""Expert parallelism: a Switch-style mixture-of-denoisers over an `expert` mesh axis.

Net-new — the reference (single-process TF1) has no parallelism at all (SURVEY §2.1);
this completes the framework's mesh-axis set (dp/tp/sp/pp/ep). The model is a routed
ensemble of the paper's modified DAEs (models/dae_core.py semantics per expert:
H_e = act(x̃ W_e + bh_e) − act(bh_e), tied decode): a linear router picks ONE expert
per article (top-1, Switch-transformer style), the chosen expert's encode/decode are
scaled by the router probability so the gate receives gradient, and a load-balance
auxiliary loss keeps the routing spread.

TPU-native layout (one expert per device, E == mesh axis size):

  - expert weights `W [E, F, D]` are sharded one-per-device along the leading axis —
    each device holds only its own [F, D] expert (HBM scales with E);
  - the batch is sharded over the SAME axis (data parallelism rides the expert axis);
  - routing runs per shard: rows are packed into a [E, capacity, F] dispatch block
    and exchanged with `lax.all_to_all` over ICI, the local expert runs ONE dense
    [E*C, F] x [F, D] MXU matmul on its routed rows, and a second all_to_all returns
    codes/reconstructions to the source shards;
  - static capacity C = ceil(B_local / E * capacity_factor) keeps every shape
    XLA-static; overflow rows are dropped from dispatch (standard Switch semantics)
    and excluded from the loss via the returned `routed` mask.

`moe_forward_dense` is the single-device oracle (computes ALL experts on all rows and
selects — exact same math when nothing overflows); `tests/test_ep.py` asserts the
all_to_all path matches it bitwise-close on the virtual 8-device mesh, gradients
included.
"""

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import telemetry
from ..models import dae_core
from ..ops import losses, triplet
from ..ops.initializers import xavier_init
from ..telemetry.health import embedding_health, mining_health, sentinel_metrics
from ..train.step import materialize_x
from . import mining
from .dp import _key_spec
from .mesh import _shard_map


def moe_init_params(key, config, n_experts):
    """Router [F, E] + per-expert DAE params stacked on a leading expert axis."""
    kg, ke = jax.random.split(key)
    expert_keys = jax.random.split(ke, n_experts)
    w = jnp.stack([
        xavier_init(k, config.n_features, config.n_components, config.xavier_const)
        for k in expert_keys
    ])
    return {
        "gate": xavier_init(kg, config.n_features, n_experts),
        "W": w,  # [E, F, D]
        "bh": jnp.zeros((n_experts, config.n_components), jnp.float32),
        "bv": jnp.zeros((n_experts, config.n_features), jnp.float32),
    }


def _route(params, x_corr):
    """Top-1 routing. Returns (expert_id [B], prob [B], probs [B, E])."""
    logits = x_corr @ params["gate"]
    probs = jax.nn.softmax(logits, axis=-1)
    e = jnp.argmax(probs, axis=-1)
    p = jnp.max(probs, axis=-1)
    return e, p, probs


def _expert_forward(expert_params, x, config):
    """One expert's DAE pass on its routed rows (dae_core semantics)."""
    h = dae_core.encode(expert_params, x, config)
    y = dae_core.decode(expert_params, h, config)
    return h, y


def _aux_loss(probs, one_hot, valid, n_experts):
    """Switch load-balance loss over the VALID rows: E * sum_e f_e * pbar_e where
    f_e = fraction of valid rows routed to e, pbar_e = mean router prob over
    valid rows. Padded rows must not enter the stats — they would bias the
    router gradient toward whichever expert absorbs all-zero inputs."""
    n = jnp.maximum(jnp.sum(valid), 1.0)
    f = jnp.sum(one_hot * valid[:, None], axis=0) / n
    pbar = jnp.sum(probs * valid[:, None], axis=0) / n
    return n_experts * jnp.sum(f * pbar)


def moe_forward_dense(params, x_corr, config, row_valid=None):
    """Single-device oracle: run EVERY expert on every row, select the top-1.

    Returns (h [B, D], y [B, F], routed [B] == row_valid, aux scalar). Exactly
    what the routed path computes when no valid row overflows capacity."""
    e, p, probs = _route(params, x_corr)
    n_experts = params["gate"].shape[1]
    valid = (jnp.ones(x_corr.shape[0], probs.dtype) if row_valid is None
             else row_valid.astype(probs.dtype))

    def one_expert(wp):
        return _expert_forward(wp, x_corr, config)

    h_all, y_all = jax.vmap(one_expert)(
        {"W": params["W"], "bh": params["bh"], "bv": params["bv"]}
    )  # [E, B, D], [E, B, F]
    rows = jnp.arange(x_corr.shape[0])
    h = p[:, None] * h_all[e, rows]
    y = p[:, None] * y_all[e, rows]
    one_hot = jax.nn.one_hot(e, n_experts, dtype=probs.dtype)
    return h, y, valid, _aux_loss(probs, one_hot, valid, n_experts)


def capacity(batch_rows, n_experts, capacity_factor):
    """Static per-(source shard, expert) dispatch capacity."""
    return max(1, math.ceil(batch_rows / n_experts * capacity_factor))


def moe_forward_routed(params, x_corr, config, cap, axis_name="expert",
                       row_valid=None):
    """The EP path, called per shard inside shard_map over `axis_name`.

    `params['W']/['bh']/['bv']` carry this device's expert only (leading axis 1);
    the gate is replicated. x_corr is this shard's [B_local, F] rows. Two
    all_to_alls move rows to their expert and results back; everything between is
    one dense MXU matmul per direction on the local expert. Padded rows
    (row_valid == 0) never dispatch: they consume no capacity, enter no routing
    statistic, and come back with routed == 0.
    """
    n_experts = params["gate"].shape[1]
    b_local, f = x_corr.shape
    valid = (jnp.ones(b_local, x_corr.dtype) if row_valid is None
             else row_valid.astype(x_corr.dtype))

    e, p, probs = _route(params, x_corr)
    one_hot = jax.nn.one_hot(e, n_experts, dtype=probs.dtype) * valid[:, None]
    # position of each row within its expert's local queue; rows past `cap` drop.
    # Padded rows (all-zero one_hot row) are pushed to pos == cap: out of bounds
    # HIGH so the 'drop'-mode scatter discards them — NOT -1, which would wrap
    # (negative indices index from the end even under mode='drop') and clobber a
    # real row's slot. `routed` masks them exactly like capacity drops.
    pos = (jnp.cumsum(one_hot, axis=0) * one_hot).sum(-1).astype(jnp.int32) - 1
    pos = jnp.where(valid > 0, pos, cap)
    routed = (pos < cap).astype(x_corr.dtype)

    # pack [E, C, F]: .at[] 'drop' mode discards overflow rows (pos >= cap)
    disp = jnp.zeros((n_experts, cap, f), x_corr.dtype)
    disp = disp.at[e, pos].set(x_corr, mode="drop")

    # exchange: each device ends up with [E, C, F] = its expert's rows from every
    # source shard; flatten to one dense batch for the local expert
    recv = jax.lax.all_to_all(disp, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
    local = {"W": params["W"][0], "bh": params["bh"][0], "bv": params["bv"][0]}
    h_flat, y_flat = _expert_forward(local, recv.reshape(n_experts * cap, f), config)

    # return trip + combine at the source shard; overflow rows read garbage via the
    # clamped gather and are zeroed by `routed`
    d = h_flat.shape[-1]
    h_back = jax.lax.all_to_all(h_flat.reshape(n_experts, cap, d), axis_name,
                                split_axis=0, concat_axis=0, tiled=True)
    y_back = jax.lax.all_to_all(y_flat.reshape(n_experts, cap, f), axis_name,
                                split_axis=0, concat_axis=0, tiled=True)
    pos_c = jnp.clip(pos, 0, cap - 1)
    scale = (p * routed)[:, None]
    h = scale * h_back[e, pos_c]
    y = scale * y_back[e, pos_c]

    # aux over the GLOBAL batch's VALID rows: psum the ROUTING STATS, not the
    # per-shard aux — the Switch formula is bilinear in (frac, pbar), so
    # mean-of-products over shards would differ from the global-batch value the
    # dense oracle computes
    n = jnp.maximum(jax.lax.psum(jnp.sum(valid), axis_name), 1.0)
    frac = jax.lax.psum(jnp.sum(one_hot, axis=0), axis_name) / n
    pbar = jax.lax.psum(jnp.sum(probs * valid[:, None], axis=0), axis_name) / n
    aux = n_experts * jnp.sum(frac * pbar)
    return h, y, routed, aux


def _gather_rows(v, axis_name):
    """all_gather local rows into the global batch, preserving shard order."""
    return jax.lax.all_gather(v, axis_name, tiled=True)


def _global_weighted_mean(per_row, weight, axis_name):
    """sum(per_row*weight)/sum(weight) over the WHOLE batch: psum numerator and
    denominator so the reduction matches the single-device weighted_loss exactly
    (pmean of per-shard means would weight shards, not rows)."""
    num = jax.lax.psum(jnp.sum(per_row * weight), axis_name)
    den = jax.lax.psum(jnp.sum(weight), axis_name)
    return num / jnp.maximum(den, 1e-16)


def moe_loss_and_metrics(params, batch, key, config, router_weight=0.01,
                         cap=None, axis_name=None):
    """Training objective for the mixture: routed (or oracle) corrupt -> route ->
    expert encode/decode -> weighted reconstruction + optional triplet mining on
    the codes + router load-balance term. Rows dropped at capacity are excluded
    from every loss term via the row mask.

    Mining is GLOBAL-batch in both modes (dp.py's cheap-comms choice: the [B, D]
    codes and labels are all_gathered over the expert axis; the [B, F]
    reconstructions never move) — the routed objective is bit-for-bit the dense
    oracle whenever capacity doesn't drop rows."""
    from ..train.step import _corrupt_batch

    batch = materialize_x(batch, config)
    x = batch["x"]
    row_valid = batch.get("row_valid")
    x_corr = batch.get("x_corr")
    if x_corr is None:
        x_corr = _corrupt_batch(key, batch, config)

    if axis_name is None:
        h, y, routed, aux = moe_forward_dense(params, x_corr, config,
                                              row_valid=row_valid)
    else:
        h, y, routed, aux = moe_forward_routed(params, x_corr, config, cap,
                                               axis_name, row_valid=row_valid)
    # routed <= row_valid by construction (padded rows never dispatch)
    valid = routed
    # routed fraction among the REAL rows (padding isn't a drop)
    if row_valid is None:
        n_real, n_routed = float(routed.shape[0]), jnp.sum(routed)
    else:
        n_real, n_routed = jnp.sum(row_valid), jnp.sum(routed)
    if axis_name is not None:
        n_real = jax.lax.psum(n_real, axis_name)
        n_routed = jax.lax.psum(n_routed, axis_name)
    routed_fraction = n_routed / jnp.maximum(n_real, 1.0)

    if config.triplet_strategy != "none":
        if axis_name is None:
            mine = (triplet.batch_all_triplet_loss
                    if config.triplet_strategy == "batch_all"
                    else triplet.batch_hard_triplet_loss)
            t_loss, data_weight, fraction, num, extras = mine(
                batch["labels"], h, row_valid=valid)
            ae_loss = losses.weighted_loss(x, y, config.loss_func,
                                           weight=data_weight, row_valid=valid)
            health = mining_health(data_weight, fraction, row_valid=valid)
        else:
            # global mining, anchor-partitioned: gather only the small [B, D]
            # codes + labels; each device mines ITS rows as anchors (1/E of the
            # batch_all cube) and the cross-anchor sums psum (parallel/mining.py)
            mine = (mining.sharded_batch_all_triplet_loss
                    if config.triplet_strategy == "batch_all"
                    else mining.sharded_batch_hard_triplet_loss)
            t_loss, data_weight_local, fraction, num, extras = mine(
                _gather_rows(batch["labels"], axis_name), h,
                _gather_rows(h, axis_name), axis_name,
                row_valid=_gather_rows(valid, axis_name))
            per_row = losses.reconstruction_loss_per_row(x, y, config.loss_func)
            ae_loss = _global_weighted_mean(per_row, data_weight_local * valid,
                                            axis_name)
            # per-shard data_weight stats; the step's pmean over the expert
            # axis turns them into the global-batch means the dense path
            # reports (means of per-shard means over equal-size shards)
            health = mining_health(data_weight_local, fraction, row_valid=valid)
        cost = ae_loss + config.alpha * t_loss + router_weight * aux
        metrics = {"cost": cost, "autoencoder_loss": ae_loss,
                   "triplet_loss": t_loss, "fraction_triplet": fraction,
                   "num_triplet": num, "router_aux": aux,
                   "routed_fraction": routed_fraction, **extras, **health}
    else:
        if axis_name is None:
            ae_loss = losses.weighted_loss(x, y, config.loss_func,
                                           row_valid=valid)
        else:
            per_row = losses.reconstruction_loss_per_row(x, y, config.loss_func)
            ae_loss = _global_weighted_mean(per_row, valid, axis_name)
        cost = ae_loss + router_weight * aux
        metrics = {"cost": cost, "autoencoder_loss": ae_loss, "router_aux": aux,
                   "routed_fraction": routed_fraction}
    # embedding health over this shard's codes (routed mode: per-shard stats,
    # pmean'd by the step; capacity-dropped rows are masked out via `valid`)
    metrics.update(embedding_health(h, row_valid=valid))
    return cost, metrics


def make_moe_train_step(config, optimizer, mesh, capacity_factor=2.0,
                        router_weight=0.01, axis_name="expert", donate=True,
                        health=True):
    """Jitted EP train step over `mesh` (one expert per device along `axis_name`).

    Batch rows are sharded over the expert axis (dp rides the same axis); expert
    params are sharded one-per-device; the gate is replicated (its gradient
    transposes to a psum). Returns step(params, opt_state, key, batch).
    `health=True` adds the numeric sentinel (telemetry/health.py) over the
    global (post-shard_map) grads/updates."""
    n_experts = mesh.shape[axis_name]

    def step(params, opt_state, key, batch):
        keys = jax.random.split(key, n_experts)
        # dp.py owns the batch-key taxonomy (row matrices / nnz pairs / row
        # vectors / replicated scalars); rows shard over the expert axis here
        b_specs = {k: _key_spec(k, data_axis=axis_name) for k in batch}
        p_specs = {"gate": P(), "W": P(axis_name), "bh": P(axis_name),
                   "bv": P(axis_name)}
        row_key = next((k for k in ("x", "indices", "labels") if k in batch),
                       None)
        if row_key is None:
            raise ValueError(
                "MoE step supports single-input batches only ('x' or "
                f"'indices'/'values' [+ 'labels']); got keys {sorted(batch)}. "
                "Precomputed-triplet (org/pos/neg) batches are not routable — "
                "use make_parallel_train_step for those.")
        cap = capacity(batch[row_key].shape[0] // n_experts, n_experts,
                       capacity_factor)

        def local(p, b, k):
            cost, metrics = moe_loss_and_metrics(
                p, b, k[0], config, router_weight=router_weight, cap=cap,
                axis_name=axis_name)
            cost = jax.lax.pmean(cost, axis_name)
            # diagnostics only: stop_gradient keeps shard_map's transpose
            # away from their symbolic-Zero cotangents (jax 0.4.x bug)
            return cost, jax.lax.stop_gradient(
                {m: jax.lax.pmean(v, axis_name) for m, v in metrics.items()})

        def loss_of(p):
            return _shard_map(
                local, mesh=mesh,
                in_specs=(p_specs, b_specs, P(axis_name)),
                out_specs=(P(), P()),
            )(p, batch, keys)

        (cost, metrics), grads = jax.value_and_grad(loss_of, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if health:
            metrics = {**metrics,
                       **sentinel_metrics(cost, grads, updates, params)}
        params = jax.tree_util.tree_map(lambda a, u: a + u, params, updates)
        return params, opt_state, metrics

    return telemetry.instrument(
        jax.jit(step, donate_argnums=(0, 1) if donate else ()), "train/step")


def make_moe_encode_fn(config, mesh=None, capacity_factor=2.0, axis_name="expert"):
    """Jitted mixture encode (transform analog). With a mesh, runs the routed EP
    path; without, the dense oracle.

    Returns run(params, x) -> (h [B, D], routed [B]). `routed` marks rows that
    actually reached an expert: capacity-dropped rows come back as exact-zero
    codes, and callers must not treat those as real embeddings (the dense path
    never drops — its mask is all ones)."""
    if mesh is None:
        @jax.jit
        def run(params, x):
            h, _, routed, _ = moe_forward_dense(params, x, config)
            return h, routed

        return run

    n_experts = mesh.shape[axis_name]
    p_specs = {"gate": P(), "W": P(axis_name), "bh": P(axis_name),
               "bv": P(axis_name)}

    @jax.jit
    def run(params, x):
        cap = capacity(x.shape[0] // n_experts, n_experts, capacity_factor)

        def local(p, xs):
            h, _, routed, _ = moe_forward_routed(p, xs, config, cap, axis_name)
            return h, routed

        return _shard_map(
            local, mesh=mesh, in_specs=(p_specs, P(axis_name)),
            out_specs=(P(axis_name), P(axis_name)),
        )(params, x)

    return run
