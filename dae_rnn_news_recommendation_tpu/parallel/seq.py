"""Sequence (context) parallelism for the GRU user model.

The GRU recurrence (models/gru_user.py — the net-new second half of the Yahoo!
pipeline) is sequential in T, so long-context scaling can't shard T naively: state
at chunk c needs the state out of chunk c-1. This module pipelines it, the
time-axis analog of GPipe:

  - the time axis is sharded over the mesh: device d holds chunk [d*T/P, (d+1)*T/P)
    of every sequence (shard_map in_spec P(None, 'seq', None));
  - the batch is split into M microbatches; at pipeline step s device d scans its
    local chunk for microbatch m = s - d, then hands the resulting [Bm, H] state to
    device d+1 with `ppermute` (one hop on the ICI ring) while starting microbatch
    m+1. After M + P - 1 steps every chunk of every microbatch has been scanned
    exactly once — work-conserving, with the usual (P-1)/(M+P-1) pipeline bubble;
  - only [Bm, H] states cross devices (H ~ 500: KBs per hop), never the [B, T, D]
    activations — the property that makes ring/CP formulations win for long T;
  - per-step states stay resident where their chunk lives: the output [B, T, H] is
    sharded over T exactly like the input, so the downstream pairwise rank loss
    (pairwise_rank_loss) consumes it without any gather.

Semantics match gru_apply exactly (same masks-carry-state rule, tested against it
on a virtual 8-device mesh), so this is a drop-in for long histories.
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..models.gru_user import gru_apply
from .mesh import _shard_map, pcast_varying


def pipeline_gru_apply(params, seq, mask, mesh, axis_name="seq", microbatches=None):
    """gru_apply over a time-sharded mesh: returns (states [B, T, H] sharded over T,
    final [B, H] replicated).

    :param seq: [B, T, D]; T divisible by mesh[axis_name], B by `microbatches`
    :param mask: [B, T] (1.0 = real step); required — pass ones for dense histories
    :param microbatches: pipeline microbatch count (default: the mesh size, which
        bounds the bubble at ~50%; raise it to amortize further)
    """
    n_dev = mesh.shape[axis_name]
    b, t, d = seq.shape
    h_dim = params["bz"].shape[0]
    m_micro = n_dev if microbatches is None else int(microbatches)
    assert m_micro >= 1, f"microbatches must be >= 1, got {microbatches}"
    assert t % n_dev == 0, f"T={t} not divisible by mesh axis {n_dev}"
    assert b % m_micro == 0, f"B={b} not divisible by microbatches {m_micro}"
    bm = b // m_micro

    def local_fn(params, seq_l, mask_l):
        # seq_l [B, Tc, D], mask_l [B, Tc] — this device's time chunk
        stage = jax.lax.axis_index(axis_name)
        tc = seq_l.shape[1]
        seq_m = seq_l.reshape(m_micro, bm, tc, d)
        mask_m = mask_l.reshape(m_micro, bm, tc)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

        def body(s, carry):
            recv, states_buf = carry
            m = s - stage
            active = (m >= 0) & (m < m_micro)
            mc = jnp.clip(m, 0, m_micro - 1)
            x = jax.lax.dynamic_index_in_dim(seq_m, mc, 0, keepdims=False)
            mk = jax.lax.dynamic_index_in_dim(mask_m, mc, 0, keepdims=False)
            # stage 0 starts every microbatch from zeros; later stages continue
            # from the state handed over by the previous chunk
            h0 = jnp.where(stage == 0, jnp.zeros_like(recv), recv)
            states_c, h_out = gru_apply(params, x, mk, h0=h0)

            upd = jax.lax.dynamic_update_index_in_dim(states_buf, states_c, mc, 0)
            states_buf = jnp.where(active, upd, states_buf)

            # one ICI hop; the wrapped-around value into stage 0 is never read
            recv = jax.lax.ppermute(h_out, axis_name, perm)
            return recv, states_buf

        zeros_h = jnp.zeros((bm, h_dim), seq_l.dtype)
        states_buf = jnp.zeros((m_micro, bm, tc, h_dim), seq_l.dtype)
        recv = pcast_varying(zeros_h, axis_name)
        states_buf = pcast_varying(states_buf, axis_name)
        _, states_buf = jax.lax.fori_loop(
            0, m_micro + n_dev - 1, body, (recv, states_buf))
        return states_buf.reshape(b, tc, h_dim)

    fn = _shard_map(
        local_fn, mesh=mesh,
        in_specs=(P(), P(None, axis_name, None), P(None, axis_name)),
        out_specs=P(None, axis_name, None),
    )
    states = fn(params, seq, mask)
    # masked steps carry state through (gru_apply's scan emits the carry at
    # every step), so the last time slice IS the final state — reading it off
    # the states output instead of psum-ing a separate per-stage buffer keeps
    # the shard_map single-output, which jax 0.4.x's transpose requires when a
    # caller differentiates through states only (a dead second output reaches
    # the transpose as a symbolic Zero and crashes it)
    return states, states[:, -1, :]
