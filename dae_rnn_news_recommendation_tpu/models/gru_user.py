"""GRU user-state model over per-user sequences of article embeddings.

The second half of the Yahoo! paper ("Embedding-based News Recommendation for Millions
of Users" §4): a user's state is a GRU over the embeddings of articles they browsed;
relevance of article `a` to user `u` is the dot product <state_u, embed_a>; training is
pairwise: clicked (positive) articles should score above non-clicked (negative) ones.
The reference repo never implemented this (its README.md:5 defers it; SURVEY §1) — this
is the net-new completion of the pipeline, TPU-native: the sequence loop is a
`lax.scan` (compiled, no Python-level recurrence), batched over users, with a length
mask for ragged histories.

Loss (paper eq. 8 family, matched to the repo's softplus convention):
    L = mean over (u, t) of softplus(-(s_pos - s_neg))
with s = <h_t, e>, h_t the GRU state after the first t articles.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..train.optimizers import make_optimizer


def gru_init_params(key, d_embed, d_hidden, dtype=jnp.float32):
    """Standard GRU cell parameters (update z, reset r, candidate n gates)."""
    k = jax.random.split(key, 6)
    s_in = 1.0 / np.sqrt(d_embed)
    s_h = 1.0 / np.sqrt(d_hidden)

    def u(key, shape, s):
        return jax.random.uniform(key, shape, minval=-s, maxval=s, dtype=dtype)

    return {
        "Wz": u(k[0], (d_embed, d_hidden), s_in), "Uz": u(k[1], (d_hidden, d_hidden), s_h),
        "bz": jnp.zeros((d_hidden,), dtype),
        "Wr": u(k[2], (d_embed, d_hidden), s_in), "Ur": u(k[3], (d_hidden, d_hidden), s_h),
        "br": jnp.zeros((d_hidden,), dtype),
        "Wn": u(k[4], (d_embed, d_hidden), s_in), "Un": u(k[5], (d_hidden, d_hidden), s_h),
        "bn": jnp.zeros((d_hidden,), dtype),
    }


def gru_cell(params, h, x):
    """One GRU step: h' = (1-z)*n + z*h."""
    z = jax.nn.sigmoid(x @ params["Wz"] + h @ params["Uz"] + params["bz"])
    r = jax.nn.sigmoid(x @ params["Wr"] + h @ params["Ur"] + params["br"])
    n = jnp.tanh(x @ params["Wn"] + (r * h) @ params["Un"] + params["bn"])
    return (1.0 - z) * n + z * h


def gru_apply(params, seq, mask=None, h0=None):
    """Run the GRU over a batch of sequences.

    :param seq: [B, T, D] article embeddings in browse order
    :param mask: [B, T] 1.0 for real steps; masked steps carry the state through
    :return: (states [B, T, H] after each step, final state [B, H])
    """
    b, t, d = seq.shape
    h_dim = params["bz"].shape[0]
    if h0 is None:
        h0 = jnp.zeros((b, h_dim), seq.dtype)

    def step(h, inputs):
        x, m = inputs
        h_new = gru_cell(params, h, x)
        if m is not None:
            h_new = jnp.where(m[:, None] > 0, h_new, h)
        return h_new, h_new

    xs = jnp.swapaxes(seq, 0, 1)  # [T, B, D] for scan
    ms = jnp.swapaxes(mask, 0, 1) if mask is not None else jnp.ones((t, b), seq.dtype)
    final, states = jax.lax.scan(step, h0, (xs, ms))
    return jnp.swapaxes(states, 0, 1), final


def rank_loss_from_states(states, pos, neg, mask=None):
    """softplus margin loss given the per-step states (shared by the local and
    sequence-parallel paths)."""
    s_pos = jnp.sum(states * pos, axis=-1)
    s_neg = jnp.sum(states * neg, axis=-1)
    per_step = jax.nn.softplus(-(s_pos - s_neg))
    if mask is None:
        return jnp.mean(per_step)
    m = mask.astype(per_step.dtype)
    return jnp.sum(per_step * m) / (jnp.sum(m) + 1e-16)


def pairwise_rank_loss(params, seq, pos, neg, mask=None):
    """softplus margin loss over per-step states: score clicked above non-clicked.

    :param seq: [B, T, D] browsed-article embeddings
    :param pos: [B, T, D] clicked article at each step (the paper uses the next click)
    :param neg: [B, T, D] sampled non-clicked article
    """
    states, _ = gru_apply(params, seq, mask)
    return rank_loss_from_states(states, pos, neg, mask)


class GRUUserModel:
    """Thin trainer around the functional GRU: fit on (seq, pos, neg) batches,
    produce user states with `user_state`."""

    def __init__(self, d_embed, d_hidden=None, opt="adam", learning_rate=1e-3,
                 momentum=0.5, num_epochs=5, batch_size=64, seed=0, verbose=False,
                 mesh=None, seq_microbatches=None):
        """:param mesh: optional Mesh with a 'seq' axis — training (and inference,
        when shapes allow) then runs the recurrence through the sequence-parallel
        pipeline (parallel/seq.py): T sharded over the axis, exact semantics,
        gradients flow through the ppermute handoffs. Constraints: the mesh axis
        size must divide T, and `seq_microbatches` (default: the axis size) must
        divide the batch size — fit() validates both up front; inference falls
        back to the local scan for incompatible shapes."""
        self.d_embed = d_embed
        self.d_hidden = d_hidden or d_embed
        self.opt = opt
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.num_epochs = num_epochs
        self.batch_size = batch_size
        self.seed = seed
        self.verbose = verbose
        self.mesh = mesh
        self.seq_microbatches = seq_microbatches
        self.params = None

    def _mesh_compatible(self, b, t):
        if self.mesh is None:
            return False
        n_dev = self.mesh.shape["seq"]
        m = self.seq_microbatches or n_dev
        return t % n_dev == 0 and b % m == 0

    def _apply(self, params, seq, mask=None, allow_fallback=False):
        """gru_apply, routed through the sequence-parallel pipeline when a mesh
        was given. With allow_fallback (inference), incompatible shapes use the
        local scan instead of failing — identical results either way."""
        if self.mesh is None or (
                allow_fallback and not self._mesh_compatible(*seq.shape[:2])):
            return gru_apply(params, seq, mask)
        from ..parallel.seq import pipeline_gru_apply

        if mask is None:
            mask = jnp.ones(seq.shape[:2], seq.dtype)
        return pipeline_gru_apply(params, seq, mask, self.mesh,
                                  microbatches=self.seq_microbatches)

    def fit(self, seq, pos, neg, mask=None):
        """:param seq/pos/neg: [N, T, D] float arrays; mask [N, T].

        A ragged tail batch is wrapped with rows from the permutation head to keep
        shapes static, but the wrapped rows are masked out of the loss so no row
        gets two gradient contributions per epoch."""
        from ..utils.seeding import resolve_seed

        seed = resolve_seed(self.seed)  # seed<0 means unseeded: draw fresh
        key = jax.random.PRNGKey(seed)
        key, init_key = jax.random.split(key)
        self.params = gru_init_params(init_key, self.d_embed, self.d_hidden)
        optimizer = make_optimizer(self.opt, self.learning_rate, self.momentum)
        opt_state = optimizer.init(self.params)

        @jax.jit
        def step(params, opt_state, batch):
            def loss_fn(p):
                states, _ = self._apply(p, batch["seq"], batch.get("mask"))
                return rank_loss_from_states(states, batch["pos"], batch["neg"],
                                             batch.get("mask"))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss

        n = seq.shape[0]
        bs = min(self.batch_size, n)
        if self.mesh is not None and not self._mesh_compatible(bs, seq.shape[1]):
            n_dev = self.mesh.shape["seq"]
            m = self.seq_microbatches or n_dev
            raise ValueError(
                f"sequence-parallel fit needs the mesh axis ({n_dev}) to divide "
                f"T={seq.shape[1]} and seq_microbatches ({m}) to divide the "
                f"effective batch size ({bs}); adjust batch_size/seq_microbatches")
        rng = np.random.default_rng(seed)
        ones_mask = np.ones((bs, seq.shape[1]), np.float32) if mask is None else None
        last = None
        for epoch in range(self.num_epochs):
            order = rng.permutation(n)
            for start in range(0, n, bs):
                idx = order[start:start + bs]
                n_real = len(idx)
                if n_real < bs:  # wrap the tail so shapes stay static...
                    idx = np.concatenate([idx, order[: bs - n_real]])
                batch = {"seq": jnp.asarray(seq[idx]), "pos": jnp.asarray(pos[idx]),
                         "neg": jnp.asarray(neg[idx])}
                m = ones_mask if mask is None else np.asarray(mask[idx], np.float32)
                if n_real < bs:  # ...but mask the wrapped rows out of the loss so
                    m = m.copy()  # no row gets two gradient contributions per epoch
                    m[n_real:] = 0.0
                batch["mask"] = jnp.asarray(m)
                self.params, opt_state, last = step(self.params, opt_state, batch)
            if self.verbose and last is not None:
                print(f"epoch {epoch+1}: loss={float(last):.4f}")
        return self

    def save(self, path):
        """Persist the trained cell (npz: gate arrays + geometry)."""
        assert self.params is not None, "nothing to save: call fit() first"
        np.savez(path, __d_embed=np.asarray(self.d_embed),
                 __d_hidden=np.asarray(self.d_hidden),
                 **{k: np.asarray(v) for k, v in self.params.items()})
        return path

    @classmethod
    def load(cls, path, **kwargs):
        """Rebuild a model saved by save(); extra kwargs go to the constructor
        (training hyperparameters are not needed for inference)."""
        data = np.load(path)
        model = cls(int(data["__d_embed"]), d_hidden=int(data["__d_hidden"]),
                    **kwargs)
        model.params = {k: jnp.asarray(data[k]) for k in data.files
                        if not k.startswith("__")}
        return model

    def user_state(self, seq, mask=None):
        """Final user state for each sequence: [N, H]."""
        _, final = self._apply(self.params, jnp.asarray(seq),
                               None if mask is None else jnp.asarray(mask),
                               allow_fallback=True)
        return np.asarray(final)

    def score(self, seq, candidates, mask=None):
        """Relevance <state_u, embed_a> for each user x candidate: [N, C]."""
        states = self.user_state(seq, mask)
        return states @ np.asarray(candidates).T
