"""DenoisingAutoencoderTriplet — the precomputed anchor/pos/neg triplet estimator.

Twin of reference autoencoder/autoencoder_triplet.py: three weight-sharing
encode/decode towers (in JAX simply the same pure function applied to org/pos/neg),
cost = sum of three reconstruction losses + alpha * softplus(enc.neg - enc.pos)
(reference :296-315). Fixes the reference's known defects rather than replicating them
(SURVEY §2.3.4 summary-fetch AttributeError, §2.3.5 stray decode assignment, §2.3.10
sparse `!= None` comparison).

fit() takes dicts {'org','pos','neg'} of aligned row matrices (reference :40-77).
"""

import numpy as np

from ..data.batcher import TripletPaddedBatcher
from ..train.step import triplet_loss_and_metrics
from ..utils.provenance import write_parameter_file
from ..utils.metrics import MetricsWriter
from .estimator import DenoisingAutoencoder
import os


class DenoisingAutoencoderTriplet(DenoisingAutoencoder):
    _loss_fn = staticmethod(triplet_loss_and_metrics)
    _needs_labels = False
    _batcher_cls = TripletPaddedBatcher

    def __init__(self, algo_name="dae_triplet", model_name="dae_triplet",
                 compress_factor=10, main_dir="dae_triplet/", enc_act_func="tanh",
                 dec_act_func="none", loss_func="mean_squared", num_epochs=10,
                 batch_size=10, xavier_init=1, opt="gradient_descent",
                 learning_rate=0.01, momentum=0.5, corr_type="none", corr_frac=0.0,
                 verbose=True, verbose_step=5, seed=-1, alpha=1, **tpu_kwargs):
        super().__init__(
            algo_name=algo_name, model_name=model_name, compress_factor=compress_factor,
            main_dir=main_dir, enc_act_func=enc_act_func, dec_act_func=dec_act_func,
            loss_func=loss_func, num_epochs=num_epochs, batch_size=batch_size,
            xavier_init=xavier_init, opt=opt, learning_rate=learning_rate,
            momentum=momentum, corr_type=corr_type, corr_frac=corr_frac,
            verbose=verbose, verbose_step=verbose_step, seed=seed, alpha=alpha,
            triplet_strategy="none", **tpu_kwargs)

    def _data_extremes(self, train_set):
        if self.corr_type != "salt_and_pepper":
            return {}
        mns, mxs = [], []
        for key in ("org", "pos", "neg"):
            e = super()._data_extremes(train_set[key])
            mns.append(e["corr_min"]); mxs.append(e["corr_max"])
        return {"corr_min": np.float32(min(mns)), "corr_max": np.float32(max(mxs))}

    def fit(self, train_set, validation_set=None, restore_previous_model=False):
        """Fit on {'org','pos','neg'} dicts (reference autoencoder_triplet.py:40-77)."""
        assert type(train_set["org"]) == type(train_set["pos"])
        assert type(train_set["org"]) == type(train_set["neg"])
        assert train_set["org"].shape == train_set["pos"].shape
        assert train_set["org"].shape == train_set["neg"].shape
        if validation_set is not None:
            assert validation_set["org"].shape == validation_set["pos"].shape
            assert validation_set["org"].shape == validation_set["neg"].shape

        n_features = train_set["org"].shape[1]
        self.sparse_input = not isinstance(train_set["org"], np.ndarray)
        self._build(n_features, restore_previous_model)
        write_parameter_file(self.parameter_file, self._parameter_dict(),
                             append=restore_previous_model)
        # run manifest, same contract as the base fit (telemetry/manifest.py)
        self.run_manifest_path = os.path.join(self.tf_summary_dir,
                                              "manifest.json")

        train_writer = MetricsWriter(os.path.join(self.tf_summary_dir, "train/"),
                                     self.use_tensorboard)
        val_writer = MetricsWriter(os.path.join(self.tf_summary_dir, "validation/"),
                                   self.use_tensorboard)
        extremes = self._data_extremes(train_set)
        seed = self.seed if self.seed is not None and self.seed >= 0 else None
        batcher = self._batcher_cls(self.batch_size, shuffle=True, seed=seed,
                                    mesh_batch_multiple=self._batch_multiple)
        # triplet mode always reports the 3-way cost split
        self.triplet_strategy_reported = "precomputed"
        try:
            self._train_loop(train_set, None, validation_set, None,
                             batcher, extremes, train_writer, val_writer)
        finally:
            train_writer.close()
            val_writer.close()
        self._save(self._epoch0 + self.num_epochs)
        return self
