"""sklearn-style estimator for the expert-parallel mixture-of-denoisers.

Net-new model family (no reference counterpart — the reference implements a single
DAE, autoencoder/autoencoder.py): a Switch-style top-1-routed ensemble of the
paper's modified DAEs (parallel/ep.py). Same estimator surface as
`DenoisingAutoencoder` (ctor / fit / transform / load_model /
get_model_parameters / get_weights_as_images) so the drivers and eval tail work
unchanged; `cli/main_autoencoder.py --n_experts E` selects it.

Device story:
  - single device (n_devices=1): the dense mixture — every expert runs on every
    row, top-1 selected. Exact, no capacity drops; fine while E·F·D params fit
    one HBM.
  - expert-parallel (n_devices=E>1): one expert per device over an `expert` mesh
    axis, all_to_all dispatch with static capacity (parallel/ep.py). Training
    may drop overflow rows (Switch semantics, excluded from the loss);
    validation and transform use the dense path, which never drops.
"""

import functools
import os

import jax
import numpy as np
import scipy.sparse as sp

from .. import telemetry
from ..parallel.ep import (make_moe_train_step, moe_forward_dense,
                           moe_init_params, moe_loss_and_metrics)
from ..train.optimizers import make_optimizer
from ..train.step import make_eval_step, make_train_step
from ..utils.checkpoint import latest_checkpoint, load_checkpoint
from .estimator import DenoisingAutoencoder


class MoEDenoisingAutoencoder(DenoisingAutoencoder):
    """Mixture-of-denoisers with online triplet mining; sklearn-like interface."""

    def __init__(self, algo_name="moe_dae", n_experts=4, capacity_factor=2.0,
                 router_weight=0.01, **kwargs):
        """:param n_experts: number of expert DAEs (== n_devices when
            expert-parallel; any value on a single device)
        :param capacity_factor: static dispatch capacity multiplier (routed path
            only); rows past ceil(B_local/E * cf) drop from the training loss
        :param router_weight: weight of the Switch load-balance auxiliary loss
        Everything else: see DenoisingAutoencoder."""
        super().__init__(algo_name=algo_name, **kwargs)
        if self.weight_update_sharding:
            raise ValueError(
                "weight_update_sharding applies to the data-parallel estimator "
                "(parallel/dp.py); the expert-parallel mixture already shards "
                "its optimizer state with the per-device expert params")
        assert int(n_experts) >= 1
        self.n_experts = int(n_experts)
        self.capacity_factor = float(capacity_factor)
        self.router_weight = float(router_weight)
        # the estimator machinery (dense train step, eval step) runs the mixture
        # through the standard loss_fn hook
        self._loss_fn = functools.partial(moe_loss_and_metrics,
                                          router_weight=self.router_weight)

    def _parameter_dict(self):
        d = super()._parameter_dict()
        d.update({"n_experts": self.n_experts,
                  "capacity_factor": self.capacity_factor,
                  "router_weight": self.router_weight})
        return d

    def _build(self, n_features, restore_previous_model):
        self.config = self._make_config(n_features)
        self.optimizer = make_optimizer(self.opt, self.learning_rate, self.momentum)
        key = self._root_key()
        self._key, init_key = jax.random.split(key)
        self.params = moe_init_params(init_key, self.config, self.n_experts)
        self.opt_state = self.optimizer.init(self.params)
        self._epoch0 = 0

        if restore_previous_model:
            path, step = latest_checkpoint(self.model_path)
            if path is None:
                raise FileNotFoundError(
                    f"restore_previous_model=True but no checkpoint under "
                    f"{self.model_path}")
            state = load_checkpoint(path, {"params": self.params,
                                           "opt_state": self.opt_state,
                                           "epoch": np.asarray(0)})
            self.params = state["params"]
            self.opt_state = state["opt_state"]
            self._epoch0 = int(state["epoch"])

        if self.mesh is not None or self.n_devices > 1:
            from ..parallel.mesh import get_mesh

            if self.mesh is None:
                self.mesh = get_mesh(self.n_devices, axis_name="expert")
            assert "expert" in self.mesh.shape, (
                "MoE runs over an 'expert' mesh axis; got axes "
                f"{tuple(self.mesh.shape)}")
            assert self.mesh.shape["expert"] == self.n_experts, (
                f"one expert per device: n_experts={self.n_experts} must equal "
                f"the expert axis size {self.mesh.shape['expert']}")
            self._train_step = make_moe_train_step(
                self.config, self.optimizer, self.mesh,
                capacity_factor=self.capacity_factor,
                router_weight=self.router_weight)
            self._batch_multiple = self.n_experts
        else:
            self._train_step = make_train_step(self.config, self.optimizer,
                                               loss_fn=self._loss_fn)
            self._batch_multiple = 1
        # validation + transform run the dense mixture: exact, never drops, and
        # the [E, F, D] params fit a single device at this model family's scale
        self._eval_step = make_eval_step(self.config, loss_fn=self._loss_fn)
        config = self.config
        self._encode_fn = telemetry.instrument(
            jax.jit(lambda p, x: moe_forward_dense(p, x, config)[0]),
            "train/encode")
        self._sparse_encode_fn = None

    def _transform_sparse(self, data, batch_size):
        """Sparse inputs densify per batch on host and take the dense mixture
        encode (the DAE's gather-accumulate stream keys on a single [F, D]
        weight; the routed equivalent would need per-row expert gathers —
        not worth it for an eval-path encode)."""
        return self._dense_encode_loop(data.tocsr(), batch_size)

    def _log_param_histograms(self, train_writer, gstep):
        for tag, leaf in (("gate", self.params["gate"]),
                          ("enc_w", self.params["W"]),
                          ("hidden_bias", self.params["bh"]),
                          ("visible_bias", self.params["bv"])):
            train_writer.histogram(tag, np.asarray(leaf), gstep)

    def load_model(self, shape, model_path):
        """Restore a trained mixture given (n_features, n_components)."""
        import dataclasses

        from ..utils.checkpoint import load_params

        n_features, n_components = shape
        self.config = dataclasses.replace(self._make_config(n_features),
                                          n_components=int(n_components))
        self.n_components = int(n_components)
        self.optimizer = make_optimizer(self.opt, self.learning_rate,
                                        self.momentum)
        self.params = moe_init_params(jax.random.PRNGKey(0), self.config,
                                      self.n_experts)
        self.opt_state = self.optimizer.init(self.params)
        config = self.config
        self._encode_fn = telemetry.instrument(
            jax.jit(lambda p, x: moe_forward_dense(p, x, config)[0]),
            "train/encode")
        self._sparse_encode_fn = None
        path, _ = latest_checkpoint(model_path)
        self.params = load_params(path or model_path, self.params)
        self._loaded_path = model_path
        return self

    def get_model_parameters(self):
        self._restore_latest()
        return {
            "gate": np.asarray(self.params["gate"]),
            "enc_w": np.asarray(self.params["W"]),      # [E, F, D]
            "enc_b": np.asarray(self.params["bh"]),     # [E, D]
            "dec_b": np.asarray(self.params["bv"]),     # [E, F]
        }

    def get_weights_as_images(self, width, height, outdir="img/", max_images=10,
                              model_path=None):
        """Per-expert hidden-unit weight images (parent semantics, one set per
        expert, suffixed -e{i})."""
        assert max_images <= self.n_components
        if model_path is not None:
            self.load_model((self.config.n_features, self.n_components),
                            model_path)
        else:
            self._restore_latest()
        outdir = os.path.join(self.data_dir, outdir)
        os.makedirs(outdir, exist_ok=True)
        import matplotlib
        matplotlib.use("Agg")
        from matplotlib import pyplot as plt

        w = np.asarray(self.params["W"])  # [E, F, D]
        perm = np.random.permutation(self.n_components)[:max_images]
        for e in range(w.shape[0]):
            for p in perm:
                img = w[e, :, p][: width * height].reshape(height, width)
                path = os.path.join(
                    outdir, f"{self.model_name}-e{e}-enc_weights_{p}.png")
                plt.imsave(path, img, cmap="gray")
