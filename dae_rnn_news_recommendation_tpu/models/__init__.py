from .dae_core import (  # noqa: F401
    DAEConfig,
    init_params,
    encode,
    decode,
    forward,
    resolve_activation,
)
from .estimator import DenoisingAutoencoder  # noqa: F401
from .estimator_triplet import DenoisingAutoencoderTriplet  # noqa: F401
from .stacked import StackedDenoisingAutoencoder  # noqa: F401
from .gru_user import GRUUserModel, gru_init_params, gru_apply  # noqa: F401
