from .dae_core import (  # noqa: F401
    DAEConfig,
    init_params,
    encode,
    decode,
    forward,
    resolve_activation,
)
from .gru_user import GRUUserModel, gru_init_params, gru_apply  # noqa: F401

# The estimators (and the stacked model) import train/, and train/step imports
# models.dae_core — eager imports here would close that cycle when models/ is
# reached through train/ (e.g. `import ...parallel` -> dp -> train.step).
# Resolving them lazily keeps both entry orders working.
_LAZY = {
    "DenoisingAutoencoder": "estimator",
    "DenoisingAutoencoderTriplet": "estimator_triplet",
    "StackedDenoisingAutoencoder": "stacked",
    "MoEDenoisingAutoencoder": "estimator_moe",
}

# __all__ lists only the eager names: a star-import must not trigger __getattr__,
# which would eagerly import estimator/stacked and close the train/ cycle the lazy
# scheme exists to avoid. __dir__ still advertises the lazy names for completion.
__all__ = [
    "DAEConfig", "init_params", "encode", "decode", "forward",
    "resolve_activation", "GRUUserModel", "gru_init_params", "gru_apply",
]


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{_LAZY[name]}", __name__)
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
